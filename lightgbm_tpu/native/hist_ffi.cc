// XLA FFI custom-call wrapper for the native CPU histogram kernel.
//
// The C loops in hist.c run here as a REGISTERED XLA CUSTOM CALL on the
// CPU backend (jax.ffi), not a Python callback: the handler executes on
// XLA's compute thread with no GIL and no host round-trip, so it is
// legal inside jit/while_loop/shard_map — the same integration class as
// the reference's compiled kernels (src/io/dense_bin.hpp:105
// ConstructHistogram called from the C++ tree learner), realized the
// XLA-native way.
//
// Operands (all host/CPU buffers):
//   bins       [R, F]  u8 | s32   dense bin matrix
//   gh         [Rc, 3] f32 | s8   (grad, hess, count) — compacted
//   row_leaf   [Rc]    s32        leaf slot per stream position, -1 dead
//   leaf_ids   [L]     s32        slots to build (-2 sentinels allowed)
//   row_gather [Rc|1]  s32        bins-row per stream position
//   num_rows   [1]     s32        live stream bound
// Attrs: bf16_round (f32 only), use_gather.
// Result: [L, F, B, 3] f32 (s32 for the s8 variant), zeroed here.
//
// Compiled at first use by native/__init__.py with
// `g++ -O3 -shared -fPIC -I $(jax.ffi.include_dir())` and registered
// via jax.ffi.register_ffi_target; ops/histogram.py falls back to the
// XLA scatter formulation when the toolchain is missing.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>
#include <type_traits>

#if defined(__SSE2__) || defined(_M_X64)
#include <emmintrin.h>
#define LGBTPU_SSE2 1
#endif

#include "xla/ffi/api/c_api.h"
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Worker count for the histogram kernel. LIGHTGBM_TPU_NUM_THREADS
// overrides; default is the hardware concurrency (the reference's
// OpenMP default, src/io/dense_bin.hpp histograms are num_threads-
// parallel the same way). Like the reference, the float accumulation
// ORDER depends on the worker count, so results are deterministic for
// a fixed thread count but may differ in the last ulp across counts
// (int8-quantized histograms stay exact regardless).
inline int hist_threads() {
  // re-read per call (getenv is ns next to a ms-scale kernel) so tests
  // and callers can retune without reloading the library
  const char* env = std::getenv("LIGHTGBM_TPU_NUM_THREADS");
  if (env) {
    int v = std::atoi(env);
    if (v >= 1) return v > 64 ? 64 : v;
  }
  unsigned hw = std::thread::hardware_concurrency();
  int v = hw ? static_cast<int>(hw) : 1;
  return v > 16 ? 16 : v;
}

inline float bf16_round_f(float x) {
  uint32_t u;
  std::memcpy(&u, &x, 4);
  u += ((u >> 16) & 1u) + 0x7fffu;
  u &= 0xffff0000u;
  float y;
  std::memcpy(&y, &u, 4);
  return y;
}

// Build slot -> output-row LUT from leaf_ids (slots are small ints).
inline void build_lut(const int32_t* leaf_ids, int64_t L,
                      std::vector<int32_t>& lut) {
  int32_t max_slot = -1;
  for (int64_t i = 0; i < L; i++)
    if (leaf_ids[i] > max_slot) max_slot = leaf_ids[i];
  lut.assign(static_cast<size_t>(max_slot) + 1, -1);
  for (int64_t i = 0; i < L; i++)
    if (leaf_ids[i] >= 0) lut[leaf_ids[i]] = static_cast<int32_t>(i);
}

template <typename BinT, typename GhT, typename AccT, bool kBf16>
void hist_core(const BinT* bins, const GhT* gh, const int32_t* row_leaf,
               const std::vector<int32_t>& lut, const int32_t* row_gather,
               int64_t num_rows, int64_t R_bins, int64_t F, int64_t B,
               AccT* out) {
  const int64_t lut_size = static_cast<int64_t>(lut.size());
  const int64_t FB3 = F * B * 3;
  for (int64_t r = 0; r < num_rows; r++) {
    const int32_t rl = row_leaf[r];
    if (rl < 0 || rl >= lut_size) continue;
    const int32_t li = lut[rl];
    if (li < 0) continue;
    const int64_t row = row_gather ? static_cast<int64_t>(row_gather[r]) : r;
    if (row < 0 || row >= R_bins) continue;   // corrupt gather guard
    AccT g = static_cast<AccT>(gh[r * 3]);
    AccT h = static_cast<AccT>(gh[r * 3 + 1]);
    AccT c = static_cast<AccT>(gh[r * 3 + 2]);
    if (kBf16) {
      g = bf16_round_f(g);
      h = bf16_round_f(h);
      c = bf16_round_f(c);
    }
    AccT* hb = out + static_cast<int64_t>(li) * FB3;
    const BinT* br = bins + row * F;
    for (int64_t f = 0; f < F; f++) {
      const int64_t bv = static_cast<int64_t>(br[f]);
      if (bv < 0 || bv >= B) continue;   // defensive (B < dtype range)
      AccT* cell = hb + (f * B + bv) * 3;
      cell[0] += g;
      cell[1] += h;
      cell[2] += c;
    }
  }
}

template <typename GhT, typename AccT>
ffi::Error HistImpl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                    ffi::AnyBuffer row_leaf, ffi::AnyBuffer leaf_ids,
                    ffi::AnyBuffer row_gather, ffi::AnyBuffer num_rows,
                    bool bf16_round, bool use_gather,
                    ffi::Result<ffi::AnyBuffer> out) {
  auto bdims = bins.dimensions();
  auto odims = out->dimensions();
  if (bdims.size() != 2 || odims.size() != 4)
    return ffi::Error::InvalidArgument("hist: bad operand ranks");
  const int64_t F = bdims[1];
  const int64_t L = odims[0];
  const int64_t B = odims[2];
  if (odims[1] != F || odims[3] != 3)
    return ffi::Error::InvalidArgument("hist: bad output shape");
  if (leaf_ids.element_count() != L)
    return ffi::Error::InvalidArgument("hist: leaf_ids/output mismatch");
  const int64_t Rc = row_leaf.element_count();
  if (gh.element_count() != Rc * 3)
    return ffi::Error::InvalidArgument("hist: gh/row_leaf mismatch");

  const int32_t* rl = reinterpret_cast<const int32_t*>(row_leaf.untyped_data());
  const int32_t* lid = reinterpret_cast<const int32_t*>(leaf_ids.untyped_data());
  const int32_t* rg =
      use_gather ? reinterpret_cast<const int32_t*>(row_gather.untyped_data())
                 : nullptr;
  if (use_gather && row_gather.element_count() < Rc)
    return ffi::Error::InvalidArgument("hist: short row_gather");
  int64_t nr = *reinterpret_cast<const int32_t*>(num_rows.untyped_data());
  if (nr < 0) nr = 0;
  if (nr > Rc) nr = Rc;
  // without a gather the stream indexes bins directly: bound by R too
  if (!use_gather && nr > bdims[0]) nr = bdims[0];

  std::vector<int32_t> lut;
  build_lut(lid, L, lut);

  const GhT* ghp = reinterpret_cast<const GhT*>(gh.untyped_data());
  AccT* op = reinterpret_cast<AccT*>(out->untyped_data());
  std::memset(op, 0, static_cast<size_t>(L * F * B * 3) * sizeof(AccT));

  const bool u8 = bins.element_type() == ffi::U8;
  const void* bp = bins.untyped_data();
  if (u8) {
    if (bf16_round)
      hist_core<uint8_t, GhT, AccT, true>(
          reinterpret_cast<const uint8_t*>(bp), ghp, rl, lut, rg, nr, bdims[0], F, B, op);
    else
      hist_core<uint8_t, GhT, AccT, false>(
          reinterpret_cast<const uint8_t*>(bp), ghp, rl, lut, rg, nr, bdims[0], F, B, op);
  } else {
    if (bf16_round)
      hist_core<int32_t, GhT, AccT, true>(
          reinterpret_cast<const int32_t*>(bp), ghp, rl, lut, rg, nr, bdims[0], F, B, op);
    else
      hist_core<int32_t, GhT, AccT, false>(
          reinterpret_cast<const int32_t*>(bp), ghp, rl, lut, rg, nr, bdims[0], F, B, op);
  }
  return ffi::Error::Success();
}

ffi::Error HistF32Impl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                       ffi::AnyBuffer row_leaf, ffi::AnyBuffer leaf_ids,
                       ffi::AnyBuffer row_gather, ffi::AnyBuffer num_rows,
                       bool bf16_round, bool use_gather,
                       ffi::Result<ffi::AnyBuffer> out) {
  return HistImpl<float, float>(bins, gh, row_leaf, leaf_ids, row_gather,
                                num_rows, bf16_round, use_gather, out);
}

ffi::Error HistI8Impl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                      ffi::AnyBuffer row_leaf, ffi::AnyBuffer leaf_ids,
                      ffi::AnyBuffer row_gather, ffi::AnyBuffer num_rows,
                      bool bf16_round, bool use_gather,
                      ffi::Result<ffi::AnyBuffer> out) {
  (void)bf16_round;  // int8 accumulates exactly; no rounding
  return HistImpl<int8_t, int32_t>(bins, gh, row_leaf, leaf_ids, row_gather,
                                   num_rows, false, use_gather, out);
}

// DataPartition::Split (the relabel pass of tree_builder.relabel) as a
// custom call: rows whose leaf is not splitting this round short-circuit
// after a 4-byte row_leaf read — the XLA formulation streams the pend_*
// gather/select chain over every row every round (~16 ms/round at 1M
// rows, measured). Decision semantics match tree_builder.relabel (and
// tree.h NumericalDecision bin space) exactly.
//
// Operands: bins [R,F] u8|s32, row_leaf [R] s32, pend_active [L+1] u8,
// pend_feat [L+1] s32, pend_thr [L+1] s32, pend_dl [L+1] u8,
// pend_cat [L+1] u8, pend_right [L+1] s32, pend_bits [L+1, BW] u32,
// nan_bin_pf [F] s32. Result: new row_leaf [R] s32.
template <typename BinT, bool kColMajor>
void relabel_core(const BinT* bins, const int32_t* rl_in, int64_t R,
                  int64_t F, int64_t n_slots, const uint8_t* active,
                  const int32_t* feat, const int32_t* thr,
                  const uint8_t* dl, const uint8_t* cat,
                  const int32_t* right, const uint32_t* bits, int64_t BW,
                  const int32_t* nan_bin_pf, int32_t* out) {
  for (int64_t r = 0; r < R; r++) {
    const int32_t rl = rl_in[r];
    out[r] = rl;
    if (rl < 0 || rl >= n_slots || !active[rl]) continue;
    const int32_t f = feat[rl];
    if (f < 0 || f >= F) continue;
    // column-major ([F, R] transposed copy): reading one feature byte
    // costs ~1 B/row instead of the 64 B cache line a row-major row
    // pulls in — the same reason the reference stores per-feature
    // columns (dense_bin.hpp, one DenseBin per feature)
    const int64_t bv = static_cast<int64_t>(
        kColMajor ? bins[static_cast<int64_t>(f) * R + r]
                  : bins[r * F + f]);
    bool go_left;
    if (cat[rl]) {
      const int64_t w = bv >> 5;
      go_left = w < BW && ((bits[rl * BW + w] >> (bv & 31)) & 1u);
    } else if (nan_bin_pf[f] >= 0 && bv == nan_bin_pf[f]) {
      go_left = dl[rl];
    } else {
      go_left = bv <= thr[rl];
    }
    if (!go_left) out[r] = right[rl];
  }
}

// Shared split-decision (tree_builder.relabel / tree.h bin-space
// NumericalDecision semantics), used by the partition op.
inline bool decide_left(int64_t bv, int32_t slot, const int32_t* thr,
                        const uint8_t* dl, const uint8_t* cat,
                        const uint32_t* bits, int64_t BW,
                        int32_t nan_bin) {
  if (cat[slot]) {
    const int64_t w = bv >> 5;
    return w < BW && ((bits[slot * BW + w] >> (bv & 31)) & 1u);
  }
  if (nan_bin >= 0 && bv == nan_bin) return dl[slot];
  return bv <= thr[slot];
}

ffi::Error RelabelImpl(ffi::AnyBuffer bins, ffi::AnyBuffer row_leaf,
                       ffi::AnyBuffer active, ffi::AnyBuffer feat,
                       ffi::AnyBuffer thr, ffi::AnyBuffer dl,
                       ffi::AnyBuffer cat, ffi::AnyBuffer right,
                       ffi::AnyBuffer bits, ffi::AnyBuffer nan_bin_pf,
                       bool col_major, ffi::Result<ffi::AnyBuffer> out) {
  auto bdims = bins.dimensions();
  if (bdims.size() != 2)
    return ffi::Error::InvalidArgument("relabel: bins must be 2-D");
  const int64_t R = bdims[col_major ? 1 : 0];
  const int64_t F = bdims[col_major ? 0 : 1];
  if (row_leaf.element_count() != R || out->element_count() != R)
    return ffi::Error::InvalidArgument("relabel: row_leaf/out mismatch");
  const int64_t n_slots = active.element_count();
  if (feat.element_count() != n_slots || thr.element_count() != n_slots ||
      dl.element_count() != n_slots || cat.element_count() != n_slots ||
      right.element_count() != n_slots ||
      nan_bin_pf.element_count() != F)
    return ffi::Error::InvalidArgument("relabel: pend_* size mismatch");
  auto bitdims = bits.dimensions();
  if (bitdims.size() != 2 || bitdims[0] != n_slots)
    return ffi::Error::InvalidArgument("relabel: bits must be [L+1, BW]");
  const int64_t BW = bitdims[1];

  const int32_t* rl = reinterpret_cast<const int32_t*>(row_leaf.untyped_data());
  const uint8_t* ac = reinterpret_cast<const uint8_t*>(active.untyped_data());
  const int32_t* ft = reinterpret_cast<const int32_t*>(feat.untyped_data());
  const int32_t* th = reinterpret_cast<const int32_t*>(thr.untyped_data());
  const uint8_t* dlp = reinterpret_cast<const uint8_t*>(dl.untyped_data());
  const uint8_t* ct = reinterpret_cast<const uint8_t*>(cat.untyped_data());
  const int32_t* rt = reinterpret_cast<const int32_t*>(right.untyped_data());
  const uint32_t* bt = reinterpret_cast<const uint32_t*>(bits.untyped_data());
  const int32_t* nb = reinterpret_cast<const int32_t*>(nan_bin_pf.untyped_data());
  int32_t* op = reinterpret_cast<int32_t*>(out->untyped_data());

  const bool u8 = bins.element_type() == ffi::U8;
  const void* bp = bins.untyped_data();
  if (u8 && col_major)
    relabel_core<uint8_t, true>(reinterpret_cast<const uint8_t*>(bp), rl,
                                R, F, n_slots, ac, ft, th, dlp, ct, rt,
                                bt, BW, nb, op);
  else if (u8)
    relabel_core<uint8_t, false>(reinterpret_cast<const uint8_t*>(bp), rl,
                                 R, F, n_slots, ac, ft, th, dlp, ct, rt,
                                 bt, BW, nb, op);
  else if (col_major)
    relabel_core<int32_t, true>(reinterpret_cast<const int32_t*>(bp), rl,
                                R, F, n_slots, ac, ft, th, dlp, ct, rt,
                                bt, BW, nb, op);
  else
    relabel_core<int32_t, false>(reinterpret_cast<const int32_t*>(bp), rl,
                                 R, F, n_slots, ac, ft, th, dlp, ct, rt,
                                 bt, BW, nb, op);
  return ffi::Error::Success();
}

// DataPartition::Split (data_partition.hpp semantics, realized as a
// loop-carried ordered index set): `perm` holds row indices grouped by
// leaf slot, `leaf_begin/leaf_cnt` delimit each slot's contiguous
// segment. Splitting a leaf stably partitions ITS segment in place —
// the left child keeps the front of the parent's range, the right
// child takes the back (exactly data_partition.hpp:116 Split) — so
// only the split leaves' rows are touched and histogram construction
// can walk a child's rows with no scan over R and no branch per row.
//
// Operands: bins ([R,F] row-major or [F,R] col-major per attr),
// row_leaf [R] s32, perm [R] s32, leaf_begin [L+1] s32,
// leaf_cnt [L+1] s32, pend_active/feat/thr/dl/cat/right [L+1],
// pend_bits [L+1, BW] u32, nan_bin_pf [F] s32.
// Results: new row_leaf, perm, leaf_begin, leaf_cnt.
template <typename BinT, bool kColMajor>
void partition_core(const BinT* bins, int64_t R, int64_t F,
                    int64_t n_slots, const uint8_t* active,
                    const int32_t* feat, const int32_t* thr,
                    const uint8_t* dl, const uint8_t* cat,
                    const int32_t* right, const uint32_t* bits,
                    int64_t BW, const int32_t* nan_bin_pf,
                    int32_t* rl_out, int32_t* perm_out,
                    int32_t* begin_out, int32_t* cnt_out) {
  // alias-safe: each split segment is copied to scratch before being
  // rewritten in place (perm_out may BE the input buffer when XLA
  // donates the loop carry via input_output_aliases)
  std::vector<int32_t> seg;
  for (int32_t s = 0; s < n_slots; s++) {
    if (!active[s]) continue;
    const int32_t f = feat[s];
    if (f < 0 || f >= F) continue;
    const int32_t rs = right[s];
    if (rs < 0 || rs >= n_slots || rs == s) continue;
    const int64_t b = begin_out[s];
    const int64_t c = cnt_out[s];
    if (b < 0 || c < 0 || b + c > R) continue;   // corrupt state guard
    const int32_t nb = nan_bin_pf[f];
    seg.assign(perm_out + b, perm_out + b + c);
    int64_t nl = 0;
    int64_t nr = 0;
    for (int64_t i = 0; i < c; i++) {
      if (i + 16 < c) {
        const int64_t rp = seg[i + 16];
        __builtin_prefetch(kColMajor
                               ? bins + static_cast<int64_t>(f) * R + rp
                               : bins + rp * F + f);
      }
      const int32_t r = seg[i];
      if (r < 0 || r >= R) continue;   // corrupt perm: never deref/write
      const int64_t bv = static_cast<int64_t>(
          kColMajor ? bins[static_cast<int64_t>(f) * R + r]
                    : bins[static_cast<int64_t>(r) * F + f]);
      if (decide_left(bv, s, thr, dl, cat, bits, BW, nb)) {
        perm_out[b + nl++] = r;
      } else {
        // rights go to the BACK of the parent's range, order preserved:
        // fill from the end backwards, then reverse once
        perm_out[b + c - 1 - nr++] = r;
        rl_out[r] = rs;
      }
    }
    // restore stable order of the right block (it was written reversed,
    // filling backward from b+c). Anchor at b+c-nr, NOT b+nl: when the
    // corrupt-row guard skipped entries, nl+nr < c and stale slots sit
    // between the blocks — the counts below exclude them so no child
    // ever walks a stale (duplicate) row
    for (int64_t i = 0; i < nr / 2; i++) {
      std::swap(perm_out[b + c - nr + i], perm_out[b + c - 1 - i]);
    }
    begin_out[s] = static_cast<int32_t>(b);
    cnt_out[s] = static_cast<int32_t>(nl);
    begin_out[rs] = static_cast<int32_t>(b + c - nr);
    cnt_out[rs] = static_cast<int32_t>(nr);
  }
}

ffi::Error PartitionImpl(ffi::AnyBuffer bins, ffi::AnyBuffer row_leaf,
                         ffi::AnyBuffer perm, ffi::AnyBuffer leaf_begin,
                         ffi::AnyBuffer leaf_cnt, ffi::AnyBuffer active,
                         ffi::AnyBuffer feat, ffi::AnyBuffer thr,
                         ffi::AnyBuffer dl, ffi::AnyBuffer cat,
                         ffi::AnyBuffer right, ffi::AnyBuffer bits,
                         ffi::AnyBuffer nan_bin_pf, bool col_major,
                         ffi::Result<ffi::AnyBuffer> rl_out,
                         ffi::Result<ffi::AnyBuffer> perm_out,
                         ffi::Result<ffi::AnyBuffer> begin_out,
                         ffi::Result<ffi::AnyBuffer> cnt_out) {
  auto bdims = bins.dimensions();
  if (bdims.size() != 2)
    return ffi::Error::InvalidArgument("partition: bins must be 2-D");
  const int64_t R = bdims[col_major ? 1 : 0];
  const int64_t F = bdims[col_major ? 0 : 1];
  if (row_leaf.element_count() != R || perm.element_count() != R ||
      rl_out->element_count() != R || perm_out->element_count() != R)
    return ffi::Error::InvalidArgument("partition: R mismatch");
  const int64_t n_slots = active.element_count();
  if (leaf_begin.element_count() != n_slots ||
      leaf_cnt.element_count() != n_slots ||
      begin_out->element_count() != n_slots ||
      cnt_out->element_count() != n_slots ||
      feat.element_count() != n_slots ||
      thr.element_count() != n_slots || dl.element_count() != n_slots ||
      cat.element_count() != n_slots ||
      right.element_count() != n_slots ||
      nan_bin_pf.element_count() != F)
    return ffi::Error::InvalidArgument("partition: slot size mismatch");
  auto bitdims = bits.dimensions();
  if (bitdims.size() != 2 || bitdims[0] != n_slots)
    return ffi::Error::InvalidArgument("partition: bad bits shape");
  const int64_t BW = bitdims[1];

  int32_t* rl = reinterpret_cast<int32_t*>(rl_out->untyped_data());
  int32_t* pm = reinterpret_cast<int32_t*>(perm_out->untyped_data());
  int32_t* bg = reinterpret_cast<int32_t*>(begin_out->untyped_data());
  int32_t* ct = reinterpret_cast<int32_t*>(cnt_out->untyped_data());
  // with input_output_aliases the carry buffers arrive donated (in
  // place); only copy when XLA handed us distinct buffers
  if (rl != row_leaf.untyped_data())
    std::memcpy(rl, row_leaf.untyped_data(), R * sizeof(int32_t));
  if (pm != perm.untyped_data())
    std::memcpy(pm, perm.untyped_data(), R * sizeof(int32_t));
  if (bg != leaf_begin.untyped_data())
    std::memcpy(bg, leaf_begin.untyped_data(),
                n_slots * sizeof(int32_t));
  if (ct != leaf_cnt.untyped_data())
    std::memcpy(ct, leaf_cnt.untyped_data(),
                n_slots * sizeof(int32_t));

  const uint8_t* ac = reinterpret_cast<const uint8_t*>(active.untyped_data());
  const int32_t* ft = reinterpret_cast<const int32_t*>(feat.untyped_data());
  const int32_t* th = reinterpret_cast<const int32_t*>(thr.untyped_data());
  const uint8_t* dlp = reinterpret_cast<const uint8_t*>(dl.untyped_data());
  const uint8_t* ctg = reinterpret_cast<const uint8_t*>(cat.untyped_data());
  const int32_t* rt = reinterpret_cast<const int32_t*>(right.untyped_data());
  const uint32_t* bt = reinterpret_cast<const uint32_t*>(bits.untyped_data());
  const int32_t* nb = reinterpret_cast<const int32_t*>(nan_bin_pf.untyped_data());

  const bool u8 = bins.element_type() == ffi::U8;
  const void* bp = bins.untyped_data();
  if (u8 && col_major)
    partition_core<uint8_t, true>(reinterpret_cast<const uint8_t*>(bp),
                                  R, F, n_slots, ac, ft, th, dlp, ctg,
                                  rt, bt, BW, nb, rl, pm, bg, ct);
  else if (u8)
    partition_core<uint8_t, false>(reinterpret_cast<const uint8_t*>(bp),
                                   R, F, n_slots, ac, ft, th, dlp, ctg,
                                   rt, bt, BW, nb, rl, pm, bg, ct);
  else if (col_major)
    partition_core<int32_t, true>(reinterpret_cast<const int32_t*>(bp),
                                  R, F, n_slots, ac, ft, th, dlp, ctg,
                                  rt, bt, BW, nb, rl, pm, bg, ct);
  else
    partition_core<int32_t, false>(reinterpret_cast<const int32_t*>(bp),
                                   R, F, n_slots, ac, ft, th, dlp, ctg,
                                   rt, bt, BW, nb, rl, pm, bg, ct);
  return ffi::Error::Success();
}

// Histogram over the partition's ordered row lists: walks exactly the
// requested slots' segments (no scan over R, no per-row branch) — the
// native analog of dense_bin.hpp:105 ConstructHistogram iterating
// data_indices of one leaf.
// Accumulate perm rows [i0, i1) of one leaf segment into a 4-channel
// padded scratch: the per-(row,feature) update is ONE 16-byte SIMD
// load+add+store instead of three scalar read-modify-writes (the inner
// loop is store-port bound otherwise).
template <typename BinT, typename GhT, typename AccT, bool kBf16>
void perm_accum_range(const BinT* bins, const GhT* gh, const int32_t* perm,
                      int64_t i0, int64_t i1, int64_t R, int64_t F,
                      int64_t B, AccT* sc) {
  for (int64_t i = i0; i < i1; i++) {
    // deep leaves' rows are far apart: without prefetch the walk is
    // DRAM-latency bound (~84 ns/row measured); overlap the misses
    if (i + 16 < i1) {
      const int64_t rp = perm[i + 16];
      __builtin_prefetch(bins + rp * F);
      __builtin_prefetch(bins + rp * F + F - 1);   // row may straddle
      __builtin_prefetch(gh + rp * 3);
    }
    const int64_t r = perm[i];
    if (r < 0 || r >= R) continue;   // corrupt perm: never deref
    AccT g = static_cast<AccT>(gh[r * 3]);
    AccT h = static_cast<AccT>(gh[r * 3 + 1]);
    AccT cc = static_cast<AccT>(gh[r * 3 + 2]);
    if (kBf16) {
      g = bf16_round_f(g);
      h = bf16_round_f(h);
      cc = bf16_round_f(cc);
    }
    const BinT* br = bins + r * F;
#if LGBTPU_SSE2
    alignas(16) AccT ghq[4] = {g, h, cc, AccT(0)};
    __m128 ghv_f = _mm_setzero_ps();
    __m128i ghv_i = _mm_setzero_si128();
    if constexpr (std::is_floating_point<AccT>::value)
      ghv_f = _mm_load_ps(reinterpret_cast<const float*>(ghq));
    else
      ghv_i = _mm_load_si128(reinterpret_cast<const __m128i*>(ghq));
#endif
    for (int64_t f = 0; f < F; f++) {
      const int64_t bv = static_cast<int64_t>(br[f]);
      if (bv < 0 || bv >= B) continue;
      AccT* cell = sc + (f * B + bv) * 4;
#if LGBTPU_SSE2
      if constexpr (std::is_floating_point<AccT>::value) {
        float* cf = reinterpret_cast<float*>(cell);
        _mm_storeu_ps(cf, _mm_add_ps(_mm_loadu_ps(cf), ghv_f));
      } else {
        __m128i* ci = reinterpret_cast<__m128i*>(cell);
        _mm_storeu_si128(
            ci, _mm_add_epi32(_mm_loadu_si128(ci), ghv_i));
      }
#else
      cell[0] += g;
      cell[1] += h;
      cell[2] += cc;
#endif
    }
  }
}

template <typename BinT, typename GhT, typename AccT, bool kBf16>
void hist_perm_core(const BinT* bins, const GhT* gh, const int32_t* perm,
                    const int32_t* begin, const int32_t* cnt,
                    int64_t n_slots, const int32_t* leaf_ids, int64_t S,
                    int64_t R, int64_t F, int64_t B, AccT* out) {
  const int64_t FB3 = F * B * 3;
  const size_t FB4 = static_cast<size_t>(F * B * 4);

  // (slot, row-range) chunks; threads take chunks STATICALLY (t, t+T,
  // t+2T, ...) into per-thread per-slot scratches so the accumulation
  // order — and therefore the float result — is deterministic for a
  // fixed thread count (the reference's OpenMP histograms share this
  // contract)
  struct Chunk { int32_t j; int64_t i0, i1; };
  int64_t total = 0;
  for (int64_t j = 0; j < S; j++) {
    const int32_t s = leaf_ids[j];
    if (s < 0 || s >= n_slots) continue;
    const int64_t c = cnt[s];
    const int64_t b = begin[s];
    if (b < 0 || c <= 0 || b + c > R) continue;
    total += c;
  }
  int T = hist_threads();
  // thread spawn+join costs O(100 us); stay serial until the work
  // dwarfs it (a 256k-row pass is ~ms-scale)
  if (total < (int64_t{1} << 18)) T = 1;
  // bound the worst-case scratch set (every thread touching every
  // slot) to ~1 GiB so wide lattices shed workers instead of paging
  const int64_t per_thread_worst =
      S * static_cast<int64_t>(FB4) * sizeof(AccT);
  const int64_t t_mem = (int64_t{1} << 30) /
                        (per_thread_worst > 0 ? per_thread_worst : 1);
  if (t_mem < T) T = t_mem < 1 ? 1 : static_cast<int>(t_mem);
  const int64_t csz = total / (static_cast<int64_t>(T) * 8) + 1;
  const int64_t chunk = csz < 16384 ? 16384 : csz;
  std::vector<Chunk> chunks;
  for (int64_t j = 0; j < S; j++) {
    const int32_t s = leaf_ids[j];
    if (s < 0 || s >= n_slots) continue;
    const int64_t b = begin[s];
    const int64_t c = cnt[s];
    if (b < 0 || c <= 0 || b + c > R) continue;
    for (int64_t i0 = b; i0 < b + c; i0 += chunk) {
      const int64_t i1 = (i0 + chunk < b + c) ? i0 + chunk : b + c;
      chunks.push_back({static_cast<int32_t>(j), i0, i1});
    }
  }
  if (T > static_cast<int>(chunks.size()))
    T = static_cast<int>(chunks.size());

  if (T <= 1) {
    // serial: one scratch reused slot-by-slot (chunks of a slot are
    // consecutive), numerically identical to the pre-threading kernel
    std::vector<AccT> scratch(FB4, AccT(0));
    int32_t cur = -1;
    auto fold = [&](int32_t j) {
      AccT* hb = out + static_cast<int64_t>(j) * FB3;
      const AccT* sc = scratch.data();
      for (int64_t k = 0; k < F * B; k++) {
        hb[k * 3] = sc[k * 4];
        hb[k * 3 + 1] = sc[k * 4 + 1];
        hb[k * 3 + 2] = sc[k * 4 + 2];
      }
    };
    for (const Chunk& ck : chunks) {
      if (ck.j != cur) {
        if (cur >= 0) fold(cur);
        std::fill(scratch.begin(), scratch.end(), AccT(0));
        cur = ck.j;
      }
      perm_accum_range<BinT, GhT, AccT, kBf16>(bins, gh, perm, ck.i0,
                                               ck.i1, R, F, B,
                                               scratch.data());
    }
    if (cur >= 0) fold(cur);
    return;
  }

  // parallel: per-thread per-slot scratches, folded slot-major after
  // the join (fold order fixed: thread 0, 1, ...). All scratches are
  // allocated HERE, before any thread exists: an allocation failure
  // inside a worker would escape as std::terminate (no catch crosses a
  // thread boundary), while here it degrades to the serial tail below.
  std::vector<std::vector<std::vector<AccT>>> sc_t(
      static_cast<size_t>(T));
  try {
    for (int t = 0; t < T; t++) {
      sc_t[static_cast<size_t>(t)].resize(static_cast<size_t>(S));
      for (size_t k = static_cast<size_t>(t); k < chunks.size();
           k += static_cast<size_t>(T)) {
        auto& sc = sc_t[static_cast<size_t>(t)][
            static_cast<size_t>(chunks[k].j)];
        if (sc.empty()) sc.assign(FB4, AccT(0));
      }
    }
  } catch (const std::bad_alloc&) {
    // scratch set does not fit: fall back to one thread's worth
    sc_t.assign(1, {});
    sc_t[0].resize(static_cast<size_t>(S));
    for (const Chunk& ck : chunks) {
      auto& sc = sc_t[0][static_cast<size_t>(ck.j)];
      if (sc.empty()) sc.assign(FB4, AccT(0));  // S scratches: required
    }
    T = 1;
  }
  auto run_worker = [&](int t) {
    auto& mine = sc_t[static_cast<size_t>(t)];
    for (size_t k = static_cast<size_t>(t); k < chunks.size();
         k += static_cast<size_t>(T)) {
      const Chunk& ck = chunks[k];
      perm_accum_range<BinT, GhT, AccT, kBf16>(
          bins, gh, perm, ck.i0, ck.i1, R, F, B,
          mine[static_cast<size_t>(ck.j)].data());
    }
  };
  std::vector<std::thread> workers;
  workers.reserve(static_cast<size_t>(T));
  int spawned = 0;
  try {
    for (int t = 1; t < T; t++) {
      workers.emplace_back(run_worker, t);
      spawned++;
    }
  } catch (...) {
    // resource exhaustion spawning workers: the unspawned indices run
    // on this thread below, so every chunk is still processed exactly
    // once into its own scratch
  }
  run_worker(0);
  for (int t = spawned + 1; t < T; t++) run_worker(t);
  for (auto& w : workers) w.join();
  for (int64_t j = 0; j < S; j++) {
    AccT* hb = out + j * FB3;
    bool first = true;
    for (int t = 0; t < T; t++) {
      const auto& sc = sc_t[static_cast<size_t>(t)][static_cast<size_t>(j)];
      if (sc.empty()) continue;
      if (first) {
        for (int64_t k = 0; k < F * B; k++) {
          hb[k * 3] = sc[k * 4];
          hb[k * 3 + 1] = sc[k * 4 + 1];
          hb[k * 3 + 2] = sc[k * 4 + 2];
        }
        first = false;
      } else {
        for (int64_t k = 0; k < F * B; k++) {
          hb[k * 3] += sc[k * 4];
          hb[k * 3 + 1] += sc[k * 4 + 1];
          hb[k * 3 + 2] += sc[k * 4 + 2];
        }
      }
    }
  }
}

template <typename GhT, typename AccT>
ffi::Error HistPermImpl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                        ffi::AnyBuffer perm, ffi::AnyBuffer leaf_begin,
                        ffi::AnyBuffer leaf_cnt, ffi::AnyBuffer leaf_ids,
                        bool bf16_round,
                        ffi::Result<ffi::AnyBuffer> out) {
  auto bdims = bins.dimensions();
  auto odims = out->dimensions();
  if (bdims.size() != 2 || odims.size() != 4)
    return ffi::Error::InvalidArgument("hist_perm: bad ranks");
  const int64_t R = bdims[0];
  const int64_t F = bdims[1];
  const int64_t S = odims[0];
  const int64_t B = odims[2];
  if (odims[1] != F || odims[3] != 3 || leaf_ids.element_count() != S)
    return ffi::Error::InvalidArgument("hist_perm: bad output shape");
  if (perm.element_count() != R || gh.element_count() != R * 3)
    return ffi::Error::InvalidArgument("hist_perm: R mismatch");
  const int64_t n_slots = leaf_begin.element_count();
  if (leaf_cnt.element_count() != n_slots)
    return ffi::Error::InvalidArgument("hist_perm: slot mismatch");

  const int32_t* pm = reinterpret_cast<const int32_t*>(perm.untyped_data());
  const int32_t* bg = reinterpret_cast<const int32_t*>(leaf_begin.untyped_data());
  const int32_t* ct = reinterpret_cast<const int32_t*>(leaf_cnt.untyped_data());
  const int32_t* lid = reinterpret_cast<const int32_t*>(leaf_ids.untyped_data());
  const GhT* ghp = reinterpret_cast<const GhT*>(gh.untyped_data());
  AccT* op = reinterpret_cast<AccT*>(out->untyped_data());
  std::memset(op, 0, static_cast<size_t>(S * F * B * 3) * sizeof(AccT));

  const bool u8 = bins.element_type() == ffi::U8;
  const void* bp = bins.untyped_data();
  if (u8) {
    if (bf16_round)
      hist_perm_core<uint8_t, GhT, AccT, true>(
          reinterpret_cast<const uint8_t*>(bp), ghp, pm, bg, ct, n_slots,
          lid, S, R, F, B, op);
    else
      hist_perm_core<uint8_t, GhT, AccT, false>(
          reinterpret_cast<const uint8_t*>(bp), ghp, pm, bg, ct, n_slots,
          lid, S, R, F, B, op);
  } else {
    if (bf16_round)
      hist_perm_core<int32_t, GhT, AccT, true>(
          reinterpret_cast<const int32_t*>(bp), ghp, pm, bg, ct, n_slots,
          lid, S, R, F, B, op);
    else
      hist_perm_core<int32_t, GhT, AccT, false>(
          reinterpret_cast<const int32_t*>(bp), ghp, pm, bg, ct, n_slots,
          lid, S, R, F, B, op);
  }
  return ffi::Error::Success();
}

ffi::Error HistPermF32Impl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                           ffi::AnyBuffer perm, ffi::AnyBuffer leaf_begin,
                           ffi::AnyBuffer leaf_cnt,
                           ffi::AnyBuffer leaf_ids, bool bf16_round,
                           ffi::Result<ffi::AnyBuffer> out) {
  return HistPermImpl<float, float>(bins, gh, perm, leaf_begin, leaf_cnt,
                                    leaf_ids, bf16_round, out);
}

ffi::Error HistPermI8Impl(ffi::AnyBuffer bins, ffi::AnyBuffer gh,
                          ffi::AnyBuffer perm, ffi::AnyBuffer leaf_begin,
                          ffi::AnyBuffer leaf_cnt,
                          ffi::AnyBuffer leaf_ids, bool bf16_round,
                          ffi::Result<ffi::AnyBuffer> out) {
  (void)bf16_round;
  return HistPermImpl<int8_t, int32_t>(bins, gh, perm, leaf_begin,
                                       leaf_cnt, leaf_ids, false, out);
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuPartition, PartitionImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()            // bins
        .Arg<ffi::AnyBuffer>()            // row_leaf
        .Arg<ffi::AnyBuffer>()            // perm
        .Arg<ffi::AnyBuffer>()            // leaf_begin
        .Arg<ffi::AnyBuffer>()            // leaf_cnt
        .Arg<ffi::AnyBuffer>()            // pend_active
        .Arg<ffi::AnyBuffer>()            // pend_feat
        .Arg<ffi::AnyBuffer>()            // pend_thr
        .Arg<ffi::AnyBuffer>()            // pend_dl
        .Arg<ffi::AnyBuffer>()            // pend_cat
        .Arg<ffi::AnyBuffer>()            // pend_right
        .Arg<ffi::AnyBuffer>()            // pend_bits
        .Arg<ffi::AnyBuffer>()            // nan_bin_pf
        .Attr<bool>("col_major")
        .Ret<ffi::AnyBuffer>()            // row_leaf out
        .Ret<ffi::AnyBuffer>()            // perm out
        .Ret<ffi::AnyBuffer>()            // leaf_begin out
        .Ret<ffi::AnyBuffer>());          // leaf_cnt out

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuHistPermF32, HistPermF32Impl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()            // bins
        .Arg<ffi::AnyBuffer>()            // gh
        .Arg<ffi::AnyBuffer>()            // perm
        .Arg<ffi::AnyBuffer>()            // leaf_begin
        .Arg<ffi::AnyBuffer>()            // leaf_cnt
        .Arg<ffi::AnyBuffer>()            // leaf_ids
        .Attr<bool>("bf16_round")
        .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuHistPermI8, HistPermI8Impl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()            // bins
        .Arg<ffi::AnyBuffer>()            // gh
        .Arg<ffi::AnyBuffer>()            // perm
        .Arg<ffi::AnyBuffer>()            // leaf_begin
        .Arg<ffi::AnyBuffer>()            // leaf_cnt
        .Arg<ffi::AnyBuffer>()            // leaf_ids
        .Attr<bool>("bf16_round")
        .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuRelabel, RelabelImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()            // bins
        .Arg<ffi::AnyBuffer>()            // row_leaf
        .Arg<ffi::AnyBuffer>()            // pend_active
        .Arg<ffi::AnyBuffer>()            // pend_feat
        .Arg<ffi::AnyBuffer>()            // pend_thr
        .Arg<ffi::AnyBuffer>()            // pend_dl
        .Arg<ffi::AnyBuffer>()            // pend_cat
        .Arg<ffi::AnyBuffer>()            // pend_right
        .Arg<ffi::AnyBuffer>()            // pend_bits
        .Arg<ffi::AnyBuffer>()            // nan_bin_pf
        .Attr<bool>("col_major")
        .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuHistF32, HistF32Impl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()            // bins
        .Arg<ffi::AnyBuffer>()            // gh
        .Arg<ffi::AnyBuffer>()            // row_leaf
        .Arg<ffi::AnyBuffer>()            // leaf_ids
        .Arg<ffi::AnyBuffer>()            // row_gather
        .Arg<ffi::AnyBuffer>()            // num_rows
        .Attr<bool>("bf16_round")
        .Attr<bool>("use_gather")
        .Ret<ffi::AnyBuffer>());          // out

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LgbtpuHistI8, HistI8Impl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()
        .Arg<ffi::AnyBuffer>()
        .Arg<ffi::AnyBuffer>()
        .Arg<ffi::AnyBuffer>()
        .Arg<ffi::AnyBuffer>()
        .Arg<ffi::AnyBuffer>()
        .Attr<bool>("bf16_round")
        .Attr<bool>("use_gather")
        .Ret<ffi::AnyBuffer>());
