"""Runtime-compiled native helpers (the C side of the data loader).

The reference ships its parser as part of the C++ core
(``src/io/parser.cpp``); here ``parser.c`` is compiled ON FIRST USE with
``gcc -O3 -shared -fPIC`` into a content-hashed cache file and loaded
via ctypes — no install-time build step, and every caller keeps a pure
Python fallback, so a missing/broken toolchain only costs speed
(~10-40x on large text files), never functionality.

Set ``LIGHTGBM_TPU_NO_NATIVE=1`` to force the Python paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

import numpy as np

__all__ = ["native_lib", "capi_lib", "hist_lib", "jax_ffi",
           "parse_delimited", "parse_libsvm"]


def jax_ffi():
    """The jax FFI namespace across versions: ``jax.ffi`` where it
    exists (0.5+), else ``jax.extend.ffi`` (0.4.x) — same surface
    (include_dir / pycapsule / register_ffi_target / ffi_call)."""
    import jax
    ffi = getattr(jax, "ffi", None)
    if ffi is not None:
        return ffi
    import jax.extend as jex
    return jex.ffi

_LIB = None
_TRIED = False
_CAPI = None
_CAPI_TRIED = False
_HIST = None
_HIST_TRIED = False

_DOUBLE_P = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")




def _compile_and_load(src_name: str, so_prefix: str, extra_gcc=(),
                      compiler: str = "gcc"):
    """Compile a bundled C/C++ source into the content-hashed per-user
    cache (0700 — a predictable /tmp path would let another local user
    pre-plant a malicious .so) and ctypes-load it. Raises on failure."""
    src = os.path.join(os.path.dirname(__file__), src_name)
    with open(src, "rb") as f:
        code = f.read()
    tag = hashlib.sha256(code + repr(extra_gcc).encode()).hexdigest()[:16]
    cache_dir = os.environ.get("LIGHTGBM_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "lightgbm_tpu")
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    so = os.path.join(cache_dir, f"{so_prefix}_{tag}.so")
    if not os.path.exists(so):
        tmp = f"{so}.{os.getpid()}.tmp"
        subprocess.run(
            [compiler, "-O3", "-shared", "-fPIC", "-o", tmp, src,
             *extra_gcc],
            check=True, capture_output=True, timeout=300)
        os.replace(tmp, so)  # atomic: concurrent builders both win
    return ctypes.CDLL(so)

def native_lib():
    """The loaded CDLL, or None when native helpers are unavailable."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        return None
    try:
        lib = _compile_and_load("parser.c", "lightgbm_tpu_parser")
        lib.lgbtpu_max_cols.restype = ctypes.c_long
        lib.lgbtpu_max_cols.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                        ctypes.c_char]
        lib.lgbtpu_parse_delimited.restype = ctypes.c_int
        lib.lgbtpu_parse_delimited.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
            ctypes.c_long, _DOUBLE_P]
        lib.lgbtpu_libsvm_max_index.restype = ctypes.c_long
        lib.lgbtpu_libsvm_max_index.argtypes = [ctypes.c_char_p,
                                                ctypes.c_long]
        lib.lgbtpu_parse_libsvm.restype = ctypes.c_int
        lib.lgbtpu_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            _DOUBLE_P, _DOUBLE_P]
        lib.lgbtpu_greedy_bounds.restype = ctypes.c_long
        lib.lgbtpu_greedy_bounds.argtypes = [
            _DOUBLE_P, np.ctypeslib.ndpointer(np.int64,
                                              flags="C_CONTIGUOUS"),
            ctypes.c_long, ctypes.c_long, ctypes.c_double, ctypes.c_long,
            _DOUBLE_P]
        lib.lgbtpu_values_to_bins.restype = None
        lib.lgbtpu_values_to_bins.argtypes = [
            _DOUBLE_P, ctypes.c_long, _DOUBLE_P, ctypes.c_long,
            ctypes.c_long,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
        _LIB = lib
    except Exception:
        _LIB = None
    return _LIB


def capi_lib():
    """The native C inference API (capi.c), runtime-compiled and loaded
    via ctypes like :func:`native_lib`. Returns None when unavailable.
    C consumers build the .so directly (see capi.h); this loader exists
    for the test suite and for Python-side smoke use."""
    global _CAPI, _CAPI_TRIED
    if _CAPI_TRIED:
        return _CAPI
    _CAPI_TRIED = True
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        return None
    try:
        lib = _compile_and_load("capi.c", "lightgbm_tpu_capi",
                                extra_gcc=("-pthread", "-lm"))
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        lib.LGBM_BoosterCreateFromModelfile.restype = ctypes.c_int
        lib.LGBM_BoosterCreateFromModelfile.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_void_p)]
        lib.LGBM_BoosterFree.argtypes = [ctypes.c_void_p]
        lib.LGBM_BoosterGetNumClasses.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        lib.LGBM_BoosterGetNumFeature.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
        lib.LGBM_BoosterPredictForMat.restype = ctypes.c_int
        lib.LGBM_BoosterPredictForMat.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), _DOUBLE_P]
        lib.LGBM_BoosterPredictForMatSingleRow.restype = ctypes.c_int
        lib.LGBM_BoosterPredictForMatSingleRow.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int32, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), _DOUBLE_P]
        lib.LGBM_BoosterPredictForCSR.restype = ctypes.c_int
        lib.LGBM_BoosterPredictForCSR.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), _DOUBLE_P]
        for g in ("LGBM_BoosterGetCurrentIteration",
                  "LGBM_BoosterNumModelPerIteration",
                  "LGBM_BoosterNumberOfTotalModel",
                  "LGBM_BoosterGetPredictLayout"):
            fn = getattr(lib, g)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_int)]
        _CAPI = lib
    except Exception:
        _CAPI = None
    return _CAPI


_INT32_P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def hist_lib():
    """True when the native histogram kernel is compiled AND registered
    as an XLA FFI custom-call pair ("lgbtpu_hist_f32"/"lgbtpu_hist_i8",
    platform cpu); None when unavailable.

    The kernel (hist.c loops wrapped by hist_ffi.cc) is the CPU-backend
    analog of the device kernels in ops/histogram.py — dense_bin.hpp:105
    ConstructHistogram cache locality — and runs on XLA's compute thread
    with no GIL or host round-trip (a jax.pure_callback would deadlock a
    single-threaded CPU client waiting on its own executor)."""
    global _HIST, _HIST_TRIED
    if _HIST_TRIED:
        return _HIST
    _HIST_TRIED = True
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        return None
    try:
        ffi = jax_ffi()
        inc = ffi.include_dir()
        lib = _compile_and_load(
            "hist_ffi.cc", "lightgbm_tpu_hist_ffi",
            extra_gcc=("-std=c++17", "-pthread", f"-I{inc}"),
            compiler="g++")
        ffi.register_ffi_target(
            "lgbtpu_hist_f32", ffi.pycapsule(lib.LgbtpuHistF32),
            platform="cpu")
        ffi.register_ffi_target(
            "lgbtpu_hist_i8", ffi.pycapsule(lib.LgbtpuHistI8),
            platform="cpu")
        ffi.register_ffi_target(
            "lgbtpu_relabel", ffi.pycapsule(lib.LgbtpuRelabel),
            platform="cpu")
        ffi.register_ffi_target(
            "lgbtpu_partition", ffi.pycapsule(lib.LgbtpuPartition),
            platform="cpu")
        ffi.register_ffi_target(
            "lgbtpu_hist_perm_f32",
            ffi.pycapsule(lib.LgbtpuHistPermF32), platform="cpu")
        ffi.register_ffi_target(
            "lgbtpu_hist_perm_i8",
            ffi.pycapsule(lib.LgbtpuHistPermI8), platform="cpu")
        _HIST = lib
    except Exception:
        _HIST = None
    return _HIST


def greedy_bounds(distinct: np.ndarray, counts: np.ndarray,
                  max_bin: int, total_cnt: float,
                  min_data_in_bin: int) -> Optional[np.ndarray]:
    """Fast path for binning._greedy_find_bin. None -> caller falls
    back to the (exact-identical) Python loop."""
    lib = native_lib()
    if lib is None:
        return None
    distinct = np.ascontiguousarray(distinct, np.float64)
    counts = np.ascontiguousarray(counts, np.int64)
    out = np.empty(max(int(max_bin), 1) + 1, np.float64)
    n = lib.lgbtpu_greedy_bounds(distinct, counts, len(distinct),
                                 int(max_bin), float(total_cnt),
                                 int(min_data_in_bin), out)
    return out[:n]


def values_to_bins(values: np.ndarray, upper_bounds: np.ndarray,
                   nan_bin: int) -> Optional[np.ndarray]:
    """Fast path for BinMapper.values_to_bins (numerical features).
    None -> caller falls back to searchsorted."""
    lib = native_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.float64)
    upper_bounds = np.ascontiguousarray(upper_bounds, np.float64)
    out = np.empty(len(values), np.int32)
    lib.lgbtpu_values_to_bins(values, len(values), upper_bounds,
                              len(upper_bounds), int(nan_bin), out)
    return out


def parse_delimited(lines, delim: str) -> Optional[np.ndarray]:
    """Fast path for io._parse_delimited. None -> caller falls back."""
    lib = native_lib()
    if lib is None or not lines:
        return None
    body = "\n".join(lines).encode("utf-8", errors="strict")
    n = len(body)
    width = int(lib.lgbtpu_max_cols(body, n, delim.encode()[:1]))
    if width <= 0:
        return None
    out = np.full((len(lines), width), np.nan, dtype=np.float64)
    rc = lib.lgbtpu_parse_delimited(body, n, delim.encode()[:1],
                                    len(lines), width, out)
    return out if rc == 0 else None


def parse_libsvm(lines, num_features_hint: int = 0):
    """Fast path for io._parse_libsvm. None -> caller falls back."""
    lib = native_lib()
    if lib is None or not lines:
        return None
    body = "\n".join(lines).encode("utf-8", errors="strict")
    n = len(body)
    mx = int(lib.lgbtpu_libsvm_max_index(body, n))
    if mx == -2:
        return None
    if mx < 0 and num_features_hint <= 0:
        # label-only file with no width hint: the Python fallback
        # produces a 0-column matrix here; defer to it rather than
        # invent a clamped 1-column shape
        return None
    ncols = max(mx + 1, num_features_hint, 1)
    labels = np.empty(len(lines), dtype=np.float64)
    out = np.zeros((len(lines), ncols), dtype=np.float64)
    rc = lib.lgbtpu_parse_libsvm(body, n, len(lines), ncols, labels, out)
    return (labels, out) if rc == 0 else None
