"""Runtime-compiled native helpers (the C side of the data loader).

The reference ships its parser as part of the C++ core
(``src/io/parser.cpp``); here ``parser.c`` is compiled ON FIRST USE with
``gcc -O3 -shared -fPIC`` into a content-hashed cache file and loaded
via ctypes — no install-time build step, and every caller keeps a pure
Python fallback, so a missing/broken toolchain only costs speed
(~10-40x on large text files), never functionality.

Set ``LIGHTGBM_TPU_NO_NATIVE=1`` to force the Python paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional

import numpy as np

__all__ = ["native_lib", "parse_delimited", "parse_libsvm"]

_LIB = None
_TRIED = False

_DOUBLE_P = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def native_lib():
    """The loaded CDLL, or None when native helpers are unavailable."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
        return None
    src = os.path.join(os.path.dirname(__file__), "parser.c")
    try:
        with open(src, "rb") as f:
            code = f.read()
        tag = hashlib.sha256(code).hexdigest()[:16]
        # per-user 0700 cache: a predictable path in world-writable /tmp
        # would let another local user pre-plant a malicious .so
        cache_dir = os.environ.get("LIGHTGBM_TPU_CACHE") or os.path.join(
            os.path.expanduser("~"), ".cache", "lightgbm_tpu")
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        so = os.path.join(cache_dir, f"lightgbm_tpu_parser_{tag}.so")
        if not os.path.exists(so):
            tmp = f"{so}.{os.getpid()}.tmp"
            subprocess.run(
                ["gcc", "-O3", "-shared", "-fPIC", "-o", tmp, src],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)  # atomic: concurrent builders both win
        lib = ctypes.CDLL(so)
        lib.lgbtpu_max_cols.restype = ctypes.c_long
        lib.lgbtpu_max_cols.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                        ctypes.c_char]
        lib.lgbtpu_parse_delimited.restype = ctypes.c_int
        lib.lgbtpu_parse_delimited.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, ctypes.c_long,
            ctypes.c_long, _DOUBLE_P]
        lib.lgbtpu_libsvm_max_index.restype = ctypes.c_long
        lib.lgbtpu_libsvm_max_index.argtypes = [ctypes.c_char_p,
                                                ctypes.c_long]
        lib.lgbtpu_parse_libsvm.restype = ctypes.c_int
        lib.lgbtpu_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            _DOUBLE_P, _DOUBLE_P]
        _LIB = lib
    except Exception:
        _LIB = None
    return _LIB


def parse_delimited(lines, delim: str) -> Optional[np.ndarray]:
    """Fast path for io._parse_delimited. None -> caller falls back."""
    lib = native_lib()
    if lib is None or not lines:
        return None
    body = "\n".join(lines).encode("utf-8", errors="strict")
    n = len(body)
    width = int(lib.lgbtpu_max_cols(body, n, delim.encode()[:1]))
    if width <= 0:
        return None
    out = np.full((len(lines), width), np.nan, dtype=np.float64)
    rc = lib.lgbtpu_parse_delimited(body, n, delim.encode()[:1],
                                    len(lines), width, out)
    return out if rc == 0 else None


def parse_libsvm(lines, num_features_hint: int = 0):
    """Fast path for io._parse_libsvm. None -> caller falls back."""
    lib = native_lib()
    if lib is None or not lines:
        return None
    body = "\n".join(lines).encode("utf-8", errors="strict")
    n = len(body)
    mx = int(lib.lgbtpu_libsvm_max_index(body, n))
    if mx == -2:
        return None
    if mx < 0 and num_features_hint <= 0:
        # label-only file with no width hint: the Python fallback
        # produces a 0-column matrix here; defer to it rather than
        # invent a clamped 1-column shape
        return None
    ncols = max(mx + 1, num_features_hint, 1)
    labels = np.empty(len(lines), dtype=np.float64)
    out = np.zeros((len(lines), ncols), dtype=np.float64)
    rc = lib.lgbtpu_parse_libsvm(body, n, len(lines), ncols, labels, out)
    return (labels, out) if rc == 0 else None
