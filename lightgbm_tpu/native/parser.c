/* Native text parser — the C data-loader core.
 *
 * Analog of the reference's C++ parser layer (src/io/parser.cpp
 * CSVParser/TSVParser/LibSVMParser + Common::Atof): the Python loader
 * (lightgbm_tpu/io.py) handles format detection, headers, and metadata
 * columns, and hands the joined data body here for the byte-crunching
 * inner loops. Every function returns an error code instead of raising;
 * the Python caller falls back to its own (slower) parser to produce
 * the exact error message, so behavior is identical either way.
 *
 * Built at runtime with `gcc -O3 -shared -fPIC` (see native/__init__.py)
 * — no build step at install time, no hard dependency: if gcc or the
 * compile is unavailable the Python paths serve alone.
 */

#include <math.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* max columns over newline-joined, pre-stripped lines */
long lgbtpu_max_cols(const char *buf, long nbytes, char delim) {
    long mx = 0, c = 1;
    for (long i = 0; i < nbytes; i++) {
        if (buf[i] == delim) {
            c++;
        } else if (buf[i] == '\n') {
            if (c > mx) mx = c;
            c = 1;
        }
    }
    if (nbytes > 0 && c > mx) mx = c;
    return mx;
}

/* strict=1 matches bare Python float(): no NA aliases, empty rejected
 * (the LibSVM fallback parser uses plain float()); strict=0 matches the
 * CSV fallback's NA handling. Hex floats are rejected in both modes —
 * strtod accepts them but Python float() does not, and the two paths
 * must agree byte-for-byte. */
static int token_value_mode(const char *a, const char *b, double *out,
                            int strict) {
    /* trim surrounding spaces/tabs */
    while (a < b && (*a == ' ' || *a == '\t')) a++;
    while (b > a && (b[-1] == ' ' || b[-1] == '\t')) b--;
    long len = b - a;
    if (len == 0) {
        *out = NAN;
        return strict;
    }
    if (!strict
        && ((len == 2 && (!strncmp(a, "na", 2) || !strncmp(a, "NA", 2)))
            || (len == 3 && (!strncmp(a, "nan", 3)
                             || !strncmp(a, "NaN", 3)))
            || (len == 4 && (!strncmp(a, "null", 4)
                             || !strncmp(a, "None", 4))))) {
        *out = NAN;
        return 0;
    }
    for (long i = 0; i < len; i++) {
        if (a[i] == 'x' || a[i] == 'X') return 1;  /* no hex floats */
        /* strtod accepts C99 "nan(tag)"; Python float() does not —
         * reject so both paths fail the token identically */
        if (a[i] == '(' || a[i] == ')') return 1;
    }
    if (len >= 63) return 1;
    char tmp[64];
    memcpy(tmp, a, len);
    tmp[len] = 0;
    char *endp;
    *out = strtod(tmp, &endp);
    return endp != tmp + len;
}

static int token_value(const char *a, const char *b, double *out) {
    return token_value_mode(a, b, out, 0);
}

/* CSV/TSV body -> row-major doubles. `out` must be pre-filled with NaN
 * (ragged short rows keep NaN, matching the Python parser). Returns 0
 * on success, 1 on any bad token / too-wide row (caller falls back). */
int lgbtpu_parse_delimited(const char *buf, long nbytes, char delim,
                           long nrows, long ncols, double *out) {
    const char *p = buf;
    const char *end = buf + nbytes;
    long r = 0;
    while (p < end && r < nrows) {
        long c = 0;
        for (;;) {
            const char *q = p;
            while (q < end && *q != delim && *q != '\n') q++;
            double v;
            if (token_value(p, q, &v)) return 1;
            if (c >= ncols) return 1;
            out[r * ncols + c] = v;
            c++;
            if (q >= end || *q == '\n') {
                p = q < end ? q + 1 : end;
                break;
            }
            p = q + 1;
        }
        r++;
    }
    return r == nrows ? 0 : 1;
}

/* LibSVM pass 1: max feature index over `label idx:val ...` lines.
 * Tokens without ':' after the label are skipped (same as the Python
 * parser). Returns -2 on parse error, else the max index (-1 if none).
 */
long lgbtpu_libsvm_max_index(const char *buf, long nbytes) {
    const char *p = buf;
    const char *end = buf + nbytes;
    long mx = -1;
    while (p < end) {
        int first = 1;
        while (p < end && *p != '\n') {
            while (p < end && (*p == ' ' || *p == '\t')) p++;
            const char *q = p;
            while (q < end && *q != ' ' && *q != '\t' && *q != '\n') q++;
            if (q > p) {
                if (first) {
                    first = 0; /* label token, validated in pass 2 */
                } else {
                    const char *colon = memchr(p, ':', q - p);
                    if (colon) {
                        char tmp[32];
                        long len = colon - p;
                        if (len <= 0 || len >= 31) return -2;
                        memcpy(tmp, p, len);
                        tmp[len] = 0;
                        char *endp;
                        long idx = strtol(tmp, &endp, 10);
                        if (endp != tmp + len || idx < 0) return -2;
                        if (idx > mx) mx = idx;
                    }
                }
            }
            p = q;
        }
        if (p < end) p++; /* consume newline */
    }
    return mx;
}

/* LibSVM pass 2: labels [nrows] + dense out [nrows * ncols] (caller
 * pre-zeroes out). Returns 0 ok, 1 on parse error. */
int lgbtpu_parse_libsvm(const char *buf, long nbytes, long nrows,
                        long ncols, double *labels, double *out) {
    const char *p = buf;
    const char *end = buf + nbytes;
    long r = 0;
    while (p < end && r < nrows) {
        int first = 1;
        while (p < end && *p != '\n') {
            while (p < end && (*p == ' ' || *p == '\t')) p++;
            const char *q = p;
            while (q < end && *q != ' ' && *q != '\t' && *q != '\n') q++;
            if (q > p) {
                if (first) {
                    double v;
                    if (token_value_mode(p, q, &v, 1)) return 1;
                    labels[r] = v;
                    first = 0;
                } else {
                    const char *colon = memchr(p, ':', q - p);
                    if (colon) {
                        char tmp[64];
                        long klen = colon - p;
                        long vlen = q - colon - 1;
                        if (klen <= 0 || klen >= 31 || vlen <= 0
                            || vlen >= 63)
                            return 1;
                        memcpy(tmp, p, klen);
                        tmp[klen] = 0;
                        char *endp;
                        long idx = strtol(tmp, &endp, 10);
                        if (endp != tmp + klen || idx < 0 || idx >= ncols)
                            return 1;
                        double v;
                        if (token_value_mode(colon + 1, q, &v, 1))
                            return 1;
                        out[r * ncols + idx] = v;
                    }
                }
            }
            p = q;
        }
        if (first) return 1; /* blank line should not reach here */
        if (p < end) p++;
        r++;
    }
    return r == nrows ? 0 : 1;
}

/* GreedyFindBin boundary search (bin.cpp:97 GreedyFindBin semantics,
 * matching binning._greedy_find_bin exactly — the Python loop costs
 * ~1 s per 200k distinct values; this is the DatasetLoader-side C hot
 * loop like the parsers above). distinct ascending, counts int64.
 * out must hold max_bin + 1 doubles; returns the number written (last
 * is +inf). */
long lgbtpu_greedy_bounds(const double *dv, const long long *counts,
                          long nd, long max_bin, double total_cnt,
                          long min_data_in_bin, double *out) {
    long nb = 0;
    if (nd == 0) {
        out[nb++] = INFINITY;
        return nb;
    }
    if (nd <= max_bin) {
        long long cur = 0;
        for (long i = 0; i < nd - 1; i++) {
            cur += counts[i];
            if (cur >= min_data_in_bin) {
                out[nb++] = (dv[i] + dv[i + 1]) / 2.0;
                cur = 0;
            }
        }
        out[nb++] = INFINITY;
        return nb;
    }
    if (max_bin < 1) max_bin = 1;
    double mean_bin_size = total_cnt / (double)max_bin;
    long long big_sum = 0;
    long n_big = 0;
    for (long i = 0; i < nd; i++)
        if ((double)counts[i] >= mean_bin_size) {
            big_sum += counts[i];
            n_big++;
        }
    double rest_cnt = total_cnt - (double)big_sum;
    long rest_bins = max_bin - n_big;
    if (rest_bins < 1) rest_bins = 1;
    double rest_bin_size = rest_cnt / (double)rest_bins;
    double half = rest_bin_size / 2.0;
    if (half < 1.0) half = 1.0;
    long long cur = 0;
    long bins_made = 0;
    for (long i = 0; i < nd - 1; i++) {
        int big_i = (double)counts[i] >= mean_bin_size;
        if (!big_i) cur += counts[i];
        int big_n = (double)counts[i + 1] >= mean_bin_size;
        if (big_i || (double)cur >= rest_bin_size ||
            (big_n && (double)cur >= half)) {
            out[nb++] = (dv[i] + dv[i + 1]) / 2.0;
            bins_made++;
            cur = 0;
            if (bins_made >= max_bin - 1) break;
        }
    }
    out[nb++] = INFINITY;
    return nb;
}

/* Vectorized ValueToBin over a column (bin.h:173; the hot half of
 * binning.values_to_bins): binary search each value against the upper
 * bounds, NaN routed to nan_bin (missing_type in {none,zero} -> the
 * default bin, nan -> last bin). */
void lgbtpu_values_to_bins(const double *vals, long n,
                           const double *ub, long n_ub,
                           long nan_bin, int32_t *out) {
    for (long r = 0; r < n; r++) {
        double v = vals[r];
        if (isnan(v)) {
            out[r] = (int32_t)nan_bin;
            continue;
        }
        /* searchsorted(ub, v, side='left'): first i with ub[i] >= v */
        long lo = 0, hi = n_ub;
        while (lo < hi) {
            long mid = (lo + hi) >> 1;
            if (ub[mid] < v) lo = mid + 1;
            else hi = mid;
        }
        out[r] = (int32_t)lo;
    }
    return;
}
