/* Native C inference API — the deployment subset of the reference's
 * C ABI (reference: src/c_api.cpp LGBM_BoosterCreateFromModelfile /
 * LGBM_BoosterPredictForMat, include/LightGBM/c_api.h).
 *
 * Pure C, zero dependencies: parses the LightGBM v4 model TEXT format
 * (the durable ABI this project standardizes on — README "Scope") and
 * walks the ensemble with the exact decision semantics of the
 * reference's Tree::NumericalDecision / CategoricalDecision
 * (include/LightGBM/tree.h:345-399): NaN folds to 0.0 unless
 * missing_type==NaN, MissingType::Zero treats |v| <= 1e-35 as missing,
 * categorical NaN/negative route right, bitset membership via the
 * cat_boundaries/cat_threshold words.
 *
 * Scope: model load + predict (normal / raw / leaf index) for
 * regression, binary (sigmoid), multiclass (softmax), multiclassova
 * (per-class sigmoid), ranking; average_output (random forest) honored.
 * Training from C is NOT provided — train in Python, deploy from C (or
 * use codegen.py for fully compiled models).
 *
 * Prediction engine: at load the ensemble is additionally flattened
 * into a contiguous SoA node layout (FlatModel below) and batch
 * predict runs a row-block x tree-block kernel over it — 8-row
 * interleaved, cmov-friendly decisions, nodes streaming through L1 —
 * instead of per-row pointer chasing across per-tree mallocs. The
 * legacy walker remains (LIGHTGBM_TPU_PREDICT_LEGACY=1, or when the
 * layout cannot be built) and both are bit-identical by construction;
 * LGBM_BoosterGetPredictLayout reports which one serves.
 *
 * Build: gcc -O3 -shared -fPIC -pthread -o liblightgbm_tpu_capi.so capi.c -lm
 */

#include <math.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define LGBM_API_OK 0
#define LGBM_API_ERR (-1)

#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32 (2)
#define C_API_DTYPE_INT64 (3)

#define C_API_PREDICT_NORMAL (0)
#define C_API_PREDICT_RAW_SCORE (1)
#define C_API_PREDICT_LEAF_INDEX (2)

static __thread char g_err[512] = "ok";

static int set_err(const char *msg) {
    snprintf(g_err, sizeof(g_err), "%s", msg);
    return LGBM_API_ERR;
}

const char *LGBM_GetLastError(void) { return g_err; }

/* ---------------- model structures ---------------- */

typedef struct {
    int num_leaves;
    int num_cat;
    int *split_feature;   /* [num_leaves-1] */
    double *threshold;
    int *decision_type;
    int *left_child;
    int *right_child;
    double *leaf_value;   /* [num_leaves] */
    int *cat_boundaries;  /* [num_cat+1] or NULL */
    uint32_t *cat_threshold;
    int n_cat_words;
    int is_linear;
} CTree;

/* Serving layout (Treelite/QuickScorer-shape): every tree's nodes
 * flattened ONCE at model load into contiguous SoA arrays indexed by
 * [node_ofs[t] + node], so the whole ensemble's decision data is a
 * handful of linear buffers instead of num_trees*8 scattered mallocs.
 * The blocked kernel walks row-blocks through L1-sized tree-blocks of
 * this layout. Per tree the nodes are its num_leaves-1 internals
 * followed by one self-looping SENTINEL per leaf (leaf c -> node
 * ni + c), so the lockstep walk needs no per-lane liveness guards;
 * the decision semantics are byte-for-byte those of tree_leaf()
 * below, so both walkers are bit-identical. */
typedef struct {
    int32_t *sf;         /* [total_nodes] split feature */
    double *thr;         /* [total_nodes] numerical threshold */
    uint8_t *dt;         /* [total_nodes] decision_type */
    int64_t *pair;       /* [total_nodes] children packed rc<<32 | lc
                          * (one load + shift selects either) */
    int32_t *ci;         /* [total_nodes] cat split idx ((int)threshold) */
    double *leaf;        /* [total_leaves] leaf values */
    int32_t *cat_bnd;    /* flattened cat_boundaries */
    uint32_t *cat_words; /* flattened cat_threshold words */
    int64_t *node_ofs;   /* [num_trees+1] */
    int64_t *leaf_ofs;   /* [num_trees+1] */
    int64_t *bnd_ofs;    /* [num_trees+1] */
    int64_t *word_ofs;   /* [num_trees+1] */
    uint8_t *simple;     /* [num_trees] 1 = no cat splits and every
                          * split MissingType::None -> the reduced
                          * threshold-only step applies */
} FlatModel;

typedef struct {
    int num_class;        /* classes in the MODEL output */
    int num_tpi;          /* num_tree_per_iteration */
    int max_feature_idx;
    int num_trees;
    int average_output;
    int obj;              /* 0 identity, 1 sigmoid, 2 softmax, 3 ova */
    double sigmoid;
    CTree *trees;
    FlatModel *flat;      /* NULL -> legacy per-tree walk only */
} CBooster;

static void free_tree(CTree *t) {
    free(t->split_feature); free(t->threshold); free(t->decision_type);
    free(t->left_child); free(t->right_child); free(t->leaf_value);
    free(t->cat_boundaries); free(t->cat_threshold);
}

/* ---------------- text parsing ---------------- */

/* value string of "key=..." if the line matches, else NULL */
static const char *kv(const char *line, const char *key) {
    size_t k = strlen(key);
    if (strncmp(line, key, k) == 0 && line[k] == '=') return line + k + 1;
    return NULL;
}

static int count_tokens(const char *s) {
    int n = 0;
    while (*s) {
        while (*s == ' ') s++;
        if (*s && *s != '\n') { n++; while (*s && *s != ' ' && *s != '\n') s++; }
    }
    return n;
}

static int *parse_ints(const char *s, int expect) {
    int n = count_tokens(s);
    if (n != expect) return NULL;
    int *out = (int *)malloc(sizeof(int) * (n > 0 ? n : 1));
    if (!out) return NULL;
    const char *p = s;
    for (int i = 0; i < n; i++) {
        char *e;
        out[i] = (int)strtol(p, &e, 10);
        if (e == p) { free(out); return NULL; }
        p = e;
    }
    return out;
}

static uint32_t *parse_u32s(const char *s, int expect) {
    int n = count_tokens(s);
    if (n != expect) return NULL;
    uint32_t *out = (uint32_t *)malloc(sizeof(uint32_t) * (n > 0 ? n : 1));
    if (!out) return NULL;
    const char *p = s;
    for (int i = 0; i < n; i++) {
        char *e;
        out[i] = (uint32_t)strtoul(p, &e, 10);
        if (e == p) { free(out); return NULL; }
        p = e;
    }
    return out;
}

static double *parse_doubles(const char *s, int expect) {
    int n = count_tokens(s);
    if (n != expect) return NULL;
    double *out = (double *)malloc(sizeof(double) * (n > 0 ? n : 1));
    if (!out) return NULL;
    const char *p = s;
    for (int i = 0; i < n; i++) {
        char *e;
        out[i] = strtod(p, &e);
        if (e == p) { free(out); return NULL; }
        p = e;
    }
    return out;
}

/* next line start; *len excludes the line terminator, *adv is the
 * full distance to the next line (so CRLF strips don't desync) */
static const char *next_line(const char *p, const char *end, size_t *len,
                             size_t *adv) {
    if (p >= end) return NULL;
    const char *nl = memchr(p, '\n', (size_t)(end - p));
    size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
    *adv = nl ? n + 1 : n;
    if (n > 0 && p[n - 1] == '\r') n--;      /* CRLF model files */
    *len = n;
    return p;
}

/* free-old-then-assign: duplicate keys in a malformed block must not
 * leak the first allocation */
#define SET_ARR(field, expr) do { free(t->field); t->field = (expr); } \
    while (0)

static int parse_tree(const char **pp, const char *end, CTree *t) {
    memset(t, 0, sizeof(*t));
    t->num_leaves = -1;
    t->num_cat = 0;
    const char *p = *pp;
    size_t len, adv;
    char *line = NULL;
    size_t line_cap = 0;
    while ((p = next_line(p, end, &len, &adv)) != NULL) {
        const char *cur = p;
        p += adv;
        if (len == 0) break;                     /* blank ends the block */
        if (len + 1 > line_cap) {                /* lines can be ~MBs
                                                    (leaf_value of wide
                                                    trees) */
            free(line);
            line_cap = len + 1;
            line = (char *)malloc(line_cap);
            if (!line) { *pp = p; free_tree(t); return set_err("oom"); }
        }
        memcpy(line, cur, len);
        line[len] = 0;
        const char *v;
        int ni = t->num_leaves > 1 ? t->num_leaves - 1 : 0;
        if ((v = kv(line, "num_leaves"))) t->num_leaves = atoi(v);
        else if ((v = kv(line, "num_cat"))) t->num_cat = atoi(v);
        else if ((v = kv(line, "split_feature")))
            SET_ARR(split_feature, parse_ints(v, ni));
        else if ((v = kv(line, "threshold")))
            SET_ARR(threshold, parse_doubles(v, ni));
        else if ((v = kv(line, "decision_type")))
            SET_ARR(decision_type, parse_ints(v, ni));
        else if ((v = kv(line, "left_child")))
            SET_ARR(left_child, parse_ints(v, ni));
        else if ((v = kv(line, "right_child")))
            SET_ARR(right_child, parse_ints(v, ni));
        else if ((v = kv(line, "leaf_value")))
            SET_ARR(leaf_value, parse_doubles(
                v, t->num_leaves > 0 ? t->num_leaves : 1));
        else if ((v = kv(line, "cat_boundaries")))
            SET_ARR(cat_boundaries, parse_ints(v, t->num_cat + 1));
        else if ((v = kv(line, "cat_threshold"))) {
            t->n_cat_words = count_tokens(v);
            SET_ARR(cat_threshold, parse_u32s(v, t->n_cat_words));
        } else if ((v = kv(line, "is_linear")))
            t->is_linear = atoi(v);
        /* leaf_weight/count, internal_*, split_gain, is_linear,
         * shrinkage: not needed for prediction */
    }
    free(line);
    *pp = p ? p : end;
    int bad = (t->num_leaves < 1 || !t->leaf_value) ||
              (t->num_leaves > 1 &&
               (!t->split_feature || !t->threshold ||
                !t->decision_type || !t->left_child ||
                !t->right_child)) ||
              (t->num_cat > 0 && (!t->cat_boundaries ||
                                  !t->cat_threshold));
    if (bad) {
        free_tree(t);
        memset(t, 0, sizeof(*t));
        return set_err("tree block missing or malformed arrays");
    }
    return LGBM_API_OK;
}

/* bounds-check every file-derived index BEFORE the predict walk ever
 * dereferences it: corrupt/hand-edited models must fail the load, not
 * read out of bounds in a serving process */
static int validate_tree(const CTree *t, int max_feature_idx) {
    if (t->is_linear)
        return set_err("linear-tree models are not supported by the C "
                       "inference API (predict them in Python or via "
                       "codegen)");
    int ni = t->num_leaves - 1;
    for (int i = 0; i < ni; i++) {
        if (t->split_feature[i] < 0 ||
            t->split_feature[i] > max_feature_idx)
            return set_err("split_feature out of range");
        int lc = t->left_child[i], rc = t->right_child[i];
        if ((lc >= 0 && lc >= ni) || (lc < 0 && ~lc >= t->num_leaves) ||
            (rc >= 0 && rc >= ni) || (rc < 0 && ~rc >= t->num_leaves))
            return set_err("child index out of range");
        /* internal children must point FORWARD (both this writer and the
         * reference allocate child internal nodes after their parent), so
         * node indices strictly increase along any root-to-leaf path:
         * every walk terminates, and a crafted cycle (left_child[0]=0)
         * fails the load instead of hanging tree_leaf */
        if ((lc >= 0 && lc <= i) || (rc >= 0 && rc <= i))
            return set_err("child index not after parent (cycle?)");
        if (t->decision_type[i] & 1) {
            /* range-check as double BEFORE the int cast: casting a NaN
             * or out-of-int-range double is undefined behavior in C */
            double ct = t->threshold[i];
            if (!(ct >= 0.0 && ct < 2147483647.0))
                return set_err("categorical threshold out of range");
            int ci = (int)ct;
            if (ci >= t->num_cat)
                return set_err("categorical threshold out of range");
        }
    }
    for (int c = 0; c < t->num_cat; c++) {
        if (t->cat_boundaries[c] < 0 ||
            t->cat_boundaries[c + 1] < t->cat_boundaries[c] ||
            t->cat_boundaries[c + 1] > t->n_cat_words)
            return set_err("cat_boundaries out of range");
    }
    return LGBM_API_OK;
}

static void free_flat(FlatModel *fm) {
    if (!fm) return;
    free(fm->sf); free(fm->thr); free(fm->dt); free(fm->pair);
    free(fm->ci); free(fm->leaf); free(fm->cat_bnd);
    free(fm->cat_words); free(fm->node_ofs); free(fm->leaf_ofs);
    free(fm->bnd_ofs); free(fm->word_ofs); free(fm->simple);
    free(fm);
}

/* Flatten the parsed trees into the serving layout. Best-effort: any
 * failure (oom, decision_type outside the byte range the reference's
 * int8 allows) leaves b->flat NULL and the legacy walker serves the
 * model — functionality never depends on the fast layout. */
static void build_flat(CBooster *b) {
    int64_t tn = 0, tl = 0, tb = 0, tw = 0;
    for (int t = 0; t < b->num_trees; t++) {
        const CTree *tr = &b->trees[t];
        int ni = tr->num_leaves - 1;
        for (int i = 0; i < ni; i++)
            if (tr->decision_type[i] < 0 || tr->decision_type[i] > 255)
                return;
        /* internal nodes PLUS one self-looping sentinel per leaf */
        tn += (ni > 0 ? ni : 0) + tr->num_leaves;
        tl += tr->num_leaves;
        tb += tr->num_cat > 0 ? tr->num_cat + 1 : 0;
        tw += tr->n_cat_words;
    }
    FlatModel *fm = (FlatModel *)calloc(1, sizeof(FlatModel));
    if (!fm) return;
    int nt = b->num_trees;
    fm->sf = (int32_t *)malloc(sizeof(int32_t) * (size_t)(tn ? tn : 1));
    fm->thr = (double *)malloc(sizeof(double) * (size_t)(tn ? tn : 1));
    fm->dt = (uint8_t *)malloc(sizeof(uint8_t) * (size_t)(tn ? tn : 1));
    fm->pair = (int64_t *)malloc(sizeof(int64_t) * (size_t)(tn ? tn : 1));
    fm->ci = (int32_t *)malloc(sizeof(int32_t) * (size_t)(tn ? tn : 1));
    fm->leaf = (double *)malloc(sizeof(double) * (size_t)(tl ? tl : 1));
    fm->cat_bnd = (int32_t *)malloc(sizeof(int32_t) *
                                    (size_t)(tb ? tb : 1));
    fm->cat_words = (uint32_t *)malloc(sizeof(uint32_t) *
                                       (size_t)(tw ? tw : 1));
    fm->node_ofs = (int64_t *)malloc(sizeof(int64_t) * (size_t)(nt + 1));
    fm->leaf_ofs = (int64_t *)malloc(sizeof(int64_t) * (size_t)(nt + 1));
    fm->bnd_ofs = (int64_t *)malloc(sizeof(int64_t) * (size_t)(nt + 1));
    fm->word_ofs = (int64_t *)malloc(sizeof(int64_t) * (size_t)(nt + 1));
    fm->simple = (uint8_t *)malloc(sizeof(uint8_t) * (size_t)nt);
    if (!fm->sf || !fm->thr || !fm->dt || !fm->pair ||
        !fm->ci || !fm->leaf || !fm->cat_bnd || !fm->cat_words ||
        !fm->node_ofs || !fm->leaf_ofs || !fm->bnd_ofs ||
        !fm->word_ofs || !fm->simple) {
        free_flat(fm);
        return;
    }
    int64_t on = 0, ol = 0, ob = 0, ow = 0;
    for (int t = 0; t < nt; t++) {
        const CTree *tr = &b->trees[t];
        int ni = tr->num_leaves > 1 ? tr->num_leaves - 1 : 0;
        fm->node_ofs[t] = on;
        fm->leaf_ofs[t] = ol;
        fm->bnd_ofs[t] = ob;
        fm->word_ofs[t] = ow;
        int smp = 1;
        /* leaf c (stored as ~c in the parsed tree) becomes sentinel
         * node ni + c; internal children keep their index */
        for (int i = 0; i < ni; i++) {
            int dt = tr->decision_type[i];
            int lc = tr->left_child[i], rc = tr->right_child[i];
            lc = lc >= 0 ? lc : ni + ~lc;
            rc = rc >= 0 ? rc : ni + ~rc;
            fm->sf[on + i] = tr->split_feature[i];
            fm->thr[on + i] = tr->threshold[i];
            fm->dt[on + i] = (uint8_t)dt;
            fm->pair[on + i] = ((int64_t)(uint32_t)rc << 32) |
                               (uint32_t)lc;
            /* pre-cast the categorical split index (validate_tree
             * range-checked it); saves a double->int cast per visit */
            fm->ci[on + i] = (dt & 1) ? (int32_t)tr->threshold[i] : 0;
            /* simple: numerical split, MissingType::None (dt bits 2-3
             * clear) — the reduced step is exactly equivalent there */
            smp &= !(dt & 1) && ((dt >> 2) & 3) == 0;
        }
        fm->simple[t] = (uint8_t)smp;
        /* sentinels: both children point back at the node itself, so a
         * lane that reached its leaf keeps stepping harmlessly — the
         * walk loop needs no per-lane liveness guards at all */
        for (int j = 0; j < tr->num_leaves; j++) {
            int s = ni + j;
            fm->sf[on + s] = 0;
            fm->thr[on + s] = 0.0;
            fm->dt[on + s] = 0;
            fm->ci[on + s] = 0;
            fm->pair[on + s] = ((int64_t)(uint32_t)s << 32) |
                               (uint32_t)s;
        }
        memcpy(fm->leaf + ol, tr->leaf_value,
               sizeof(double) * (size_t)tr->num_leaves);
        if (tr->num_cat > 0) {
            for (int c = 0; c <= tr->num_cat; c++)
                fm->cat_bnd[ob + c] = tr->cat_boundaries[c];
            ob += tr->num_cat + 1;
        }
        if (tr->n_cat_words > 0) {
            memcpy(fm->cat_words + ow, tr->cat_threshold,
                   sizeof(uint32_t) * (size_t)tr->n_cat_words);
            ow += tr->n_cat_words;
        }
        on += ni + tr->num_leaves;
        ol += tr->num_leaves;
    }
    fm->node_ofs[nt] = on;
    fm->leaf_ofs[nt] = ol;
    fm->bnd_ofs[nt] = ob;
    fm->word_ofs[nt] = ow;
    b->flat = fm;
}

int LGBM_BoosterCreateFromModelfile(const char *filename,
                                    int *out_num_iterations,
                                    void **out) {
    *out = NULL;
    FILE *f = fopen(filename, "rb");
    if (!f) return set_err("cannot open model file");
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    if (sz < 0) { fclose(f); return set_err("unseekable model file"); }
    fseek(f, 0, SEEK_SET);
    char *buf = (char *)malloc((size_t)sz + 1);
    if (!buf) { fclose(f); return set_err("oom"); }
    if (fread(buf, 1, (size_t)sz, f) != (size_t)sz) {
        free(buf); fclose(f); return set_err("short read");
    }
    fclose(f);
    buf[sz] = 0;
    const char *end = buf + sz;
    /* stop at the trailer: everything after "end of trees" is
     * feature importances / parameters */
    const char *eot = strstr(buf, "\nend of trees");
    if (eot) end = eot;

    CBooster *b = (CBooster *)calloc(1, sizeof(CBooster));
    if (!b) { free(buf); return set_err("oom"); }
    b->num_class = 1;
    b->num_tpi = 1;
    b->sigmoid = 1.0;
    int cap = 16;
    b->trees = (CTree *)malloc(sizeof(CTree) * cap);
    if (!b->trees) { free(buf); free(b); return set_err("oom"); }

    const char *p = buf;
    size_t len, adv;
    char *line = NULL;
    size_t line_cap = 0;
    int ok = 1;
    while (ok && (p = next_line(p, end, &len, &adv)) != NULL) {
        const char *cur = p;
        p += adv;
        if (len == 0) continue;
        if (len + 1 > line_cap) {
            free(line);
            line_cap = len + 1;
            line = (char *)malloc(line_cap);
            if (!line) { ok = 0; set_err("oom"); break; }
        }
        memcpy(line, cur, len);
        line[len] = 0;
        const char *v;
        if ((v = kv(line, "num_class"))) b->num_class = atoi(v);
        else if ((v = kv(line, "num_tree_per_iteration")))
            b->num_tpi = atoi(v);
        else if ((v = kv(line, "max_feature_idx")))
            b->max_feature_idx = atoi(v);
        else if (strcmp(line, "average_output") == 0)
            b->average_output = 1;
        else if ((v = kv(line, "objective"))) {
            if (strncmp(v, "binary", 6) == 0) {
                b->obj = 1;
                const char *s = strstr(v, "sigmoid:");
                if (s) b->sigmoid = atof(s + 8);
            } else if (strncmp(v, "cross_entropy_lambda", 20) == 0) {
                b->obj = 5;             /* 1 - exp(-exp(raw)) */
            } else if (strncmp(v, "multiclassova", 13) == 0 ||
                       strncmp(v, "cross_entropy", 13) == 0) {
                b->obj = (strncmp(v, "multiclassova", 13) == 0) ? 3 : 1;
                const char *s = strstr(v, "sigmoid:");
                if (s) b->sigmoid = atof(s + 8);
                if (b->obj == 1 && !s) b->sigmoid = 1.0;
            } else if (strncmp(v, "multiclass", 10) == 0) {
                b->obj = 2;
            } else if (strncmp(v, "custom", 6) == 0 ||
                       strncmp(v, "none", 4) == 0) {
                b->obj = 0;
            }
            /* regression family / ranking: raw scores (obj 0); the
             * exp-family objectives (poisson/gamma/tweedie) transform
             * with exp; "regression sqrt" squares with sign
             * (regression_objective.hpp:160 ToString suffix) */
            else if (strncmp(v, "poisson", 7) == 0 ||
                     strncmp(v, "gamma", 5) == 0 ||
                     strncmp(v, "tweedie", 7) == 0)
                b->obj = 4;
            else if (strncmp(v, "regression", 10) == 0 &&
                     strstr(v, " sqrt"))
                b->obj = 6; /* sign(x) * x^2 */
        } else if (kv(line, "Tree")) {
            if (b->num_trees == cap) {
                cap *= 2;
                CTree *nt = (CTree *)realloc(b->trees,
                                             sizeof(CTree) * cap);
                if (!nt) { ok = 0; set_err("oom"); break; }
                b->trees = nt;
            }
            if (parse_tree(&p, end, &b->trees[b->num_trees]) !=
                LGBM_API_OK) { ok = 0; break; }
            b->num_trees++;
            if (validate_tree(&b->trees[b->num_trees - 1],
                              b->max_feature_idx) != LGBM_API_OK) {
                ok = 0; break;
            }
        }
    }
    free(line);
    free(buf);
    /* booster-level header validation: the predict accumulator is sized
     * num_class and indexed acc[t % num_tpi], so a corrupt/hand-edited
     * header with num_tpi > num_class (or non-positive counts) would
     * write past the heap buffer — such models must fail the load, the
     * same contract validate_tree enforces per tree */
    if (ok && (b->num_class < 1 || b->num_tpi < 1 ||
               b->num_tpi > b->num_class || b->max_feature_idx < 0)) {
        ok = 0;
        set_err("invalid model header (num_class/num_tree_per_iteration/"
                "max_feature_idx)");
    }
    if (!ok || b->num_trees == 0) {
        if (ok) set_err("model file holds no trees");
        for (int i = 0; i < b->num_trees; i++) free_tree(&b->trees[i]);
        free(b->trees); free(b);
        return LGBM_API_ERR;
    }
    *out_num_iterations = b->num_trees / (b->num_tpi > 0 ? b->num_tpi : 1);
    build_flat(b);
    *out = b;
    return LGBM_API_OK;
}

int LGBM_BoosterFree(void *handle) {
    CBooster *b = (CBooster *)handle;
    if (!b) return LGBM_API_OK;
    for (int i = 0; i < b->num_trees; i++) free_tree(&b->trees[i]);
    free(b->trees);
    free_flat(b->flat);
    free(b);
    return LGBM_API_OK;
}

int LGBM_BoosterGetNumClasses(void *handle, int *out_len) {
    CBooster *b = (CBooster *)handle;
    if (!b) return set_err("null handle");
    *out_len = b->num_class;
    return LGBM_API_OK;
}

int LGBM_BoosterGetNumFeature(void *handle, int *out_len) {
    CBooster *b = (CBooster *)handle;
    if (!b) return set_err("null handle");
    *out_len = b->max_feature_idx + 1;
    return LGBM_API_OK;
}

/* tree.h:345 NumericalDecision + :383 CategoricalDecision, exactly */
static int tree_leaf(const CTree *t, const double *row) {
    int node = 0;
    if (t->num_leaves == 1) return 0;
    for (;;) {
        int dt = t->decision_type[node];
        double v = row[t->split_feature[node]];
        int next;
        if (dt & 1) {                                   /* categorical */
            int go_right = 0;
            /* route NaN and out-of-int-range values right BEFORE the
             * cast — (int)v on such doubles is undefined behavior (the
             * reference's static_cast shares the hazard). v <= -1.0
             * rather than v < 0.0 keeps the reference's truncation
             * semantics: values in (-1, 0) cast to 0 and consult the
             * bitset, exactly like tree.h CategoricalDecision */
            if (isnan(v) || v <= -1.0 || v >= 2147483648.0) go_right = 1;
            else {
                int iv = (int)v;
                int ci = (int)t->threshold[node];
                int lo = t->cat_boundaries[ci];
                int n_words = t->cat_boundaries[ci + 1] - lo;
                if (iv >= n_words * 32 ||
                    !((t->cat_threshold[lo + (iv >> 5)] >>
                       (iv & 31)) & 1u))
                    go_right = 1;
            }
            next = go_right ? t->right_child[node] : t->left_child[node];
        } else {
            int mtype = (dt >> 2) & 3;
            if (isnan(v) && mtype != 2) v = 0.0;
            int missing = (mtype == 1 && v >= -1e-35 && v <= 1e-35) ||
                          (mtype == 2 && isnan(v));
            if (missing)
                next = (dt & 2) ? t->left_child[node]
                                : t->right_child[node];
            else
                next = (v <= t->threshold[node]) ? t->left_child[node]
                                                 : t->right_child[node];
        }
        if (next < 0) return ~next;
        node = next;
    }
}

/* resolve the [start_iteration, num_iteration) request into a tree
 * range; shared by every predict entry point */
static int tree_range(const CBooster *b, int start_iteration,
                      int num_iteration, int *t0, int *t1,
                      int *use_iters) {
    int tpi = b->num_tpi > 0 ? b->num_tpi : 1;
    int iters = b->num_trees / tpi;
    if (start_iteration < 0 || start_iteration > iters)
        return set_err("bad start_iteration");
    int ui = (num_iteration <= 0) ? iters - start_iteration
                                  : num_iteration;
    if (start_iteration + ui > iters)
        ui = iters - start_iteration;
    *t0 = start_iteration * tpi;
    *t1 = (start_iteration + ui) * tpi;
    *use_iters = ui;
    return LGBM_API_OK;
}

/* average_output + NORMAL objective transform on one row's per-class
 * raw sums, in place — shared by the legacy and blocked walkers so
 * the two paths stay bit-identical by construction */
static void finish_scores(const CBooster *b, double *acc, int use_iters,
                          int predict_type) {
    if (b->average_output && use_iters > 0)
        for (int k = 0; k < b->num_class; k++) acc[k] /= use_iters;
    if (predict_type == C_API_PREDICT_NORMAL) {
        if (b->obj == 1 || b->obj == 3) {
            for (int k = 0; k < b->num_class; k++)
                acc[k] = 1.0 / (1.0 + exp(-b->sigmoid * acc[k]));
        } else if (b->obj == 2) {
            double mx = acc[0];
            for (int k = 1; k < b->num_class; k++)
                if (acc[k] > mx) mx = acc[k];
            double s = 0.0;
            for (int k = 0; k < b->num_class; k++) {
                acc[k] = exp(acc[k] - mx);
                s += acc[k];
            }
            for (int k = 0; k < b->num_class; k++) acc[k] /= s;
        } else if (b->obj == 4) {
            for (int k = 0; k < b->num_class; k++)
                acc[k] = exp(acc[k]);
        } else if (b->obj == 5) {   /* xentlambda */
            for (int k = 0; k < b->num_class; k++)
                acc[k] = 1.0 - exp(-exp(acc[k]));
        } else if (b->obj == 6) {   /* regression sqrt */
            for (int k = 0; k < b->num_class; k++)
                acc[k] = (acc[k] >= 0 ? 1.0 : -1.0) * acc[k] * acc[k];
        }
    }
}

/* one dense row -> leaf indices (t1-t0 values) or transformed scores
 * (num_class values); acc is caller scratch of num_class doubles */
static void predict_row(const CBooster *b, const double *row,
                        int t0, int t1, int use_iters, int predict_type,
                        double *acc, double *out) {
    int tpi = b->num_tpi > 0 ? b->num_tpi : 1;
    if (predict_type == C_API_PREDICT_LEAF_INDEX) {
        for (int t = t0; t < t1; t++)
            out[t - t0] = (double)tree_leaf(&b->trees[t], row);
        return;
    }
    for (int k = 0; k < b->num_class; k++) acc[k] = 0.0;
    for (int t = t0; t < t1; t++)
        acc[t % tpi] +=
            b->trees[t].leaf_value[tree_leaf(&b->trees[t], row)];
    finish_scores(b, acc, use_iters, predict_type);
    for (int k = 0; k < b->num_class; k++) out[k] = acc[k];
}

/* ---------------- blocked flat-layout walker ---------------- */

#define FLAT_ROW_BLOCK 64     /* rows per block: 64x28 f64 rows ~ 14KB */
#define FLAT_BLOCK_NODES 1536 /* nodes per tree-block: ~38KB SoA in L1 */

/* branchless child select: pair packs rc<<32 | lc, the shift picks one.
 * NOT `? :` — the compiler turns a ternary here into a branch, and a
 * ~50/50 split direction mispredicts every other visit */
static inline int flat_child(int64_t pair, int go_left) {
    return (int32_t)(pair >> ((1 - go_left) << 5));
}

/* one decision on the flat layout — semantics identical to tree_leaf
 * (tree.h:345 NumericalDecision / :383 CategoricalDecision) */
static inline int flat_step(const FlatModel *fm, int64_t nb, int64_t cb,
                            int64_t wb, const double *row, int node) {
    const int dt = fm->dt[nb + node];
    const double v = row[fm->sf[nb + node]];
    const int64_t pr = fm->pair[nb + node];
    if (dt & 1) {                                   /* categorical */
        int go_right = 0;
        if (isnan(v) || v <= -1.0 || v >= 2147483648.0) go_right = 1;
        else {
            int iv = (int)v;
            int cidx = fm->ci[nb + node];
            int lo = fm->cat_bnd[cb + cidx];
            int n_words = fm->cat_bnd[cb + cidx + 1] - lo;
            if (iv >= n_words * 32 ||
                !((fm->cat_words[wb + lo + (iv >> 5)] >>
                   (iv & 31)) & 1u))
                go_right = 1;
        }
        return flat_child(pr, !go_right);
    }
    const int mtype = (dt >> 2) & 3;
    const int nanv = isnan(v);
    const double vz = (nanv && mtype != 2) ? 0.0 : v;
    const int missing = (mtype == 1 && vz >= -1e-35 && vz <= 1e-35) ||
                        (mtype == 2 && nanv);
    const int go_left = missing ? ((dt & 2) != 0)
                                : (vz <= fm->thr[nb + node]);
    return flat_child(pr, go_left);
}

/* the generic step with (dt & 1) == 0 and mtype == 0 folded in: NaN->0
 * then a plain threshold compare. build_flat marks trees where EVERY
 * node satisfies that (fm->simple), so results are identical and each
 * visit drops the mtype/missing logic — about half the uops, which is
 * what the 8-lane lockstep walk is throughput-bound on. */
static inline int flat_step_simple(const FlatModel *fm, int64_t nb,
                                   const double *row, int node) {
    const double v0 = row[fm->sf[nb + node]];
    const double v = isnan(v0) ? 0.0 : v0;
    return flat_child(fm->pair[nb + node], v <= fm->thr[nb + node]);
}

/* leaf index of tree t for every row of the block; rows walk 8-wide in
 * lockstep so eight dependent-load chains overlap (the latency-hiding
 * trick of FIL/QuickScorer-style inference kernels; 8 scalar lanes
 * measured fastest on x86 — 4 leaves latency on the table, 12 spills
 * registers). The sentinel encoding makes every round guard-free: a
 * lane that reached its leaf keeps re-selecting the same sentinel, so
 * the loop runs unguarded round pairs and only checks "are all lanes
 * on sentinels" (node >= ni) between pairs — overshooting is free. */
static void flat_tree_leaves(const FlatModel *fm, int t,
                             const double *const *rows, int rn,
                             int *leaves) {
    const int64_t nb = fm->node_ofs[t];
    const int64_t cb = fm->bnd_ofs[t], wb = fm->word_ofs[t];
    /* nodes = internals + leaves = 2 * num_leaves - 1 */
    const int ni = (int)((fm->node_ofs[t + 1] - nb - 1) >> 1);
    const int smp = fm->simple[t];

#define FLAT_ROUND(STEP)                                               \
            n0 = STEP(p0, n0);                                         \
            n1 = STEP(p1, n1);                                         \
            n2 = STEP(p2, n2);                                         \
            n3 = STEP(p3, n3);                                         \
            n4 = STEP(p4, n4);                                         \
            n5 = STEP(p5, n5);                                         \
            n6 = STEP(p6, n6);                                         \
            n7 = STEP(p7, n7);
/* all lanes sentinel <=> every n - ni >= 0 <=> no sign bit in the OR */
#define FLAT_WALK8(STEP)                                               \
        do {                                                           \
            FLAT_ROUND(STEP)                                           \
            FLAT_ROUND(STEP)                                           \
        } while ((((n0 - ni) | (n1 - ni) | (n2 - ni) | (n3 - ni) |     \
                   (n4 - ni) | (n5 - ni) | (n6 - ni) | (n7 - ni))      \
                  & INT32_MIN) != 0);
#define FLAT_STEP_GEN(p, n) flat_step(fm, nb, cb, wb, (p), (n))
#define FLAT_STEP_SIMPLE(p, n) flat_step_simple(fm, nb, (p), (n))

    for (int i = 0; i < rn; i += 8) {
        const int m = rn - i < 8 ? rn - i : 8;
        const double *p0 = rows[i];
        const double *p1 = rows[i + (m > 1 ? 1 : 0)];
        const double *p2 = rows[i + (m > 2 ? 2 : 0)];
        const double *p3 = rows[i + (m > 3 ? 3 : 0)];
        const double *p4 = rows[i + (m > 4 ? 4 : 0)];
        const double *p5 = rows[i + (m > 5 ? 5 : 0)];
        const double *p6 = rows[i + (m > 6 ? 6 : 0)];
        const double *p7 = rows[i + (m > 7 ? 7 : 0)];
        int n0 = 0, n1 = 0, n2 = 0, n3 = 0;
        int n4 = 0, n5 = 0, n6 = 0, n7 = 0;
        if (ni > 0) {
            if (smp) {
                FLAT_WALK8(FLAT_STEP_SIMPLE)
            } else {
                FLAT_WALK8(FLAT_STEP_GEN)
            }
        }
        leaves[i] = n0 - ni;
        if (m > 1) leaves[i + 1] = n1 - ni;
        if (m > 2) leaves[i + 2] = n2 - ni;
        if (m > 3) leaves[i + 3] = n3 - ni;
        if (m > 4) leaves[i + 4] = n4 - ni;
        if (m > 5) leaves[i + 5] = n5 - ni;
        if (m > 6) leaves[i + 6] = n6 - ni;
        if (m > 7) leaves[i + 7] = n7 - ni;
    }
#undef FLAT_ROUND
#undef FLAT_WALK8
#undef FLAT_STEP_GEN
#undef FLAT_STEP_SIMPLE
}

/* walk one row-block through trees [t0, t1): trees stream through in
 * L1-sized blocks while the row block's feature data stays resident —
 * the row-block x tree-block tiling that replaces the per-row
 * pointer-chasing walk. Accumulation visits trees in the same
 * ascending order per row as predict_row, so sums are bit-identical.
 * acc: rn*num_class scratch; leafbuf: rn scratch; out: rn rows of w. */
static void flat_block_predict(const CBooster *b,
                               const double *const *rows, int rn,
                               int t0, int t1, int use_iters,
                               int predict_type, int w,
                               double *acc, int *leafbuf, double *out) {
    const FlatModel *fm = b->flat;
    const int K = b->num_class;
    const int tpi = b->num_tpi > 0 ? b->num_tpi : 1;
    if (predict_type != C_API_PREDICT_LEAF_INDEX)
        memset(acc, 0, sizeof(double) * (size_t)rn * (size_t)K);
    int t = t0;
    while (t < t1) {
        int64_t nodes = fm->node_ofs[t + 1] - fm->node_ofs[t];
        int tb_end = t + 1;
        while (tb_end < t1 &&
               nodes + (fm->node_ofs[tb_end + 1] -
                        fm->node_ofs[tb_end]) <= FLAT_BLOCK_NODES) {
            nodes += fm->node_ofs[tb_end + 1] - fm->node_ofs[tb_end];
            tb_end++;
        }
        for (int tt = t; tt < tb_end; tt++) {
            flat_tree_leaves(fm, tt, rows, rn, leafbuf);
            if (predict_type == C_API_PREDICT_LEAF_INDEX) {
                for (int r = 0; r < rn; r++)
                    out[(size_t)r * w + (tt - t0)] = (double)leafbuf[r];
            } else {
                const double *lv = fm->leaf + fm->leaf_ofs[tt];
                const int k = tt % tpi;
                for (int r = 0; r < rn; r++)
                    acc[(size_t)r * K + k] += lv[leafbuf[r]];
            }
        }
        t = tb_end;
    }
    if (predict_type != C_API_PREDICT_LEAF_INDEX) {
        for (int r = 0; r < rn; r++) {
            double *a = acc + (size_t)r * K;
            finish_scores(b, a, use_iters, predict_type);
            for (int k = 0; k < K; k++) out[(size_t)r * w + k] = a[k];
        }
    }
}

/* LIGHTGBM_TPU_PREDICT_LEGACY=1 pins the per-row legacy walker (parity
 * tests and the layout ablation use this; checked per predict call) */
static int flat_enabled(const CBooster *b) {
    if (!b->flat) return 0;
    const char *env = getenv("LIGHTGBM_TPU_PREDICT_LEGACY");
    return !(env && atoi(env) >= 1);
}

static int predict_threads(void) {
    const char *env = getenv("LIGHTGBM_TPU_NUM_THREADS");
    if (env) {
        int v = atoi(env);
        if (v >= 1) return v > 64 ? 64 : v;
    }
    long hw = sysconf(_SC_NPROCESSORS_ONLN);
    int v = hw > 0 ? (int)hw : 1;
    return v > 16 ? 16 : v;
}

typedef struct {
    pthread_t tid;
    const CBooster *b;
    const void *data;
    int data_type;
    int32_t ncol;
    int64_t r0, r1;
    int t0, t1, use_iters, predict_type, w, blocked;
    double *out;
    int rc;
} PredRange;

static void *predict_range_thread(void *arg) {
    PredRange *j = (PredRange *)arg;
    const CBooster *b = j->b;
    const int32_t ncol = j->ncol;
    if (j->blocked) {
        /* blocked path: the same row-range split, traversed in
         * FLAT_ROW_BLOCK chunks through the flat layout. Contiguous
         * f64 input is walked in place (rows[] points straight into
         * the caller's matrix — zero copies on the serving path). */
        const double *rows[FLAT_ROW_BLOCK];
        const int need_buf = (j->data_type != C_API_DTYPE_FLOAT64);
        double *acc = (double *)malloc(
            sizeof(double) * FLAT_ROW_BLOCK * (size_t)b->num_class);
        int *leafbuf = (int *)malloc(sizeof(int) * FLAT_ROW_BLOCK);
        double *rowbuf = need_buf
            ? (double *)malloc(sizeof(double) * FLAT_ROW_BLOCK *
                               (size_t)ncol)
            : NULL;
        if (!acc || !leafbuf || (need_buf && !rowbuf)) {
            free(acc); free(leafbuf); free(rowbuf);
            j->rc = 1;
            return NULL;
        }
        for (int64_t r = j->r0; r < j->r1; r += FLAT_ROW_BLOCK) {
            int rn = (int)(j->r1 - r < FLAT_ROW_BLOCK ? j->r1 - r
                                                      : FLAT_ROW_BLOCK);
            for (int i = 0; i < rn; i++) {
                if (!need_buf) {
                    rows[i] = ((const double *)j->data) + (r + i) * ncol;
                } else {
                    const float *src =
                        ((const float *)j->data) + (r + i) * ncol;
                    double *dst = rowbuf + (size_t)i * ncol;
                    for (int c = 0; c < ncol; c++)
                        dst[c] = (double)src[c];
                    rows[i] = dst;
                }
            }
            flat_block_predict(b, rows, rn, j->t0, j->t1, j->use_iters,
                               j->predict_type, j->w, acc, leafbuf,
                               j->out + (size_t)r * j->w);
        }
        free(acc); free(leafbuf); free(rowbuf);
        return NULL;
    }
    double *row = (double *)malloc(sizeof(double) * (size_t)ncol);
    double *acc =
        (double *)malloc(sizeof(double) * (size_t)b->num_class);
    if (!row || !acc) {
        free(row);
        free(acc);
        j->rc = 1;
        return NULL;
    }
    for (int64_t r = j->r0; r < j->r1; r++) {
        const double *rp;
        if (j->data_type == C_API_DTYPE_FLOAT64) {
            /* contiguous f64 input: walk it in place — no extra pass
             * over the matrix on the hot serving path */
            rp = ((const double *)j->data) + r * ncol;
        } else {
            const float *src = ((const float *)j->data) + r * ncol;
            for (int c = 0; c < ncol; c++) row[c] = (double)src[c];
            rp = row;
        }
        predict_row(b, rp, j->t0, j->t1, j->use_iters,
                    j->predict_type, acc, j->out + (size_t)r * j->w);
    }
    free(row);
    free(acc);
    return NULL;
}

int LGBM_BoosterPredictForMat(void *handle, const void *data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char *parameter, int64_t *out_len,
                              double *out_result) {
    (void)parameter;
    CBooster *b = (CBooster *)handle;
    if (!b) return set_err("null handle");
    if (!is_row_major) return set_err("only row-major input supported");
    if (ncol != b->max_feature_idx + 1)
        return set_err("wrong number of feature columns");
    int t0, t1, use_iters;
    if (tree_range(b, start_iteration, num_iteration, &t0, &t1,
                   &use_iters) != LGBM_API_OK)
        return LGBM_API_ERR;
    int w = (predict_type == C_API_PREDICT_LEAF_INDEX) ? t1 - t0
                                                       : b->num_class;

    if (data_type != C_API_DTYPE_FLOAT32 &&
        data_type != C_API_DTYPE_FLOAT64)
        return set_err("data_type must be float32(0)/float64(1)");

    /* rows are independent: split [0, nrow) across pthreads (the
     * reference predictor's OpenMP batch loop, predictor.hpp:30);
     * LIGHTGBM_TPU_NUM_THREADS overrides the hardware default */
    int T = predict_threads();
    if ((int64_t)nrow * (t1 - t0) < (int64_t)1 << 16) T = 1;
    if (T > nrow) T = nrow > 0 ? nrow : 1;
    PredRange *jobs =
        (PredRange *)malloc(sizeof(PredRange) * (size_t)T);
    if (!jobs) return set_err("oom");
    int spawned = 0;
    int oom = 0;
    int blocked = flat_enabled(b);
    for (int t = 0; t < T; t++) {
        jobs[t].b = b;
        jobs[t].data = data;
        jobs[t].data_type = data_type;
        jobs[t].ncol = ncol;
        jobs[t].r0 = (int64_t)nrow * t / T;
        jobs[t].r1 = (int64_t)nrow * (t + 1) / T;
        jobs[t].t0 = t0;
        jobs[t].t1 = t1;
        jobs[t].use_iters = use_iters;
        jobs[t].predict_type = predict_type;
        jobs[t].w = w;
        jobs[t].blocked = blocked;
        jobs[t].out = out_result;
        jobs[t].rc = 0;
    }
    for (int t = 1; t < T; t++) {
        if (pthread_create(&jobs[t].tid, NULL, predict_range_thread,
                           &jobs[t]) != 0)
            break;               /* unspawned ranges run on this thread */
        spawned = t;
    }
    predict_range_thread(&jobs[0]);
    for (int t = spawned + 1; t < T; t++) predict_range_thread(&jobs[t]);
    for (int t = 1; t <= spawned; t++) pthread_join(jobs[t].tid, NULL);
    for (int t = 0; t < T; t++) oom |= jobs[t].rc;
    free(jobs);
    if (oom) return set_err("oom");
    *out_len = (int64_t)nrow * w;
    return LGBM_API_OK;
}

int LGBM_BoosterPredictForMatSingleRow(void *handle, const void *data,
                                       int data_type, int32_t ncol,
                                       int is_row_major, int predict_type,
                                       int start_iteration,
                                       int num_iteration,
                                       const char *parameter,
                                       int64_t *out_len,
                                       double *out_result) {
    /* c_api.cpp LGBM_BoosterPredictForMatSingleRow — the serving fast
     * path; same contract as ForMat with nrow == 1 */
    return LGBM_BoosterPredictForMat(handle, data, data_type, 1, ncol,
                                     is_row_major, predict_type,
                                     start_iteration, num_iteration,
                                     parameter, out_len, out_result);
}

typedef struct {
    pthread_t tid;
    const CBooster *b;
    const void *indptr;
    int indptr_type;
    const int32_t *indices;
    const void *data;
    int data_type;
    int64_t r0, r1;
    int t0, t1, use_iters, predict_type, w, blocked;
    double *out;
    int rc;
} CsrRange;

static void csr_densify_row(const CsrRange *j, int64_t r, double *row,
                            int ncol) {
    int64_t lo, hi;
    if (j->indptr_type == C_API_DTYPE_INT32) {
        lo = ((const int32_t *)j->indptr)[r];
        hi = ((const int32_t *)j->indptr)[r + 1];
    } else {
        lo = ((const int64_t *)j->indptr)[r];
        hi = ((const int64_t *)j->indptr)[r + 1];
    }
    for (int c = 0; c < ncol; c++) row[c] = 0.0;
    for (int64_t i = lo; i < hi; i++) {
        int32_t c = j->indices[i];
        if (c >= ncol) continue;       /* feature unused by the model */
        row[c] = (j->data_type == C_API_DTYPE_FLOAT64)
                     ? ((const double *)j->data)[i]
                     : (double)((const float *)j->data)[i];
    }
}

static void *csr_range_thread(void *arg) {
    CsrRange *j = (CsrRange *)arg;
    const CBooster *b = j->b;
    const int ncol = b->max_feature_idx + 1;
    if (j->blocked) {
        /* CSR shares the flat layout: densify a row-block, then the
         * same blocked kernel as the dense path */
        const double *rows[FLAT_ROW_BLOCK];
        double *acc = (double *)malloc(
            sizeof(double) * FLAT_ROW_BLOCK * (size_t)b->num_class);
        int *leafbuf = (int *)malloc(sizeof(int) * FLAT_ROW_BLOCK);
        double *rowbuf = (double *)malloc(
            sizeof(double) * FLAT_ROW_BLOCK * (size_t)ncol);
        if (!acc || !leafbuf || !rowbuf) {
            free(acc); free(leafbuf); free(rowbuf);
            j->rc = 1;
            return NULL;
        }
        for (int64_t r = j->r0; r < j->r1; r += FLAT_ROW_BLOCK) {
            int rn = (int)(j->r1 - r < FLAT_ROW_BLOCK ? j->r1 - r
                                                      : FLAT_ROW_BLOCK);
            for (int i = 0; i < rn; i++) {
                double *dst = rowbuf + (size_t)i * ncol;
                csr_densify_row(j, r + i, dst, ncol);
                rows[i] = dst;
            }
            flat_block_predict(b, rows, rn, j->t0, j->t1, j->use_iters,
                               j->predict_type, j->w, acc, leafbuf,
                               j->out + (size_t)r * j->w);
        }
        free(acc); free(leafbuf); free(rowbuf);
        return NULL;
    }
    double *row = (double *)malloc(sizeof(double) * (size_t)ncol);
    double *acc =
        (double *)malloc(sizeof(double) * (size_t)b->num_class);
    if (!row || !acc) {
        free(row);
        free(acc);
        j->rc = 1;
        return NULL;
    }
    for (int64_t r = j->r0; r < j->r1; r++) {
        csr_densify_row(j, r, row, ncol);
        predict_row(b, row, j->t0, j->t1, j->use_iters,
                    j->predict_type, acc, j->out + (size_t)r * j->w);
    }
    free(row);
    free(acc);
    return NULL;
}

int LGBM_BoosterPredictForCSR(void *handle, const void *indptr,
                              int indptr_type, const int32_t *indices,
                              const void *data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int start_iteration, int num_iteration,
                              const char *parameter, int64_t *out_len,
                              double *out_result) {
    /* c_api.cpp LGBM_BoosterPredictForCSR: sparse rows densify to the
     * feature buffer (absent entries are 0.0, which MissingType::Zero
     * then treats as missing — reference semantics) */
    (void)parameter;
    CBooster *b = (CBooster *)handle;
    if (!b) return set_err("null handle");
    if (num_col < b->max_feature_idx + 1)
        return set_err("num_col smaller than the model's feature count");
    if (nindptr < 1) return set_err("empty indptr");
    if (data_type != C_API_DTYPE_FLOAT32 &&
        data_type != C_API_DTYPE_FLOAT64)
        return set_err("data_type must be float32(0)/float64(1)");
    int t0, t1, use_iters;
    if (tree_range(b, start_iteration, num_iteration, &t0, &t1,
                   &use_iters) != LGBM_API_OK)
        return LGBM_API_ERR;
    if (indptr_type != C_API_DTYPE_INT32 &&
        indptr_type != C_API_DTYPE_INT64)
        return set_err("indptr_type must be int32(2)/int64(3)");
    int w = (predict_type == C_API_PREDICT_LEAF_INDEX) ? t1 - t0
                                                       : b->num_class;
    int64_t nrow = nindptr - 1;

    /* validate all file/caller-derived extents BEFORE any walk (each
     * worker below trusts them) */
    for (int64_t r = 0; r < nrow; r++) {
        int64_t lo = (indptr_type == C_API_DTYPE_INT32)
                         ? ((const int32_t *)indptr)[r]
                         : ((const int64_t *)indptr)[r];
        int64_t hi = (indptr_type == C_API_DTYPE_INT32)
                         ? ((const int32_t *)indptr)[r + 1]
                         : ((const int64_t *)indptr)[r + 1];
        if (lo < 0 || hi < lo || hi > nelem)
            return set_err("indptr out of range");
    }
    for (int64_t i = 0; i < nelem; i++)
        if (indices[i] < 0 || indices[i] >= num_col)
            return set_err("column index out of range");

    /* rows are independent: same pthread split as PredictForMat */
    int T = predict_threads();
    if (nrow * (t1 - t0) < (int64_t)1 << 16) T = 1;
    if (T > nrow) T = nrow > 0 ? (int)nrow : 1;
    CsrRange *jobs = (CsrRange *)malloc(sizeof(CsrRange) * (size_t)T);
    if (!jobs) return set_err("oom");
    int spawned = 0;
    int oom = 0;
    int blocked = flat_enabled(b);
    for (int t = 0; t < T; t++) {
        jobs[t].b = b;
        jobs[t].indptr = indptr;
        jobs[t].indptr_type = indptr_type;
        jobs[t].indices = indices;
        jobs[t].data = data;
        jobs[t].data_type = data_type;
        jobs[t].r0 = nrow * t / T;
        jobs[t].r1 = nrow * (t + 1) / T;
        jobs[t].t0 = t0;
        jobs[t].t1 = t1;
        jobs[t].use_iters = use_iters;
        jobs[t].predict_type = predict_type;
        jobs[t].w = w;
        jobs[t].blocked = blocked;
        jobs[t].out = out_result;
        jobs[t].rc = 0;
    }
    for (int t = 1; t < T; t++) {
        if (pthread_create(&jobs[t].tid, NULL, csr_range_thread,
                           &jobs[t]) != 0)
            break;
        spawned = t;
    }
    csr_range_thread(&jobs[0]);
    for (int t = spawned + 1; t < T; t++) csr_range_thread(&jobs[t]);
    for (int t = 1; t <= spawned; t++) pthread_join(jobs[t].tid, NULL);
    for (int t = 0; t < T; t++) oom |= jobs[t].rc;
    free(jobs);
    if (oom) return set_err("oom");
    *out_len = nrow * w;
    return LGBM_API_OK;
}

int LGBM_BoosterGetCurrentIteration(void *handle, int *out_iteration) {
    CBooster *b = (CBooster *)handle;
    if (!b || !out_iteration) return set_err("null handle");
    int tpi = b->num_tpi > 0 ? b->num_tpi : 1;
    *out_iteration = b->num_trees / tpi;
    return LGBM_API_OK;
}

int LGBM_BoosterNumModelPerIteration(void *handle, int *out_tpi) {
    CBooster *b = (CBooster *)handle;
    if (!b || !out_tpi) return set_err("null handle");
    *out_tpi = b->num_tpi > 0 ? b->num_tpi : 1;
    return LGBM_API_OK;
}

int LGBM_BoosterNumberOfTotalModel(void *handle, int *out_models) {
    CBooster *b = (CBooster *)handle;
    if (!b || !out_models) return set_err("null handle");
    *out_models = b->num_trees;
    return LGBM_API_OK;
}

int LGBM_BoosterGetPredictLayout(void *handle, int *out_blocked) {
    CBooster *b = (CBooster *)handle;
    if (!b || !out_blocked) return set_err("null handle");
    *out_blocked = flat_enabled(b) ? 1 : 0;
    return LGBM_API_OK;
}
