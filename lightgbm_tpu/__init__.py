"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

Ground-up JAX/XLA/Pallas rebuild of the capabilities of LightGBM
(reference: veneres/LightGBM v4.6.0.99). Not a port: histograms are MXU
one-hot matmuls, tree growth is a fixed-shape on-device loop, distributed
training is jax.sharding over ICI/DCN instead of sockets/MPI.

Public API mirrors the reference Python package
(``python-package/lightgbm/__init__.py``).
"""

from .binning import BinMapper
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       record_evaluation, reset_parameter)
from .config import Config
from .dataset import Dataset, Sequence
from .engine import (Booster, CVBooster, PredictSession, cv,
                     enable_compilation_cache, train)
from .log import register_logger
from . import serving
from . import telemetry
from .serving import (MicroBatcher, ModelRegistry, PredictionServer,
                      ServingMetrics)
from .tree import Tree
from . import plotting
from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                       plot_split_value_histogram, plot_tree)

try:  # sklearn-style wrappers need scikit-learn at import time
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)
    _SKLEARN = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    _SKLEARN = []

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "CVBooster", "PredictSession", "train",
           "cv", "Config", "enable_compilation_cache",
           "serving", "telemetry", "MicroBatcher", "ModelRegistry",
           "PredictionServer", "ServingMetrics",
           "BinMapper", "Tree", "Sequence", "early_stopping", "log_evaluation",
           "record_evaluation", "reset_parameter", "EarlyStopException",
           "register_logger", "plotting", "plot_importance", "plot_metric",
           "plot_split_value_histogram", "plot_tree",
           "create_tree_digraph"] + _SKLEARN
