"""Model code generation: C emission and the XLA ensemble tensorizer.

Two backends share this module because both lower a *whole trained
ensemble* into one standalone program:

- ``model_to_c`` — the reference's ``GBDT::SaveModelToIfElse`` /
  ``ModelToIfElse`` analog (``src/boosting/gbdt_model_text.cpp:286``,
  ``Tree::ToIfElse`` ``src/io/tree.cpp``): a self-contained C file with
  one nested if-else function per tree plus an aggregate ``PredictRaw``
  — for embedding models in environments without the framework (the
  reference CLI's ``task=convert_model``).
- ``CompiledEnsemble`` / ``tensorize_ensemble`` — the serving-side
  tensorizer (ISSUE 15): every tree is packed into dense
  ``[n_trees, max_nodes]`` node tables (feature, threshold, packed
  children, decision bits) and the whole ensemble becomes ONE jittable
  XLA program — a branchless depth-clamped gather loop vectorized over
  ``[batch, n_trees]`` (the GPU-predict layout of arXiv 1806.11248:
  level-synchronous traversal, no per-tree dispatch), with the leaf
  reduction done in one pass. One compile per (model version, ladder
  rung); ``warm()`` pre-pays every rung off the serving path.

Missing-value and categorical decision semantics match the decision_type
bit layout used everywhere else (bit0 cat, bit1 default_left, bits 2-3
missing type) — the tensorized walk is bit-compatible with the host
walk (``tree.h`` NumericalDecision / CategoricalDecision) on every
missing type and categorical bitset, and the default ``host64`` output
mode reduces per-tree leaf values on the host in float64 in tree order,
reproducing ``PredictSession.predict``'s scores bit-for-bit.
"""

from __future__ import annotations

import functools
import threading
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

__all__ = ["model_to_c", "tensorize_ensemble", "TensorizedTables",
           "CompiledEnsemble"]


def _tree_fn(tree, i: int) -> str:
    lines = [f"static double PredictTree{i}(const double* f) {{"]

    def emit(node: int, depth: int):
        pad = "  " * (depth + 1)
        if node < 0:
            lines.append(f"{pad}return {float(tree.leaf_value[~node])!r};")
            return
        fidx = int(tree.split_feature[node])
        dt = int(tree.decision_type[node])
        if dt & 1:  # categorical: membership in the split's value set
            cat_idx = int(tree.threshold[node])
            lo = tree.cat_boundaries[cat_idx]
            hi = tree.cat_boundaries[cat_idx + 1]
            cats = [c for c in range((hi - lo) * 32)
                    if (tree.cat_threshold[lo + c // 32] >> (c % 32)) & 1]
            cond = " || ".join(f"(int)f[{fidx}] == {c}" for c in cats)
            lines.append(f"{pad}if (!isnan(f[{fidx}]) && f[{fidx}] >= 0 "
                         f"&& ({cond})) {{")
        else:
            thr = float(tree.threshold[node])
            mt = (dt >> 2) & 3
            defl = bool(dt & 2)
            if mt == 2:  # NaN-aware: missing follows default_left
                nan_br = "isnan(f[%d])" % fidx
                cond = (f"({nan_br} ? 1 : f[{fidx}] <= {thr!r})" if defl
                        else f"(!{nan_br} && f[{fidx}] <= {thr!r})")
                lines.append(f"{pad}if {cond} {{")
            elif mt == 1:
                # Zero-as-missing: NaN folds to 0.0 and |v| <= 1e-35
                # routes to the DEFAULT side (tree.h:359), not through
                # the threshold compare
                zv = (f"(isnan(f[{fidx}]) ? 0.0 : f[{fidx}])")
                miss = f"(fabs({zv}) <= 1e-35)"
                cond = (f"({miss} ? 1 : {zv} <= {thr!r})" if defl
                        else f"(!{miss} && {zv} <= {thr!r})")
                lines.append(f"{pad}if {cond} {{")
            else:  # None: NaN treated as 0.0
                lines.append(
                    f"{pad}if ((isnan(f[{fidx}]) ? 0.0 : f[{fidx}])"
                    f" <= {thr!r}) {{")
        emit(int(tree.left_child[node]), depth + 1)
        lines.append(f"{pad}}} else {{")
        emit(int(tree.right_child[node]), depth + 1)
        lines.append(f"{pad}}}")

    if tree.num_leaves == 1:
        lines.append(f"  return {float(tree.leaf_value[0])!r};")
    else:
        # emit() recursion depth equals TREE depth — measure it
        # (wide-but-shallow trees are fine at any leaf count)
        import sys
        depth, stack = 0, [(0, 1)]
        while stack:
            nd, d = stack.pop()
            if nd < 0:
                depth = max(depth, d)
                continue
            stack.append((int(tree.left_child[nd]), d + 1))
            stack.append((int(tree.right_child[nd]), d + 1))
        if depth > sys.getrecursionlimit() // 4:
            raise ValueError(
                f"tree too deep for if-else codegen (depth {depth})")
        emit(0, 0)
    lines.append("}")
    return "\n".join(lines)


def model_to_c(trees: List, num_class: int = 1,
               objective: str = "regression",
               average_output: bool = False) -> str:
    """Standalone C translation unit for the ensemble.

    Exposes ``void PredictRaw(const double* features, double* out)``
    (raw scores, ``out[num_class]``) — sigmoid/softmax conversion is the
    caller's job, like the reference's generated code.
    """
    K = max(1, num_class)
    parts = [
        "/* generated by lightgbm_tpu (convert_model; analog of",
        "   gbdt_model_text.cpp ModelToIfElse) */",
        "#include <math.h>",
        f"#define NUM_CLASS {K}",
        f"#define NUM_TREES {len(trees)}",
        f"/* objective: {objective} */",
        "",
    ]
    for i, t in enumerate(trees):
        if getattr(t, "is_linear", False):
            raise ValueError("convert_model does not support linear trees")
        parts.append(_tree_fn(t, i))
        parts.append("")
    calls = "\n".join(
        f"  out[{i % K}] += PredictTree{i}(features);"
        for i in range(len(trees)))
    avg = ""
    if average_output and trees:
        # RF mode: raw scores are running AVERAGES (rf.hpp)
        per_class = max(1, len(trees) // K)
        avg = (f"  for (k = 0; k < NUM_CLASS; ++k) "
               f"out[k] /= {per_class}.0;")
    parts += [
        "void PredictRaw(const double* features, double* out) {",
        "  int k;",
        "  for (k = 0; k < NUM_CLASS; ++k) out[k] = 0.0;",
        calls,
        avg,
        "}",
        "",
    ]
    return "\n".join(parts)


# ---------------------------------------------------------------------
# XLA tensorizer (ISSUE 15): ensemble -> one jittable program
# ---------------------------------------------------------------------

class TensorizedTables(NamedTuple):
    """Dense SoA node tables of a whole ensemble (host numpy; the
    :class:`CompiledEnsemble` device-places them per replica).

    ``children`` packs both child references of a node into one int32:
    ``(left & 0xffff) << 16 | (right & 0xffff)``. References use the
    writer's numbering (child >= 0 internal node, child < 0 means
    ``~leaf_index``), so each half is a SIGNED 16-bit field — unpacking
    with arithmetic shifts (``>> 16`` / ``<< 16 >> 16``) sign-extends
    negative leaf refs for free. One gather per step fetches both
    children instead of two.
    """

    feature: np.ndarray     # [T, N] int32 split feature per node
    threshold: np.ndarray   # [T, N] f32 (cat splits: cat split index)
    decision: np.ndarray    # [T, N] int32 decision_type bits
    children: np.ndarray    # [T, N] int32 packed left/right
    init_node: np.ndarray   # [T] int32 root (or ~0 for stump trees)
    leaf_value: np.ndarray  # [T, L] f32
    cat_bound: np.ndarray   # [T, C+1] int32 cat split word bounds
    cat_words: np.ndarray   # [T, W] int32 bitset words (uint32 bits)


def tensorize_ensemble(trees: List) -> "tuple[TensorizedTables, int]":
    """Host Trees -> dense tables + static max depth.

    Raises ``ValueError`` for models the dense layout cannot represent
    (linear-leaf trees; > 32767 internal nodes / 32768 leaves per tree —
    the packed int16 child fields' range).
    """
    if not trees:
        raise ValueError("tensorize_ensemble needs a nonempty ensemble")
    from .ops.predict_ensemble import _tree_depth
    for t in trees:
        if getattr(t, "is_linear", False):
            raise ValueError("linear-leaf trees are not tensorizable "
                             "(leaf outputs depend on raw features)")
        if t.num_leaves > (1 << 15):
            raise ValueError(
                f"tree with {t.num_leaves} leaves exceeds the packed "
                "int16 child range (32768)")
    T = len(trees)
    N = max(max(t.num_leaves - 1, 1) for t in trees)
    L = max(t.num_leaves for t in trees)
    C = max(t.num_cat for t in trees) + 1
    W = max(max(len(t.cat_threshold), 1) for t in trees)

    sf = np.zeros((T, N), np.int32)
    thr = np.zeros((T, N), np.float32)
    dt = np.zeros((T, N), np.int32)
    ch = np.zeros((T, N), np.int32)
    init = np.zeros(T, np.int32)
    lv = np.zeros((T, L), np.float32)
    cb = np.zeros((T, C + 1), np.int32)
    cw = np.zeros((T, W), np.int64)
    depth = 1
    for i, t in enumerate(trees):
        ni = t.num_leaves - 1
        lv[i, :t.num_leaves] = t.leaf_value
        if ni <= 0:
            init[i] = -1           # stump: start AT leaf 0 (~0)
            continue
        depth = max(depth, _tree_depth(t))
        sf[i, :ni] = t.split_feature
        thr[i, :ni] = t.threshold
        dt[i, :ni] = t.decision_type
        lc = np.asarray(t.left_child, np.int32)
        rc = np.asarray(t.right_child, np.int32)
        ch[i, :ni] = ((lc & 0xffff) << 16) | (rc & 0xffff)
        cb[i, :len(t.cat_boundaries)] = t.cat_boundaries
        if t.cat_threshold:
            cw[i, :len(t.cat_threshold)] = t.cat_threshold
    # bitset words are uint32 BIT PATTERNS; reinterpret, never convert
    cw32 = cw.astype(np.uint32).view(np.int32)
    return (TensorizedTables(sf, thr, dt, ch, init, lv, cb, cw32),
            int(depth))


def _tensor_leaves(tables: TensorizedTables, X, *, depth: int):
    """[n, T] leaf indices for X [n, F] f32 — the branchless walk.

    A ``fori_loop`` with a STATIC trip count (the ensemble's max
    root-to-leaf depth, fixed at tensorize time) instead of the packed
    walk's early-exit ``while_loop``: every step is pure gathers and
    selects over the ``[batch, trees]`` lattice, no convergence check,
    no host round-trip — the shape XLA vectorizes and pipelines best.
    Lanes that reached a leaf hold their (negative) node id; decision
    semantics are identical to ``ops.predict_ensemble._walk`` (tree.h
    NumericalDecision / CategoricalDecision incl. missing types).
    """
    import jax
    import jax.numpy as jnp
    n = X.shape[0]
    F = X.shape[1]
    T, N = tables.feature.shape
    L = tables.leaf_value.shape[1]
    Cb = tables.cat_bound.shape[1]
    W = tables.cat_words.shape[1]
    # flattened tables + per-tree offsets: one 1-D take per field
    # fetches the [n, T] lattice
    offs = jnp.arange(T, dtype=jnp.int32)[None, :] * N
    cat_offs = jnp.arange(T, dtype=jnp.int32)[None, :] * Cb
    word_offs = jnp.arange(T, dtype=jnp.int32)[None, :] * W
    feat_f = tables.feature.reshape(-1)
    thr_f = tables.threshold.reshape(-1)
    dec_f = tables.decision.reshape(-1)
    ch_f = tables.children.reshape(-1)
    cb_f = tables.cat_bound.reshape(-1)
    cw_f = tables.cat_words.reshape(-1)
    node0 = jnp.broadcast_to(tables.init_node[None, :], (n, T))

    def body(_, node):
        at_leaf = node < 0
        idx = jnp.clip(node, 0, N - 1) + offs
        feat = jnp.take(feat_f, idx)
        v = jnp.take_along_axis(X, jnp.clip(feat, 0, F - 1), axis=1)
        dt = jnp.take(dec_f, idx)
        thr = jnp.take(thr_f, idx)
        is_cat = (dt & 1) != 0
        nan = jnp.isnan(v)
        mt = (dt >> 2) & 3
        vz = jnp.where(nan & (mt != 2), 0.0, v)
        gl_num = vz <= thr
        defl = (dt & 2) != 0
        # missing -> default side: NaN under MissingType::NaN, and
        # |v| <= 1e-35 (incl. NaN folded to 0) under MissingType::Zero
        # (tree.h:359; zeros must NOT take the threshold compare)
        miss = ((nan & (mt == 2))
                | ((jnp.abs(vz) <= 1e-35) & (mt == 1)))
        gl_num = jnp.where(miss, defl, gl_num)
        # categorical: threshold holds the cat split index
        cat_idx = jnp.clip(thr.astype(jnp.int32), 0, Cb - 2)
        lo = jnp.take(cb_f, cat_idx + cat_offs)
        hi = jnp.take(cb_f, cat_idx + 1 + cat_offs)
        cval = jnp.where(nan | (v < 0), -1, v).astype(jnp.int32)
        word = jnp.clip(lo + (cval >> 5), 0, W - 1)
        wv = jnp.take(cw_f, word + word_offs)
        in_set = ((wv >> (cval & 31)) & 1) == 1
        gl_cat = (cval >= 0) & (lo + (cval >> 5) < hi) & in_set
        go_left = jnp.where(is_cat, gl_cat, gl_num)
        ch = jnp.take(ch_f, idx)
        # packed signed-int16 halves: arithmetic shifts sign-extend
        nxt = jnp.where(go_left, ch >> 16, (ch << 16) >> 16)
        return jnp.where(at_leaf, node, nxt)

    node = jax.lax.fori_loop(0, depth, body, node0)
    return jnp.clip(~node, 0, L - 1)


def _tensor_values(tables: TensorizedTables, X, *, depth: int):
    """[n, T] f32 per-tree leaf values (one fused gather epilogue)."""
    import jax.numpy as jnp
    T, _ = tables.feature.shape
    L = tables.leaf_value.shape[1]
    leaf = _tensor_leaves(tables, X, depth=depth)
    lv_f = tables.leaf_value.reshape(-1)
    offs = jnp.arange(T, dtype=jnp.int32)[None, :] * L
    return jnp.take(lv_f, leaf + offs)


def _tensor_reduced(tables: TensorizedTables, X, cls, *, depth: int,
                    num_class: int):
    """[n, K] f32 raw class sums reduced IN-program (one matmul pass).

    Accumulates in f32 on device — the TPU-throughput mode. The exact
    serving path (``CompiledEnsemble.predict``) keeps the reduction on
    host in f64 for bit-parity with ``PredictSession``; this program is
    the single-device-pass variant for accelerators without cheap
    host readback (same caveat as ``pred_early_stop``'s f32 sums).
    """
    import jax.numpy as jnp
    vals = _tensor_values(tables, X, depth=depth)
    onehot = (cls[:, None] == jnp.arange(num_class,
                                         dtype=jnp.int32)[None, :])
    return vals @ onehot.astype(jnp.float32)


class CompiledEnsemble:
    """One whole ensemble as a single jittable XLA program.

    Built from a Booster (same tree-window kwargs as
    :class:`~lightgbm_tpu.engine.PredictSession`); raises ``ValueError``
    for windows the dense layout cannot express (linear trees,
    ``pred_contrib``, early stopping) so callers can gate and fall back
    to the session path with a named reason.

    Output modes:

    - ``predict(X)`` — the serving path. Device walks all trees
      branchlessly and returns leaf indices; the per-class reduction
      runs on host in float64 IN TREE ORDER, then shares the Booster's
      ``_finalize_scores`` (RF averaging, squeeze, objective
      transform). Bit-identical to ``PredictSession.predict`` wherever
      the f32 device routing agrees with the f64 host routing — the
      same contract the packed device walk documents.
    - ``predict(X)`` with ``pred_leaf=True`` at construction — [n, T]
      leaf indices (parity with ``predict_leaf_index``).
    - ``predict_device(X)`` — raw class sums reduced in-program in f32
      (one pass, no host readback of per-tree values): the TPU
      throughput mode, with the documented f32-accumulation caveat.

    Compile discipline: one compile per (model version, batch shape,
    device). ``warm(ladder)`` pre-pays every ladder rung off the
    serving path; replicas pass ``device=`` so each mesh device holds
    its own table copy and executable.
    """

    def __init__(self, booster, *, start_iteration: int = 0,
                 num_iteration: Optional[int] = None,
                 raw_score: bool = False, pred_leaf: bool = False,
                 **kwargs):
        import jax
        if kwargs.pop("pred_contrib", False):
            raise ValueError("pred_contrib is not tensorizable "
                             "(TreeSHAP walks all paths)")
        if booster._early_stop_config(kwargs) is not None:
            raise ValueError("pred_early_stop is not tensorizable "
                             "(chunked early exit; use the session)")
        booster._sync_trees()
        K = max(1, booster._num_class)
        trees = booster._all_trees()
        ni = num_iteration
        if ni is None or ni < 0:
            ni = (booster.best_iteration if booster.best_iteration > 0
                  else len(trees) // K)
        lo = start_iteration * K
        hi = min(len(trees), (start_iteration + ni) * K)
        use = trees[lo:hi]
        tables, depth = tensorize_ensemble(use)
        self.booster = booster
        self.model_version = booster._model_version
        self.num_features = booster._max_feature_idx + 1
        self.num_class = K
        self.num_trees = len(use)
        self.depth = depth
        self.raw_score = bool(raw_score)
        self.pred_leaf = bool(pred_leaf)
        self._use = use
        self._lo = lo
        self._tables_np = tables
        # f64 leaf tables for the exact host reduction (tree order)
        self._leaf64 = [np.asarray(t.leaf_value, np.float64)
                        for t in use]
        self._cls_np = np.asarray(
            [(lo + i) % K for i in range(len(use))], np.int32)
        self._jit_leaves = jax.jit(
            functools.partial(_tensor_leaves, depth=depth))
        self._jit_reduced = jax.jit(functools.partial(
            _tensor_reduced, depth=depth, num_class=K))
        self._place_lock = threading.Lock()
        self._placed: dict = {}

    # -- device placement ---------------------------------------------
    def tables_for(self, device=None):
        """The tables as device arrays, placed (and cached) on
        ``device`` — each replica's copy lives on its own mesh
        device."""
        import jax
        import jax.numpy as jnp
        key = device
        got = self._placed.get(key)
        if got is None:
            with self._place_lock:
                got = self._placed.get(key)
                if got is None:
                    if device is None:
                        got = TensorizedTables(
                            *map(jnp.asarray, self._tables_np))
                    else:
                        got = TensorizedTables(*(
                            jax.device_put(a, device)
                            for a in self._tables_np))
                    self._placed[key] = got
        return got

    def _as_f32_matrix(self, X, device=None):
        import jax
        import jax.numpy as jnp
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"CompiledEnsemble expects [rows, {self.num_features}] "
                f"features, got {X.shape}")
        Xd = jnp.asarray(X, jnp.float32)
        if device is not None:
            Xd = jax.device_put(Xd, device)
        return Xd

    def _check_version(self):
        if self.booster._model_version != self.model_version:
            raise RuntimeError(
                "model version moved under a CompiledEnsemble — "
                "registered models are serving-only; swap in a new "
                "version instead of training in place")

    # -- prediction ----------------------------------------------------
    def predict_leaf(self, X, device=None) -> np.ndarray:
        """[n, T] leaf indices (``pred_leaf`` output)."""
        self._check_version()
        tb = self.tables_for(device)
        Xd = self._as_f32_matrix(X, device)
        return np.asarray(self._jit_leaves(tb, Xd))

    def predict(self, X, device=None) -> np.ndarray:
        """The exact serving path: device walk + host f64 reduction in
        tree order + shared finalize — ``PredictSession.predict``'s
        score pipeline bit-for-bit."""
        if self.pred_leaf:
            return self.predict_leaf(X, device)
        leaf = self.predict_leaf(X, device)
        raw = np.zeros((leaf.shape[0], self.num_class))
        cls = self._cls_np
        for i, lv in enumerate(self._leaf64):
            raw[:, cls[i]] += lv[leaf[:, i]]
        return self.booster._finalize_scores(
            raw, self._use, self.num_class, self.raw_score)

    def predict_device(self, X, device=None) -> np.ndarray:
        """Raw sums reduced in-program (f32 accumulation), finalized on
        host — the no-per-tree-readback throughput mode."""
        self._check_version()
        tb = self.tables_for(device)
        Xd = self._as_f32_matrix(X, device)
        import jax.numpy as jnp
        cls = jnp.asarray(self._cls_np)
        raw = np.asarray(self._jit_reduced(tb, Xd, cls), np.float64)
        return self.booster._finalize_scores(
            raw, self._use, self.num_class, self.raw_score)

    # -- warmup / introspection ---------------------------------------
    def warm(self, rungs: Sequence[int], device=None,
             mode: str = "serving") -> "CompiledEnsemble":
        """Compile every batch-ladder rung now, off the serving path.
        ``mode="serving"`` warms the leaf-walk program ``predict`` uses;
        ``mode="device"`` additionally warms the in-program reduction.
        """
        for r in sorted(set(int(r) for r in rungs)):
            Z = np.zeros((r, self.num_features), np.float64)
            self.predict(Z, device=device)
            if mode == "device":
                self.predict_device(Z, device=device)
        return self

    def compiled_signatures(self) -> int:
        """Distinct compiled signatures of the serving walk (the
        recompile-guard bound: ladder size x replicas)."""
        from .analysis.recompile_guard import cache_size
        return cache_size(self._jit_leaves)

    def lower_serving(self, rows: int = 256):
        """AOT-compile the serving walk at one shape (cost model /
        trace doctor hook)."""
        import jax
        tb = self.tables_for(None)
        X = self._as_f32_matrix(
            np.zeros((rows, self.num_features), np.float32))
        return jax.jit(functools.partial(
            _tensor_leaves, depth=self.depth)).lower(tb, X).compile()

    def describe(self) -> dict:
        return {"num_trees": self.num_trees, "depth": self.depth,
                "num_class": self.num_class,
                "max_nodes": int(self._tables_np.feature.shape[1]),
                "compiled_signatures": self.compiled_signatures(),
                "placed_devices": len(self._placed)}
