"""Logging with levels + redirectable sink.

Analog of the reference logging system (``include/LightGBM/utils/
log.h:78-185``): four levels gated by ``verbosity``, output redirectable
to a user callback / standard logger (``LGBM_RegisterLogCallback`` /
python ``register_logger``, basic.py).

Level mapping follows config.h ``verbosity``: <0 fatal-only, 0 warning,
1 info (default), >1 debug.
"""

from __future__ import annotations

import sys
from typing import Any, Optional

__all__ = ["register_logger", "set_verbosity", "debug", "info", "warning",
           "fatal"]

_DEBUG, _INFO, _WARNING, _FATAL = 10, 20, 30, 40


class _State:
    level = _INFO
    logger: Optional[Any] = None
    info_method = "info"
    warning_method = "warning"


def register_logger(logger: Any, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Redirect output to a custom logger (basic.py register_logger)."""
    for m in (info_method_name, warning_method_name):
        if not callable(getattr(logger, m, None)):
            raise TypeError(f"logger has no callable method {m!r}")
    _State.logger = logger
    _State.info_method = info_method_name
    _State.warning_method = warning_method_name


def set_verbosity(verbosity: int) -> None:
    """config.h verbosity -> level filter (log.h ResetLogLevel)."""
    if verbosity < 0:
        _State.level = _FATAL
    elif verbosity == 0:
        _State.level = _WARNING
    elif verbosity == 1:
        _State.level = _INFO
    else:
        _State.level = _DEBUG


def _emit(level: int, msg: str, warn: bool = False) -> None:
    if level < _State.level:
        return
    if _State.logger is not None:
        method = (_State.warning_method if warn else _State.info_method)
        getattr(_State.logger, method)(msg)
    else:
        print(msg, file=sys.stderr if warn else sys.stdout, flush=True)


def _record(level: str, msg: str) -> None:
    """Single choke point routing warnings/fatals into the active run's
    event log (telemetry/events.py). Best-effort and lazy: telemetry
    imports this module, so the import happens at call time, and a run
    with no active EventLog makes this a no-op."""
    try:
        from .telemetry.events import record_log
    except Exception:  # noqa: BLE001 — logging must never raise
        return
    record_log(level, msg)


def eval_info(msg: str) -> None:
    """Evaluation lines from user-requested callbacks (log_evaluation,
    early_stopping): honor the logger redirection but bypass the
    verbosity filter — the user explicitly asked for them."""
    if _State.logger is not None:
        getattr(_State.logger, _State.info_method)(msg)
    else:
        print(msg, flush=True)


def debug(msg: str) -> None:
    _emit(_DEBUG, f"[LightGBM-TPU] [Debug] {msg}")


def info(msg: str) -> None:
    _emit(_INFO, f"[LightGBM-TPU] [Info] {msg}")


def warning(msg: str) -> None:
    _record("warning", msg)
    _emit(_WARNING, f"[LightGBM-TPU] [Warning] {msg}", warn=True)


def fatal(msg: str) -> None:
    """Log::Fatal throws (log.h:143); always raises regardless of level."""
    _record("fatal", msg)
    raise RuntimeError(f"[LightGBM-TPU] [Fatal] {msg}")
