"""Two-pass out-of-core ingest: stream → sketch → binned shards.

``python -m lightgbm_tpu ingest data=<csv|npy|npz> out=<dir>`` runs:

1. **Sketch pass** (phase ``ingest_sketch``): stream fixed-size row
   blocks through a :class:`~.sketch.SketchSet`, then fit
   ``BinMapper``s via :meth:`BinMapper.from_distinct`.  The fitted
   mapper state (+ an ingest fingerprint) is saved atomically to
   ``_mappers.npz`` in the output directory.
2. **Write pass** (phase ``ingest_write``): stream again, bin each
   block, and cut fixed ``ingest_rows_per_shard`` partitions into
   ``.lgbtpu`` shards (``shardfile.write_shard``; atomic rename).

Crash safety / idempotence: the partition is a pure function of
(total_rows, rows_per_shard), every shard write is atomic, and the
mapper sidecar is written before any shard.  A SIGKILL at any point
leaves only complete checksum-valid artifacts; re-running the same
ingest validates what exists (fingerprint + checksum) and rewrites
ONLY missing or invalid shards — completed shards are not touched.

Host memory is O(chunk): the raw matrix never materializes, binned
rows buffer at most one shard (``rows_per_shard × F`` bytes of uint8).
"""

from __future__ import annotations

import io as _io
import os
from typing import Dict, List, Optional

import numpy as np

from .reader import ChunkReader, open_chunk_reader
from .shardfile import (SHARD_VERSION, ShardReader, list_shards,
                        shard_name, write_shard)
from .sketch import SketchSet

__all__ = ["ingest", "MAPPERS_SIDECAR", "resolve_categoricals",
           "ingest_fingerprint", "load_mappers_sidecar"]

MAPPERS_SIDECAR = "_mappers.npz"


def resolve_categoricals(cfg, names: List[str]) -> set:
    """``categorical_feature`` spec → raw feature indices (the
    Dataset._resolve_categoricals rules, minus pandas 'auto')."""
    spec = cfg.categorical_feature
    if not spec:
        return set()
    out = set()
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if not tok.lstrip("-").isdigit():
            if tok in names:
                out.add(names.index(tok))
        else:
            out.add(int(tok))
    return out


def ingest_fingerprint(cfg, num_features: int, cat_idx: set) -> dict:
    """Binning-relevant parameters a shard set must agree on; reuse of
    sidecars/shards across runs is gated on an exact match."""
    return {
        "format_version": SHARD_VERSION,
        "num_features": int(num_features),
        "max_bin": int(cfg.max_bin),
        "max_bin_by_feature": [int(v) for v in
                               (cfg.max_bin_by_feature or [])],
        "min_data_in_bin": int(cfg.min_data_in_bin),
        "use_missing": bool(cfg.use_missing),
        "zero_as_missing": bool(cfg.zero_as_missing),
        "sketch_capacity": int(cfg.sketch_capacity),
        "rows_per_shard": int(cfg.ingest_rows_per_shard),
        "categorical": sorted(int(c) for c in cat_idx),
    }


def _save_mappers_sidecar(path: str, mappers, fingerprint: dict,
                          total_rows: int, sketch: SketchSet) -> None:
    import json
    from ..resilience.atomic_io import atomic_write_bytes
    from .shardfile import _mapper_state_sections
    payload = dict(_mapper_state_sections(mappers))
    payload["fingerprint_json"] = np.frombuffer(
        json.dumps(fingerprint, sort_keys=True).encode(), np.uint8)
    payload["total_rows"] = np.asarray([total_rows], np.int64)
    payload["max_level"] = np.asarray([sketch.max_level], np.int64)
    buf = _io.BytesIO()
    np.savez(buf, **payload)
    atomic_write_bytes(path, buf.getvalue())


def load_mappers_sidecar(path: str, fingerprint: Optional[dict] = None):
    """(mappers, total_rows, max_level) from ``_mappers.npz``, or None
    when missing/corrupt/fingerprint-mismatched."""
    import json
    from .shardfile import mappers_from_sections
    try:
        with np.load(path) as z:
            payload = {k: z[k] for k in z.files}
        got_fp = json.loads(bytes(
            payload["fingerprint_json"].tobytes()).decode())
        if fingerprint is not None and got_fp != json.loads(
                json.dumps(fingerprint, sort_keys=True)):
            return None
        mappers = mappers_from_sections(payload)
        return (mappers, int(payload["total_rows"][0]),
                int(payload["max_level"][0]))
    except (OSError, KeyError, ValueError):
        return None


def _valid_existing_shard(path: str, fingerprint: dict, row0: int,
                          num_rows: int) -> bool:
    try:
        r = ShardReader(path, verify=True)
    except Exception:
        return False
    ok = (r.header["fingerprint"] == fingerprint
          and r.row0 == row0 and r.num_rows == num_rows)
    r.close()
    return ok


def _bin_block(X: np.ndarray, mappers, used_features, dtype):
    out = np.empty((X.shape[0], len(used_features)), dtype=dtype)
    for j, f in enumerate(used_features):
        out[:, j] = mappers[f].values_to_bins(X[:, f]).astype(dtype)
    return out


def ingest(source, out_dir: str, params: Optional[Dict] = None,
           label=None, chunk_rows: Optional[int] = None,
           verbose: bool = True) -> dict:
    """Run the two-pass ingest; returns a summary dict."""
    import time

    from ..config import Config
    from ..profiler import phase
    from ..telemetry import events as _events
    from .. import phases

    cfg = Config(dict(params or {}))
    os.makedirs(out_dir, exist_ok=True)
    reader: ChunkReader = open_chunk_reader(source, cfg, label=label)
    F = reader.num_features
    names = reader.feature_names or [f"Column_{i}" for i in range(F)]
    cat_idx = resolve_categoricals(cfg, names)
    fingerprint = ingest_fingerprint(cfg, F, cat_idx)
    rows_per_shard = int(cfg.ingest_rows_per_shard)
    if chunk_rows is None:
        chunk_rows = max(1, min(rows_per_shard, 65536))
    sidecar = os.path.join(out_dir, MAPPERS_SIDECAR)

    def _say(msg):
        if verbose:
            print(f"[ingest] {msg}", flush=True)

    # -- pass 1: sketch (skipped when a matching sidecar exists) ------
    t0 = time.perf_counter()
    cached = load_mappers_sidecar(sidecar, fingerprint)
    if cached is not None:
        mappers, total_rows, max_level = cached
        _say(f"sketch pass skipped: reusing valid {MAPPERS_SIDECAR} "
             f"({total_rows} rows)")
    else:
        sketch = SketchSet(F, capacity=int(cfg.sketch_capacity),
                           cat_idx=cat_idx)
        with phase(phases.INGEST_SKETCH):
            for chunk in reader.iter_chunks(chunk_rows):
                sketch.update(chunk.X)
        total_rows = sketch.num_rows
        if total_rows == 0:
            raise ValueError("ingest source has no rows")
        mappers = sketch.fit_mappers(cfg)
        max_level = sketch.max_level
        _save_mappers_sidecar(sidecar, mappers, fingerprint,
                              total_rows, sketch)
        _say(f"sketch pass: {total_rows} rows, {F} features, "
             f"coarsen level {max_level} "
             f"({time.perf_counter() - t0:.2f}s)")
    used_features = np.asarray(
        [f for f, m in enumerate(mappers) if not m.is_trivial],
        np.int32)
    if len(used_features) == 0:
        raise ValueError("cannot ingest: all features are trivial "
                         "(single value)")
    max_num_bin = max(mappers[f].num_bin for f in used_features)
    dtype = np.uint8 if max_num_bin <= 256 else np.int32

    # -- pass 2: bin + write fixed partitions -------------------------
    num_shards = (total_rows + rows_per_shard - 1) // rows_per_shard
    reuse = []
    for si in range(num_shards):
        row0 = si * rows_per_shard
        nrows = min(rows_per_shard, total_rows - row0)
        p = os.path.join(out_dir, shard_name(si, num_shards))
        reuse.append(_valid_existing_shard(p, fingerprint, row0, nrows))
    written = 0
    t1 = time.perf_counter()
    if all(reuse):
        _say(f"write pass skipped: all {num_shards} shards valid")
    else:
        # per-shard accumulators: a chunk is split along shard
        # boundaries and only sub-ranges of NON-reused shards are
        # binned/buffered; a shard writes (atomically) the moment its
        # rows complete, so at most two partial shards are ever pending
        acc: Dict[int, dict] = {}
        chaos_kill = os.environ.get("LIGHTGBM_TPU_CHAOS_KILL_SHARD")
        chaos_kill = int(chaos_kill) if chaos_kill is not None else None

        def _write(si: int, ent: dict) -> None:
            nonlocal written
            row0 = si * rows_per_shard
            write_shard(
                os.path.join(out_dir, shard_name(si, num_shards)),
                bins=np.concatenate(ent["b"]), mappers=mappers,
                used_features=used_features, feature_names=names,
                row0=row0, shard_index=si, num_shards=num_shards,
                total_rows=total_rows,
                label=(np.concatenate(ent["l"]) if ent["l"] else None),
                weight=(np.concatenate(ent["w"]) if ent["w"] else None),
                fingerprint=fingerprint)
            written += 1
            if chaos_kill is not None and written == chaos_kill:
                # fault-injection hook (scripts/chaos_train.py): die
                # right after the Nth shard of this run lands — atomic
                # rename means nothing partial can survive us
                import signal as _signal
                os.kill(os.getpid(), _signal.SIGKILL)

        with phase(phases.INGEST_WRITE):
            seen_rows = 0
            for chunk in reader.iter_chunks(chunk_rows):
                r = chunk.X.shape[0]
                pos = 0
                while pos < r:
                    grow = chunk.row0 + pos
                    if grow >= total_rows:
                        raise ValueError(
                            "ingest source grew between passes: "
                            f"sketch saw {total_rows} rows")
                    si = grow // rows_per_shard
                    s_end = min((si + 1) * rows_per_shard, total_rows)
                    take = min(r - pos, s_end - grow)
                    if not reuse[si]:
                        ent = acc.setdefault(
                            si, {"b": [], "l": [], "w": [], "n": 0})
                        ent["b"].append(_bin_block(
                            chunk.X[pos:pos + take], mappers,
                            used_features, dtype))
                        if chunk.label is not None:
                            ent["l"].append(np.asarray(
                                chunk.label[pos:pos + take], np.float64))
                        if chunk.weight is not None:
                            ent["w"].append(np.asarray(
                                chunk.weight[pos:pos + take],
                                np.float64))
                        ent["n"] += take
                        if ent["n"] == s_end - si * rows_per_shard:
                            _write(si, acc.pop(si))
                    pos += take
                seen_rows += r
            if seen_rows != total_rows or acc:
                raise ValueError(
                    f"ingest source changed between passes: sketch "
                    f"saw {total_rows} rows, write pass saw "
                    f"{seen_rows} ({len(acc)} shards incomplete)")
        _say(f"write pass: {written}/{num_shards} shards written "
             f"({sum(reuse)} reused, "
             f"{time.perf_counter() - t1:.2f}s)")

    log = _events.active()
    if log is not None:
        log.append("ingest", action="complete", rows=int(total_rows),
                   shards=int(num_shards))
    return {
        "out_dir": out_dir,
        "total_rows": int(total_rows),
        "num_features": int(F),
        "num_used_features": int(len(used_features)),
        "num_shards": int(num_shards),
        "shards_written": int(written),
        "shards_reused": int(sum(reuse)),
        "max_num_bin": int(max_num_bin),
        "sketch_level": int(max_level),
        "rows_per_shard": rows_per_shard,
        "paths": list_shards(out_dir),
    }
