"""Chunked row-block readers: stream [r, F] blocks, never the matrix.

The in-memory loader (:func:`lightgbm_tpu.io.load_data_file`)
materializes the full dense matrix; these readers yield fixed-size row
blocks instead so the ingest pipeline (sketch pass + shard writer) and
the Sequence construction path run in O(chunk) host memory.  Column
semantics (label/weight/ignore specs, header handling, NaN tokens,
delimiter autodetect) reuse ``io.py``'s helpers verbatim so a file
ingested chunked bins identically to one loaded whole.

Readers:

- :class:`CsvChunkReader` — delimited text; first block fixes the
  width/column layout, later blocks must agree (ragged tails raise).
  LibSVM needs a global max-feature-index pass and stays on the
  in-memory loader.
- :class:`NpyChunkReader` — ``.npy`` via ``np.load(mmap_mode="r")``
  (zero-copy) and ``.npz`` members via a sequential stream over the
  zip entry, so a compressed archive never decompresses whole.
- :class:`ArrayChunkReader` — an in-RAM array, sliced (used when an
  already-constructed Dataset falls back to the chunked trainer).
- :class:`SequenceChunkReader` — ``lightgbm_tpu.Dataset`` Sequence
  objects; also provides the random-row gather the sampled mapper fit
  needs (grouped per sequence, one ``__getitem__`` batch per run).
"""

from __future__ import annotations

import os
import zipfile
from typing import Iterator, List, NamedTuple, Optional

import numpy as np

__all__ = ["Chunk", "ChunkReader", "CsvChunkReader", "NpyChunkReader",
           "ArrayChunkReader", "SequenceChunkReader", "open_chunk_reader",
           "DEFAULT_CHUNK_ROWS"]

DEFAULT_CHUNK_ROWS = 65536


class Chunk(NamedTuple):
    row0: int
    X: np.ndarray                  # [r, F] float64 raw values
    label: Optional[np.ndarray]    # [r] float64 or None
    weight: Optional[np.ndarray]   # [r] float64 or None


class ChunkReader:
    """Base: ``iter_chunks`` yields :class:`Chunk` blocks in row order."""

    num_features: int = 0
    num_rows: Optional[int] = None   # None until a full pass (CSV)
    feature_names: Optional[List[str]] = None
    has_label: bool = False

    def iter_chunks(self, chunk_rows: int) -> Iterator[Chunk]:
        raise NotImplementedError


class CsvChunkReader(ChunkReader):
    """Delimited text file, parsed ``chunk_rows`` lines at a time."""

    def __init__(self, path: str, config=None):
        from ..config import Config
        from ..io import (_detect_delimiter, _is_libsvm, _load_sidecar,
                          _parse_column_spec, _parse_index_list)
        self.path = str(path)
        if not os.path.exists(self.path):
            raise FileNotFoundError(f"data file not found: {self.path}")
        cfg = config if config is not None else Config({})
        self.has_header = bool(getattr(cfg, "header", False))
        # probe the first data line for format detection (parser.cpp:317)
        with open(self.path, "r", encoding="utf-8") as f:
            first = ""
            probe = ""
            for ln in f:
                ln = ln.rstrip("\r\n")
                if not ln.strip():
                    continue
                if not first:
                    first = ln
                    if not self.has_header:
                        probe = ln
                        break
                else:
                    probe = ln
                    break
            if not first:
                raise ValueError(f"data file is empty: {self.path}")
            if not probe:
                probe = first
        self.delim = _detect_delimiter(probe)
        if _is_libsvm(probe, self.delim):
            raise NotImplementedError(
                "chunked ingest does not support LibSVM (the dense "
                "width needs a global max-feature-index pass); load "
                "it through lightgbm_tpu.io.load_data_file instead")
        names: List[str] = []
        if self.has_header:
            names = [t.strip() for t in first.split(self.delim)]
        width = len(first.split(self.delim)) if names else \
            len(probe.split(self.delim))
        if not names:
            names = [f"Column_{i}" for i in range(width)]
        label_idx = _parse_column_spec(
            getattr(cfg, "label_column", ""), names,
            counts_label=True, label_idx=-1)
        if label_idx is None:
            label_idx = 0
        weight_idx = _parse_column_spec(
            getattr(cfg, "weight_column", ""), names,
            counts_label=False, label_idx=label_idx)
        group_idx = _parse_column_spec(
            getattr(cfg, "group_column", ""), names,
            counts_label=False, label_idx=label_idx)
        if group_idx is not None:
            raise NotImplementedError(
                "chunked ingest does not support a group column "
                "(ranking shards are not in the v1 format)")
        ignore = _parse_index_list(
            getattr(cfg, "ignore_column", ""), names, label_idx)
        drop = {label_idx}
        if weight_idx is not None:
            drop.add(weight_idx)
        drop.update(ignore)
        self._width = width
        self._label_idx = label_idx
        self._weight_idx = weight_idx
        self._keep = [j for j in range(width) if j not in drop]
        self.feature_names = [names[j] for j in self._keep]
        self.num_features = len(self._keep)
        self.has_label = True
        # .weight sidecar beats an in-file weight column, matching
        # load_data_file's override order (metadata.cpp:632)
        self._sidecar_weight = _load_sidecar(self.path + ".weight",
                                             np.float64)
        for ext in (".query", ".group"):
            if os.path.exists(self.path + ext):
                raise NotImplementedError(
                    "chunked ingest does not support query/group "
                    f"sidecars ({self.path + ext})")

    def iter_chunks(self, chunk_rows: int) -> Iterator[Chunk]:
        row0 = 0
        buf: List[str] = []
        with open(self.path, "r", encoding="utf-8") as f:
            skip = self.has_header
            for ln in f:
                if skip:
                    skip = False
                    continue
                ln = ln.rstrip("\r\n")
                if not ln.strip():
                    continue
                buf.append(ln)
                if len(buf) >= chunk_rows:
                    yield self._emit(row0, buf)
                    row0 += len(buf)
                    buf = []
            if buf:
                yield self._emit(row0, buf)
                row0 += len(buf)
        self.num_rows = row0

    def _emit(self, row0: int, lines: List[str]) -> Chunk:
        from ..io import _parse_delimited
        mat = _parse_delimited(lines, self.delim)
        if mat.shape[1] > self._width:
            raise ValueError(
                f"ragged CSV: row block at {row0} has {mat.shape[1]} "
                f"columns, expected {self._width}")
        if mat.shape[1] < self._width:
            pad = np.full((mat.shape[0], self._width - mat.shape[1]),
                          np.nan)
            mat = np.concatenate([mat, pad], axis=1)
        label = mat[:, self._label_idx].copy()
        weight = None
        if self._sidecar_weight is not None:
            weight = self._sidecar_weight[row0:row0 + mat.shape[0]]
        elif self._weight_idx is not None:
            weight = mat[:, self._weight_idx].copy()
        return Chunk(row0, np.ascontiguousarray(mat[:, self._keep]),
                     label, weight)


def _stream_npz_member(zf: zipfile.ZipFile, name: str, chunk_rows: int):
    """Yield [r, F] blocks of a 2-D npz member without loading it whole.

    Reads the npy stream sequentially through the zip decompressor —
    peak memory is one chunk regardless of archive size."""
    with zf.open(name) as fp:
        version = np.lib.format.read_magic(fp)
        shape, fortran, dtype = np.lib.format._read_array_header(
            fp, version)
        if fortran:
            raise NotImplementedError(
                f"npz member {name!r} is Fortran-ordered; chunked "
                "streaming needs C row-major")
        if len(shape) != 2:
            raise ValueError(f"npz member {name!r} is not 2-D: {shape}")
        rows, cols = shape
        rowbytes = cols * dtype.itemsize
        done = 0
        while done < rows:
            take = min(chunk_rows, rows - done)
            raw = fp.read(take * rowbytes)
            if len(raw) != take * rowbytes:
                raise ValueError(f"npz member {name!r} truncated")
            yield np.frombuffer(raw, dtype=dtype).reshape(take, cols)
            done += take


def _npz_member_shape(zf: zipfile.ZipFile, name: str):
    with zf.open(name) as fp:
        version = np.lib.format.read_magic(fp)
        shape, _, dtype = np.lib.format._read_array_header(fp, version)
    return shape, dtype


class NpyChunkReader(ChunkReader):
    """``.npy`` (mmap) or ``.npz`` (streamed members) reader.

    For ``.npz`` the data member is ``X``/``data``/the first 2-D array;
    the label member is ``y``/``label``/``labels`` when present.  For
    ``.npy`` a label array can be supplied separately (``label=``)."""

    _X_KEYS = ("X", "x", "data", "features")
    _Y_KEYS = ("y", "label", "labels", "target")

    def __init__(self, path: str, label=None):
        self.path = str(path)
        self._npz = self.path.endswith(".npz")
        self._label_full = None
        if self._npz:
            self._zf = zipfile.ZipFile(self.path, "r")
            members = {os.path.splitext(n)[0]: n
                       for n in self._zf.namelist() if n.endswith(".npy")}
            self._xname = next(
                (members[k] for k in self._X_KEYS if k in members), None)
            if self._xname is None:
                for key, n in members.items():
                    shape, _ = _npz_member_shape(self._zf, n)
                    if len(shape) == 2:
                        self._xname = n
                        break
            if self._xname is None:
                raise ValueError(f"no 2-D array member found in {path}")
            shape, _ = _npz_member_shape(self._zf, self._xname)
            self.num_rows, self.num_features = int(shape[0]), int(shape[1])
            yname = next(
                (members[k] for k in self._Y_KEYS if k in members), None)
            if yname is not None:
                with self._zf.open(yname) as fp:
                    self._label_full = np.asarray(
                        np.lib.format.read_array(fp),
                        np.float64).ravel()
        else:
            self._mm = np.load(self.path, mmap_mode="r")
            if self._mm.ndim != 2:
                raise ValueError(f"{path} is not a 2-D array")
            self.num_rows, self.num_features = map(int, self._mm.shape)
        if label is not None:
            if isinstance(label, (str, os.PathLike)):
                label = np.load(str(label))
            self._label_full = np.asarray(label, np.float64).ravel()
        if self._label_full is not None:
            if len(self._label_full) != self.num_rows:
                raise ValueError(
                    f"label length {len(self._label_full)} != num rows "
                    f"{self.num_rows}")
            self.has_label = True
        self.feature_names = [f"Column_{i}"
                              for i in range(self.num_features)]

    def iter_chunks(self, chunk_rows: int) -> Iterator[Chunk]:
        def lab(lo, r):
            return (self._label_full[lo:lo + r]
                    if self._label_full is not None else None)
        if self._npz:
            row0 = 0
            for block in _stream_npz_member(self._zf, self._xname,
                                            chunk_rows):
                X = np.asarray(block, np.float64)
                yield Chunk(row0, X, lab(row0, X.shape[0]), None)
                row0 += X.shape[0]
        else:
            for lo in range(0, self.num_rows, chunk_rows):
                hi = min(lo + chunk_rows, self.num_rows)
                X = np.asarray(self._mm[lo:hi], np.float64)
                yield Chunk(lo, X, lab(lo, hi - lo), None)


class ArrayChunkReader(ChunkReader):
    """Slice an in-RAM array into chunks (fallback-path source)."""

    def __init__(self, X: np.ndarray, label=None, weight=None):
        self.X = X
        self.num_rows, self.num_features = map(int, X.shape)
        self._label = (np.asarray(label, np.float64).ravel()
                       if label is not None else None)
        self._weight = (np.asarray(weight, np.float64).ravel()
                        if weight is not None else None)
        self.has_label = self._label is not None
        self.feature_names = [f"Column_{i}"
                              for i in range(self.num_features)]

    def iter_chunks(self, chunk_rows: int) -> Iterator[Chunk]:
        for lo in range(0, self.num_rows, chunk_rows):
            hi = min(lo + chunk_rows, self.num_rows)
            yield Chunk(
                lo, np.asarray(self.X[lo:hi], np.float64),
                self._label[lo:hi] if self._label is not None else None,
                self._weight[lo:hi] if self._weight is not None else None)


class SequenceChunkReader(ChunkReader):
    """Stream ``Dataset`` Sequence objects as row blocks.

    ``__getitem__`` results pass through ``np.asarray`` so sequences
    returning non-contiguous views/strided slices are handled; each
    block is one slice call per sequence (the reference's push-rows
    batching), not a per-row gather."""

    def __init__(self, seqs):
        self.seqs = list(seqs) if isinstance(seqs, (list, tuple)) \
            else [seqs]
        self._lens = [len(s) for s in self.seqs]
        self.num_rows = int(sum(self._lens))
        self._starts = np.concatenate([[0], np.cumsum(self._lens)])
        first = np.asarray(self.seqs[0][0], dtype=np.float64)
        self.num_features = int(first.reshape(-1).shape[0])
        self.feature_names = [f"Column_{i}"
                              for i in range(self.num_features)]

    @staticmethod
    def _as_block(batch) -> np.ndarray:
        batch = np.asarray(batch, dtype=np.float64)
        if batch.ndim == 1:
            batch = batch[None, :]
        return np.ascontiguousarray(batch)

    def iter_chunks(self, chunk_rows: int) -> Iterator[Chunk]:
        row0 = 0
        for s in self.seqs:
            bs = int(getattr(s, "batch_size", 0) or chunk_rows)
            bs = min(max(1, bs), chunk_rows)
            for lo in range(0, len(s), bs):
                block = self._as_block(s[lo:lo + bs])
                yield Chunk(row0, block, None, None)
                row0 += block.shape[0]

    def read_rows_at(self, global_idx: np.ndarray) -> np.ndarray:
        """Gather arbitrary rows, batched per owning sequence (the
        sampled mapper fit calls this with a sorted random subset)."""
        global_idx = np.asarray(global_idx, np.int64)
        out = np.empty((len(global_idx), self.num_features), np.float64)
        owner = np.searchsorted(self._starts, global_idx,
                                side="right") - 1
        for si in np.unique(owner):
            sel = np.nonzero(owner == si)[0]
            local = global_idx[sel] - int(self._starts[si])
            seq = self.seqs[int(si)]
            # one __getitem__ per run of consecutive local rows: a
            # sorted sample is mostly runs, so this stays O(runs) calls
            runs = np.split(sel, np.nonzero(np.diff(local) != 1)[0] + 1)
            for run in runs:
                lo = int(local[np.searchsorted(sel, run[0])])
                block = self._as_block(seq[lo:lo + len(run)])
                out[run] = block
        return out


def open_chunk_reader(source, config=None, label=None) -> ChunkReader:
    """Dispatch a data source to its chunked reader."""
    if isinstance(source, (str, os.PathLike)):
        p = str(source)
        if p.endswith(".npy") or p.endswith(".npz"):
            return NpyChunkReader(p, label=label)
        return CsvChunkReader(p, config=config)
    if isinstance(source, np.ndarray):
        return ArrayChunkReader(source, label=label)
    from ..dataset import Sequence
    if isinstance(source, Sequence) or (
            isinstance(source, (list, tuple)) and source
            and all(isinstance(s, Sequence) for s in source)):
        return SequenceChunkReader(source)
    raise TypeError(
        f"no chunked reader for source type {type(source).__name__}")
