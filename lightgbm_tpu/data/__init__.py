"""Out-of-core ingest subsystem.

Streams datasets that do not fit in host RAM or device HBM:

- :mod:`.reader` — chunked readers (CSV/TSV/LibSVM, ``.npy``/``.npz``,
  arrays, ``Sequence`` objects) yielding fixed-size row blocks.
- :mod:`.sketch` — mergeable per-feature quantile sketches whose merge
  is exactly associative/commutative; feeds
  :meth:`lightgbm_tpu.binning.BinMapper.from_distinct`.
- :mod:`.shardfile` — the versioned, checksummed, mmap-able ``.lgbtpu``
  binned shard format.
- :mod:`.ingest` — the two-pass (sketch, then bin+write) ingest driver
  behind ``python -m lightgbm_tpu ingest``.
- :mod:`.chunked` — the chunked training driver: double-buffered
  host→device prefetch with per-chunk histogram accumulation.
"""

from .sketch import FeatureSketch, SketchSet  # noqa: F401
from .shardfile import (  # noqa: F401
    SHARD_SUFFIX, ShardFormatError, ShardReader, is_shard_path,
    list_shards, open_shard_dir, write_shard,
)
from .reader import open_chunk_reader  # noqa: F401
from .ingest import ingest  # noqa: F401
from .prefetch import ChunkPrefetcher, chunk_rows_for  # noqa: F401
from .chunked import (  # noqa: F401
    ArraySource, ChunkedTreeBuilder, ShardSource,
)
