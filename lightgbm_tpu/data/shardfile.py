"""The ``.lgbtpu`` binned shard format.

One shard = one contiguous global row range, already binned.  A
directory of shards is a dataset: every shard is self-describing
(mapper state, feature layout, global row extent), so any subset can
be validated or rebuilt independently — the property the crash-safe
ingest retry and the multi-process loaders lean on.

Layout (little-endian)::

    [0:8)    magic  b"LGBTPU1\\0"
    [8:16)   uint64 header JSON length
    [16:..)  header JSON (utf-8), then zero padding to 64-byte
             alignment
    sections 64-byte aligned, each described in the header as
             {"offset", "dtype", "shape"}:
               bins            uint8/int32 [num_rows, F_used] row-major
               label           float64 [num_rows]      (optional)
               weight          float64 [num_rows]      (optional)
               mapper_scalars  int64  [F_total, 6]  (BinMapper.state_arrays)
               mapper_ub       float64 flat + mapper_ub_offsets
               mapper_cats     int64  flat  + mapper_cats_offsets
    [-32:]   SHA-256 of everything before it

The header also carries a ``row_blocks`` index — ``[row_start,
byte_offset]`` pairs every ``rows_per_block`` rows into the bins
section — so a consumer can mmap the file and address any row block
without arithmetic on trust; ``ShardReader.bins`` returns a view over
the mmap, so touching one chunk faults in only that chunk.

Writes go through ``resilience.atomic_io.atomic_write_bytes``
(mkstemp + fsync + rename): a SIGKILL mid-ingest can only ever leave
complete, checksum-valid shards plus ignorable temp files.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..binning import BinMapper

__all__ = ["SHARD_MAGIC", "SHARD_VERSION", "SHARD_SUFFIX", "ShardReader",
           "write_shard", "shard_name", "list_shards", "is_shard_path",
           "ShardFormatError"]

SHARD_MAGIC = b"LGBTPU1\x00"
SHARD_VERSION = 1
SHARD_SUFFIX = ".lgbtpu"
_ALIGN = 64
_DIGEST = 32  # sha256

_NAME_RE = re.compile(r"^shard-(\d{5})-of-(\d{5})\.lgbtpu$")


class ShardFormatError(ValueError):
    """Raised for missing magic, bad checksum, or malformed headers."""


def shard_name(index: int, num_shards: int) -> str:
    return f"shard-{index:05d}-of-{num_shards:05d}{SHARD_SUFFIX}"


def list_shards(directory: str) -> List[str]:
    """Shard paths in ``directory``, ordered by shard index."""
    out = []
    for p in glob.glob(os.path.join(directory, "*" + SHARD_SUFFIX)):
        m = _NAME_RE.match(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def is_shard_path(path) -> bool:
    """True for a ``.lgbtpu`` file or a directory holding shards."""
    if not isinstance(path, (str, os.PathLike)):
        return False
    p = str(path)
    if p.endswith(SHARD_SUFFIX):
        return os.path.isfile(p)
    return os.path.isdir(p) and bool(list_shards(p))


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _mapper_state_sections(mappers: List[BinMapper]):
    scalars, ubs, cats = [], [], []
    for m in mappers:
        s, u, c = m.state_arrays()
        scalars.append(s)
        ubs.append(u)
        cats.append(c)
    ub_off = np.concatenate(
        [[0], np.cumsum([len(u) for u in ubs])]).astype(np.int64)
    cat_off = np.concatenate(
        [[0], np.cumsum([len(c) for c in cats])]).astype(np.int64)
    return {
        "mapper_scalars": np.stack(scalars).astype(np.int64),
        "mapper_ub": (np.concatenate(ubs) if ubs
                      else np.empty(0, np.float64)),
        "mapper_ub_offsets": ub_off,
        "mapper_cats": (np.concatenate(cats).astype(np.int64) if cats
                        else np.empty(0, np.int64)),
        "mapper_cats_offsets": cat_off,
    }


def mappers_from_sections(sections: Dict[str, np.ndarray]) \
        -> List[BinMapper]:
    scal = np.asarray(sections["mapper_scalars"], np.int64)
    ub = np.asarray(sections["mapper_ub"], np.float64)
    uo = np.asarray(sections["mapper_ub_offsets"], np.int64)
    cats = np.asarray(sections["mapper_cats"], np.int64)
    co = np.asarray(sections["mapper_cats_offsets"], np.int64)
    return [BinMapper.from_state_arrays(
        scal[f], ub[uo[f]:uo[f + 1]], cats[co[f]:co[f + 1]])
        for f in range(len(scal))]


def write_shard(path: str, *, bins: np.ndarray,
                mappers: List[BinMapper],
                used_features: np.ndarray,
                feature_names: List[str],
                row0: int, shard_index: int, num_shards: int,
                total_rows: int,
                label: Optional[np.ndarray] = None,
                weight: Optional[np.ndarray] = None,
                fingerprint: Optional[dict] = None,
                rows_per_block: int = 4096) -> str:
    """Serialize one shard and atomically publish it at ``path``."""
    from ..resilience.atomic_io import atomic_write_bytes
    bins = np.ascontiguousarray(bins)
    if bins.dtype not in (np.dtype(np.uint8), np.dtype(np.int32)):
        raise ValueError(f"bins dtype must be uint8/int32, got "
                         f"{bins.dtype}")
    num_rows, width = bins.shape
    arrays: Dict[str, np.ndarray] = {"bins": bins}
    if label is not None:
        arrays["label"] = np.ascontiguousarray(label, np.float64)
        if len(arrays["label"]) != num_rows:
            raise ValueError("label length != shard rows")
    if weight is not None:
        arrays["weight"] = np.ascontiguousarray(weight, np.float64)
        if len(arrays["weight"]) != num_rows:
            raise ValueError("weight length != shard rows")
    arrays.update(_mapper_state_sections(mappers))

    rowbytes = width * bins.dtype.itemsize
    row_blocks = [[int(r), int(r * rowbytes)]
                  for r in range(0, max(num_rows, 1), rows_per_block)]
    header = {
        "version": SHARD_VERSION,
        "num_rows": int(num_rows),
        "row0": int(row0),
        "shard_index": int(shard_index),
        "num_shards": int(num_shards),
        "total_rows": int(total_rows),
        "num_total_features": len(mappers),
        "used_features": [int(f) for f in used_features],
        "feature_names": list(feature_names),
        "max_num_bin": int(max(
            (mappers[f].num_bin for f in used_features), default=1)),
        "bin_dtype": bins.dtype.name,
        "rows_per_block": int(rows_per_block),
        "row_blocks": row_blocks,
        "has_label": label is not None,
        "has_weight": weight is not None,
        "fingerprint": fingerprint or {},
        "sections": {},
    }
    # lay out sections: offsets depend on the header length, which
    # depends on the offsets — fix by padding the header to a stable
    # size first (offsets only shrink the pad, never move sections)
    probe = dict(header)
    probe["sections"] = {
        k: {"offset": 2 ** 62, "dtype": a.dtype.name,
            "shape": list(a.shape)} for k, a in arrays.items()}
    hdr_len = len(json.dumps(probe).encode()) + _ALIGN
    base = _align(16 + hdr_len)
    off = base
    for k, a in arrays.items():
        header["sections"][k] = {"offset": off, "dtype": a.dtype.name,
                                 "shape": list(a.shape)}
        off = _align(off + a.nbytes)
    hdr = json.dumps(header).encode()
    if len(hdr) > hdr_len:  # can't happen: real offsets print shorter
        raise AssertionError("shard header overflow")
    buf = bytearray(off + _DIGEST)
    buf[0:8] = SHARD_MAGIC
    buf[8:16] = np.uint64(len(hdr)).tobytes()
    buf[16:16 + len(hdr)] = hdr
    for k, a in arrays.items():
        o = header["sections"][k]["offset"]
        buf[o:o + a.nbytes] = a.tobytes()
    buf[-_DIGEST:] = hashlib.sha256(bytes(buf[:-_DIGEST])).digest()
    atomic_write_bytes(path, bytes(buf))
    return path


def verify_shard(path: str) -> bool:
    """True iff the file is a complete, checksum-valid shard."""
    try:
        ShardReader(path, verify=True).close()
        return True
    except (ShardFormatError, OSError, ValueError):
        return False


class ShardReader:
    """mmap-backed reader for one ``.lgbtpu`` file."""

    def __init__(self, path: str, verify: bool = True):
        self.path = str(path)
        size = os.path.getsize(self.path)
        if size < 16 + _DIGEST:
            raise ShardFormatError(f"{path}: too short to be a shard")
        self._mm = np.memmap(self.path, dtype=np.uint8, mode="r")
        if bytes(self._mm[0:8]) != SHARD_MAGIC:
            raise ShardFormatError(f"{path}: bad magic")
        hdr_len = int(np.frombuffer(self._mm[8:16], np.uint64)[0])
        if 16 + hdr_len > size - _DIGEST:
            raise ShardFormatError(f"{path}: header overruns file")
        try:
            self.header = json.loads(bytes(self._mm[16:16 + hdr_len]))
        except ValueError as e:
            raise ShardFormatError(f"{path}: bad header: {e}") from None
        if self.header.get("version") != SHARD_VERSION:
            raise ShardFormatError(
                f"{path}: unsupported shard version "
                f"{self.header.get('version')}")
        if verify:
            h = hashlib.sha256()
            step = 1 << 24
            for lo in range(0, size - _DIGEST, step):
                h.update(self._mm[lo:min(lo + step, size - _DIGEST)])
            if h.digest() != bytes(self._mm[-_DIGEST:]):
                raise ShardFormatError(f"{path}: checksum mismatch")
        for name, sec in self.header["sections"].items():
            nbytes = int(np.prod(sec["shape"]) *
                         np.dtype(sec["dtype"]).itemsize)
            if sec["offset"] + nbytes > size - _DIGEST:
                raise ShardFormatError(
                    f"{path}: section {name} overruns file")

    # -- section access ------------------------------------------------
    def _section(self, name: str) -> Optional[np.ndarray]:
        sec = self.header["sections"].get(name)
        if sec is None:
            return None
        dt = np.dtype(sec["dtype"])
        n = int(np.prod(sec["shape"]))
        o = int(sec["offset"])
        flat = self._mm[o:o + n * dt.itemsize].view(dt)
        return flat.reshape(sec["shape"])

    @property
    def num_rows(self) -> int:
        return int(self.header["num_rows"])

    @property
    def row0(self) -> int:
        return int(self.header["row0"])

    @property
    def bins(self) -> np.ndarray:
        """[num_rows, F_used] mmap-backed view (no copy)."""
        return self._section("bins")

    def read_rows(self, lo: int, hi: int) -> np.ndarray:
        """Copy of shard-local rows [lo, hi)."""
        return np.array(self.bins[lo:hi])

    @property
    def label(self) -> Optional[np.ndarray]:
        return self._section("label")

    @property
    def weight(self) -> Optional[np.ndarray]:
        return self._section("weight")

    def mappers(self) -> List[BinMapper]:
        return mappers_from_sections(
            {k: self._section(k) for k in
             ("mapper_scalars", "mapper_ub", "mapper_ub_offsets",
              "mapper_cats", "mapper_cats_offsets")})

    def close(self) -> None:
        mm = getattr(self, "_mm", None)
        if mm is not None:
            del self._mm


def open_shard_dir(path: str, verify: bool = True) \
        -> Tuple[List[ShardReader], dict]:
    """Open every shard of a dataset directory (or a single file).

    Validates that the set is complete and mutually consistent: all
    indices present, row extents contiguous, identical fingerprints.
    Returns (readers ordered by row0, shared header of shard 0)."""
    paths = [str(path)] if str(path).endswith(SHARD_SUFFIX) \
        else list_shards(str(path))
    if not paths:
        raise ShardFormatError(f"no {SHARD_SUFFIX} shards under {path}")
    readers = [ShardReader(p, verify=verify) for p in paths]
    readers.sort(key=lambda r: r.row0)
    h0 = readers[0].header
    n = int(h0["num_shards"])
    seen = sorted(int(r.header["shard_index"]) for r in readers)
    if seen != list(range(n)):
        raise ShardFormatError(
            f"{path}: incomplete shard set — have indices {seen}, "
            f"expected 0..{n - 1}")
    row = 0
    for r in readers:
        if r.row0 != row:
            raise ShardFormatError(
                f"{r.path}: row0 {r.row0} != expected {row}")
        if r.header["fingerprint"] != h0["fingerprint"] or \
                r.header["used_features"] != h0["used_features"]:
            raise ShardFormatError(
                f"{r.path}: shard metadata disagrees with "
                f"{readers[0].path}")
        row += r.num_rows
    if row != int(h0["total_rows"]):
        raise ShardFormatError(
            f"{path}: shards cover {row} rows, header says "
            f"{h0['total_rows']}")
    return readers, h0
