"""Mergeable per-feature quantile sketches for out-of-core binning.

The reference's ``DatasetLoader`` streams text through per-feature
bin-boundary sketches so dataset size is decoupled from host RAM
(SURVEY L2).  This module is the TPU-repo analog, built around one
invariant that makes distributed ingest trivial to reason about:

    **the sketch state is a pure function of the value multiset.**

A sketch holds the exact ``(distinct values, counts, n_nan)`` summary
of everything fed to it, up to ``capacity`` distinct values.  Past
capacity it coarsens deterministically by truncating low IEEE-754
mantissa bits — ``trunc_l(trunc_k(v)) == trunc_l(v)`` for ``l >= k``
(zeroing low bits nests), and the truncation level is defined as the
*smallest* level at which the multiset fits in ``capacity``.  Both the
level and the coarsened multiset are therefore functions of the total
multiset alone, never of arrival order, so:

- merges are exactly **associative and commutative**: shards sketched
  by different processes in any grouping produce bit-identical state;
- when the sketch never overflows (``level == 0``) the summary is the
  exact multiset and :meth:`BinMapper.from_distinct` is bit-identical
  to the in-memory :meth:`BinMapper.from_values` on the same rows.

Accuracy bound (documented contract): truncating ``k`` low mantissa
bits perturbs a value ``v`` by less than ``2**(k-52) * |v|``.  Bin
upper bounds are midpoints of adjacent distinct values, so every
boundary produced from an overflowed sketch lies within relative error
``2**(level-52)`` of a boundary the exact mapper could produce from a
multiset within that same perturbation; with the default capacity
(65536 distinct values per feature against ``max_bin <= 65535``) the
level stays 0 for integer-ish features and a handful of bits for
continuous ones (level 12 still means < 2.4e-13 relative error).
Counts are always exact — only value resolution coarsens, and NaN is
counted out-of-band so missing handling is unaffected.

Categorical features are sketched exactly (integer category → count;
never truncated): category ordering by count must match the in-memory
fit bit-for-bit, and categorical cardinality is already capped by
``max_bin`` downstream.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..binning import BinMapper

__all__ = ["FeatureSketch", "SketchSet", "truncate_mantissa",
           "DEFAULT_CAPACITY", "MAX_LEVEL"]

DEFAULT_CAPACITY = 1 << 16
MAX_LEVEL = 52  # whole mantissa; beyond this only exponents distinguish


def truncate_mantissa(values: np.ndarray, level: int) -> np.ndarray:
    """Zero the ``level`` low mantissa bits (toward zero, sign kept).

    Nested: ``truncate(truncate(v, k), l) == truncate(v, l)`` for
    ``l >= k``.  ``-0.0`` canonicalizes to ``+0.0`` (subnormals can
    truncate to a signed zero) so the state stays a pure function of
    the multiset under IEEE equality.
    """
    v = np.ascontiguousarray(values, dtype=np.float64)
    if level <= 0:
        return v + 0.0
    mask = np.uint64(~np.uint64((1 << level) - 1))
    out = (v.view(np.uint64) & mask).view(np.float64)
    return out + 0.0


def _merge_distinct(va, ca, vb, cb):
    """Union two sorted-distinct (values, counts) arrays exactly."""
    if not len(va):
        return vb.copy(), cb.copy()
    if not len(vb):
        return va.copy(), ca.copy()
    v = np.concatenate([va, vb])
    c = np.concatenate([ca, cb])
    uv, inverse = np.unique(v, return_inverse=True)
    uc = np.zeros(len(uv), np.int64)
    np.add.at(uc, inverse, c)
    return uv, uc


class FeatureSketch:
    """Order-independent distinct-value/count summary of one feature."""

    __slots__ = ("capacity", "exact", "level", "values", "counts",
                 "n_nan")

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 exact: bool = False):
        if capacity < 2:
            raise ValueError("sketch capacity must be >= 2")
        self.capacity = int(capacity)
        self.exact = bool(exact)  # categorical: never coarsen
        self.level = 0
        self.values = np.empty(0, np.float64)
        self.counts = np.empty(0, np.int64)
        self.n_nan = 0

    # -- updates -------------------------------------------------------
    def update(self, column: np.ndarray) -> "FeatureSketch":
        col = np.asarray(column, dtype=np.float64).ravel()
        nan_mask = np.isnan(col)
        self.n_nan += int(nan_mask.sum())
        v = truncate_mantissa(col[~nan_mask], self.level)
        dv, cnts = np.unique(v, return_counts=True)
        self.values, self.counts = _merge_distinct(
            self.values, self.counts, dv, cnts.astype(np.int64))
        self._compact()
        return self

    def merge(self, other: "FeatureSketch") -> "FeatureSketch":
        if self.capacity != other.capacity or self.exact != other.exact:
            raise ValueError("cannot merge sketches with different "
                             "capacity/exactness")
        self.n_nan += other.n_nan
        level = max(self.level, other.level)
        self._retruncate(level)
        ov, oc = other.values, other.counts
        if level > other.level:
            ov, oc = _regroup(ov, oc, level)
        self.values, self.counts = _merge_distinct(
            self.values, self.counts, ov, oc)
        self._compact()
        return self

    def _retruncate(self, level: int) -> None:
        if level > self.level:
            self.values, self.counts = _regroup(self.values, self.counts,
                                                level)
            self.level = level

    def _compact(self) -> None:
        if self.exact:
            return
        while len(self.values) > self.capacity and self.level < MAX_LEVEL:
            self._retruncate(self.level + 1)

    # -- consumption ---------------------------------------------------
    @property
    def total_count(self) -> int:
        return int(self.counts.sum()) + self.n_nan

    def to_mapper(self, **kwargs) -> BinMapper:
        """Fit a :class:`BinMapper` — bit-identical to ``from_values``
        over the same rows whenever ``level == 0``."""
        return BinMapper.from_distinct(self.values, self.counts,
                                       self.n_nan, **kwargs)

    # -- serialization -------------------------------------------------
    def state(self) -> Dict[str, np.ndarray]:
        meta = np.asarray([self.capacity, int(self.exact), self.level,
                           self.n_nan], np.int64)
        return {"meta": meta, "values": self.values,
                "counts": self.counts}

    @classmethod
    def from_state(cls, meta, values, counts) -> "FeatureSketch":
        s = cls(capacity=int(meta[0]), exact=bool(meta[1]))
        s.level = int(meta[2])
        s.n_nan = int(meta[3])
        s.values = np.asarray(values, np.float64)
        s.counts = np.asarray(counts, np.int64)
        return s

    def __repr__(self):
        return (f"FeatureSketch(n_distinct={len(self.values)}, "
                f"level={self.level}, n_nan={self.n_nan}, "
                f"total={self.total_count})")


def _regroup(values: np.ndarray, counts: np.ndarray, level: int):
    tv = truncate_mantissa(values, level)
    uv, inverse = np.unique(tv, return_inverse=True)
    uc = np.zeros(len(uv), np.int64)
    np.add.at(uc, inverse, counts)
    return uv, uc


class SketchSet:
    """One :class:`FeatureSketch` per column of a [R, F] stream."""

    def __init__(self, num_features: int,
                 capacity: int = DEFAULT_CAPACITY,
                 cat_idx: Optional[Set[int]] = None):
        cat_idx = set() if cat_idx is None else set(cat_idx)
        self.num_features = int(num_features)
        self.cat_idx = cat_idx
        self.sketches: List[FeatureSketch] = [
            FeatureSketch(capacity=capacity, exact=(f in cat_idx))
            for f in range(num_features)]
        self.num_rows = 0

    def update(self, block: np.ndarray) -> "SketchSet":
        block = np.asarray(block, dtype=np.float64)
        if block.ndim == 1:
            block = block[None, :]
        if block.shape[1] != self.num_features:
            raise ValueError(
                f"block has {block.shape[1]} features, sketch set has "
                f"{self.num_features}")
        self.num_rows += block.shape[0]
        for f, sk in enumerate(self.sketches):
            sk.update(block[:, f])
        return self

    def merge(self, other: "SketchSet") -> "SketchSet":
        if other.num_features != self.num_features:
            raise ValueError("feature count mismatch in sketch merge")
        self.num_rows += other.num_rows
        for sk, o in zip(self.sketches, other.sketches):
            sk.merge(o)
        return self

    @property
    def max_level(self) -> int:
        return max((s.level for s in self.sketches), default=0)

    def fit_mappers(self, cfg) -> List[BinMapper]:
        """Per-feature mappers, mirroring ``Dataset._fit_mappers``
        (max_bin_by_feature + forcedbins_filename honored)."""
        mbf = list(cfg.max_bin_by_feature or [])
        if mbf and len(mbf) != self.num_features:
            raise ValueError(
                f"max_bin_by_feature has {len(mbf)} entries but the "
                f"dataset has {self.num_features} features")
        forced: Dict[int, list] = {}
        if cfg.forcedbins_filename:
            import json as _json
            with open(cfg.forcedbins_filename) as fh:
                for item in _json.load(fh):
                    forced[int(item["feature"])] = [
                        float(x) for x in item["bin_upper_bound"]]
        mappers = []
        for f, sk in enumerate(self.sketches):
            bt = "categorical" if f in self.cat_idx else "numerical"
            mappers.append(sk.to_mapper(
                max_bin=int(mbf[f]) if mbf else cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin, bin_type=bt,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                forced_bounds=forced.get(f)))
        return mappers

    # -- serialization (flat arrays, npz/shard-header friendly) --------
    def state_arrays(self) -> Dict[str, np.ndarray]:
        metas = np.stack([s.state()["meta"] for s in self.sketches])
        vals = [s.values for s in self.sketches]
        cnts = [s.counts for s in self.sketches]
        offs = np.concatenate(
            [[0], np.cumsum([len(v) for v in vals])]).astype(np.int64)
        return {
            "sketch_meta": metas,
            "sketch_values": (np.concatenate(vals) if vals
                              else np.empty(0, np.float64)),
            "sketch_counts": (np.concatenate(cnts) if cnts
                              else np.empty(0, np.int64)),
            "sketch_offsets": offs,
            "sketch_rows": np.asarray([self.num_rows], np.int64),
            "sketch_cat_idx": np.asarray(sorted(self.cat_idx), np.int64),
        }

    @classmethod
    def from_state_arrays(cls, arrays) -> "SketchSet":
        metas = np.asarray(arrays["sketch_meta"], np.int64)
        offs = np.asarray(arrays["sketch_offsets"], np.int64)
        cat_idx = set(int(c) for c in arrays["sketch_cat_idx"])
        ss = cls(len(metas), capacity=int(metas[0][0]) if len(metas)
                 else DEFAULT_CAPACITY, cat_idx=cat_idx)
        for f in range(len(metas)):
            lo, hi = int(offs[f]), int(offs[f + 1])
            ss.sketches[f] = FeatureSketch.from_state(
                metas[f], arrays["sketch_values"][lo:hi],
                arrays["sketch_counts"][lo:hi])
        ss.num_rows = int(np.asarray(arrays["sketch_rows"]).ravel()[0])
        return ss


def sketch_stream(blocks: Sequence[np.ndarray], num_features: int,
                  capacity: int = DEFAULT_CAPACITY,
                  cat_idx: Optional[Set[int]] = None) -> SketchSet:
    """Sketch an iterable of [r, F] blocks (convenience for tests)."""
    ss = SketchSet(num_features, capacity=capacity, cat_idx=cat_idx)
    for b in blocks:
        ss.update(b)
    return ss
