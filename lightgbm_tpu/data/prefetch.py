"""Double-buffered host→device chunk staging for out-of-core training.

The chunked tree builder (:mod:`.chunked`) consumes the binned row
stream once per leaf-growth round. Each sweep walks the fixed chunk
sequence ``[0, C), [C, 2C), ...``; while the device accumulates
histograms over chunk k, chunk k+1 is already being read from its
shard (host mmap) and copied host→device on a staging thread — the
transfer overlaps the compute, so steady-state wall clock per sweep is
``max(compute, transfer)``, not their sum.

Device footprint is bounded by TWO chunk buffers (the one being
consumed and the one in flight) regardless of dataset size — that is
what ``chunk_budget_mb`` budgets.

Overlap accounting: the consumer records how long it BLOCKED waiting
for a staged chunk (``wait_s``) against the staging thread's total
work time (``stage_s``); ``overlap_fraction = 1 - wait_s / stage_s``.
1.0 means every read+copy hid completely behind compute; 0.0 means
fully serialized (the first chunk of every sweep always serializes —
there is nothing to hide it behind). The ``ingest_bench`` probe
(bench.py) reports this number.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Tuple

import numpy as np

__all__ = ["ChunkPrefetcher", "PrefetchStats", "chunk_rows_for"]


def chunk_rows_for(num_rows: int, num_features: int, itemsize: int,
                   budget_mb: float, block_rows: int) -> int:
    """Chunk size from the staging budget: two in-flight ``[C, F]``
    bin buffers must fit in ``budget_mb``. C is rounded DOWN to a
    multiple of ``block_rows`` so the chunked histogram walks the same
    row-block sequence as a resident pass — that alignment is what
    makes carried accumulation bit-identical (see
    ``ops.histogram.build_histograms``'s ``init`` contract)."""
    block = max(1, int(block_rows))
    budget = int(float(budget_mb) * (1 << 20))
    c = budget // max(1, 2 * int(num_features) * int(itemsize))
    c = max(block, (c // block) * block)
    # no point chunking finer than the block-padded dataset
    r_pad = -(-max(1, int(num_rows)) // block) * block
    return int(min(c, r_pad))


class PrefetchStats:
    """Cumulative staging counters across sweeps (one prefetcher
    serves every round of every tree)."""

    __slots__ = ("wait_s", "stage_s", "chunks", "bytes")

    def __init__(self):
        self.wait_s = 0.0
        self.stage_s = 0.0
        self.chunks = 0
        self.bytes = 0

    def overlap_fraction(self) -> float:
        if self.stage_s <= 0.0:
            return 1.0
        return float(min(1.0, max(0.0, 1.0 - self.wait_s / self.stage_s)))

    def as_dict(self) -> dict:
        return {"wait_s": round(self.wait_s, 6),
                "stage_s": round(self.stage_s, 6),
                "chunks": int(self.chunks), "bytes": int(self.bytes),
                "overlap_fraction": round(self.overlap_fraction(), 4)}


class ChunkPrefetcher:
    """Sweep a :class:`~.chunked.ChunkSource` as fixed-shape device
    chunks, staging one chunk ahead on a worker thread.

    Every chunk has the STATIC shape ``[chunk_rows, F]`` (the tail is
    zero-padded; padded rows carry ``row_leaf == -1`` on the consumer
    side, a histogram/relabel no-op), so the per-chunk jitted program
    compiles once."""

    def __init__(self, source, chunk_rows: int):
        self.source = source
        self.chunk_rows = int(chunk_rows)
        if self.chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        self.num_chunks = max(
            1, -(-int(source.num_rows) // self.chunk_rows))
        self.padded_rows = self.num_chunks * self.chunk_rows
        self.stats = PrefetchStats()
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="lgbtpu-prefetch")

    def _stage(self, k: int):
        import jax

        from .. import phases, profiler
        t0 = time.perf_counter()
        with profiler.phase(phases.PREFETCH):
            lo = k * self.chunk_rows
            hi = min(lo + self.chunk_rows, int(self.source.num_rows))
            X = np.ascontiguousarray(self.source.read_rows(lo, hi))
            if X.shape[0] < self.chunk_rows:
                X = np.concatenate(
                    [X, np.zeros((self.chunk_rows - X.shape[0],
                                  X.shape[1]), X.dtype)])
            dev = jax.device_put(X)
        self.stats.stage_s += time.perf_counter() - t0
        self.stats.bytes += X.nbytes
        return dev

    def chunks(self) -> Iterator[Tuple[int, object]]:
        """One sequential sweep: yields ``(row_offset, device_bins)``
        with the next chunk's stage already in flight."""
        fut = self._pool.submit(self._stage, 0)
        for k in range(self.num_chunks):
            t0 = time.perf_counter()
            dev = fut.result()
            self.stats.wait_s += time.perf_counter() - t0
            self.stats.chunks += 1
            if k + 1 < self.num_chunks:
                fut = self._pool.submit(self._stage, k + 1)
            yield k * self.chunk_rows, dev

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
