"""Out-of-core leaf-wise tree growth over a streamed bin matrix.

The resident builder (``boosting/tree_builder._build_tree_impl``)
stages the whole ``[R, F]`` bin matrix into one on-device while_loop.
When the matrix exceeds device capacity (``dataset.
check_device_capacity``), this module grows the SAME tree from a
stream of fixed-size row chunks:

- the per-row state that the loop actually mutates — ``row_leaf`` [R]
  int32 and ``gh`` [R, 3] — stays device-resident (16 bytes/row; it is
  the [R, F] bin matrix that blows the budget, not these);
- each leaf-growth round re-streams the chunks through ONE jitted
  program (:meth:`ChunkedTreeBuilder._chunk_impl`) that relabels the
  chunk's rows against the round's pending splits and folds their
  histogram contribution into a carried accumulator via
  ``build_histograms(..., init=acc)``;
- split selection / tree recording run in small jitted programs
  between sweeps, replicating the resident builder's pop→record→
  find-best round body outside the while_loop (the loop goes eager —
  chunk count is a host decision, not a traced one).

Bit-equivalence: ``build_histograms``'s ``init`` carry makes chunked
accumulation over ``block_rows``-aligned chunk boundaries add in the
SAME order as one resident pass (its docstring carries the argument),
the relabel is per-row elementwise, and the pop/record/find-best code
here mirrors the resident body line for line — so a chunked build over
matching bin boundaries produces bit-identical trees to the resident
path with ``hist_subtraction=false`` and the same pinned ``hist_impl``
(tests/test_ingest.py locks this).

Scope: the chunked path deliberately supports the SERIAL simple-branch
feature set (bagging/GOSS, quantized gradients, categoricals,
feature_fraction, gain_scale, valid-set tracking). With
``hist_subtraction`` on (the default) each round streams only the W
SMALLER siblings and derives the big ones from a per-leaf RAW parent
cache by subtraction ([L+1, F, B, 3] device state — tiny next to the
[R, F] matrix chunking exists to avoid; exact in int32 quantized mode,
f32 subtraction rounding otherwise — the resident builder's own
hist_sub caveat). ``hist_subtraction=false`` restores the full
per-round rebuild, which is what the resident-vs-chunked bitwise
parity tests pin.
Everything that bends the round body — EFB bundles, linear trees,
CEGB, forced splits, monotone constraints, interaction constraints,
per-node sampling, extra-trees, meshes — gates back to resident in
``GBDT._chunked_gate_reason``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.histogram import HIST_CH, build_histograms, resolve_impl
from ..ops.predict import row_feature_gather
from ..ops.split import SplitParams, find_best_splits, leaf_output

__all__ = ["ArraySource", "ShardSource", "ChunkedTreeBuilder"]

NEG_INF = -jnp.inf


# ----------------------------------------------------------------------
# chunk sources: host-side providers of binned rows by global row range


class ArraySource:
    """Host-resident bin matrix as a chunk source (the transparent
    fallback when a device capacity check fails but the matrix still
    fits host RAM)."""

    def __init__(self, bins: np.ndarray):
        self.bins = np.asarray(bins)

    @property
    def num_rows(self) -> int:
        return int(self.bins.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.bins.shape[1])

    def read_rows(self, lo: int, hi: int) -> np.ndarray:
        return self.bins[lo:hi]

    def close(self) -> None:
        pass


class ShardSource:
    """A ``.lgbtpu`` shard directory as one contiguous global row
    stream (mmap-backed; a read only touches the pages it spans)."""

    def __init__(self, readers):
        self.readers = sorted(readers, key=lambda r: r.row0)
        if not self.readers:
            raise ValueError("ShardSource needs at least one shard")

    @property
    def num_rows(self) -> int:
        last = self.readers[-1]
        return int(last.row0 + last.num_rows)

    @property
    def num_features(self) -> int:
        return int(self.readers[0].bins.shape[1])

    def read_rows(self, lo: int, hi: int) -> np.ndarray:
        parts = []
        for r in self.readers:
            a, b = max(lo, r.row0), min(hi, r.row0 + r.num_rows)
            if a < b:
                parts.append(r.read_rows(a - r.row0, b - r.row0))
        if not parts:
            raise ValueError(f"row range [{lo}, {hi}) outside shards")
        out = parts[0] if len(parts) == 1 else np.concatenate(parts)
        if out.shape[0] != hi - lo:
            raise ValueError(
                f"shard set has a gap inside row range [{lo}, {hi})")
        return out

    def close(self) -> None:
        for r in self.readers:
            r.close()


# ----------------------------------------------------------------------
# the chunked builder


class ChunkedTreeBuilder:
    """Leaf-wise growth with the round body split into jitted pieces
    around an eager chunk sweep. Construct ONCE per booster (the four
    jitted programs cache their compilations across trees/iterations).
    """

    def __init__(self, *, num_bins_pf, nan_bin_pf, is_cat_pf,
                 num_leaves: int, leaf_batch: int, max_depth: int,
                 num_bins: int, split_params: SplitParams,
                 hist_dtype: str = "bfloat16", hist_impl: str = "auto",
                 block_rows: int = 0,
                 cat_sorted_mask: Optional[jax.Array] = None,
                 hist_sub: bool = True):
        impl = resolve_impl(hist_impl)
        if impl not in ("scatter", "matmul"):
            # native/pallas have no carried-init formulation that is
            # bit-stable under chunking (post-add reorders f32 sums)
            impl = "scatter"
        self.impl = impl
        self.hist_dtype = hist_dtype
        self.block_rows = int(block_rows)
        self.num_bins_pf = jnp.asarray(num_bins_pf, jnp.int32)
        self.nan_bin_pf = jnp.asarray(nan_bin_pf, jnp.int32)
        self.is_cat_pf = jnp.asarray(is_cat_pf, bool)
        self.cat_sorted_mask = cat_sorted_mask
        self.sp = split_params
        self.L = int(num_leaves)
        self.W = max(1, min(int(leaf_batch), self.L - 1))
        self.MAXN = 2 * self.L - 1
        self.B = int(num_bins)
        self.F = int(self.num_bins_pf.shape[0])
        self.max_depth = int(max_depth)
        self.DUMMY_LEAF = self.L
        self.DUMMY_NODE = self.MAXN
        self.BW = (self.B + 31) // 32
        from ..boosting.tree_builder import max_rounds_for
        self.rounds_bound = max_rounds_for(self.L, self.W)
        # parent-minus-child subtraction (serial_tree_learner.cpp:567
        # Subtract analog, ROADMAP item 2 leftover): keep a per-leaf RAW
        # parent histogram cache across rounds so each sweep streams
        # only the W SMALLER siblings' histograms and derives the big
        # ones by subtraction — the cache is [L+1, F, B, 3] device
        # state, tiny next to the [R, F] matrix chunking exists to
        # avoid. Exact (bit-identical to the full rebuild) in int32
        # quantized mode; f32 differs by subtraction rounding, the same
        # accepted variance as the resident builder's hist_sub path.
        self.hist_sub = bool(hist_sub)

        self._pop_j = jax.jit(self._pop_impl)
        self._chunk_j = jax.jit(self._chunk_impl)
        self._root_j = jax.jit(self._root_impl)
        self._finish_j = jax.jit(self._finish_impl)
        self._sub_j = jax.jit(self._sub_impl)

    # -------------------------- shared pieces -------------------------

    def _dequant(self, h, quant_scales):
        if quant_scales is None:
            return h
        f32 = jnp.float32
        dq = jnp.concatenate(
            [quant_scales.astype(f32), jnp.ones((1,), f32)])
        return h.astype(f32) * dq

    def _relabel(self, bmat, rl, pend):
        """The resident builder's vectorized partition update
        (DataPartition::Split analog) over an arbitrary row window."""
        (pend_active, pend_feat, pend_thr, pend_dl, pend_cat,
         pend_right, pend_bits) = pend
        rlc = jnp.where(rl < 0, self.DUMMY_LEAF, rl)
        active = jnp.take(pend_active, rlc)
        feat = jnp.take(pend_feat, rlc)
        binv = row_feature_gather(bmat, feat)
        thr = jnp.take(pend_thr, rlc)
        nb = jnp.take(self.nan_bin_pf, feat)
        isnan = (binv == nb) & (nb >= 0)
        cat_row = jnp.take(pend_cat, rlc)
        word = binv >> 5
        rbits = jnp.take(pend_bits, rlc, axis=0)
        wsel = (jnp.arange(self.BW, dtype=jnp.int32)[None, :]
                == word[:, None])
        wval = jnp.sum(jnp.where(wsel, rbits, jnp.uint32(0)), axis=1)
        in_set = ((wval >> (binv & 31).astype(jnp.uint32))
                  & jnp.uint32(1)) == 1
        go_left = jnp.where(cat_row, in_set, binv <= thr)
        go_left = jnp.where(isnan & ~cat_row,
                            jnp.take(pend_dl, rlc), go_left)
        return jnp.where(active & ~go_left,
                         jnp.take(pend_right, rlc), rl)

    def _best(self, hist2w, slot_depth, slot_valid, slots_c, tree,
              feature_mask, gain_scale):
        """The resident ``best_for`` simple branch + its gain masks."""
        S = hist2w.shape[0]
        fmask_s = jnp.broadcast_to(feature_mask[None, :], (S, self.F))
        node_of = jnp.take(tree.leaf2node, slots_c)
        parent_out = jnp.take(tree.node_value, node_of)
        bs = find_best_splits(
            hist2w, self.num_bins_pf, self.nan_bin_pf, self.is_cat_pf,
            self.sp, feature_mask=fmask_s, mono_type=None,
            leaf_lo=None, leaf_hi=None, parent_output=parent_out,
            slot_depth=slot_depth, rand_bin=None,
            cat_sorted_mask=self.cat_sorted_mask,
            gain_scale=gain_scale, gain_penalty=None, adv_bounds=None)
        g = bs["gain"]
        if self.max_depth > 0:
            g = jnp.where(slot_depth < self.max_depth, g, NEG_INF)
        g = jnp.where(slot_valid, g, NEG_INF)
        bs["gain"] = g
        return bs

    def _init_tree(self):
        from ..boosting.tree_builder import TreeArrays
        MAXN, L, BW = self.MAXN, self.L, self.BW
        f32 = jnp.float32
        tree = TreeArrays(
            split_feature=jnp.full((MAXN + 1,), -1, jnp.int32),
            threshold_bin=jnp.zeros((MAXN + 1,), jnp.int32),
            default_left=jnp.zeros((MAXN + 1,), bool),
            is_cat=jnp.zeros((MAXN + 1,), bool),
            left_child=jnp.full((MAXN + 1,), -1, jnp.int32),
            right_child=jnp.full((MAXN + 1,), -1, jnp.int32),
            gain=jnp.zeros((MAXN + 1,), f32),
            node_value=jnp.zeros((MAXN + 1,), f32),
            node_count=jnp.zeros((MAXN + 1,), f32),
            node_hess=jnp.zeros((MAXN + 1,), f32),
            cat_bitset=jnp.zeros((MAXN + 1, BW), jnp.uint32),
            leaf2node=jnp.full((L + 1,), self.DUMMY_NODE, jnp.int32),
            leaf_values=jnp.zeros((L + 1,), f32),
            num_leaves=jnp.asarray(1, jnp.int32),
            num_nodes=jnp.asarray(1, jnp.int32),
        )
        return tree._replace(leaf2node=tree.leaf2node.at[0].set(0))

    def _zero_pend(self):
        L, BW = self.L, self.BW
        return (jnp.zeros((L + 1,), bool),
                jnp.zeros((L + 1,), jnp.int32),
                jnp.zeros((L + 1,), jnp.int32),
                jnp.zeros((L + 1,), bool),
                jnp.zeros((L + 1,), bool),
                jnp.zeros((L + 1,), jnp.int32),
                jnp.zeros((L + 1, BW), jnp.uint32))

    # -------------------------- jitted programs ------------------------

    def _chunk_impl(self, chunk_bins, row_leaf, gh, acc, offset, slots,
                    pend):
        """One chunk of one sweep: relabel the chunk's rows against the
        round's pending splits, then fold their histogram contribution
        into the carried accumulator. Root sweeps pass an all-inactive
        ``pend`` (relabel is the identity)."""
        C = chunk_bins.shape[0]
        rl_c = jax.lax.dynamic_slice(row_leaf, (offset,), (C,))
        gh_c = jax.lax.dynamic_slice(
            gh, (offset, jnp.int32(0)), (C, gh.shape[1]))
        rl_new = self._relabel(chunk_bins, rl_c, pend)
        hist = build_histograms(
            chunk_bins, gh_c, rl_new, slots, num_bins=self.B,
            block_rows=self.block_rows, hist_dtype=self.hist_dtype,
            impl=self.impl, init=acc)
        row_leaf = jax.lax.dynamic_update_slice(row_leaf, rl_new,
                                                (offset,))
        return row_leaf, hist

    def _root_impl(self, acc0, tree, feature_mask, quant_scales,
                   gain_scale):
        """Record the root and seed the best-split caches from the
        root sweep's histogram (the resident root phase)."""
        L, W = self.L, self.W
        f32 = jnp.float32
        sp = self.sp
        hist0 = self._dequant(acc0, quant_scales)
        root_sums = hist0[0, 0, :, :].sum(axis=0)
        root_val = leaf_output(root_sums[0], root_sums[1],
                               sp.lambda_l1, sp.lambda_l2,
                               sp.max_delta_step)
        tree = tree._replace(
            node_value=tree.node_value.at[0].set(root_val),
            node_count=tree.node_count.at[0].set(root_sums[2]),
            node_hess=tree.node_hess.at[0].set(root_sums[1]),
            leaf_values=tree.leaf_values.at[0].set(root_val),
        )
        slot_valid0 = jnp.zeros((2 * W,), bool).at[0].set(True)
        bs0 = self._best(hist0, jnp.zeros((2 * W,), jnp.int32),
                         slot_valid0, jnp.zeros((2 * W,), jnp.int32),
                         tree, feature_mask, gain_scale)
        caches = dict(
            gain=jnp.full((L + 1,), NEG_INF, f32).at[0]
            .set(bs0["gain"][0]),
            feat=jnp.zeros((L + 1,), jnp.int32).at[0]
            .set(bs0["feature"][0]),
            thr=jnp.zeros((L + 1,), jnp.int32).at[0]
            .set(bs0["threshold"][0]),
            dl=jnp.zeros((L + 1,), bool).at[0]
            .set(bs0["default_left"][0]),
            cat=jnp.zeros((L + 1,), bool).at[0]
            .set(bs0["is_cat_split"][0]),
            left=jnp.zeros((L + 1, HIST_CH), f32).at[0]
            .set(bs0["left_sum"][0]),
            right=jnp.zeros((L + 1, HIST_CH), f32).at[0]
            .set(bs0["right_sum"][0]),
            bits=jnp.zeros((L + 1, self.BW), jnp.uint32).at[0]
            .set(bs0["cat_bitset"][0]),
            lout=jnp.zeros((L + 1,), f32).at[0]
            .set(bs0["left_out"][0]),
            rout=jnp.zeros((L + 1,), f32).at[0]
            .set(bs0["right_out"][0]),
        )
        more = (tree.num_leaves < L) & jnp.any(caches["gain"][:L]
                                               > NEG_INF)
        return tree, caches, more

    def _pop_impl(self, tree, caches, leaf_depth, valid_bins,
                  valid_row_leaf):
        """Pop the top-W cached splits, record them in the node
        arrays, build the round's pending-split tables, and relabel
        the (resident) validation matrices — everything of the
        resident round body that does NOT touch the training bins."""
        W = self.W
        DUMMY_LEAF, DUMMY_NODE = self.DUMMY_LEAF, self.DUMMY_NODE
        t = tree
        cur = t.num_leaves
        nodes = t.num_nodes
        gains, sel = jax.lax.top_k(caches["gain"][:self.L], W)
        sel = sel.astype(jnp.int32)
        budget = self.L - cur
        valid = jnp.isfinite(gains) & (jnp.arange(W) < budget)
        n_valid = valid.sum().astype(jnp.int32)
        pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
        sel_s = jnp.where(valid, sel, DUMMY_LEAF)
        right_slot = jnp.where(valid, cur + pos, DUMMY_LEAF)
        ln = jnp.where(valid, nodes + 2 * pos, DUMMY_NODE)
        rn = jnp.where(valid, nodes + 2 * pos + 1, DUMMY_NODE)
        parent = jnp.where(valid, jnp.take(t.leaf2node, sel_s),
                           DUMMY_NODE)

        sfeat = jnp.take(caches["feat"], sel_s)
        sthr = jnp.take(caches["thr"], sel_s)
        sdl = jnp.take(caches["dl"], sel_s)
        scat = jnp.take(caches["cat"], sel_s)
        sgain = jnp.take(caches["gain"], sel_s)
        slsum = jnp.take(caches["left"], sel_s, axis=0)
        srsum = jnp.take(caches["right"], sel_s, axis=0)
        sbits = jnp.take(caches["bits"], sel_s, axis=0)
        lval = jnp.take(caches["lout"], sel_s)
        rval = jnp.take(caches["rout"], sel_s)

        t = t._replace(
            split_feature=t.split_feature.at[parent].set(sfeat),
            threshold_bin=t.threshold_bin.at[parent].set(sthr),
            default_left=t.default_left.at[parent].set(sdl),
            is_cat=t.is_cat.at[parent].set(scat),
            left_child=t.left_child.at[parent].set(ln),
            right_child=t.right_child.at[parent].set(rn),
            gain=t.gain.at[parent].set(sgain),
            node_value=t.node_value.at[ln].set(lval).at[rn].set(rval),
            node_count=t.node_count.at[ln].set(slsum[:, 2])
                                     .at[rn].set(srsum[:, 2]),
            node_hess=t.node_hess.at[ln].set(slsum[:, 1])
                                    .at[rn].set(srsum[:, 1]),
            cat_bitset=t.cat_bitset.at[parent].set(sbits),
            leaf2node=t.leaf2node.at[sel_s].set(ln)
                                 .at[right_slot].set(rn),
            leaf_values=t.leaf_values.at[sel_s].set(lval)
                                     .at[right_slot].set(rval),
            num_leaves=cur + n_valid,
            num_nodes=nodes + 2 * n_valid,
        )
        new_depth = jnp.take(leaf_depth, sel_s) + 1
        leaf_depth = leaf_depth.at[sel_s].set(new_depth) \
                               .at[right_slot].set(new_depth)

        pend = (jnp.zeros((self.L + 1,), bool).at[sel_s].set(valid)
                .at[DUMMY_LEAF].set(False),
                jnp.zeros((self.L + 1,), jnp.int32).at[sel_s].set(sfeat),
                jnp.zeros((self.L + 1,), jnp.int32).at[sel_s].set(sthr),
                jnp.zeros((self.L + 1,), bool).at[sel_s].set(sdl),
                jnp.zeros((self.L + 1,), bool).at[sel_s].set(scat),
                jnp.zeros((self.L + 1,), jnp.int32).at[sel_s]
                .set(right_slot),
                jnp.zeros((self.L + 1, self.BW), jnp.uint32).at[sel_s]
                .set(sbits))

        valid_row_leaf = tuple(
            self._relabel(vb, vrl, pend)
            for vb, vrl in zip(valid_bins, valid_row_leaf))

        slots2w = jnp.concatenate([jnp.where(valid, sel_s, -2),
                                   jnp.where(valid, right_slot, -2)])
        slots2w_c = jnp.where(slots2w >= 0, slots2w, DUMMY_LEAF)
        depth2w = jnp.take(leaf_depth,
                           jnp.concatenate([sel_s, right_slot]))
        valid2w = jnp.concatenate([valid, valid])
        # subtraction mode sweeps only the smaller child of each split:
        # the cached split sums carry the exact per-child count channel
        # (integers in f32; the quantized count scale is 1), so the
        # choice is made before any chunk is streamed
        small_is_left = slsum[:, 2] <= srsum[:, 2]
        small_slots = jnp.where(
            valid, jnp.where(small_is_left, sel_s, right_slot), -2)
        return (t, leaf_depth, pend, slots2w, slots2w_c, depth2w,
                valid2w, small_slots, small_is_left, valid_row_leaf)

    def _sub_impl(self, acc_small, hist_cache, slots2w, small_is_left):
        """Assemble the round's full [2W, F, B, 3] RAW lattice from the
        W swept smaller children + the per-leaf parent cache (big =
        parent - small), and roll the cache forward to the children.
        Mirrors the resident builder's fused_children/hist_sub scatter:
        invalid lanes park their writes on the DUMMY_LEAF row."""
        W = self.W
        sel_s = slots2w[:W]
        right_slot = slots2w[W:]
        valid = sel_s >= 0
        parent_raw = jnp.take(hist_cache, jnp.clip(sel_s, 0, self.L),
                              axis=0)
        hbig = parent_raw - acc_small
        sil = small_is_left.reshape((W,) + (1,) * (acc_small.ndim - 1))
        left_raw = jnp.where(sil, acc_small, hbig)
        right_raw = jnp.where(sil, hbig, acc_small)
        hist_cache = hist_cache \
            .at[jnp.where(valid, sel_s, self.DUMMY_LEAF)].set(left_raw) \
            .at[jnp.where(valid, right_slot, self.DUMMY_LEAF)] \
            .set(right_raw)
        return jnp.concatenate([left_raw, right_raw]), hist_cache

    def _finish_impl(self, acc, tree, caches, slots2w_c, depth2w,
                     valid2w, feature_mask, quant_scales, gain_scale):
        """Children best-splits from the sweep's accumulated histogram,
        scattered back into the per-leaf caches."""
        hist2w = self._dequant(acc, quant_scales)
        bs = self._best(hist2w, depth2w, valid2w, slots2w_c, tree,
                        feature_mask, gain_scale)
        caches = dict(
            gain=caches["gain"].at[slots2w_c].set(bs["gain"])
            .at[self.DUMMY_LEAF].set(NEG_INF),
            feat=caches["feat"].at[slots2w_c].set(bs["feature"]),
            thr=caches["thr"].at[slots2w_c].set(bs["threshold"]),
            dl=caches["dl"].at[slots2w_c].set(bs["default_left"]),
            cat=caches["cat"].at[slots2w_c].set(bs["is_cat_split"]),
            left=caches["left"].at[slots2w_c].set(bs["left_sum"]),
            right=caches["right"].at[slots2w_c].set(bs["right_sum"]),
            bits=caches["bits"].at[slots2w_c].set(bs["cat_bitset"]),
            lout=caches["lout"].at[slots2w_c].set(bs["left_out"]),
            rout=caches["rout"].at[slots2w_c].set(bs["right_out"]),
        )
        more = (tree.num_leaves < self.L) & jnp.any(caches["gain"][:self.L]
                                                    > NEG_INF)
        return caches, more

    # -------------------------- eager driver --------------------------

    def _sweep(self, pref, row_leaf, gh, slots, pend, acc_dt):
        S = int(slots.shape[0])
        acc = jnp.zeros((S, self.F, self.B, HIST_CH), acc_dt)
        for off, dev_bins in pref.chunks():
            row_leaf, acc = self._chunk_j(dev_bins, row_leaf, gh, acc,
                                          off, slots, pend)
        return row_leaf, acc

    def build(self, pref, gh, row_leaf0, feature_mask, *,
              quant_scales: Optional[jax.Array] = None,
              gain_scale: Optional[jax.Array] = None,
              valid_bins: Tuple[jax.Array, ...] = (),
              valid_row_leaf0: Tuple[jax.Array, ...] = ()):
        """Grow one tree from the prefetcher's chunk stream. Same
        return contract as the resident builder:
        ``(TreeArrays, row_leaf, valid_row_leafs)`` — ``row_leaf`` is
        sized to the prefetcher's padded row count (pad rows carry
        -1)."""
        Rp = pref.padded_rows
        row_leaf = jnp.asarray(row_leaf0, jnp.int32)
        gh = jnp.asarray(gh)
        R0 = int(row_leaf.shape[0])
        if R0 > Rp:
            raise ValueError(
                f"row_leaf0 has {R0} rows but the chunk stream only "
                f"covers {Rp}")
        if R0 < Rp:
            row_leaf = jnp.concatenate(
                [row_leaf, jnp.full((Rp - R0,), -1, jnp.int32)])
            gh = jnp.concatenate(
                [gh, jnp.zeros((Rp - R0, gh.shape[1]), gh.dtype)])
        acc_dt = jnp.int32 if gh.dtype == jnp.int8 else jnp.float32
        feature_mask = jnp.asarray(feature_mask, bool)

        tree = self._init_tree()
        leaf_depth = jnp.zeros((self.L + 1,), jnp.int32)
        vrl = tuple(jnp.asarray(v, jnp.int32) for v in valid_row_leaf0)
        vbins = tuple(valid_bins)

        root_slots = jnp.full((2 * self.W,), -2, jnp.int32).at[0].set(0)
        row_leaf, acc0 = self._sweep(pref, row_leaf, gh, root_slots,
                                     self._zero_pend(), acc_dt)
        tree, caches, more = self._root_j(acc0, tree, feature_mask,
                                          quant_scales, gain_scale)
        hist_cache = None
        if self.hist_sub:
            hist_cache = jnp.zeros(
                (self.L + 1,) + acc0.shape[1:], acc_dt).at[0].set(acc0[0])
        r = 0
        while r < self.rounds_bound and bool(more):
            (tree, leaf_depth, pend, slots2w, slots2w_c, depth2w,
             valid2w, small_slots, small_is_left,
             vrl) = self._pop_j(tree, caches, leaf_depth, vbins, vrl)
            if self.hist_sub:
                # stream only the W smaller siblings; the big ones come
                # from the parent cache by subtraction — halves the
                # sweep's histogram lattice and skips the larger
                # child's bin traffic entirely
                row_leaf, acc_s = self._sweep(pref, row_leaf, gh,
                                              small_slots, pend, acc_dt)
                acc, hist_cache = self._sub_j(acc_s, hist_cache,
                                              slots2w, small_is_left)
            else:
                row_leaf, acc = self._sweep(pref, row_leaf, gh, slots2w,
                                            pend, acc_dt)
            caches, more = self._finish_j(acc, tree, caches, slots2w_c,
                                          depth2w, valid2w,
                                          feature_mask, quant_scales,
                                          gain_scale)
            r += 1
        return tree, row_leaf, vrl
