"""Objective functions: score -> (grad, hess), plus init score & output link.

TPU-native analog of the reference objective layer
(``include/LightGBM/objective_function.h`` interface; implementations in
``src/objective/regression_objective.hpp``, ``binary_objective.hpp``,
``multiclass_objective.hpp``, ``xentropy_objective.hpp``,
``rank_objective.hpp``; factory ``src/objective/objective_function.cpp:20``).

All gradient math is derived from the loss definitions (not transcribed):
each objective is a pure jnp function jitted into the boosting step, the
natural XLA form of ``GetGradients(score, grad, hess)``. Row weights
multiply both grad and hess, as in the reference.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config

__all__ = ["Objective", "create_objective"]


def _sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


class Objective:
    """Bundle of (get_gradients, boost_from_score, convert_output).

    num_tree_per_iteration mirrors GBDT::num_tree_per_iteration_
    (gbdt.h): num_class for multiclass objectives, else 1.
    """

    name: str = "custom"
    num_model_per_iteration: int = 1
    is_ranking: bool = False
    # whether raw scores need ConvertOutput for human-facing prediction
    needs_convert: bool = False

    def __init__(self, cfg: Config):
        self.cfg = cfg

    # -- interface ---------------------------------------------------------
    def init(self, label: np.ndarray, weight: Optional[np.ndarray],
             query_boundaries: Optional[np.ndarray] = None):
        self.label = label
        self.weight = weight
        self.query_boundaries = query_boundaries

    def get_gradients(self, score: jax.Array, label: jax.Array,
                      weight: Optional[jax.Array]
                      ) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self) -> np.ndarray:
        """Initial raw score(s) (BoostFromScore / BoostFromAverage analog).
        Returns array of shape [num_model_per_iteration]."""
        return np.zeros(self.num_model_per_iteration)

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        return raw

    def _wmean(self):
        if self.weight is None:
            return float(np.mean(self.label))
        return float(np.average(self.label, weights=self.weight))


# ---------------------------------------------------------------------------
# regression family (regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(Objective):
    name = "regression"

    def __init__(self, cfg):
        super().__init__(cfg)
        self.sqrt = bool(cfg.reg_sqrt)
        # sqrt mode trains in sqrt-space; predictions must square back
        # (RegressionL2loss::ConvertOutput, regression_objective.hpp)
        self.needs_convert = self.sqrt

    def init(self, label, weight, query_boundaries=None):
        if self.sqrt:
            label = np.sign(label) * np.sqrt(np.abs(label))
        super().init(label, weight, query_boundaries)

    def get_gradients(self, score, label, weight):
        g = score - label
        h = jnp.ones_like(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(1)
        return np.asarray([self._wmean()])

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw


class RegressionL1(Objective):
    name = "regression_l1"

    def get_gradients(self, score, label, weight):
        g = jnp.sign(score - label)
        h = jnp.ones_like(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(1)
        # weighted median of labels (regression_objective.hpp BoostFromScore
        # for L1 uses the (weighted) 50% percentile)
        lab, w = self.label, self.weight
        if w is None:
            return np.asarray([np.median(lab)])
        order = np.argsort(lab)
        cw = np.cumsum(w[order])
        idx = np.searchsorted(cw, 0.5 * cw[-1])
        return np.asarray([lab[order[min(idx, len(lab) - 1)]]])


class Huber(Objective):
    name = "huber"

    def get_gradients(self, score, label, weight):
        a = self.cfg.alpha
        r = score - label
        g = jnp.clip(r, -a, a)
        h = jnp.ones_like(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        return np.asarray([self._wmean()]) if self.cfg.boost_from_average \
            else np.zeros(1)


class Fair(Objective):
    name = "fair"

    def get_gradients(self, score, label, weight):
        c = self.cfg.fair_c
        x = score - label
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


class Poisson(Objective):
    name = "poisson"
    needs_convert = True

    def get_gradients(self, score, label, weight):
        # loss = exp(score) - label * score  (log link)
        g = jnp.exp(score) - label
        h = jnp.exp(score + self.cfg.poisson_max_delta_step)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        m = max(self._wmean(), 1e-20)
        return np.asarray([np.log(m)])

    def convert_output(self, raw):
        return np.exp(raw)


class Quantile(Objective):
    name = "quantile"

    def get_gradients(self, score, label, weight):
        a = self.cfg.alpha
        g = jnp.where(score >= label, 1.0 - a, -a)
        h = jnp.ones_like(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(1)
        lab, w = self.label, self.weight
        a = self.cfg.alpha
        if w is None:
            return np.asarray([np.quantile(lab, a)])
        order = np.argsort(lab)
        cw = np.cumsum(w[order])
        idx = np.searchsorted(cw, a * cw[-1])
        return np.asarray([lab[order[min(idx, len(lab) - 1)]]])


class Mape(Objective):
    name = "mape"

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        # rows are reweighted by 1/max(1, |label|)
        # (regression_objective.hpp RegressionMAPELOSS)
        scale = 1.0 / np.maximum(1.0, np.abs(label))
        self.weight = scale if weight is None else weight * scale

    def get_gradients(self, score, label, weight):
        g = jnp.sign(score - label)
        h = jnp.ones_like(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(1)
        lab, w = self.label, self.weight
        order = np.argsort(lab)
        cw = np.cumsum(w[order] if w is not None else np.ones(len(lab)))
        idx = np.searchsorted(cw, 0.5 * cw[-1])
        return np.asarray([lab[order[min(idx, len(lab) - 1)]]])


class Gamma(Objective):
    name = "gamma"
    needs_convert = True

    def get_gradients(self, score, label, weight):
        # gamma deviance with log link
        e = jnp.exp(-score)
        g = 1.0 - label * e
        h = label * e
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        return np.asarray([np.log(max(self._wmean(), 1e-20))])

    def convert_output(self, raw):
        return np.exp(raw)


class Tweedie(Objective):
    name = "tweedie"
    needs_convert = True

    def get_gradients(self, score, label, weight):
        rho = self.cfg.tweedie_variance_power
        a = jnp.exp((1.0 - rho) * score)
        b = jnp.exp((2.0 - rho) * score)
        g = -label * a + b
        h = -label * (1.0 - rho) * a + (2.0 - rho) * b
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        return np.asarray([np.log(max(self._wmean(), 1e-20))])

    def convert_output(self, raw):
        return np.exp(raw)


# ---------------------------------------------------------------------------
# binary (binary_objective.hpp)
# ---------------------------------------------------------------------------
class Binary(Objective):
    name = "binary"
    needs_convert = True

    def __init__(self, cfg):
        super().__init__(cfg)
        self.sig = cfg.sigmoid

    def init(self, label, weight, query_boundaries=None):
        u = np.unique(label[~np.isnan(label)])
        if not np.all(np.isin(u, [0.0, 1.0])):
            raise ValueError("binary objective requires labels in {0, 1}")
        super().init(label, weight, query_boundaries)
        # is_unbalance / scale_pos_weight fold into per-row label weights
        npos = float((label == 1).sum())
        nneg = float(len(label) - npos)
        if self.cfg.is_unbalance and npos > 0 and nneg > 0:
            if npos > nneg:
                self.pos_w, self.neg_w = 1.0, npos / nneg
            else:
                self.pos_w, self.neg_w = nneg / npos, 1.0
        else:
            self.pos_w, self.neg_w = self.cfg.scale_pos_weight, 1.0

    def get_gradients(self, score, label, weight):
        sig = self.sig
        p = _sigmoid(sig * score)
        lw = jnp.where(label > 0, self.pos_w, self.neg_w)
        g = sig * (p - label) * lw
        h = sig * sig * p * (1.0 - p) * lw
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(1)
        pbar = self._wmean()
        pbar = min(max(pbar, 1e-15), 1 - 1e-15)
        return np.asarray([np.log(pbar / (1.0 - pbar)) / self.sig])

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sig * raw))


# ---------------------------------------------------------------------------
# multiclass (multiclass_objective.hpp)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(Objective):
    name = "multiclass"
    needs_convert = True

    def __init__(self, cfg):
        super().__init__(cfg)
        self.num_class = cfg.num_class
        self.num_model_per_iteration = cfg.num_class

    def init(self, label, weight, query_boundaries=None):
        lab = label.astype(np.int64)
        if lab.min() < 0 or lab.max() >= self.num_class:
            raise ValueError("multiclass labels must be in "
                             f"[0, {self.num_class})")
        super().init(label, weight, query_boundaries)

    def get_gradients(self, score, label, weight):
        # score: [R, K]; softmax grads with the reference's hessian
        # scaling factor K/(K-1) (multiclass_objective.hpp:31 factor_;
        # equals the familiar 2.0 only at K=2)
        p = jax.nn.softmax(score, axis=1)
        y = jax.nn.one_hot(label.astype(jnp.int32), self.num_class,
                           dtype=score.dtype)
        g = p - y
        factor = self.num_class / max(self.num_class - 1.0, 1.0)
        h = factor * p * (1.0 - p)
        if weight is not None:
            g, h = g * weight[:, None], h * weight[:, None]
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(self.num_class)
        counts = np.bincount(self.label.astype(np.int64),
                             weights=self.weight,
                             minlength=self.num_class).astype(np.float64)
        p = np.maximum(counts / counts.sum(), 1e-15)
        return np.log(p)

    def convert_output(self, raw):
        raw = raw - raw.max(axis=-1, keepdims=True)
        e = np.exp(raw)
        return e / e.sum(axis=-1, keepdims=True)


class MulticlassOVA(Objective):
    name = "multiclassova"
    needs_convert = True

    def __init__(self, cfg):
        super().__init__(cfg)
        self.num_class = cfg.num_class
        self.num_model_per_iteration = cfg.num_class
        self.sig = cfg.sigmoid

    def get_gradients(self, score, label, weight):
        sig = self.sig
        y = jax.nn.one_hot(label.astype(jnp.int32), self.num_class,
                           dtype=score.dtype)
        p = _sigmoid(sig * score)
        g = sig * (p - y)
        h = sig * sig * p * (1.0 - p)
        if weight is not None:
            g, h = g * weight[:, None], h * weight[:, None]
        return g, h

    def boost_from_score(self):
        if not self.cfg.boost_from_average:
            return np.zeros(self.num_class)
        counts = np.bincount(self.label.astype(np.int64),
                             weights=self.weight,
                             minlength=self.num_class).astype(np.float64)
        p = np.clip(counts / counts.sum(), 1e-15, 1 - 1e-15)
        return np.log(p / (1 - p)) / self.sig

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sig * raw))


# ---------------------------------------------------------------------------
# cross entropy on [0,1] labels (xentropy_objective.hpp)
# ---------------------------------------------------------------------------
class CrossEntropy(Objective):
    name = "cross_entropy"
    needs_convert = True

    def get_gradients(self, score, label, weight):
        p = _sigmoid(score)
        g = p - label
        h = p * (1.0 - p)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        pbar = min(max(self._wmean(), 1e-15), 1 - 1e-15)
        return np.asarray([np.log(pbar / (1.0 - pbar))])

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(Objective):
    name = "cross_entropy_lambda"
    needs_convert = True

    # alternative parameterization with log-link intensity
    # (xentropy_objective.hpp CrossEntropyLambda): p = 1 - exp(-exp(s))
    def get_gradients(self, score, label, weight):
        el = jnp.exp(score)
        expel = jnp.expm1(el)  # e^{e^s} - 1
        # d/ds of [-y*log(1-exp(-e^s)) - (1-y)*e^s]
        g = el * (1.0 - label * (1.0 + 1.0 / jnp.maximum(expel, 1e-30)))
        # second derivative, clipped for stability
        h = el * (1.0 - label) + label * el * (el * (1.0 + expel)
                                               - expel) \
            / jnp.maximum(expel, 1e-30) ** 2 * el
        h = jnp.maximum(h, 1e-15)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self):
        pbar = min(max(self._wmean(), 1e-15), 1 - 1e-15)
        return np.asarray([np.log(-np.log(1.0 - pbar))])

    def convert_output(self, raw):
        return 1.0 - np.exp(-np.exp(raw))


# ---------------------------------------------------------------------------
# ranking (rank_objective.hpp) — LambdaRank / XE-NDCG
# ---------------------------------------------------------------------------
from .ranking import LambdaRank, RankXENDCG  # noqa: E402  (separate module)


_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": Mape,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": Binary,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdaRank,
    "rank_xendcg": RankXENDCG,
}


def create_objective(cfg: Config) -> Optional[Objective]:
    """Factory (objective_function.cpp:20 analog). None for custom fobj."""
    name = cfg.objective
    if name == "custom":
        return None
    if name not in _REGISTRY:
        raise ValueError(f"Unknown objective: {name}")
    return _REGISTRY[name](cfg)
