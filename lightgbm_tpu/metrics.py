"""Evaluation metrics.

TPU-native analog of the reference metric layer
(``include/LightGBM/metric.h`` interface; ``src/metric/regression_metric.hpp``,
``binary_metric.hpp``, ``multiclass_metric.hpp``, ``rank_metric.hpp``,
``map_metric.hpp``, ``xentropy_metric.hpp``; factory ``src/metric/metric.cpp``).

Metrics run on host NumPy in float64: evaluation touches each row once per
``metric_freq`` iterations and is bandwidth-trivial next to histogram
construction, so device kernels would buy nothing; float64 keeps AUC/NDCG
comparable to the reference bit-for-bit-ish. Each metric reports
``(name, value, bigger_is_better)`` like ``factor_to_bigger_better``.
"""

from __future__ import annotations

import numpy as np
from typing import List, Tuple

from .config import Config

__all__ = ["Metric", "create_metrics", "METRIC_ALIASES"]


class Metric:
    name: str = ""
    bigger_is_better: bool = False

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def init(self, label, weight, query_boundaries=None):
        self.label = label
        self.weight = weight
        self.query_boundaries = query_boundaries

    def eval(self, pred: np.ndarray) -> List[Tuple[str, float, bool]]:
        """pred: converted output (probabilities for binary/multiclass,
        raw for regression/ranking)."""
        raise NotImplementedError

    def _avg(self, per_row: np.ndarray) -> float:
        if self.weight is None:
            return float(np.mean(per_row))
        return float(np.sum(per_row * self.weight) / np.sum(self.weight))


# -- regression (regression_metric.hpp) ------------------------------------
class _Pointwise(Metric):
    def eval(self, pred):
        return [(self.name, self._avg(self.point(pred, self.label)),
                 self.bigger_is_better)]


class L2(_Pointwise):
    name = "l2"

    def point(self, p, y):
        return (p - y) ** 2


class RMSE(_Pointwise):
    name = "rmse"

    def eval(self, pred):
        mse = self._avg((pred - self.label) ** 2)
        return [(self.name, float(np.sqrt(mse)), False)]


class L1(_Pointwise):
    name = "l1"

    def point(self, p, y):
        return np.abs(p - y)


class QuantileMetric(_Pointwise):
    name = "quantile"

    def point(self, p, y):
        a = self.cfg.alpha
        d = y - p
        return np.where(d >= 0, a * d, (a - 1) * d)


class HuberMetric(_Pointwise):
    name = "huber"

    def point(self, p, y):
        a = self.cfg.alpha
        d = np.abs(p - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_Pointwise):
    name = "fair"

    def point(self, p, y):
        c = self.cfg.fair_c
        x = np.abs(p - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_Pointwise):
    name = "poisson"

    def point(self, p, y):
        eps = 1e-10
        return p - y * np.log(np.maximum(p, eps))


class MAPEMetric(_Pointwise):
    name = "mape"

    def point(self, p, y):
        return np.abs(p - y) / np.maximum(1.0, np.abs(y))


class GammaMetric(_Pointwise):
    name = "gamma"

    def point(self, p, y):
        eps = 1e-10
        p = np.maximum(p, eps)
        # negative log-likelihood of Gamma with unit shape (reference form)
        return y / p + np.log(p)


class GammaDeviance(_Pointwise):
    name = "gamma_deviance"

    def point(self, p, y):
        eps = 1e-10
        r = y / np.maximum(p, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(r, eps), eps))
                      + r - 1.0)


class TweedieMetric(_Pointwise):
    name = "tweedie"

    def point(self, p, y):
        rho = self.cfg.tweedie_variance_power
        eps = 1e-10
        p = np.maximum(p, eps)
        return -y * np.power(p, 1 - rho) / (1 - rho) \
            + np.power(p, 2 - rho) / (2 - rho)


# -- binary (binary_metric.hpp) ---------------------------------------------
class BinaryLogloss(_Pointwise):
    name = "binary_logloss"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class BinaryError(_Pointwise):
    name = "binary_error"

    def point(self, p, y):
        return ((p > 0.5) != (y > 0)).astype(np.float64)


class AUC(Metric):
    name = "auc"
    bigger_is_better = True

    def eval(self, pred):
        y = self.label > 0
        w = self.weight if self.weight is not None else np.ones(len(y))
        order = np.argsort(pred, kind="mergesort")
        p, ys, ws = pred[order], y[order], w[order]
        # tie-aware trapezoid accumulation (binary_metric.hpp AUCMetric)
        wpos = np.where(ys, ws, 0.0)
        wneg = np.where(~ys, ws, 0.0)
        cpos, cneg = np.cumsum(wpos), np.cumsum(wneg)
        # group boundaries where prediction changes
        newv = np.empty(len(p), dtype=bool)
        newv[0] = True
        newv[1:] = p[1:] != p[:-1]
        idx = np.nonzero(newv)[0]
        # per-group sums
        ends = np.append(idx[1:] - 1, len(p) - 1)
        pos_end, neg_end = cpos[ends], cneg[ends]
        pos_start = np.append([0.0], pos_end[:-1])
        neg_start = np.append([0.0], neg_end[:-1])
        g_pos = pos_end - pos_start
        g_neg = neg_end - neg_start
        # positives in a group tie with negatives in the same group: 0.5
        area = np.sum(g_pos * (neg_start + 0.5 * g_neg))
        tot_pos, tot_neg = cpos[-1], cneg[-1]
        if tot_pos <= 0 or tot_neg <= 0:
            return [(self.name, 0.5, True)]
        return [(self.name, float(area / (tot_pos * tot_neg)), True)]


class AveragePrecision(Metric):
    name = "average_precision"
    bigger_is_better = True

    def eval(self, pred):
        y = (self.label > 0).astype(np.float64)
        w = self.weight if self.weight is not None else np.ones(len(y))
        order = np.argsort(-pred, kind="mergesort")
        ys, ws = y[order], w[order]
        tp = np.cumsum(ys * ws)
        denom = np.cumsum(ws)
        prec = tp / denom
        total_pos = tp[-1]
        if total_pos <= 0:
            return [(self.name, 0.0, True)]
        ap = np.sum(prec * ys * ws) / total_pos
        return [(self.name, float(ap), True)]


# -- multiclass (multiclass_metric.hpp) -------------------------------------
class MultiLogloss(Metric):
    name = "multi_logloss"

    def eval(self, pred):
        y = self.label.astype(np.int64)
        eps = 1e-15
        p = np.clip(pred[np.arange(len(y)), y], eps, 1.0)
        return [(self.name, self._avg(-np.log(p)), False)]


class AucMu(Metric):
    """AUC-mu (multiclass_metric.hpp:183, Kleiman & Page 2019): mean over
    class pairs (i, j) of the AUC of samples of those classes ranked by
    their distance from the pair's separating direction,
    ``dist = (v_i - v_j) * (v . raw_score)`` with
    ``v = weights[i] - weights[j]``. Supports the ``auc_mu_weights``
    K*K matrix (row-major, like config.cpp:220-232); default is all-ones
    with a zero diagonal. Ranks raw scores (needs_raw_score), exactly as
    the reference does.
    """
    name = "auc_mu"
    bigger_is_better = True
    needs_raw_score = True

    def _weights_matrix(self, K: int) -> np.ndarray:
        wm = self.cfg.auc_mu_weights
        if wm:
            wm = np.asarray(wm, np.float64)
            if wm.size != K * K:
                raise ValueError(
                    f"auc_mu_weights must have {K * K} entries, got "
                    f"{wm.size}")
            return wm.reshape(K, K)
        out = np.ones((K, K))
        np.fill_diagonal(out, 0.0)
        return out

    def eval(self, score):
        y = self.label.astype(np.int64)
        score = np.asarray(score, np.float64)
        if score.ndim == 1:
            score = score[:, None]
        K = score.shape[1]
        if K < 2:
            raise ValueError(
                "auc_mu requires a multiclass model (num_class >= 2); "
                f"got {K} score column(s)")
        W = self._weights_matrix(K)
        w = self.weight
        ans = 0.0
        for i in range(K):
            mi = y == i
            if not mi.any():
                continue
            for j in range(i + 1, K):
                mj = y == j
                if not mj.any():
                    continue
                v = W[i] - W[j]
                t1 = v[i] - v[j]
                di = t1 * (score[mi] @ v)
                dj = t1 * (score[mj] @ v)
                wi = w[mi] if w is not None else np.ones(int(mi.sum()))
                wj = w[mj] if w is not None else np.ones(int(mj.sum()))
                order = np.argsort(dj, kind="stable")
                djs = dj[order]
                cw = np.concatenate([[0.0], np.cumsum(wj[order])])
                left = np.searchsorted(djs, di, side="left")
                right = np.searchsorted(djs, di, side="right")
                # class-j weight strictly below + half the tied weight
                s = np.sum(wi * (cw[left] + 0.5 * (cw[right] - cw[left])))
                ans += s / (wi.sum() * wj.sum())
        ans = 2.0 * ans / (K * (K - 1))
        return [(self.name, float(ans), True)]


class MultiError(Metric):
    name = "multi_error"

    def eval(self, pred):
        y = self.label.astype(np.int64)
        k = self.cfg.multi_error_top_k
        if k <= 1:
            err = (np.argmax(pred, axis=1) != y).astype(np.float64)
        else:
            topk = np.argpartition(-pred, min(k, pred.shape[1] - 1),
                                   axis=1)[:, :k]
            err = (~(topk == y[:, None]).any(axis=1)).astype(np.float64)
        return [(self.name, self._avg(err), False)]


# -- cross entropy (xentropy_metric.hpp) ------------------------------------
class XentropyMetric(_Pointwise):
    name = "cross_entropy"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class XentLambdaMetric(_Pointwise):
    name = "cross_entropy_lambda"

    def point(self, p, y):
        # NLL in the lambda parameterization: p = 1 - exp(-el), el = e^s;
        # -y log p - (1-y) log(1-p)  =  el - y*log(expm1(el))
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        el = -np.log1p(-p)
        return el - y * np.log(np.expm1(el))


class KullbackLeibler(_Pointwise):
    name = "kldiv"

    def point(self, p, y):
        eps = 1e-15
        p = np.clip(p, eps, 1 - eps)
        yc = np.clip(y, eps, 1 - eps)
        return (yc * np.log(yc / p)
                + (1 - yc) * np.log((1 - yc) / (1 - p)))


# -- ranking (rank_metric.hpp, map_metric.hpp) ------------------------------
class NDCG(Metric):
    name = "ndcg"
    bigger_is_better = True

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        if query_boundaries is None:
            raise ValueError("ndcg metric requires query information")
        lg = list(self.cfg.label_gain)
        max_label = int(np.max(label)) if len(label) else 0
        if not lg:
            lg = [(1 << i) - 1 for i in range(max(max_label + 1, 2))]
        self.label_gain = np.asarray(lg, dtype=np.float64)

    def _dcg_at(self, gains_sorted, k):
        top = gains_sorted[:k]
        return np.sum(top / np.log2(np.arange(2, 2 + len(top))))

    def eval(self, pred):
        qb = self.query_boundaries
        ks = [int(k) for k in (self.cfg.eval_at or [1, 2, 3, 4, 5])]
        sums = np.zeros(len(ks))
        nq = len(qb) - 1
        wsum = 0.0
        for q in range(nq):
            lo, hi = qb[q], qb[q + 1]
            y = self.label[lo:hi].astype(np.int64)
            gains = self.label_gain[y]
            order = np.argsort(-pred[lo:hi], kind="mergesort")
            ideal = np.sort(gains)[::-1]
            w = 1.0
            wsum += w
            for i, k in enumerate(ks):
                idcg = self._dcg_at(ideal, k)
                if idcg > 0:
                    sums[i] += w * self._dcg_at(gains[order], k) / idcg
                else:
                    sums[i] += w  # reference counts all-zero queries as 1
        return [(f"ndcg@{k}", float(sums[i] / max(wsum, 1)), True)
                for i, k in enumerate(ks)]


class MAP(Metric):
    name = "map"
    bigger_is_better = True

    def init(self, label, weight, query_boundaries=None):
        super().init(label, weight, query_boundaries)
        if query_boundaries is None:
            raise ValueError("map metric requires query information")

    def eval(self, pred):
        qb = self.query_boundaries
        ks = [int(k) for k in (self.cfg.eval_at or [1, 2, 3, 4, 5])]
        sums = np.zeros(len(ks))
        nq = len(qb) - 1
        for q in range(nq):
            lo, hi = qb[q], qb[q + 1]
            y = (self.label[lo:hi] > 0).astype(np.float64)
            order = np.argsort(-pred[lo:hi], kind="mergesort")
            ys = y[order]
            cum = np.cumsum(ys)
            prec = cum / np.arange(1, len(ys) + 1)
            for i, k in enumerate(ks):
                kk = min(k, len(ys))
                npos = cum[kk - 1]
                if npos > 0:
                    sums[i] += np.sum(prec[:kk] * ys[:kk]) / min(
                        kk, max(1, int(y.sum())))
        return [(f"map@{k}", float(sums[i] / max(nq, 1)), True)
                for i, k in enumerate(ks)]


_REGISTRY = {
    "l2": L2, "mse": L2, "mean_squared_error": L2, "regression": L2,
    "regression_l2": L2,
    "rmse": RMSE, "root_mean_squared_error": RMSE, "l2_root": RMSE,
    "l1": L1, "mae": L1, "mean_absolute_error": L1, "regression_l1": L1,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDeviance,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLogloss, "binary": BinaryLogloss,
    "binary_error": BinaryError,
    "auc": AUC,
    "average_precision": AveragePrecision,
    "multi_logloss": MultiLogloss, "multiclass": MultiLogloss,
    "softmax": MultiLogloss, "multiclassova": MultiLogloss,
    "multi_error": MultiError,
    "auc_mu": AucMu,
    "cross_entropy": XentropyMetric, "xentropy": XentropyMetric,
    "cross_entropy_lambda": XentLambdaMetric, "xentlambda": XentLambdaMetric,
    "kldiv": KullbackLeibler, "kullback_leibler": KullbackLeibler,
    "ndcg": NDCG, "lambdarank": NDCG, "rank_xendcg": NDCG, "xendcg": NDCG,
    "map": MAP, "mean_average_precision": MAP,
}

METRIC_ALIASES = _REGISTRY

_DEFAULT_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss", "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss", "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg", "rank_xendcg": "ndcg",
}


def create_metrics(cfg: Config) -> List[Metric]:
    """Factory (metric.cpp analog); defaults to the objective's metric."""
    names = cfg.metric
    if isinstance(names, str):
        names = [names] if names else []
    names = [n for n in names if n not in ("", "None", "na", "null",
                                           "custom")]
    if not names:
        default = _DEFAULT_FOR_OBJECTIVE.get(cfg.objective)
        names = [default] if default else []
    out, seen = [], set()
    for n in names:
        if n in ("none",):
            continue
        if n not in _REGISTRY:
            raise ValueError(f"Unknown metric: {n}")
        cls = _REGISTRY[n]
        if cls in seen:
            continue
        seen.add(cls)
        out.append(cls(cfg))
    return out
