"""Parameter/config system.

TPU-native analog of the reference config layer (LightGBM
``include/LightGBM/config.h:39`` ``struct Config``, ``src/io/config.cpp``
``Config::Set`` and the generated alias table in ``src/io/config_auto.cpp``).

Differences from the reference, by design:
- Pure Python: a registry of :class:`Param` entries replaces the generated
  C++ parse members; aliases resolve through one table like
  ``ParameterAlias::KeyAliasTransform``.
- Only parameters that are meaningful for the TPU build are registered.
  Unknown keys raise (same spirit as LightGBM's strict parsing) unless they
  start with an underscore.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["Config", "ParamSpec", "PARAMS", "ALIASES", "parse_params"]


@dataclasses.dataclass
class ParamSpec:
    name: str
    default: Any
    typ: type
    aliases: Tuple[str, ...] = ()
    check: Optional[Callable[[Any], bool]] = None
    doc: str = ""


def _p(name, default, typ, aliases=(), check=None, doc=""):
    return ParamSpec(name, default, typ, tuple(aliases), check, doc)


# Registry. Aliases mirror config_auto.cpp's table for the supported subset.
PARAMS: Dict[str, ParamSpec] = {
    p.name: p
    for p in [
        # -- core (config.h "Core Parameters") --
        _p("objective", "regression", str,
           aliases=("objective_type", "app", "application", "loss"),
           doc="regression | regression_l1 | huber | fair | poisson | quantile"
               " | mape | gamma | tweedie | binary | multiclass | multiclassova"
               " | cross_entropy | cross_entropy_lambda | lambdarank"
               " | rank_xendcg | custom"),
        _p("boosting", "gbdt", str, aliases=("boosting_type", "boost"),
           doc="gbdt | dart | rf | goss (alias for data_sample_strategy)"),
        _p("data_sample_strategy", "bagging", str),
        _p("num_iterations", 100, int,
           aliases=("num_iteration", "n_iter", "num_tree", "num_trees",
                    "num_round", "num_rounds", "nrounds", "num_boost_round",
                    "n_estimators", "max_iter")),
        _p("learning_rate", 0.1, float, aliases=("shrinkage_rate", "eta"),
           check=lambda v: v > 0),
        _p("num_leaves", 31, int, aliases=("num_leaf", "max_leaves", "max_leaf",
                                           "max_leaf_nodes"),
           check=lambda v: 1 < v <= 131072),
        _p("tree_learner", "auto", str,
           aliases=("tree", "tree_type", "tree_learner_type"),
           doc="auto | serial | data | feature | voting — auto scales to "
               "every local device (data-parallel) when more than one is "
               "visible; serial pins one device"),
        # -- accepted no-ops on TPU (documented, not silently wrong):
        # num_threads/force_*_wise tune OpenMP & CPU histogram layout —
        # XLA owns scheduling here and hist_impl selects the kernel;
        # device_type is always the JAX backend; feature_pre_filter,
        # precise_float_parser, parser_config_file, time_out concern the
        # reference's CPU parser/socket stack.
        _p("num_threads", 0, int, aliases=("num_thread", "nthread", "nthreads",
                                           "n_jobs")),
        _p("device_type", "tpu", str, aliases=("device",)),
        _p("seed", 0, int, aliases=("random_seed", "random_state")),
        _p("deterministic", False, bool),
        # -- learning control --
        _p("force_col_wise", False, bool),
        _p("force_row_wise", False, bool),
        _p("max_depth", -1, int),
        _p("min_data_in_leaf", 20, int,
           aliases=("min_data_per_leaf", "min_data", "min_child_samples",
                    "min_samples_leaf"),
           check=lambda v: v >= 0),
        _p("min_sum_hessian_in_leaf", 1e-3, float,
           aliases=("min_sum_hessian_per_leaf", "min_sum_hessian",
                    "min_hessian", "min_child_weight")),
        _p("bagging_fraction", 1.0, float,
           aliases=("sub_row", "subsample", "bagging"),
           check=lambda v: 0 < v <= 1),
        _p("bagging_freq", 0, int, aliases=("subsample_freq",)),
        _p("pos_bagging_fraction", 1.0, float,
           aliases=("pos_sub_row", "pos_subsample", "pos_bagging"),
           check=lambda v: 0 < v <= 1),
        _p("neg_bagging_fraction", 1.0, float,
           aliases=("neg_sub_row", "neg_subsample", "neg_bagging"),
           check=lambda v: 0 < v <= 1),
        _p("bagging_by_query", False, bool),
        _p("bagging_seed", 3, int, aliases=("bagging_fraction_seed",)),
        _p("feature_fraction", 1.0, float,
           aliases=("sub_feature", "colsample_bytree"),
           check=lambda v: 0 < v <= 1),
        _p("feature_fraction_bynode", 1.0, float,
           aliases=("sub_feature_bynode", "colsample_bynode"),
           check=lambda v: 0 < v <= 1),
        _p("feature_fraction_seed", 2, int),
        _p("extra_trees", False, bool, aliases=("extra_tree",)),
        _p("extra_seed", 6, int),
        _p("early_stopping_round", 0, int,
           aliases=("early_stopping_rounds", "early_stopping",
                    "n_iter_no_change")),
        _p("early_stopping_min_delta", 0.0, float),
        _p("first_metric_only", False, bool),
        _p("max_delta_step", 0.0, float,
           aliases=("max_tree_output", "max_leaf_output")),
        _p("lambda_l1", 0.0, float, aliases=("reg_alpha", "l1_regularization"),
           check=lambda v: v >= 0),
        _p("lambda_l2", 0.0, float, aliases=("reg_lambda", "lambda",
                                             "l2_regularization"),
           check=lambda v: v >= 0),
        _p("linear_lambda", 0.0, float, check=lambda v: v >= 0),
        _p("min_gain_to_split", 0.0, float,
           aliases=("min_split_gain",), check=lambda v: v >= 0),
        # dart
        _p("drop_rate", 0.1, float, aliases=("rate_drop",)),
        _p("max_drop", 50, int),
        _p("skip_drop", 0.5, float),
        _p("xgboost_dart_mode", False, bool),
        _p("uniform_drop", False, bool),
        _p("drop_seed", 4, int),
        # goss
        _p("top_rate", 0.2, float),
        _p("other_rate", 0.1, float),
        _p("min_data_per_group", 100, int),
        _p("max_cat_threshold", 32, int),
        _p("cat_l2", 10.0, float),
        _p("cat_smooth", 10.0, float),
        _p("max_cat_to_onehot", 4, int),
        _p("top_k", 20, int, aliases=("topk",)),
        _p("feature_shard_storage", False, bool,
           doc="with tree_learner=feature: store only each device's "
               "feature shard of the bin matrix ([R, F/devices] per "
               "chip instead of a replicated [R, F]) — the TPU-native "
               "answer to datasets whose dense matrix exceeds one "
               "chip's HBM (the reference instead has per-feature "
               "sparse storage, sparse_bin.hpp). Split finding is "
               "already feature-local; the partition step resolves "
               "each row's split-feature bin with a one-hot psum over "
               "the feature axis"),
        _p("monotone_constraints", [], list,
           aliases=("mc", "monotone_constraint", "monotonic_cst")),
        _p("monotone_constraints_method", "basic", str,
           aliases=("monotone_constraining_method", "mc_method")),
        _p("monotone_penalty", 0.0, float, aliases=("monotone_splits_penalty",
                                                    "ms_penalty", "mc_penalty")),
        _p("feature_contri", [], list, aliases=("feature_contrib", "fc",
                                                "fp", "feature_penalty")),
        _p("interaction_constraints", [], list),
        _p("refit_decay_rate", 0.9, float),
        _p("cegb_tradeoff", 1.0, float),
        _p("cegb_penalty_split", 0.0, float),
        _p("cegb_penalty_feature_lazy", [], list),
        _p("cegb_penalty_feature_coupled", [], list),
        _p("path_smooth", 0.0, float, check=lambda v: v >= 0),
        _p("verbosity", 1, int, aliases=("verbose",)),
        _p("use_quantized_grad", False, bool),
        _p("num_grad_quant_bins", 4, int),
        _p("quant_train_renew_leaf", False, bool),
        _p("stochastic_rounding", True, bool),
        # -- TPU-specific learning control (no reference analog) --
        _p("fused_train", True, bool,
           doc="drive training with the fused single-dispatch boosting "
               "step (grads+sampling+build+update in one jitted program, "
               "trees materialized in batches at eval points). false "
               "pins the legacy per-phase dispatch loop; configs the "
               "fused step cannot express (custom fobj, linear trees, "
               "CEGB, multi-process meshes) fall back automatically. "
               "LIGHTGBM_TPU_FUSED_TRAIN=0 pins legacy from the env"),
        _p("eval_period", 1, int, aliases=("eval_freq",),
           check=lambda v: v >= 1,
           doc="engine.train eval cadence: callbacks and early stopping "
               "observe metrics every eval_period iterations (plus the "
               "final one). 1 = reference-parity per-iteration "
               "evaluation; larger values let the fused trainer run "
               "dispatch-ahead with zero host syncs between eval "
               "points"),
        _p("class_batch", "auto", str,
           check=lambda v: v in ("auto", "on", "off"),
           doc="multiclass tree construction: auto/on grow all "
               "num_class per-class trees of an iteration in ONE "
               "class-batched build (the class axis rides the "
               "histogram kernel's leaf-slot axis, so trace size and "
               "compile time stop scaling with num_class and every "
               "histogram dispatch gets K x more MXU work); off pins "
               "the sequential per-class loop. Configs the batched "
               "build cannot express (linear trees, forced splits, "
               "CEGB, feature-parallel learners) fall back "
               "automatically; results are bit-identical either way. "
               "LIGHTGBM_TPU_CLASS_BATCH=0/1 pins from the env"),
        _p("fused_split", "auto", str,
           check=lambda v: v in ("auto", "on", "off"),
           doc="fused histogram+split-find Pallas kernel: auto/on run "
               "the per-(leaf, feature-chunk) gain epilogue inside the "
               "histogram kernel's VMEM-resident accumulator and emit "
               "only best-split candidate records, eliminating the "
               "[F,B,3] HBM histogram round-trip between the hist and "
               "split phases; off pins the two-pass histogram-only "
               "kernel + find_best_splits scan. Configs the epilogue "
               "cannot express fall back automatically (non-pallas "
               "hist_impl, categorical sorted-subset, extra-trees "
               "random thresholds, forced splits, CEGB, advanced "
               "monotone, EFB bundles, feature/data-parallel plans, "
               "chunked out-of-core, unaligned chunk plans); auto "
               "additionally requires the fused probe to compile on "
               "this backend. LIGHTGBM_TPU_FUSED_SPLIT=0/1 pins from "
               "the env"),
        _p("dp_hist_merge", "auto", str,
           check=lambda v: v in ("auto", "allreduce", "reduce_scatter"),
           doc="histogram merge collective for tree_learner=data/voting "
               "on a multi-chip mesh: reduce_scatter (each chip "
               "receives only its F/n feature-slot block of the merged "
               "histogram, finds its local best split, and winners sync "
               "SplitInfo-sized — the reference Network::ReduceScatter "
               "algorithm; ~2x less wire traffic and 1/n the per-chip "
               "histogram HBM of allreduce), allreduce (full-histogram "
               "psum, replicated split finding — the ablation "
               "baseline), or auto (reduce_scatter when the mesh has "
               ">1 device). LIGHTGBM_TPU_DP_HIST_MERGE overrides from "
               "the env; forced splits pin allreduce"),
        _p("leaf_batch", 16, int,
           doc="Leaves split per on-device round; 1 = exact best-first"
               " (reference semantics), >1 batches frontier growth to keep the"
               " MXU histogram matmul wide. See ops/histogram.py."),
        _p("hist_dtype", "bfloat16", str,
           doc="matmul input dtype for histogram accumulation: bfloat16 "
               "(default; f32 accumulate) or float32 (exact)"),
        _p("hist_impl", "auto", str,
           check=lambda v: v in ("auto", "matmul", "scatter", "pallas",
                                 "native"),
           doc="histogram kernel: auto (pallas on tpu, native C on cpu "
               "when a toolchain exists, else scatter), matmul (MXU "
               "one-hot), scatter (XLA scatter-add), pallas (fused VMEM "
               "kernel), native (runtime-compiled C host kernel)"),
        _p("hist_subtraction", True, bool,
           doc="histogram the smaller child only and derive the sibling "
               "by parent-minus-child subtraction from a per-leaf cache "
               "(serial_tree_learner.cpp:567 Subtract analog); "
               "auto-disabled when the cache exceeds "
               "histogram_pool_size"),
        _p("histogram_pool_size", -1.0, float,
           aliases=("hist_pool_size",),
           doc="MB budget for the per-leaf histogram cache "
               "(config.h histogram_pool_size analog); <=0 means an "
               "automatic 512 MB device budget"),
        # -- IO / dataset --
        _p("max_bin", 255, int, aliases=("max_bins",), check=lambda v: v > 1),
        _p("max_bin_by_feature", [], list),
        _p("min_data_in_bin", 3, int, check=lambda v: v > 0),
        _p("bin_construct_sample_cnt", 200000, int,
           aliases=("subsample_for_bin",), check=lambda v: v > 0),
        _p("data_random_seed", 1, int, aliases=("data_seed",)),
        _p("is_enable_sparse", True, bool,
           aliases=("is_sparse", "enable_sparse", "sparse")),
        _p("enable_bundle", True, bool, aliases=("is_enable_bundle", "bundle")),
        _p("max_conflict_rate", 0.0, float, check=lambda v: 0 <= v < 1),
        _p("max_bundle_bins", 256, int, check=lambda v: v >= 4,
           doc="TPU EFB cap: total bins per bundle column (256 keeps "
               "uint8 storage; also the histogram lattice width unit)"),
        _p("use_missing", True, bool),
        _p("zero_as_missing", False, bool),
        _p("feature_pre_filter", True, bool),
        _p("pre_partition", False, bool, aliases=("is_pre_partition",)),
        _p("two_round", False, bool, aliases=("two_round_loading",
                                              "use_two_round_loading")),
        _p("header", False, bool, aliases=("has_header",)),
        _p("label_column", "", str, aliases=("label",)),
        _p("weight_column", "", str, aliases=("weight",)),
        _p("group_column", "", str, aliases=("group", "group_id",
                                             "query_column", "query",
                                             "query_id")),
        _p("ignore_column", "", str, aliases=("ignore_feature",
                                              "blacklist")),
        _p("categorical_feature", "", str, aliases=("cat_feature",
                                                    "categorical_column",
                                                    "cat_column")),
        _p("forcedbins_filename", "", str),
        _p("forcedsplits_filename", "", str,
           aliases=("fs", "forced_splits_filename", "forced_splits_file",
                    "forced_splits")),
        _p("save_binary", False, bool, aliases=("is_save_binary",
                                                "is_save_binary_file")),
        _p("precise_float_parser", False, bool),
        _p("parser_config_file", "", str),
        # -- out-of-core ingest / chunked training (data/) --
        _p("out_of_core", "auto", str,
           check=lambda v: v in ("auto", "on", "off"),
           doc="chunked (non-device-resident) training from .lgbtpu "
               "shard datasets: auto streams row chunks only when the "
               "device capacity check rejects the resident layout, on "
               "forces streaming, off always materializes (raising if "
               "the device can't hold it)"),
        _p("chunk_budget_mb", 64.0, float, check=lambda v: v > 0,
           doc="per-buffer byte budget for streamed bin-matrix chunks; "
               "the chunked trainer double-buffers, so peak staged "
               "bytes are ~2x this and host RSS stays O(chunk), not "
               "O(dataset)"),
        _p("ingest_rows_per_shard", 262144, int, check=lambda v: v > 0,
           doc="row count per .lgbtpu shard written by `python -m "
               "lightgbm_tpu ingest` (fixed partition: retries of an "
               "interrupted ingest rewrite only missing/invalid "
               "shards)"),
        _p("sketch_capacity", 65536, int, check=lambda v: v >= 2,
           doc="distinct values kept per feature by the ingest "
               "quantile sketch before deterministic mantissa-"
               "truncation coarsening (data/sketch.py documents the "
               "2^(level-52) relative accuracy bound)"),
        # -- predict --
        _p("start_iteration_predict", 0, int),
        _p("num_iteration_predict", -1, int),
        _p("predict_raw_score", False, bool, aliases=("is_predict_raw_score",
                                                      "predict_rawscore",
                                                      "raw_score")),
        _p("predict_leaf_index", False, bool, aliases=("is_predict_leaf_index",
                                                       "leaf_index")),
        _p("predict_contrib", False, bool, aliases=("is_predict_contrib",
                                                    "contrib")),
        _p("predict_disable_shape_check", False, bool),
        _p("pred_early_stop", False, bool),
        _p("pred_early_stop_freq", 10, int, check=lambda v: v > 0),
        _p("pred_early_stop_margin", 10.0, float, check=lambda v: v >= 0),
        # -- objective --
        _p("num_class", 1, int, aliases=("num_classes",),
           check=lambda v: v > 0),
        _p("is_unbalance", False, bool, aliases=("unbalance",
                                                 "unbalanced_sets")),
        _p("scale_pos_weight", 1.0, float, check=lambda v: v > 0),
        _p("sigmoid", 1.0, float, check=lambda v: v > 0),
        _p("boost_from_average", True, bool),
        _p("reg_sqrt", False, bool),
        _p("alpha", 0.9, float, check=lambda v: v > 0),
        _p("fair_c", 1.0, float, check=lambda v: v > 0),
        _p("poisson_max_delta_step", 0.7, float, check=lambda v: v > 0),
        _p("tweedie_variance_power", 1.5, float,
           check=lambda v: 1 <= v < 2),
        _p("lambdarank_truncation_level", 30, int, check=lambda v: v > 0),
        _p("lambdarank_norm", True, bool),
        _p("label_gain", [], list),
        _p("lambdarank_position_bias_regularization", 0.0, float),
        _p("objective_seed", 5, int),
        # -- metric --
        _p("metric", [], list, aliases=("metrics", "metric_types")),
        _p("metric_freq", 1, int, aliases=("output_freq",)),
        _p("is_provide_training_metric", False, bool,
           aliases=("training_metric", "is_training_metric",
                    "train_metric")),
        _p("eval_at", [1, 2, 3, 4, 5], list,
           aliases=("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
        _p("multi_error_top_k", 1, int, check=lambda v: v > 0),
        _p("auc_mu_weights", [], list),
        # -- network (reference: machines/ports; here: a jax mesh) --
        _p("num_machines", 1, int, aliases=("num_machine",)),
        _p("local_listen_port", 12400, int, aliases=("local_port", "port")),
        _p("time_out", 120, int),
        _p("machine_list_filename", "", str,
           aliases=("machine_list_file", "machine_list", "mlist")),
        _p("machines", "", str, aliases=("workers", "nodes")),
        # -- misc application-level --
        _p("task", "train", str, aliases=("task_type",)),
        _p("data", "", str, aliases=("train", "train_data", "train_data_file",
                                     "data_filename")),
        _p("valid", [], list, aliases=("test", "valid_data", "valid_data_file",
                                       "test_data", "test_data_file",
                                       "valid_filenames")),
        _p("input_model", "", str, aliases=("model_input", "model_in")),
        _p("convert_model", "gbdt_prediction.cpp", str,
           aliases=("convert_model_file",)),
        _p("convert_model_language", "", str),
        _p("output_model", "LightGBM_model.txt", str,
           aliases=("model_output", "model_out")),
        _p("saved_feature_importance_type", 0, int),
        _p("snapshot_freq", -1, int, aliases=("save_period",)),
        _p("snapshot_keep", 3, int, check=lambda v: v >= 1,
           doc="retention for snapshot_freq artifacts: keep only the "
               "newest N *.snapshot_iter_/*.ckpt_iter_ files per "
               "output_model so long runs stop accumulating unbounded "
               "snapshots"),
        # -- fault tolerance (resilience subsystem, no reference analog)
        _p("resume", "off", str,
           check=lambda v: v in ("off", "auto") or bool(v),
           doc="preemption-safe resume: auto scans output_model for the "
               "newest VALID *.ckpt_iter_ full-state checkpoint "
               "(corrupt/truncated files are rejected by checksum and "
               "the previous one used) and continues bit-identically to "
               "an uninterrupted run; a path resumes from that exact "
               "checkpoint; off (default) disables checkpoint writes "
               "and scanning. Enabling resume also arms the "
               "SIGTERM/SIGINT preemption handler: the first signal "
               "drains pending device work, writes a final checkpoint, "
               "and exits cleanly"),
        # -- runtime telemetry (telemetry subsystem, no reference analog)
        _p("telemetry_port", -1, int,
           doc="opt-in live introspection server during training "
               "(telemetry/exporter.py): >= 0 binds 127.0.0.1:<port> "
               "(0 picks a free port) serving /metrics (Prometheus), "
               "/events tail, /healthz and /trace?duration_ms= "
               "(on-demand jax.profiler capture); -1 (default) "
               "disables. The LIGHTGBM_TPU_TELEMETRY_PORT env var is "
               "the no-code-change spelling and applies when the param "
               "is unset. Scrapes read host-side state only — the "
               "dispatch-ahead training loop gains zero host syncs"),
        _p("event_log", "", str,
           doc="structured run-event log (telemetry/events.py): a path "
               "writes append-only JSONL records (run header, "
               "eval-point iterations with per-phase seconds, "
               "checkpoint write/restore, preemption, nan-guard, "
               "warnings) emitted only at existing sync points; 'auto' "
               "derives <output_model>.events.jsonl; empty (default) "
               "disables. Render with `python -m lightgbm_tpu monitor`"),
        _p("nan_guard", "off", str,
           check=lambda v: v in ("off", "raise", "rollback"),
           doc="sync-free NaN/Inf detection on gradients/scores, "
               "carried through the fused step as a deferred device "
               "flag next to the no-split stop (zero extra host syncs "
               "between eval points): raise surfaces "
               "NumericDivergenceError; rollback restores the newest "
               "valid checkpoint and re-runs with a logged incident "
               "(requires resume != off); off skips the check"),
        _p("on_device_loss", "fail", str,
           check=lambda v: v in ("fail", "degrade"),
           doc="what engine.train does when a boosting step dies with "
               "a typed DeviceLossError (an XLA/collective runtime "
               "failure — a device went away): fail (default) "
               "surfaces the error; degrade hands the run to the "
               "supervising driver (resilience/supervisor.py), which "
               "restores the newest checkpoint, retries with "
               "exponential backoff, and after a repeat loss rebuilds "
               "the plan on the surviving device set "
               "(tree_learner=serial as the floor) — every transition "
               "recorded in the telemetry event log as "
               "degraded/reshard records. Forces resume=auto"),
        _p("linear_tree", False, bool, aliases=("linear_trees",)),
        _p("output_result", "LightGBM_predict_result.txt", str,
           aliases=("predict_result", "prediction_result", "predict_name",
                    "prediction_name", "pred_name", "name_pred")),
    ]
}

ALIASES: Dict[str, str] = {}
for _spec in PARAMS.values():
    for _a in _spec.aliases:
        ALIASES[_a] = _spec.name


_TRUE = {"true", "1", "yes", "on", "+"}
_FALSE = {"false", "0", "no", "off", "-"}


def _coerce(spec: ParamSpec, value: Any) -> Any:
    if spec.typ is bool:
        if isinstance(value, str):
            lv = value.strip().lower()
            if lv in _TRUE:
                return True
            if lv in _FALSE:
                return False
            raise ValueError(f"cannot parse bool param {spec.name}={value!r}")
        return bool(value)
    if spec.typ is int:
        return int(value)
    if spec.typ is float:
        return float(value)
    if spec.typ is list:
        if isinstance(value, str):
            if not value:
                return []
            return [_auto_num(tok) for tok in value.replace(" ", ",").split(",")
                    if tok != ""]
        if isinstance(value, (list, tuple)):
            return list(value)
        return [value]
    if spec.typ is str:
        return str(value)
    return value


def _auto_num(tok: str):
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            return tok


def parse_params(params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Resolve aliases + coerce types. Analog of ``Config::Set``."""
    out: Dict[str, Any] = {}
    if not params:
        return out
    for key, value in params.items():
        canon = ALIASES.get(key, key)
        if canon not in PARAMS:
            if key.startswith("_"):
                out[key] = value
                continue
            raise ValueError(f"Unknown parameter: {key}")
        spec = PARAMS[canon]
        if canon in out and out[canon] != value:
            # first occurrence of the canonical name wins over later aliases,
            # matching LightGBM's duplicate-alias warning behavior.
            continue
        coerced = _coerce(spec, value)
        if spec.check is not None and not spec.check(coerced):
            raise ValueError(f"Invalid value for {canon}: {value!r}")
        out[canon] = coerced
    return out


_OBJECTIVE_ALIASES = {
    # objective name aliases, mirroring objective_function.cpp factory names
    "regression_l2": "regression", "l2": "regression", "mean_squared_error":
    "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    "lambda_rank": "lambdarank", "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg", "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg", "xendcg_mart": "rank_xendcg",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "cross_entropy", "cross_entropy": "cross_entropy",
    "xentlambda": "cross_entropy_lambda",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "none": "custom", "null": "custom", "custom": "custom", "na": "custom",
}


class Config:
    """Validated parameter bag. ``cfg.<name>`` returns value or default."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        self._values = parse_params(params)
        self._apply_special_rules()
        self.check_param_conflict()

    @staticmethod
    def canonical_name(key: str) -> str:
        """Alias -> canonical param name (KeyAliasTransform analog)."""
        return ALIASES.get(key, key)

    def _apply_special_rules(self):
        v = self._values
        obj = v.get("objective")
        if obj is not None:
            # rmse/l2_root are plain aliases of L2 (reg_sqrt is separate)
            v["objective"] = _OBJECTIVE_ALIASES.get(obj, obj)
        boosting = v.get("boosting", "gbdt")
        if boosting == "goss":
            # LightGBM 4.x: boosting=goss is sugar for
            # boosting=gbdt + data_sample_strategy=goss (config.cpp).
            v["boosting"] = "gbdt"
            v["data_sample_strategy"] = "goss"

    def check_param_conflict(self):
        """Analog of Config::CheckParamConflict (config.h:1167)."""
        v = self._values
        if v.get("boosting") == "rf" \
                and self.data_sample_strategy == "bagging":
            # rf.hpp Init: with the bagging strategy, bagging OR feature
            # sampling qualifies; the goss strategy is accepted as-is
            has_bag = (self.bagging_freq > 0
                       and 0 < self.bagging_fraction < 1)
            has_ff = 0 < self.feature_fraction < 1
            if not (has_bag or has_ff):
                raise ValueError(
                    "rf boosting requires bagging (bagging_freq > 0 and "
                    "0 < bagging_fraction < 1) or feature_fraction < 1")
        if self.linear_tree:
            # config.cpp:429-444 linear tree restrictions
            if self.zero_as_missing:
                raise ValueError(
                    "zero_as_missing must be false when fitting linear "
                    "trees")
            if self.objective == "regression_l1":
                raise ValueError(
                    "Cannot use regression_l1 objective when fitting "
                    "linear trees")
            if v.get("boosting") == "dart":
                # DART's drop/restore replays constant leaf values; the
                # linear per-row outputs would corrupt running scores
                raise ValueError(
                    "linear_tree is not supported with boosting=dart")
        if v.get("lambdarank_position_bias_regularization", 0.0) < 0:
            raise ValueError(
                "lambdarank_position_bias_regularization must be >= 0")
        if self.objective in ("multiclass", "multiclassova") \
                and self.num_class < 2:
            raise ValueError("num_class must be >= 2 for multiclass objective")
        if self.objective not in ("multiclass", "multiclassova") \
                and self.num_class != 1:
            raise ValueError("num_class must be 1 for non-multiclass objective")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        spec = PARAMS.get(name)
        if spec is None:
            raise AttributeError(f"No such parameter: {name}")
        return spec.default

    def get(self, name, default=None):
        try:
            return getattr(self, name)
        except AttributeError:
            return default

    def set(self, **kwargs):
        self._values.update(parse_params(kwargs))

    def to_dict(self) -> Dict[str, Any]:
        out = {name: spec.default for name, spec in PARAMS.items()}
        out.update(self._values)
        return out

    def explicit(self) -> Dict[str, Any]:
        return dict(self._values)

    @property
    def is_set_objective(self) -> bool:
        return "objective" in self._values

    def __repr__(self):
        return f"Config({self._values!r})"
