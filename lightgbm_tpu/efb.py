"""Exclusive Feature Bundling (EFB).

TPU-native analog of the reference's feature bundling
(``include/LightGBM/feature_group.h:26`` FeatureGroup;
``src/io/dataset_loader.cpp`` FindGroups/greedy bundling): features that
are (almost) never simultaneously non-default share one storage column.

Why it matters MORE on TPU than on CPU: the MXU histogram lattice is
``columns x max_bins_per_column`` wide — one dense 255-bin feature among
4000 binary ones would blow the one-hot matmul up to ``4000 x 255``
lanes. Bundling packs the sparse features into a few 256-bin columns, so
both HBM (bins matrix bytes) and MXU work scale with the number of
BUNDLES, not features.

Encoding (per bundle g with members f_1..f_m at offsets o_1..o_m):
- bundle bin 0  = every member at its most-frequent bin;
- bundle bin o_j + b = member f_j at bin b (b != mfb_j never collides
  since o_j >= 1 and ranges are disjoint); when two members are
  non-default in the same row (a "conflict", bounded by
  max_conflict_rate) the LAST member in bundle order wins — the same
  information loss the reference accepts.

Recovery of per-feature histograms never needs the default-bin counts
stored: ``hist_f[mfb_f] = leaf_totals - sum(other bins)`` — exactly the
reference's FixHistogram most-frequent-bin accounting
(``src/io/dataset.cpp:1488``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

__all__ = ["BundlePlan", "plan_bundles", "encode_bundles",
           "decode_feature_bins", "encode_rows"]


def decode_feature_bins(raw, off, nb, mfb, xp=np):
    """Bundle-column value -> a feature's own bin id.

    THE one decode formula (train partition, device predict, host replay
    all call this): inside the feature's range -> raw - offset; outside
    -> the feature's most-frequent bin. Singleton bundles use offset 0
    and store every row directly, so the fallback never fires for them.
    ``xp`` is numpy or jax.numpy.
    """
    return xp.where((raw >= off) & (raw < off + nb), raw - off, mfb)


@dataclass
class BundlePlan:
    """Static bundling layout shared by train/valid datasets."""
    # per original (used) feature:
    feat_bundle: np.ndarray     # [F] int32 bundle column id
    feat_offset: np.ndarray     # [F] int32 offset of the feature's range
    feat_mfb: np.ndarray        # [F] int32 most-frequent (default) bin
    # layout:
    num_bundles: int
    bundle_num_bins: np.ndarray  # [G] int32 (1 + sum of member bins)
    max_bundle_bins: int         # B_g for the histogram lattice

    @property
    def is_trivial(self) -> bool:
        return self.num_bundles >= len(self.feat_bundle)

    def state_arrays(self):
        return (self.feat_bundle, self.feat_offset, self.feat_mfb,
                self.bundle_num_bins,
                np.asarray([self.num_bundles, self.max_bundle_bins]))

    @classmethod
    def from_state_arrays(cls, fb, fo, fm, bnb, scal):
        return cls(feat_bundle=fb, feat_offset=fo, feat_mfb=fm,
                   num_bundles=int(scal[0]), bundle_num_bins=bnb,
                   max_bundle_bins=int(scal[1]))


def _popcount(x: np.ndarray) -> int:
    return int(np.unpackbits(x).sum())


def plan_bundles(sample_bins: np.ndarray, num_bins: Sequence[int],
                 most_freq: Sequence[int], *,
                 max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 256) -> BundlePlan:
    """Greedy conflict-bounded packing (dataset_loader FindGroups).

    sample_bins: [S, F] int bins of a row sample; num_bins/most_freq per
    feature. Features are ordered by non-default count (descending) and
    placed into the first bundle whose accumulated conflicts and bin
    budget allow, else open a new bundle.
    """
    S, F = sample_bins.shape
    nb = np.asarray(num_bins, np.int64)
    mfb = np.asarray(most_freq, np.int64)
    nondef = sample_bins != mfb[None, :]                    # [S, F]
    nz_count = nondef.sum(axis=0)
    packed = [np.packbits(nondef[:, f]) for f in range(F)]
    max_conflicts = int(max_conflict_rate * S)

    order = np.argsort(-nz_count, kind="stable")
    bundles: List[dict] = []   # {members, bits, conflicts, bins}
    for f in order:
        placed = False
        # dense-ish features (no realistic exclusivity) go solo fast
        if nz_count[f] * 2 > S or nb[f] + 1 > max_bundle_bins:
            bundles.append(dict(members=[int(f)], bits=packed[f].copy(),
                                conflicts=0, bins=1 + int(nb[f])))
            continue
        for bd in bundles:
            if len(bd["members"]) == 1 and \
                    nz_count[bd["members"][0]] * 2 > S:
                continue  # don't co-bundle with dense columns
            if bd["bins"] + nb[f] > max_bundle_bins:
                continue
            c = _popcount(np.bitwise_and(bd["bits"], packed[f]))
            if bd["conflicts"] + c <= max_conflicts:
                bd["members"].append(int(f))
                bd["bits"] |= packed[f]
                bd["conflicts"] += c
                bd["bins"] += int(nb[f])
                placed = True
                break
        if not placed:
            bundles.append(dict(members=[int(f)], bits=packed[f].copy(),
                                conflicts=0, bins=1 + int(nb[f])))

    feat_bundle = np.zeros(F, np.int32)
    feat_offset = np.zeros(F, np.int32)
    bundle_bins = []
    for g, bd in enumerate(bundles):
        if len(bd["members"]) == 1:
            # singleton: store raw bins at offset 0 (no shared
            # all-default slot) — keeps a 256-bin feature inside uint8
            f = bd["members"][0]
            feat_bundle[f] = g
            feat_offset[f] = 0
            bundle_bins.append(int(nb[f]))
            continue
        off = 1
        for f in bd["members"]:
            feat_bundle[f] = g
            feat_offset[f] = off
            off += int(nb[f])
        bundle_bins.append(off)
    return BundlePlan(
        feat_bundle=feat_bundle, feat_offset=feat_offset,
        feat_mfb=mfb.astype(np.int32), num_bundles=len(bundles),
        bundle_num_bins=np.asarray(bundle_bins, np.int32),
        max_bundle_bins=int(max(bundle_bins)) if bundle_bins else 1)


def encode_bundles(plan: BundlePlan, col_bins_iter,
                   num_rows: int) -> np.ndarray:
    """[R, G] bundled bin matrix from per-feature bin columns.

    col_bins_iter yields (feature_index, bins[R]) — streaming so a full
    dense [R, F] matrix never exists for sparse inputs. Later members of
    a bundle overwrite earlier ones on conflict rows (bounded by
    max_conflict_rate).
    """
    dtype = np.uint8 if plan.max_bundle_bins <= 256 else np.int32
    out = np.zeros((num_rows, plan.num_bundles), dtype)
    for f, col in col_bins_iter:
        g = plan.feat_bundle[f]
        off = plan.feat_offset[f]
        if off == 0:            # singleton bundle: raw bins
            out[:, g] = col.astype(dtype)
            continue
        mfb = plan.feat_mfb[f]
        nz = col != mfb
        out[nz, g] = (off + col[nz]).astype(dtype)
    return out


def encode_rows(plan: BundlePlan, batch_bins: np.ndarray,
                out: np.ndarray, row0: int) -> None:
    """Encode a [r, F] per-feature bin batch into out[row0:row0+r, G]
    (streaming/Sequence ingestion path)."""
    r = batch_bins.shape[0]
    view = out[row0:row0 + r]
    view[:] = 0
    for f in range(batch_bins.shape[1]):
        g = plan.feat_bundle[f]
        off = plan.feat_offset[f]
        col = batch_bins[:, f]
        if off == 0:
            view[:, g] = col.astype(out.dtype)
            continue
        nz = col != plan.feat_mfb[f]
        view[nz, g] = (off + col[nz]).astype(out.dtype)
