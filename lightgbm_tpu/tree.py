"""Host-side tree model + LightGBM-v4-compatible text serialization.

Analog of the reference ``include/LightGBM/tree.h`` / ``src/io/tree.cpp``
(SoA node arrays, text round-trip at tree.cpp:339,697) and the per-tree
blocks of ``src/boosting/gbdt_model_text.cpp``.

The on-device tree (boosting/tree_builder.TreeArrays) uses flat node ids;
this module renumbers into the reference's scheme — internal nodes by split
order, leaves by leaf slot, children encoded as ``node_idx`` or ``~leaf_idx``
— so saved models are loadable by stock LightGBM tooling and vice versa.

decision_type bit layout (tree.h): bit0 = categorical, bit1 = default_left,
bits 2-3 = missing_type (0 none / 1 zero / 2 nan).
"""

from __future__ import annotations

import numpy as np
from typing import Dict, List

from .binning import MISSING_ZERO, MISSING_NAN

__all__ = ["Tree"]

_CAT_BIT = 1
_DEFAULT_LEFT_BIT = 2
_MISSING_SHIFT = 2  # bits 2-3 after the two flags


def _missing_from_decision(dt: int) -> int:
    return (dt >> _MISSING_SHIFT) & 3


class Tree:
    """One decision tree in reference numbering (host, NumPy)."""

    def __init__(self, num_leaves: int):
        self.num_leaves = num_leaves
        n_int = max(num_leaves - 1, 0)
        self.split_feature = np.zeros(n_int, np.int32)
        self.threshold = np.zeros(n_int, np.float64)      # real-valued
        self.threshold_bin = np.zeros(n_int, np.int32)    # for binned predict
        self.decision_type = np.zeros(n_int, np.int32)
        self.split_gain = np.zeros(n_int, np.float64)
        self.left_child = np.zeros(n_int, np.int32)
        self.right_child = np.zeros(n_int, np.int32)
        self.internal_value = np.zeros(n_int, np.float64)
        self.internal_weight = np.zeros(n_int, np.float64)
        self.internal_count = np.zeros(n_int, np.int64)
        self.leaf_value = np.zeros(num_leaves, np.float64)
        self.leaf_weight = np.zeros(num_leaves, np.float64)
        self.leaf_count = np.zeros(num_leaves, np.int64)
        self.shrinkage = 1.0
        # categorical split storage (tree.h cat_boundaries_/cat_threshold_)
        self.num_cat = 0
        self.cat_boundaries = [0]
        self.cat_threshold: List[int] = []
        # bin-space subsets per cat split (in-session binned replay only)
        self.cat_bitset_bins: List[np.ndarray] = []
        # linear-tree leaves (tree.h leaf_const_/leaf_coeff_/leaf_features_)
        self.is_linear = False
        self.leaf_const = np.zeros(num_leaves, np.float64)
        self.leaf_features: List[List[int]] = [[] for _ in range(num_leaves)]
        self.leaf_coeff: List[List[float]] = [[] for _ in range(num_leaves)]

    # ------------------------------------------------------------------
    @classmethod
    def from_device(cls, t, bin_mappers, used_features,
                    shrinkage: float) -> "Tree":
        """Convert a tree_builder.TreeArrays pytree (host numpy'd)."""
        num_leaves = int(t.num_leaves)
        num_nodes = int(t.num_nodes)
        tree = cls(num_leaves)
        tree.shrinkage = shrinkage

        sf = np.asarray(t.split_feature)[:num_nodes]
        internal_nodes = np.nonzero(sf >= 0)[0]
        # split order == creation order of children (node ids are assigned
        # monotonically per split)
        lc = np.asarray(t.left_child)[:num_nodes]
        order = np.argsort(lc[internal_nodes], kind="stable")
        internal_nodes = internal_nodes[order]
        int_idx = {int(n): i for i, n in enumerate(internal_nodes)}

        leaf2node = np.asarray(t.leaf2node)[:num_leaves]
        leaf_idx = {int(n): s for s, n in enumerate(leaf2node)}

        if num_leaves == 1:
            tree.leaf_value[0] = float(np.asarray(t.node_value)[0]) * shrinkage
            tree.leaf_weight[0] = float(np.asarray(t.node_hess)[0])
            tree.leaf_count[0] = int(np.asarray(t.node_count)[0])
            return tree

        thrb = np.asarray(t.threshold_bin)
        dl = np.asarray(t.default_left)
        cat = np.asarray(t.is_cat)
        bitset = np.asarray(t.cat_bitset)
        rc = np.asarray(t.right_child)
        gain = np.asarray(t.gain)
        val = np.asarray(t.node_value)
        cnt = np.asarray(t.node_count)
        hes = np.asarray(t.node_hess)

        for i, n in enumerate(internal_nodes):
            f_local = int(sf[n])
            f_global = int(used_features[f_local])
            mapper = bin_mappers[f_global]
            tree.split_feature[i] = f_global
            tree.threshold_bin[i] = int(thrb[n])
            dt = 0
            if cat[n]:
                dt |= _CAT_BIT
                tree.threshold[i] = tree.num_cat  # index into cat storage
                # decode the bin-space subset, map bins -> category values
                words = bitset[n].astype(np.uint32)
                bin_ids = [w * 32 + b for w in range(len(words))
                           for b in range(32) if (int(words[w]) >> b) & 1]
                tree._append_cat_bitset(
                    [int(mapper.categories[bi]) for bi in bin_ids])
                tree.cat_bitset_bins.append(words)
            else:
                dt |= (mapper.missing_type & 3) << _MISSING_SHIFT
                if dl[n]:
                    dt |= _DEFAULT_LEFT_BIT
                tree.threshold[i] = mapper.bin_to_threshold_value(
                    int(thrb[n]))
            tree.decision_type[i] = dt
            tree.split_gain[i] = float(gain[n])
            tree.internal_value[i] = float(val[n]) * shrinkage
            tree.internal_weight[i] = float(hes[n])
            tree.internal_count[i] = int(cnt[n])
            for child_arr, out in ((lc, tree.left_child),
                                   (rc, tree.right_child)):
                c = int(child_arr[n])
                out[i] = int_idx[c] if c in int_idx else ~leaf_idx[c]

        for s in range(num_leaves):
            n = int(leaf2node[s])
            tree.leaf_value[s] = float(val[n]) * shrinkage
            tree.leaf_weight[s] = float(hes[n])
            tree.leaf_count[s] = int(cnt[n])
        return tree

    @classmethod
    def from_device_batch(cls, host_trees, bin_mappers, used_features,
                          shrinkage: float):
        """Convert one iteration's K device-built trees (already pulled
        to host — the fused trainer's sync() fetches the whole pending
        ring in ONE device transfer, then decodes here) into ``Tree``
        models. The per-tree decode is host-only numpy; keeping it out
        of the training inner loop is what lets the fused step run
        sync-free between eval points."""
        return [cls.from_device(t, bin_mappers, used_features, shrinkage)
                for t in host_trees]

    def _append_cat_bitset(self, categories: List[int]):
        """Append one categorical split's bitset (tree.cpp cat storage)."""
        maxc = max(categories)
        nwords = maxc // 32 + 1
        words = [0] * nwords
        for c in categories:
            words[c // 32] |= (1 << (c % 32))
        self.cat_threshold.extend(words)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.num_cat += 1

    # ------------------------------------------------------------------
    def _traverse(self, X: np.ndarray) -> np.ndarray:
        """Vectorized raw-feature traversal (tree.h Predict decision path);
        returns the leaf index per row. Decision semantics live in
        _go_left_all (shared with SHAP)."""
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, np.int32)
        gl = self._go_left_all(X)          # [n, NI]
        node = np.zeros(n, np.int32)       # >=0: internal idx; <0: ~leaf
        active = np.ones(n, bool)
        out = np.zeros(n, np.int32)
        rows = np.arange(n)
        for _ in range(self.num_leaves):   # depth bound
            if not active.any():
                break
            idx = node[active]
            go_left = gl[rows[active], idx]
            nxt = np.where(go_left, self.left_child[idx],
                           self.right_child[idx])
            node[active] = nxt
            leaf_now = nxt < 0
            act_idx = np.nonzero(active)[0]
            done = act_idx[leaf_now]
            out[done] = ~nxt[leaf_now]
            active[done] = False
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaves = self._traverse(X)
        if not self.is_linear:
            return self.leaf_value[leaves]
        # linear leaves: const + coeff . x, NaN in any leaf feature falls
        # back to the piecewise-constant output (tree.cpp:133-149)
        out = np.empty(len(leaves), np.float64)
        for s in range(self.num_leaves):
            rows = np.nonzero(leaves == s)[0]
            if len(rows) == 0:
                continue
            feats = self.leaf_features[s]
            if not feats:
                out[rows] = self.leaf_const[s]
                continue
            vals = X[np.ix_(rows, feats)]
            nan = np.isnan(vals).any(axis=1)
            lin = self.leaf_const[s] + vals @ np.asarray(self.leaf_coeff[s])
            out[rows] = np.where(nan, self.leaf_value[s], lin)
        return out

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        return self._traverse(X)

    # ------------------------------------------------------------------
    def _traverse_binned(self, bins: np.ndarray, used_features: np.ndarray,
                         nan_bins: np.ndarray) -> np.ndarray:
        """Leaf index per BINNED row (threshold_bin comparison — the same
        decisions the on-device builder made). Only valid for trees built
        in-session (threshold_bin populated); used by rollback/refit score
        replay without needing the raw feature matrix.

        bins: [R, F_local] over used features; used_features maps local ->
        global; nan_bins: [F_local] nan bin per local feature (-1 none).
        """
        global_to_local = {int(g): i for i, g in enumerate(used_features)}
        n = bins.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)
        active = np.ones(n, bool)
        out = np.zeros(n, np.int32)
        feat_local = np.asarray(
            [global_to_local[int(f)] for f in self.split_feature], np.int32)
        for _ in range(self.num_leaves):
            if not active.any():
                break
            idx = node[active]
            fl = feat_local[idx]
            v = bins[active, fl]
            dt = self.decision_type[idx]
            is_cat = (dt & _CAT_BIT) != 0
            thr = self.threshold_bin[idx]
            nb = nan_bins[fl]
            isnan = (v == nb) & (nb >= 0)
            go_left = np.where(is_cat, v == thr, v <= thr)
            defl = (dt & _DEFAULT_LEFT_BIT) != 0
            go_left = np.where(isnan & ~is_cat, defl, go_left)
            nxt = np.where(go_left, self.left_child[idx],
                           self.right_child[idx])
            node[active] = nxt
            leaf_now = nxt < 0
            act_idx = np.nonzero(active)[0]
            done = act_idx[leaf_now]
            out[done] = ~nxt[leaf_now]
            active[done] = False
        return out

    def predict_binned(self, bins: np.ndarray, used_features: np.ndarray,
                       nan_bins: np.ndarray) -> np.ndarray:
        return self.leaf_value[
            self._traverse_binned(bins, used_features, nan_bins)]

    # ------------------------------------------------------------------
    def to_text(self, tree_id: int) -> str:
        """One ``Tree=<id>`` block (gbdt_model_text.cpp:311 format)."""
        def join(a, fmt="{}"):
            if fmt == "{!r}":  # full-precision float round-trip
                return " ".join(repr(float(x)) for x in a)
            return " ".join(fmt.format(x) for x in a)

        lines = [f"Tree={tree_id}",
                 f"num_leaves={self.num_leaves}",
                 f"num_cat={self.num_cat}"]
        if self.num_leaves > 1:
            lines += [
                "split_feature=" + join(self.split_feature),
                "split_gain=" + join(self.split_gain, "{:g}"),
                "threshold=" + join(self.threshold, "{!r}").replace(
                    "inf", "1.7976931348623157e+308"),
                "decision_type=" + join(self.decision_type),
                "left_child=" + join(self.left_child),
                "right_child=" + join(self.right_child),
                "leaf_value=" + join(self.leaf_value, "{!r}"),
                "leaf_weight=" + join(self.leaf_weight, "{!r}"),
                "leaf_count=" + join(self.leaf_count),
                "internal_value=" + join(self.internal_value, "{!r}"),
                "internal_weight=" + join(self.internal_weight, "{!r}"),
                "internal_count=" + join(self.internal_count),
            ]
            if self.num_cat > 0:
                lines += ["cat_boundaries=" + join(self.cat_boundaries),
                          "cat_threshold=" + join(self.cat_threshold)]
        else:
            lines += ["leaf_value=" + join(self.leaf_value, "{!r}")]
        lines += [f"is_linear={int(self.is_linear)}"]
        if self.is_linear:
            # tree.cpp ToString linear block: per-leaf const, feature
            # count, then flattened features / coefficients
            lines += [
                "leaf_const=" + join(self.leaf_const, "{!r}"),
                "num_features=" + " ".join(
                    str(len(c)) for c in self.leaf_coeff),
                "leaf_features=" + " ".join(
                    " ".join(str(f) for f in fs)
                    for fs in self.leaf_features if fs),
                "leaf_coeff=" + " ".join(
                    " ".join(repr(float(c)) for c in cs)
                    for cs in self.leaf_coeff if cs),
            ]
        lines += [f"shrinkage={self.shrinkage:g}", ""]
        return "\n".join(lines)

    @classmethod
    def from_text(cls, block: str) -> "Tree":
        """Parse one Tree block (tree.cpp:697 Tree(const char*) analog)."""
        kv: Dict[str, str] = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        num_leaves = int(kv["num_leaves"])
        tree = cls(num_leaves)

        def arr(key, dtype, n):
            if key not in kv or not kv[key]:
                return np.zeros(n, dtype)
            return np.asarray(kv[key].split(), dtype=dtype)

        tree.leaf_value = arr("leaf_value", np.float64, num_leaves)
        if num_leaves > 1:
            n_int = num_leaves - 1
            tree.split_feature = arr("split_feature", np.int32, n_int)
            tree.split_gain = arr("split_gain", np.float64, n_int)
            tree.threshold = arr("threshold", np.float64, n_int)
            tree.decision_type = arr("decision_type", np.int32, n_int)
            tree.left_child = arr("left_child", np.int32, n_int)
            tree.right_child = arr("right_child", np.int32, n_int)
            tree.leaf_weight = arr("leaf_weight", np.float64, num_leaves)
            tree.leaf_count = arr("leaf_count", np.int64, num_leaves)
            tree.internal_value = arr("internal_value", np.float64, n_int)
            tree.internal_weight = arr("internal_weight", np.float64, n_int)
            tree.internal_count = arr("internal_count", np.int64, n_int)
            tree.num_cat = int(kv.get("num_cat", "0"))
            if tree.num_cat > 0:
                tree.cat_boundaries = [int(x) for x in
                                       kv["cat_boundaries"].split()]
                tree.cat_threshold = [int(x) for x in
                                      kv["cat_threshold"].split()]
        if kv.get("is_linear", "0") == "1":
            tree.is_linear = True
            tree.leaf_const = arr("leaf_const", np.float64, num_leaves)
            nf = arr("num_features", np.int64, num_leaves)
            feats = [int(x) for x in kv.get("leaf_features", "").split()]
            coefs = [float(x) for x in kv.get("leaf_coeff", "").split()]
            pos = 0
            for s in range(num_leaves):
                n = int(nf[s])
                tree.leaf_features[s] = feats[pos:pos + n]
                tree.leaf_coeff[s] = coefs[pos:pos + n]
                pos += n
        tree.shrinkage = float(kv.get("shrinkage", "1"))
        return tree

    # ------------------------------------------------------------------
    # SHAP contributions (tree.h:141 PredictContrib — the TreeExplainer
    # path-integration algorithm of Lundberg et al., as in tree.cpp
    # TreeSHAP; recursion over the node arrays with EXTEND/UNWIND over
    # the unique feature path)
    def expected_value(self) -> float:
        total = self.leaf_count.sum()
        if total <= 0:
            return float(self.leaf_value.mean())
        return float((self.leaf_value * self.leaf_count).sum() / total)

    def _node_weight(self, node: int) -> float:
        """Row count reaching a node (internal idx >=0, leaf via ~idx)."""
        if node >= 0:
            return float(self.internal_count[node])
        return float(self.leaf_count[~node])

    def predict_contrib_reference(self, X: np.ndarray) -> np.ndarray:
        """Per-row recursive TreeSHAP — the direct transcription of the
        reference algorithm (tree.cpp TreeSHAP). Kept as the slow oracle
        for the vectorized path below; use predict_contrib."""
        n, F = X.shape
        out = np.zeros((n, F + 1))
        out[:, -1] = self.expected_value()
        if self.num_leaves == 1:
            return out
        gl = self._go_left_all(X)
        for r in range(n):
            self._tree_shap(gl[r], out[r], 0, 1.0, 1.0, -1, [])
        return out

    # -- vectorized TreeSHAP ------------------------------------------
    # The recursion above walks EXTEND/UNWIND per (row, node). The
    # vectorized form exploits two structural facts:
    # (1) at a leaf, the EXTEND polynomial is a symmetric function of the
    #     path's UNIQUE features with merged fractions (duplicate feature
    #     occurrences multiply: one = AND of direction matches, zero =
    #     product of cover ratios) — extend order never matters;
    # (2) per row, one_fraction is BINARY, so the whole row dependence is
    #     a [rows, leaves, slots] 0/1 tensor of "did this row follow the
    #     path at every node of this feature".
    # So: precompute per-leaf path slot tables once per tree (host), then
    # run the EXTEND scan and the per-slot UNWIND totals as NumPy array
    # programs over (rows x leaves x slots) — Python loop counts are
    # O(depth) and O(depth) instead of O(rows * nodes * depth^2).
    def _path_data(self):
        if getattr(self, "_paths_cache", None) is not None:
            return self._paths_cache
        L = self.num_leaves
        raw_paths = [None] * L  # leaf slot -> (nodes, dirs)
        stack = [(0, [], [])]
        while stack:
            node, nodes, dirs = stack.pop()
            if node < 0:
                raw_paths[~node] = (nodes, dirs)
                continue
            stack.append((int(self.left_child[node]), nodes + [node],
                          dirs + [1]))
            stack.append((int(self.right_child[node]), nodes + [node],
                          dirs + [0]))
        P = max(len(p[0]) for p in raw_paths)
        slot_lists = []
        for nodes, dirs in raw_paths:
            feats = {}
            for p, (nd, dr) in enumerate(zip(nodes, dirs)):
                feats.setdefault(int(self.split_feature[nd]), []).append(p)
            slot_lists.append(list(feats.items()))
        D = max(len(s) for s in slot_lists)

        path_node = np.full((L, P), -1, np.int32)
        path_dir = np.zeros((L, P), np.int8)
        path_slot = np.full((L, P), -1, np.int32)
        slot_feat = np.full((L, D), -1, np.int32)
        slot_zero = np.ones((L, D), np.float64)
        d_len = np.zeros(L, np.int32)
        for l, ((nodes, dirs), slots) in enumerate(zip(raw_paths,
                                                       slot_lists)):
            path_node[l, :len(nodes)] = nodes
            path_dir[l, :len(dirs)] = dirs
            d_len[l] = len(slots)
            for s, (f, occs) in enumerate(slots):
                slot_feat[l, s] = f
                for p in occs:
                    path_slot[l, p] = s
                    nd = nodes[p]
                    child = (int(self.left_child[nd]) if dirs[p]
                             else int(self.right_child[nd]))
                    w = self._node_weight(nd)
                    slot_zero[l, s] *= (self._node_weight(child) / w
                                        if w > 0 else 0.0)
        # mismatch-count map [L, P, D]: path position -> slot one-hot
        slot_map = np.zeros((L, P, D), np.float64)
        for l in range(L):
            for p in range(P):
                if path_slot[l, p] >= 0:
                    slot_map[l, p, path_slot[l, p]] = 1.0
        # scatter groups: feature id -> (leaf idx array, slot idx array)
        groups = {}
        for l in range(L):
            for s in range(int(d_len[l])):
                ls, ss = groups.setdefault(int(slot_feat[l, s]), ([], []))
                ls.append(l)
                ss.append(s)
        groups = {f: (np.asarray(ls, np.intp), np.asarray(ss, np.intp))
                  for f, (ls, ss) in groups.items()}
        self._paths_cache = (path_node, path_dir, slot_map, slot_feat,
                             slot_zero, d_len, groups)
        return self._paths_cache

    def _go_left_all(self, X: np.ndarray) -> np.ndarray:
        """[n, num_internal] decision per row per internal node (the same
        semantics as _decision, batched)."""
        n = X.shape[0]
        ni = self.num_leaves - 1
        v = X[:, self.split_feature]                     # [n, NI]
        dt = self.decision_type
        is_cat = (dt & _CAT_BIT) != 0
        out = np.zeros((n, ni), bool)
        num = ~is_cat
        if num.any():
            vn = v[:, num]
            nan = np.isnan(vn)
            mt = _missing_from_decision(dt[num])
            vn = np.where(nan & (mt != MISSING_NAN), 0.0, vn)
            gl = vn <= self.threshold[num]
            defl = (dt[num] & _DEFAULT_LEFT_BIT) != 0
            # missing routes to the DEFAULT side: NaN under
            # MissingType::NaN, and |v| <= kZeroThreshold (1e-35,
            # incl. NaN folded to 0 above) under MissingType::Zero —
            # tree.h:359 NumericalDecision (a zero must NOT fall
            # through to the threshold compare)
            miss = ((nan & (mt == MISSING_NAN))
                    | ((np.abs(vn) <= 1e-35) & (mt == MISSING_ZERO)))
            out[:, num] = np.where(miss, defl, gl)
        for j in np.nonzero(is_cat)[0]:
            cat_idx = int(self.threshold[j])
            lo = self.cat_boundaries[cat_idx]
            hi = self.cat_boundaries[cat_idx + 1]
            words = np.asarray(self.cat_threshold[lo:hi], np.int64)
            vv = v[:, j]
            valid = ~np.isnan(vv) & (vv >= 0)
            c = np.where(valid, vv, 0).astype(np.int64)
            w = c >> 5
            ok = w < (hi - lo)
            bits = (words[np.clip(w, 0, max(hi - lo - 1, 0))]
                    >> (c & 31)) & 1
            out[:, j] = valid & ok & bits.astype(bool)
        return out

    def predict_contrib(self, X: np.ndarray,
                        row_chunk: int = 0) -> np.ndarray:
        """[n, num_features + 1] SHAP values (last column = expected
        value); vectorized TreeSHAP (see block comment above)."""
        if self.is_linear:
            raise NotImplementedError(
                "SHAP contributions are not supported for linear trees "
                "(matches the reference's restriction)")
        n, F = X.shape
        phi = np.zeros((n, F + 1))
        phi[:, -1] = self.expected_value()
        if self.num_leaves == 1:
            return phi
        (path_node, path_dir, slot_map, slot_feat, slot_zero, d_len,
         groups) = self._path_data()
        L, P = path_node.shape
        D = slot_feat.shape[1]
        go_left = self._go_left_all(X)                   # [n, NI]
        if row_chunk <= 0:
            row_chunk = max(1, (1 << 24) // max(L * (D + 1), 1))

        karr = d_len.astype(np.float64)[None, :, None]   # [1, L, 1]
        kp1 = karr + 1.0
        valid_slot = (np.arange(D)[None, :] < d_len[:, None])  # [L, D]
        w_idx = np.arange(D + 1, dtype=np.float64)
        leaf_val = self.leaf_value[None, :, None]        # [1, L, 1]

        for lo_r in range(0, n, row_chunk):
            sl = slice(lo_r, min(lo_r + row_chunk, n))
            c = sl.stop - sl.start
            # match per path position; padding positions always match
            m = go_left[sl][:, np.clip(path_node, 0, None)] \
                == (path_dir[None, :, :] != 0)           # [c, L, P]
            mism = (~m & (path_node >= 0)[None]).astype(np.float64)
            one = (np.einsum("clp,lpd->cld", mism, slot_map) == 0) \
                .astype(np.float64)                      # [c, L, D]
            # EXTEND: pw[p] <- zero*pw[p]*(m-p)/(m+1) + one*pw[p-1]*p/(m+1)
            pw = np.zeros((c, L, D + 1))
            pw[..., 0] = 1.0
            for step in range(1, D + 1):
                vmask = valid_slot[:, step - 1][None, :, None]  # [1, L, 1]
                o = one[:, :, step - 1][:, :, None]
                z = slot_zero[:, step - 1][None, :, None]
                shifted = np.concatenate(
                    [np.zeros((c, L, 1)), pw[..., :-1]], axis=2)
                new = (z * pw * np.maximum(step - w_idx, 0.0)
                       + o * shifted * w_idx) / (step + 1.0)
                pw = np.where(vmask, new, pw)
            # UNWIND totals per excluded slot i (vectorized over i)
            tmp = np.take_along_axis(
                pw, d_len[None, :, None].astype(np.intp), axis=2)
            tmp = np.broadcast_to(tmp, (c, L, D)).copy()
            total = np.zeros((c, L, D))
            one_b = one != 0
            with np.errstate(divide="ignore", invalid="ignore"):
                for j in range(D - 1, -1, -1):
                    active = (j < d_len)[None, :, None]
                    pwj = pw[:, :, j:j + 1]
                    t = tmp * kp1 / (j + 1.0)
                    total1 = total + t
                    tmp1 = pwj - t * slot_zero[None] * (karr - j) / kp1
                    total0 = total + pwj * kp1 / (slot_zero[None]
                                                  * (karr - j))
                    total = np.where(
                        active, np.where(one_b, total1, total0), total)
                    tmp = np.where(active & one_b, tmp1, tmp)
            contrib = np.where(
                valid_slot[None], total * (one - slot_zero[None]) * leaf_val,
                0.0)                                     # [c, L, D]
            for f, (ls, ss) in groups.items():
                phi[sl, f] += contrib[:, ls, ss].sum(axis=1)
        return phi

    def _tree_shap(self, gl_row, phi, node, p_zero, p_one, p_feat, path):
        # gl_row: [num_internal] bool — this row's decisions, precomputed
        # by _go_left_all so the missing/categorical semantics live in
        # exactly one place
        # path: list of [feat, zero_frac, one_frac, pweight]; elements are
        # deep-copied — EXTEND mutates weights and the hot/cold branches
        # must not see each other's updates
        path = [list(p) for p in path] + \
            [[p_feat, p_zero, p_one, 1.0 if len(path) == 0 else 0.0]]
        # EXTEND
        for i in range(len(path) - 2, -1, -1):
            path[i + 1][3] += p_one * path[i][3] * (i + 1) / len(path)
            path[i][3] = p_zero * path[i][3] * (len(path) - 1 - i) \
                / len(path)
        if node < 0:  # leaf
            leaf_val = self.leaf_value[~node]
            for i in range(1, len(path)):
                # UNWIND sum of pweights excluding element i
                total = 0.0
                onew, zerow = path[i][2], path[i][1]
                pw = list(p[3] for p in path)
                k = len(path) - 1
                tmp = pw[k]
                for j in range(k - 1, -1, -1):
                    if onew != 0:
                        t = tmp * (k + 1) / ((j + 1) * onew)
                        total += t
                        tmp = pw[j] - t * zerow * (k - j) / (k + 1)
                    else:
                        total += pw[j] / (zerow * (k - j) / (k + 1))
                phi[path[i][0]] += total * (onew - zerow) * leaf_val
            return
        hot, cold = ((self.left_child[node], self.right_child[node])
                     if gl_row[node]
                     else (self.right_child[node], self.left_child[node]))
        w = self._node_weight(node)
        hot_zero = self._node_weight(hot) / w if w > 0 else 0.0
        cold_zero = self._node_weight(cold) / w if w > 0 else 0.0
        f = int(self.split_feature[node])
        # if f already on path, unwind its previous occurrence
        incoming_zero, incoming_one = 1.0, 1.0
        prev = next((i for i in range(len(path))
                     if path[i][0] == f), None)
        if prev is not None:
            incoming_zero, incoming_one = path[prev][1], path[prev][2]
            path = self._unwind(path, prev)
        self._tree_shap(gl_row, phi, hot, incoming_zero * hot_zero,
                        incoming_one, f, path)
        self._tree_shap(gl_row, phi, cold, incoming_zero * cold_zero,
                        0.0, f, path)

    @staticmethod
    def _unwind(path, i):
        path = [list(p) for p in path]
        k = len(path) - 1
        onew, zerow = path[i][2], path[i][1]
        tmp = path[k][3]
        for j in range(k - 1, -1, -1):
            if onew != 0:
                t = tmp * (k + 1) / ((j + 1) * onew)
                tmp = path[j][3] - t * zerow * (k - j) / (k + 1)
                path[j][3] = t
            else:
                path[j][3] = path[j][3] * (k + 1) / (zerow * (k - j))
        for j in range(i, k):
            path[j][0] = path[j + 1][0]
            path[j][1] = path[j + 1][1]
            path[j][2] = path[j + 1][2]
        return path[:-1]

    # ------------------------------------------------------------------
    def to_json(self) -> Dict:
        """Tree dict in the reference's DumpModel schema
        (tree.cpp:411 ToJSON / NodeToJSON) — nested tree_structure with
        split/leaf records."""
        out = {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "tree_features": sorted(
                {int(f) for f in self.split_feature}),
        }
        if self.num_leaves == 1:
            out["tree_structure"] = {
                "leaf_value": float(self.leaf_value[0]),
                "leaf_count": int(self.leaf_count[0]),
            }
            return out

        def make_node(idx: int):
            if idx < 0:
                s = ~idx
                rec = {
                    "leaf_index": int(s),
                    "leaf_value": float(self.leaf_value[s]),
                    "leaf_weight": float(self.leaf_weight[s]),
                    "leaf_count": int(self.leaf_count[s]),
                }
                if self.is_linear:  # LinearModelToJSON (tree.cpp:446)
                    rec["leaf_const"] = float(self.leaf_const[s])
                    rec["leaf_features"] = [int(f) for f
                                            in self.leaf_features[s]]
                    rec["leaf_coeff"] = [float(c) for c
                                         in self.leaf_coeff[s]]
                return rec
            dt = int(self.decision_type[idx])
            rec = {
                "split_index": int(idx),
                "split_feature": int(self.split_feature[idx]),
                "split_gain": float(self.split_gain[idx]),
            }
            if dt & _CAT_BIT:
                cat_idx = int(self.threshold[idx])
                lo = self.cat_boundaries[cat_idx]
                hi = self.cat_boundaries[cat_idx + 1]
                cats = [c for c in range((hi - lo) * 32)
                        if (self.cat_threshold[lo + c // 32]
                            >> (c % 32)) & 1]
                rec["threshold"] = "||".join(str(c) for c in cats)
                rec["decision_type"] = "=="
            else:
                rec["threshold"] = float(self.threshold[idx])
                rec["decision_type"] = "<="
            rec["default_left"] = bool(dt & _DEFAULT_LEFT_BIT)
            rec["missing_type"] = \
                ("None", "Zero", "NaN", "NaN")[_missing_from_decision(dt)]
            rec["internal_value"] = float(self.internal_value[idx])
            rec["internal_weight"] = float(self.internal_weight[idx])
            rec["internal_count"] = int(self.internal_count[idx])
            return rec

        # explicit-stack tree walk: leaf-wise trees can be chain-shaped
        # (depth ~ num_leaves), far past Python's recursion limit
        root = make_node(0)
        stack = [(root, 0)]
        while stack:
            rec, idx = stack.pop()
            for key, child in (("left_child", int(self.left_child[idx])),
                               ("right_child", int(self.right_child[idx]))):
                crec = make_node(child)
                rec[key] = crec
                if child >= 0:
                    stack.append((crec, child))
        out["tree_structure"] = root
        return out

    def scale(self, factor: float):
        """Shrinkage(rate) (tree.h): rescale every output in place —
        DART normalization and rollback arithmetic."""
        self.leaf_value *= factor
        self.internal_value *= factor
        if self.is_linear:
            self.leaf_const *= factor
            self.leaf_coeff = [[c * factor for c in cs]
                               for cs in self.leaf_coeff]
        self.shrinkage *= factor
        return self

    def num_nodes(self) -> int:
        return 2 * self.num_leaves - 1

    def feature_importance_split(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features)
        np.add.at(out, self.split_feature, 1.0)
        return out

    def feature_importance_gain(self, num_features: int) -> np.ndarray:
        out = np.zeros(num_features)
        np.add.at(out, self.split_feature, self.split_gain)
        return out
