"""Canonical profiler/auditor phase names — ONE source of truth.

Three layers are coupled through these strings:

1. ``profiler.phase`` emits them as TraceAnnotation spans and
   ``jax.named_scope`` prefixes, so every XLA op staged under a phase
   carries ``<name>/`` in its HLO ``op_name`` metadata;
2. the collective-traffic auditor (``parallel/comms.py``) attributes
   histogram traffic by searching compiled-HLO op names for
   :data:`HIST_MERGE` / :data:`WINNER_SYNC`;
3. the trace doctor (``analysis/hlo_lint.py``) treats any sizeable
   collective whose op name carries NONE of a program's allowed phase
   tags as out-of-phase (rule TD103).

Before this module the names were retyped string literals in each
layer, so renaming a phase at an emission site silently broke the
auditors' attribution (they would just stop matching). Now the emission
side (``profiler.phase``) asserts membership in :data:`KNOWN_PHASES` at
annotation time, and every consumer imports the constant instead of
retyping it — a rename is a one-line change here or an immediate
ValueError, never a silent attribution miss.
"""

from __future__ import annotations

__all__ = ["GRADS", "SAMPLING", "BUILD", "UPDATE", "EVAL",
           "INGEST_SKETCH", "INGEST_WRITE", "PREFETCH",
           "HIST_MERGE", "WINNER_SYNC", "TRAIN_PHASES",
           "INGEST_PHASES", "COLLECTIVE_PHASES", "KNOWN_PHASES"]

# training phases (both drivers, boosting/gbdt.py + engine.train's eval)
GRADS = "grads"
SAMPLING = "sampling"
BUILD = "build"
UPDATE = "update"
EVAL = "eval"

# out-of-core ingest/streaming phases (data/ingest.py sketch + shard
# write passes; data/prefetch.py host->device staging during chunked
# training)
INGEST_SKETCH = "ingest_sketch"
INGEST_WRITE = "ingest_write"
PREFETCH = "prefetch"

# collective phases (ops/histogram.merge_histograms,
# boosting/tree_builder._sync_best) — these reach compiled HLO as
# op-name prefixes and carry the auditors' traffic attribution
HIST_MERGE = "hist_merge"
WINNER_SYNC = "winner_sync"

TRAIN_PHASES = frozenset({GRADS, SAMPLING, BUILD, UPDATE, EVAL})
INGEST_PHASES = frozenset({INGEST_SKETCH, INGEST_WRITE, PREFETCH})
COLLECTIVE_PHASES = frozenset({HIST_MERGE, WINNER_SYNC})
KNOWN_PHASES = TRAIN_PHASES | INGEST_PHASES | COLLECTIVE_PHASES
