"""Batched on-device prediction over raw feature matrices.

Analog of the reference batch predictor (``src/application/
predictor.hpp:30`` — OpenMP over rows, per-row tree walks;
``gbdt_prediction.cpp:13`` PredictRaw). TPU shape: the whole ensemble is
packed into ``[T, num_nodes]`` SoA arrays once per model state, and all
rows of all trees walk in lock-step — a ``lax.while_loop`` whose every
step is one vectorized gather+compare over the ``[rows, trees]`` lattice.
Host trees (reference numbering: child < 0 means ~leaf_index) are used
as-is; leaf values already include shrinkage.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PackedEnsemble", "pack_ensemble", "predict_raw_device",
           "predict_raw_device_early_stop"]


class PackedEnsemble(NamedTuple):
    split_feature: jax.Array   # [T, N] int32 (N = max internal nodes)
    threshold: jax.Array       # [T, N] f64->f32
    decision_type: jax.Array   # [T, N] int32
    left_child: jax.Array      # [T, N] int32
    right_child: jax.Array     # [T, N] int32
    leaf_value: jax.Array      # [T, L] f32
    cat_bound: jax.Array       # [T, C+1] int32 cat split word bounds
    cat_words: jax.Array       # [T, W] int32 bitset words
    num_leaves: jax.Array      # [T] int32
    depth: jax.Array           # [T] int32 max root-to-leaf depth


def _tree_depth(t) -> int:
    """Max root-to-leaf edge count (children always follow their parent
    in this writer's numbering, so one forward pass suffices)."""
    ni = t.num_leaves - 1
    if ni <= 0:
        return 0
    nd = np.zeros(ni, np.int64)
    mx = 1
    for n in range(ni):
        d = int(nd[n]) + 1
        for c in (int(t.left_child[n]), int(t.right_child[n])):
            if c >= 0:
                nd[c] = max(int(nd[c]), d)
            elif d > mx:
                mx = d
    return max(mx, int(nd.max()) + 1 if ni > 0 else 1)


def pack_ensemble(trees: List) -> PackedEnsemble:
    """Host Trees -> padded device SoA (one-time per model version)."""
    T = len(trees)
    N = max(max(t.num_leaves - 1, 1) for t in trees)
    L = max(t.num_leaves for t in trees)
    C = max(t.num_cat for t in trees) + 1
    W = max(max(len(t.cat_threshold), 1) for t in trees)

    sf = np.zeros((T, N), np.int32)
    thr = np.zeros((T, N), np.float32)
    dt = np.zeros((T, N), np.int32)
    lc = np.full((T, N), -1, np.int32)
    rc = np.full((T, N), -1, np.int32)
    lv = np.zeros((T, L), np.float32)
    cb = np.zeros((T, C + 1), np.int32)
    cw = np.zeros((T, W), np.int64)
    nl = np.zeros(T, np.int32)
    dep = np.zeros(T, np.int32)
    for i, t in enumerate(trees):
        ni = t.num_leaves - 1
        nl[i] = t.num_leaves
        dep[i] = _tree_depth(t)
        lv[i, :t.num_leaves] = t.leaf_value
        if ni <= 0:
            continue
        sf[i, :ni] = t.split_feature
        thr[i, :ni] = t.threshold
        dt[i, :ni] = t.decision_type
        lc[i, :ni] = t.left_child
        rc[i, :ni] = t.right_child
        cb[i, :len(t.cat_boundaries)] = t.cat_boundaries
        if t.cat_threshold:
            cw[i, :len(t.cat_threshold)] = t.cat_threshold
    return PackedEnsemble(*map(jnp.asarray,
                               (sf, thr, dt, lc, rc, lv, cb, cw, nl,
                                dep)))


def _walk(ens: PackedEnsemble, X: jax.Array) -> jax.Array:
    """[n, T] per-tree outputs for raw features X [n, F] (f32; NaN ok).

    Decision semantics mirror tree.h NumericalDecision /
    CategoricalDecision incl. missing types (bits 2-3) and default_left
    (bit 1) — the same rules as Tree._go_left_all on host.
    """
    n = X.shape[0]
    T = ens.split_feature.shape[0]
    W = ens.cat_words.shape[1]
    node = jnp.zeros((n, T), jnp.int32)     # >=0 internal; <0 => ~leaf
    single = (ens.num_leaves <= 1)[None, :]  # stump trees: leaf 0
    node = jnp.where(single, -1, node)       # ~0
    # depth clamp: the loop early-exits when every (row, tree) lane hits
    # a leaf, and is HARD-bounded by the ensemble's max root-to-leaf
    # depth computed at pack time — a corrupted pack (cycle) can stall
    # lanes but never hang the device walk
    dmax = jnp.max(ens.depth)

    def cond(state):
        node, active, it = state
        return jnp.any(active) & (it < dmax)

    def body(state):
        node, active, it = state
        nodec = jnp.clip(node, 0, ens.split_feature.shape[1] - 1)

        def take2(a):
            # a[t, nodec[r, t]] for all (r, t)
            return jax.vmap(lambda col, at: jnp.take(at, col),
                            in_axes=(1, 0), out_axes=1)(nodec, a)

        feat = take2(ens.split_feature)                     # [n, T]
        v = jnp.take_along_axis(X, jnp.clip(feat, 0, X.shape[1] - 1),
                                axis=1)                     # [n, T]
        dt = take2(ens.decision_type)
        thr = take2(ens.threshold)
        is_cat = (dt & 1) != 0
        nan = jnp.isnan(v)
        mt = (dt >> 2) & 3
        vz = jnp.where(nan & (mt != 2), 0.0, v)
        gl_num = vz <= thr
        defl = (dt & 2) != 0
        # missing -> default side: NaN under MissingType::NaN, and
        # |v| <= 1e-35 (incl. NaN folded to 0) under MissingType::Zero
        # (tree.h:359; zeros must NOT take the threshold compare)
        miss = ((nan & (mt == 2))
                | ((jnp.abs(vz) <= 1e-35) & (mt == 1)))
        gl_num = jnp.where(miss, defl, gl_num)
        # categorical: threshold holds the cat split index
        cat_idx = jnp.clip(thr.astype(jnp.int32), 0,
                           ens.cat_bound.shape[1] - 2)
        lo = jax.vmap(lambda col, at: jnp.take(at, col),
                      in_axes=(1, 0), out_axes=1)(cat_idx, ens.cat_bound)
        hi = jax.vmap(lambda col, at: jnp.take(at, col),
                      in_axes=(1, 0), out_axes=1)(cat_idx + 1,
                                                  ens.cat_bound)
        cval = jnp.where(nan | (v < 0), -1, v).astype(jnp.int32)
        word = jnp.clip(lo + (cval >> 5), 0, W - 1)
        wv = jax.vmap(lambda col, at: jnp.take(at, col),
                      in_axes=(1, 0), out_axes=1)(word, ens.cat_words)
        in_set = ((wv >> (cval & 31)) & 1) == 1
        gl_cat = (cval >= 0) & (lo + (cval >> 5) < hi) & in_set
        go_left = jnp.where(is_cat, gl_cat, gl_num)

        nxt = jnp.where(go_left, take2(ens.left_child),
                        take2(ens.right_child))
        node = jnp.where(active, nxt, node)
        return node, node >= 0, it + 1

    node, _, _ = jax.lax.while_loop(
        cond, body, (node, node >= 0, jnp.asarray(0, jnp.int32)))
    leaf = jnp.clip(~node, 0, ens.leaf_value.shape[1] - 1)
    out = jax.vmap(lambda col, at: jnp.take(at, col),
                   in_axes=(1, 0), out_axes=1)(leaf, ens.leaf_value)
    return out


predict_raw_device = jax.jit(_walk)


@functools.partial(jax.jit, static_argnames=("K", "freq"))
def predict_raw_device_early_stop(ens: PackedEnsemble, X: jax.Array,
                                  margin: jax.Array, *, K: int,
                                  freq: int) -> jax.Array:
    """[n, K] accumulated raw scores with prediction early stopping
    (PredictionEarlyStopInstance, prediction_early_stop.cpp:91, driven
    by GBDT::PredictRaw's round counter, gbdt_prediction.cpp:13-31).

    TPU shape: per-ROW early exit cannot skip SIMD lanes, so the stop is
    chunk-granular — a while_loop over blocks of ``freq`` iterations
    (``freq * K`` trees) that exits when EVERY row has cleared the
    margin. Done rows freeze (their remaining trees are skipped exactly
    like the reference's per-row break); the wall-clock win appears once
    all rows in the batch are confident. K == 1 uses the binary margin
    2*|raw|, K > 1 the multiclass top1-top2 margin.
    """
    n = X.shape[0]
    T = ens.split_feature.shape[0]
    chunk = K * freq
    n_chunks = -(-T // chunk)
    pad = n_chunks * chunk - T
    if pad > 0:
        # stump padding: num_leaves=1 routes to leaf 0 with value 0
        def padt(a, fill=0):
            return jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                           constant_values=fill)
        ens = PackedEnsemble(
            padt(ens.split_feature), padt(ens.threshold),
            padt(ens.decision_type), padt(ens.left_child, -1),
            padt(ens.right_child, -1), padt(ens.leaf_value),
            padt(ens.cat_bound), padt(ens.cat_words),
            padt(ens.num_leaves, 1), padt(ens.depth))
    # tree i of every chunk belongs to class i % K (trees are stored
    # iteration-major, and chunks hold whole iterations)
    cls_oh = (jnp.arange(chunk, dtype=jnp.int32)[:, None] % K
              == jnp.arange(K, dtype=jnp.int32)[None, :]).astype(
        jnp.float32)

    def cond(st):
        c, _, done = st
        return (c < n_chunks) & ~jnp.all(done)

    def body(st):
        c, raw, done = st
        sub = PackedEnsemble(*[
            jax.lax.dynamic_slice_in_dim(a, c * chunk, chunk, axis=0)
            for a in ens])
        add = _walk(sub, X) @ cls_oh                      # [n, K]
        raw = raw + jnp.where(done[:, None], 0.0, add)
        if K == 1:
            m = 2.0 * jnp.abs(raw[:, 0])
        else:
            top2, _ = jax.lax.top_k(raw, 2)
            m = top2[:, 0] - top2[:, 1]
        return c + 1, raw, done | (m > margin)

    state = (jnp.asarray(0, jnp.int32),
             jnp.zeros((n, K), jnp.float32),
             jnp.zeros((n,), bool))
    _, raw, _ = jax.lax.while_loop(cond, body, state)
    return raw
