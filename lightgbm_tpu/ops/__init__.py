"""Compute ops: histogram, split finding, prediction kernels."""
