"""On-device tree traversal over binned rows.

Analog of the reference prediction path (``src/boosting/gbdt_prediction.cpp``
``PredictRaw`` :13, ``include/LightGBM/tree.h:135`` ``Tree::Predict``) for
trees still in device (TreeArrays) form — used by DART's drop/restore score
arithmetic, continued training (init_model), refit and rollback, where the
framework needs past trees' per-row outputs without leaving the device.

TPU design: the reference walks pointers per row; here all rows walk the
node SoA in lock-step — each level is one vectorized gather + compare over
[R] rows, a ``lax.while_loop`` until every row parks at a leaf. The
feature-value lookup uses the same one-hot multiply-reduce trick as the
tree builder (no serializing dynamic gather on the lane axis).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["predict_bins_leaf", "predict_bins_value", "row_feature_gather"]


def row_feature_gather(bins: jax.Array, feat: jax.Array) -> jax.Array:
    """bins[r, feat[r]] without a dynamic gather: one-hot multiply-reduce
    keeps the VPU busy instead of serializing on gathers. Shared by the
    tree builder's partition step and prediction traversal — the decision
    semantics must stay bit-identical between them.

    The select/reduce runs in the bin matrix's own dtype (exact: at most
    one non-zero per row survives the select, so a uint8 accumulator
    cannot wrap) — widening to int32 FIRST would stream the whole [R, F]
    matrix at 4x the bytes every round, and hoist a full-matrix convert
    out of the tree loop (measured 2x28 ms per iteration at 1M rows)."""
    F = bins.shape[1]
    sel = jnp.arange(F, dtype=jnp.int32)[None, :] == feat[:, None]
    picked = jnp.where(sel, bins, jnp.zeros((), bins.dtype))
    return picked.sum(axis=1, dtype=bins.dtype).astype(jnp.int32)


@jax.jit
def predict_bins_leaf(split_feature: jax.Array, threshold_bin: jax.Array,
                      default_left: jax.Array, is_cat: jax.Array,
                      left_child: jax.Array, right_child: jax.Array,
                      cat_bitset: jax.Array, nan_bin_pf: jax.Array,
                      bins: jax.Array,
                      bundle_meta=None, num_bins_pf=None) -> jax.Array:
    """Node index where each binned row lands (NumericalDecision /
    CategoricalDecision walk of tree.h, vectorized over rows).

    Tree arrays are in builder (TreeArrays) numbering: ``split_feature``
    holds -1 at leaves; children are node ids in the same arrays.
    ``cat_bitset`` [N, BW] holds the bin-space LEFT subset of categorical
    splits. Returns [R] int32 node ids of leaves.
    """
    R = bins.shape[0]
    BW = cat_bitset.shape[1]
    node = jnp.zeros((R,), jnp.int32)

    def cond(state):
        node, active = state
        return jnp.any(active)

    def body(state):
        node, _ = state
        feat = jnp.take(split_feature, node)
        internal = feat >= 0
        featc = jnp.maximum(feat, 0)
        if bundle_meta is not None:
            # EFB decode: bundle column -> this feature's own bin
            from ..efb import decode_feature_bins
            b_gof, b_off, b_mfb = bundle_meta
            raw = row_feature_gather(bins, jnp.take(b_gof, featc))
            binv = decode_feature_bins(
                raw, jnp.take(b_off, featc),
                jnp.take(num_bins_pf, featc), jnp.take(b_mfb, featc),
                xp=jnp)
        else:
            binv = row_feature_gather(bins, featc)
        thr = jnp.take(threshold_bin, node)
        nb = jnp.take(nan_bin_pf, featc)
        isnan = (binv == nb) & (nb >= 0)
        cat = jnp.take(is_cat, node)
        # categorical membership: bitset word select + bit test
        word = binv >> 5
        rbits = jnp.take(cat_bitset, node, axis=0)               # [R, BW]
        wsel = jnp.arange(BW, dtype=jnp.int32)[None, :] == word[:, None]
        wval = jnp.sum(jnp.where(wsel, rbits, jnp.uint32(0)), axis=1)
        in_set = ((wval >> (binv & 31).astype(jnp.uint32))
                  & jnp.uint32(1)) == 1
        go_left = jnp.where(cat, in_set, binv <= thr)
        go_left = jnp.where(isnan & ~cat,
                            jnp.take(default_left, node), go_left)
        nxt = jnp.where(go_left, jnp.take(left_child, node),
                        jnp.take(right_child, node))
        node = jnp.where(internal, nxt, node)
        still = jnp.take(split_feature, node) >= 0
        return node, still

    node, _ = jax.lax.while_loop(
        cond, body, (node, jnp.take(split_feature, node) >= 0))
    return node


def predict_bins_value(tree, nan_bin_pf: jax.Array, bins: jax.Array,
                       bundle_meta=None, num_bins_pf=None) -> jax.Array:
    """Per-row unshrunk leaf output of one device tree ([R] f32)."""
    leaf_node = predict_bins_leaf(
        tree.split_feature, tree.threshold_bin, tree.default_left,
        tree.is_cat, tree.left_child, tree.right_child, tree.cat_bitset,
        nan_bin_pf, bins, bundle_meta=bundle_meta,
        num_bins_pf=num_bins_pf)
    return jnp.take(tree.node_value, leaf_node)
