"""On-device value->bin mapping for dense numerical matrices.

The reference bins features on the host with multithreaded C++
(``DatasetLoader::ExtractFeaturesFromMemory`` -> ``BinMapper::ValueToBin``,
``bin.h:173``); our host path is vectorized NumPy ``searchsorted`` per
feature (binning.py). At Higgs scale (10.5M x 28) that host pass is a
visible slice of end-to-end time, so this module runs the same mapping
as ONE jitted vmapped ``searchsorted`` over a padded ``[F, B]``
upper-bound matrix on the accelerator — the natural TPU home for a
[rows x features] data-parallel transform.

Numerics: the device path compares in float32 (TPUs have no fast f64),
the host path in float64. A raw value within f32 eps of a bin boundary
can land one bin over vs the host path; boundaries are midpoints
between distinct sample values, so this only affects values
pathologically close to a boundary. The CPU/golden test paths keep the
host mapper; the device path is used on accelerators (or when
``LIGHTGBM_TPU_DEVICE_BIN=1`` forces it, as the parity tests do).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["device_bin_dense", "want_device_binning"]


def want_device_binning(num_rows: int, num_features: int) -> bool:
    """Opt-in only (LIGHTGBM_TPU_DEVICE_BIN=1): the device kernel bins
    in f32, so a value within f32 eps of a bin boundary can land in a
    different bin than the host f64 path gives — the same dataset would
    silently train differently on accelerator vs CPU hosts. The host
    path is the reproducible default; flip it on for throwaway/bench
    runs where binning wall-time matters more than bit-reproducibility
    (=1 forces the device kernel on any backend — the parity tests
    rely on that)."""
    return os.environ.get("LIGHTGBM_TPU_DEVICE_BIN") == "1"


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def _bin_kernel(vals, ubounds, nan_dest, out_dtype="uint8"):
    """vals [R, F] f32, ubounds [F, B] (+inf padded), nan_dest [F] int32
    -> [R, F] bins."""
    nan_mask = jnp.isnan(vals)
    x = jnp.where(nan_mask, 0.0, vals)
    bins = jax.vmap(
        lambda ub, col: jnp.searchsorted(ub, col, side="left"),
        in_axes=(0, 1), out_axes=1)(ubounds, x)
    bins = jnp.where(nan_mask, nan_dest[None, :], bins)
    return bins.astype(out_dtype)


def device_bin_dense(data: np.ndarray, mappers: List,
                     used_features: np.ndarray,
                     out_dtype) -> Optional[np.ndarray]:
    """[R, F_total] raw floats -> [R, F_used] bins. Categorical
    columns are binned by the host mapper (exact dict lookup); the
    numerical block rides the device kernel. Returns None when the f32
    cast cannot represent the data (|values| or bounds beyond f32 max —
    the host f64 path must handle those)."""
    num_pos, num_feat = [], []
    for j, f in enumerate(used_features):
        if mappers[f].bin_type != "categorical":
            num_pos.append(j)
            num_feat.append(int(f))
    if not num_pos:
        return None
    ubs = []
    nan_dest = []
    f32_max = np.finfo(np.float32).max
    for f in num_feat:
        m = mappers[f]
        ub = np.asarray(m.bin_upper_bound, np.float64)
        if np.any(np.abs(ub[np.isfinite(ub)]) > f32_max):
            return None
        ubs.append(ub)
        nan_dest.append(m.nan_bin if m.nan_bin >= 0 else m.default_bin)
    B = max(len(u) for u in ubs)
    ub_mat = np.full((len(ubs), B), np.inf, np.float64)
    for i, u in enumerate(ubs):
        ub_mat[i, :len(u)] = u
    # fill column-by-column: fancy-indexing the f64 matrix first would
    # allocate a full-size f64 copy before the f32 cast
    R = data.shape[0]
    cols = np.empty((R, len(num_feat)), np.float32)
    finite_ok = True
    for i, f in enumerate(num_feat):
        c = np.asarray(data[:, f], np.float64)
        if np.any(np.abs(c[np.isfinite(c)]) > f32_max):
            finite_ok = False
            break
        cols[:, i] = c
    if not finite_ok:
        return None
    out_block = np.asarray(_bin_kernel(
        jnp.asarray(cols), jnp.asarray(ub_mat, jnp.float32),
        jnp.asarray(nan_dest, jnp.int32),
        out_dtype=np.dtype(out_dtype).name))
    if len(num_pos) == len(used_features):
        return out_block
    out = np.empty((R, len(used_features)), np.dtype(out_dtype))
    out[:, num_pos] = out_block
    for j, f in enumerate(used_features):
        if j not in set(num_pos):
            out[:, j] = mappers[f].values_to_bins(
                np.asarray(data[:, f], np.float64)).astype(out_dtype)
    return out
