"""Best-split search over histograms.

TPU-native analog of the reference split finder (LightGBM
``src/treelearner/feature_histogram.hpp:165`` ``FindBestThreshold``,
``feature_histogram.cpp:120-360`` categorical,
``cuda/cuda_best_split_finder.cu``): for each (leaf, feature) scan bin
thresholds in both missing-direction variants and keep the max-gain split.

Design: the reference scans each histogram twice (missing-left /
missing-right) in scalar loops. Here the whole search is one vectorized
cumsum + gain evaluation over a dense [leaves, features, bins, 2] lattice —
an argmax XLA reduces on-device; no data-dependent control flow.

Gain math mirrors feature_histogram.hpp exactly (output-based form, so
constraints compose):
  ThresholdL1(s, l1)  = sign(s) * max(|s| - l1, 0)
  output(G, H)        = -ThresholdL1(G) / (H + l2), clipped by
                        max_delta_step, smoothed toward the parent output
                        when path_smooth > 0 (CalculateSplittedLeafOutput,
                        feature_histogram.hpp:717-756), clamped into the
                        leaf's monotone [lo, hi] range (BasicConstraint)
  gain_given_output   = -(2*ThresholdL1(G)*w + (H + l2)*w^2)
                        (GetLeafGainGivenOutput, feature_histogram.hpp:820)
  split_gain          = gain(left) + gain(right); 0 if the two outputs
                        violate the split feature's monotone direction
                        (GetSplitGains, feature_histogram.hpp:760-798)
  net gain            = split_gain - parent_gain - min_gain_to_split,
                        multiplied by the monotone depth penalty when the
                        split feature is constrained
                        (ComputeMonotoneSplitGainPenalty,
                        monotone_constraints.hpp:357-366)
Validity: counts >= min_data_in_leaf, hessians >= min_sum_hessian_in_leaf
on both sides; net gain must be positive (the reference's
``current_gain <= min_gain_shift`` rejection).

Categorical features with few bins use the one-hot path (bin == t goes
left) with plain lambda_l2 — feature_histogram.cpp:172-238 applies cat_l2
only on the sorted-subset branch (see ops/cat_split.py).

Extra-trees mode evaluates one random threshold per (leaf, feature)
(``rand_threshold``, feature_histogram.hpp:202-205); per-node feature
sampling and interaction constraints arrive pre-baked in ``feature_mask``.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["SplitParams", "find_best_splits", "leaf_output", "leaf_gain",
           "gain_given_output", "calc_output", "monotone_penalty_factor",
           "eval_split_lattice", "pack_member_bitset"]

NEG_INF = -jnp.inf
K_EPS = 1e-15


class SplitParams(NamedTuple):
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    monotone_penalty: float = 0.0
    extra_trees: bool = False
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: float = 100.0


def _threshold_l1(s, l1):
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g, h, l1, l2):
    t = _threshold_l1(g, l1)
    return jnp.where(h + l2 > 0, t * t / (h + l2), 0.0)


def leaf_output(g, h, l1, l2, max_delta_step=0.0):
    out = jnp.where(h + l2 > 0, -_threshold_l1(g, l1) / (h + l2), 0.0)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def calc_output(g, h, l1, l2, max_delta_step=0.0, path_smooth=0.0,
                count=None, parent_output=None):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:717-740):
    raw regularized output, max_delta_step clip, then path smoothing
    toward the parent's output weighted by leaf count."""
    out = leaf_output(g, h, l1, l2, max_delta_step)
    if path_smooth > 0.0:
        sm = count / path_smooth
        out = out * sm / (sm + 1.0) + parent_output / (sm + 1.0)
    return out


def gain_given_output(g, h, l1, l2, out):
    """GetLeafGainGivenOutput (feature_histogram.hpp:820-831)."""
    t = _threshold_l1(g, l1)
    return -(2.0 * t * out + (h + l2) * out * out)


def monotone_penalty_factor(depth, penalization):
    """ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:357-366)."""
    depth = depth.astype(jnp.float32)
    pen_le1 = 1.0 - penalization / jnp.exp2(depth) + K_EPS
    pen_gt1 = 1.0 - jnp.exp2(penalization - 1.0 - depth) + K_EPS
    pen = jnp.where(penalization <= 1.0, pen_le1, pen_gt1)
    return jnp.where(penalization >= depth + 1.0, K_EPS, pen)


def pack_member_bitset(member: jax.Array) -> jax.Array:
    """Pack a [L, B] bin-membership mask into uint32 words (tree.h cat
    bitset layout). Shared by `find_best_splits` and the fused-kernel
    postlude in ops/pallas_histogram.py."""
    L, B = member.shape
    BW = (B + 31) // 32
    pad = BW * 32 - B
    member_p = jnp.pad(member, ((0, 0), (0, pad)))
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(
        member_p.reshape(L, BW, 32).astype(jnp.uint32) * weights[None, None],
        axis=2, dtype=jnp.uint32)


def eval_split_lattice(hist: jax.Array, num_bins_per_feat: jax.Array,
                       nan_bin: jax.Array, is_cat: jax.Array,
                       params: SplitParams,
                       feature_mask: Optional[jax.Array] = None,
                       mono_type: Optional[jax.Array] = None,
                       leaf_lo: Optional[jax.Array] = None,
                       leaf_hi: Optional[jax.Array] = None,
                       parent_output: Optional[jax.Array] = None,
                       mono_pen: Optional[jax.Array] = None,
                       rand_bin: Optional[jax.Array] = None,
                       cat_sorted_mask: Optional[jax.Array] = None,
                       gain_scale: Optional[jax.Array] = None,
                       gain_penalty: Optional[jax.Array] = None,
                       adv_bounds: Optional[tuple] = None,
                       quant_scales: Optional[jax.Array] = None
                       ) -> Dict[str, jax.Array]:
    """Dense gain-lattice evaluation shared by `find_best_splits` and the
    fused Pallas epilogue (ops/pallas_histogram.py) — everything up to but
    excluding the argmax, so a per-chunk kernel invocation can run the
    same math on a VMEM-resident histogram block.

    Same operands/semantics as `find_best_splits` except:
      mono_pen: optional [L] f32 — precomputed
        `monotone_penalty_factor(slot_depth, params.monotone_penalty)`
        (the depth→penalty map is the caller's job here since the kernel
        epilogue streams depths in as a metadata row).
      quant_scales: optional [2] or [L, 2] f32 — (g_scale, h_scale) for
        int8-quantized training. When given, `hist` holds raw int32
        accumulator sums; prefix scans run EXACTLY in integers and the
        cumulative sums are rescaled to f32 grid values only at gain
        time (the ISSUE-14 exact-scan path; contrast the legacy two-pass
        flow which dequantizes the full histogram first).

    Returns dict: net [L,F,B,2] (NEG_INF where invalid), left/right
    [L,F,B,2,3] (f32 grid values), out_l/out_r [L,F,B,2], pg [L,F],
    totals [L,F,3] (f32 grid values), is_cat2 [M,F].
    """
    L, F, B, _ = hist.shape
    l1, l2 = params.lambda_l1, params.lambda_l2
    mds = params.max_delta_step
    use_mono = mono_type is not None
    use_smooth = params.path_smooth > 0.0
    bins_iota = jnp.arange(B, dtype=jnp.int32)

    def _2d(a):
        return a if a is None or a.ndim == 2 else a[None, :]

    nbpf = _2d(num_bins_per_feat)                              # [M, F]
    nan2 = _2d(nan_bin)
    cat2 = _2d(is_cat)
    mono2 = _2d(mono_type) if use_mono else None

    has_nan = nan2 >= 0                                        # [M, F]
    # zero out the nan bin so cumsums cover non-missing rows only
    nan_mask = ((bins_iota[None, None, :] == nan2[:, :, None])
                & has_nan[:, :, None])                         # [M, F, B]
    hist_nonan = jnp.where(nan_mask[:, :, :, None],
                           jnp.zeros((), hist.dtype), hist)
    nan_sum = (hist * nan_mask[:, :, :, None]).sum(axis=2)     # [L, F, 3]

    totals = hist_nonan.sum(axis=2) + nan_sum                  # [L, F, 3]
    cum = jnp.cumsum(hist_nonan, axis=2)                       # [L, F, B, 3]

    # ---- numerical thresholds: left = {bin <= t}, two missing directions
    # option 0: missing right (default_left=False); option 1: missing left
    gl0 = cum
    gl1 = cum + nan_sum[:, :, None, :]
    tot = totals[:, :, None, :]
    num_left = jnp.stack([gl0, gl1], axis=3)                   # [L,F,B,2,3]
    num_right = tot[:, :, :, None, :] - num_left

    nnb = nbpf - has_nan.astype(jnp.int32)                     # non-nan bins
    t_valid = bins_iota[None, None, :] < (nnb[:, :, None] - 1)  # [M, F, B]
    # when the feature has no nan, option 1 duplicates option 0 — mask it
    opt_valid = jnp.stack(
        [jnp.ones_like(has_nan), has_nan], axis=-1)            # [M, F, 2]
    num_valid = (t_valid[:, :, :, None] & opt_valid[:, :, None, :]
                 & (~cat2)[:, :, None, None])                  # [M, F, B, 2]

    # ---- categorical one-hot: left = {bin == t}; sorted-path features are
    # excluded here (reference picks ONE path by bin count, not best-of-both)
    onehot_f = (cat2 & ~_2d(cat_sorted_mask)) \
        if cat_sorted_mask is not None else cat2
    cat_left = hist[:, :, :, None, :]                           # reuse lattice
    cat_right = tot[:, :, :, None, :] - cat_left
    cat_ok = ((bins_iota[None, None, :] < nnb[:, :, None])
              & onehot_f[:, :, None])                          # [M, F, B]
    # option-0 selector built from an iota (not a literal [True, False]
    # constant) so the Pallas kernel epilogue can trace this body —
    # pallas_call rejects captured array constants
    opt0 = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 2), 3) == 0
    cat_valid = cat_ok[:, :, :, None] & opt0

    catsel = cat2[:, :, None, None, None]
    left = jnp.where(catsel, cat_left, num_left)
    right = jnp.where(catsel, cat_right, num_right)
    valid = jnp.where(cat2[:, :, None, None], cat_valid, num_valid)
    if rand_bin is not None:  # extra_trees: one threshold per (leaf, feat)
        valid = valid & (bins_iota[None, None, :, None]
                         == rand_bin[:, :, None, None])

    if quant_scales is not None:
        # exact integer scan → grid-value rescale at gain time; the count
        # channel scales by 1 so min_data thresholds stay exact
        qs = quant_scales.astype(jnp.float32)
        if qs.ndim == 1:
            qv = jnp.concatenate([qs, jnp.ones((1,), jnp.float32)])
            left = left.astype(jnp.float32) * qv
            right = right.astype(jnp.float32) * qv
            totals = totals.astype(jnp.float32) * qv
        else:                                                  # [L, 2]
            qv = jnp.concatenate(
                [qs, jnp.ones((qs.shape[0], 1), jnp.float32)], axis=1)
            left = left.astype(jnp.float32) * qv[:, None, None, None, :]
            right = right.astype(jnp.float32) * qv[:, None, None, None, :]
            totals = totals.astype(jnp.float32) * qv[:, None, :]

    gL, hL, nL = left[..., 0], left[..., 1], left[..., 2]
    gR, hR, nR = right[..., 0], right[..., 1], right[..., 2]

    # one-hot categorical uses plain l2 (feature_histogram.cpp:178 — cat_l2
    # applies only to sorted-subset splits)
    sm_kw_l = {}
    sm_kw_r = {}
    if use_smooth:
        po = parent_output[:, None, None, None]
        sm_kw_l = dict(path_smooth=params.path_smooth, count=nL,
                       parent_output=po)
        sm_kw_r = dict(path_smooth=params.path_smooth, count=nR,
                       parent_output=po)
    out_l = calc_output(gL, hL, l1, l2, mds, **sm_kw_l)
    out_r = calc_output(gR, hR, l1, l2, mds, **sm_kw_r)
    if adv_bounds is not None:
        a_lo_l, a_hi_l, a_lo_r, a_hi_r = adv_bounds
        out_l = jnp.clip(out_l, a_lo_l[:, :, :, None],
                         a_hi_l[:, :, :, None])
        out_r = jnp.clip(out_r, a_lo_r[:, :, :, None],
                         a_hi_r[:, :, :, None])
    elif use_mono:
        lo = leaf_lo[:, None, None, None]
        hi = leaf_hi[:, None, None, None]
        out_l = jnp.clip(out_l, lo, hi)
        out_r = jnp.clip(out_r, lo, hi)

    gain = (gain_given_output(gL, hL, l1, l2, out_l)
            + gain_given_output(gR, hR, l1, l2, out_r))
    if use_mono:
        mt = mono2[:, :, None, None]
        viol = (((mt > 0) & (out_l > out_r)) | ((mt < 0) & (out_l < out_r)))
        gain = jnp.where(viol, 0.0, gain)  # GetSplitGains returns 0

    md, mh = params.min_data_in_leaf, params.min_sum_hessian_in_leaf
    ok = (valid & (nL >= md) & (nR >= md) & (hL >= mh) & (hR >= mh))

    # parent gain (gain_shift, BeforeNumerical feature_histogram.hpp:198):
    # plain l2 for every feature (the categorical comment at
    # feature_histogram.cpp:164-166 — min_split_gain uses the original l2)
    g_tot, h_tot, n_tot = totals[..., 0], totals[..., 1], totals[..., 2]
    if use_smooth:
        # numerical: output smoothed toward the slot's own current output;
        # categorical: gain at the current output directly
        # (feature_histogram.cpp:160-166)
        p_out_num = calc_output(g_tot, h_tot, l1, l2, mds,
                                params.path_smooth, n_tot,
                                parent_output[:, None])
        p_out = jnp.where(cat2, parent_output[:, None], p_out_num)
        pg = gain_given_output(g_tot, h_tot, l1, l2, p_out)
    elif mds > 0.0:
        p_out = calc_output(g_tot, h_tot, l1, l2, mds)
        pg = gain_given_output(g_tot, h_tot, l1, l2, p_out)
    else:
        pg = leaf_gain(g_tot, h_tot, l1, l2)                    # [L, F]

    net = gain - pg[:, :, None, None] - params.min_gain_to_split
    net = jnp.where(ok & (net > 1e-10), net, NEG_INF)

    if use_mono and params.monotone_penalty > 0.0:
        mt = mono2[:, :, None, None]
        net = jnp.where(mt != 0, net * mono_pen[:, None, None, None], net)

    if gain_scale is not None:
        gs2 = gain_scale if gain_scale.ndim == 2 else gain_scale[None, :]
        net = jnp.where(jnp.isfinite(net),
                        net * gs2[:, :, None, None], net)
    if gain_penalty is not None:
        net = jnp.where(jnp.isfinite(net),
                        net - gain_penalty[:, :, None, None], net)
    if gain_scale is not None or gain_penalty is not None:
        # scaled/penalized gains that dropped to <= 0 are no longer
        # splittable (the reference stops on gain <= 0 downstream)
        net = jnp.where(net > 1e-10, net, NEG_INF)

    if feature_mask is not None:
        fm = (feature_mask[None, :] if feature_mask.ndim == 1
              else feature_mask)                                # [L, F]
        net = jnp.where(fm[:, :, None, None], net, NEG_INF)

    return {"net": net, "left": left, "right": right,
            "out_l": out_l, "out_r": out_r, "pg": pg,
            "totals": totals, "is_cat2": cat2}


def find_best_splits(hist: jax.Array, num_bins_per_feat: jax.Array,
                     nan_bin: jax.Array, is_cat: jax.Array,
                     params: SplitParams,
                     feature_mask: Optional[jax.Array] = None,
                     mono_type: Optional[jax.Array] = None,
                     leaf_lo: Optional[jax.Array] = None,
                     leaf_hi: Optional[jax.Array] = None,
                     parent_output: Optional[jax.Array] = None,
                     slot_depth: Optional[jax.Array] = None,
                     rand_bin: Optional[jax.Array] = None,
                     cat_sorted_mask: Optional[jax.Array] = None,
                     return_feature_gain: bool = False,
                     gain_scale: Optional[jax.Array] = None,
                     gain_penalty: Optional[jax.Array] = None,
                     adv_bounds: Optional[tuple] = None,
                     quant_scales: Optional[jax.Array] = None
                     ) -> Dict[str, jax.Array]:
    """Vectorized best split per leaf.

    Args:
      hist: [L, F, B, 3] (sum_grad, sum_hess, count) per (leaf, feature, bin).
      num_bins_per_feat: [F] or [L, F] int32 — valid bins per feature
        (<= B). All per-feature metadata below likewise accepts a
        per-slot [L, F] form — the voting-parallel learner's per-leaf
        elected feature subsets remap columns per slot.
      nan_bin: [F] or [L, F] int32 — NaN bin index, -1 if none.
      is_cat: [F] or [L, F] bool — categorical feature flags.
      params: SplitParams.
      feature_mask: optional [F] or [L, F] bool — candidate features,
        applied BEFORE the argmax (per-tree sampling, per-node sampling,
        interaction constraints).
      mono_type: optional [F] or [L, F] int32 in {-1, 0, 1}.
      leaf_lo / leaf_hi: optional [L] f32 — per-leaf output bounds
        (BasicConstraint of monotone_constraints.hpp).
      parent_output: optional [L] f32 — each slot's current output
        (unshrunk), required when path_smooth > 0.
      slot_depth: optional [L] int32 — leaf depth, for monotone_penalty.
      rand_bin: optional [L, F] int32 — extra-trees random threshold;
        only this bin is evaluated per (leaf, feature).
      cat_sorted_mask: optional [F] or per-slot [L, F] bool —
        categorical features with more than max_cat_to_onehot bins;
        they take the sorted-subset path (ops/cat_split.py) instead of
        one-hot (voting-parallel passes the per-slot elected form).
      return_feature_gain: also return "feature_gain" [L, F] — the best
        net gain per (leaf, feature) — for voting-parallel vote rounds.
      gain_scale: optional [F] or [L, F] f32 — multiplies each feature's
        net gain (feature_contri, feature_histogram.hpp:174
        ``output->gain *= meta_->penalty``).
      gain_penalty: optional [L, F] f32 — subtracted from each feature's
        net gain AFTER scaling (CEGB DeltaGain,
        cost_effective_gradient_boosting.hpp:80-98).
      adv_bounds: optional (lo_l, hi_l, lo_r, hi_r), each [L, F, B] f32
        — monotone_constraints_method=advanced per-candidate output
        bounds (AdvancedConstraintEntry's per-threshold-segment
        constraints, monotone_constraints.hpp:858, in dense lattice
        form). When given, they replace the scalar leaf_lo/leaf_hi clip
        for the threshold lattice; leaf_lo/leaf_hi (scalars, computed by
        the caller for whole-leaf adjacency) still drive the sorted-cat
        path.

    Returns dict with per-leaf arrays:
      gain [L] — NET gain (split - parent - min_gain_to_split, penalized;
        -inf when no valid split), feature [L], threshold [L],
      default_left [L] bool, left_sum/right_sum [L, 3],
      left_out/right_out [L] (constrained outputs), is_cat_split [L],
      cat_bitset [L, ceil(B/32)] uint32 — bin-space LEFT subset for
        categorical winners (single bit for one-hot).

    quant_scales: optional [2] or [L, 2] f32 (g_scale, h_scale) — when
    given, `hist` holds raw int32 quantized accumulator sums and the scan
    runs exactly in integers with a grid-value rescale at gain time (see
    `eval_split_lattice`). Incompatible with `cat_sorted_mask` (the
    sorted-cat path expects dequantized histograms).
    """
    L, F, B, _ = hist.shape
    if quant_scales is not None and cat_sorted_mask is not None:
        raise ValueError("quant_scales is incompatible with cat_sorted_mask")
    mono_pen = None
    if mono_type is not None and params.monotone_penalty > 0.0:
        mono_pen = monotone_penalty_factor(slot_depth,
                                           params.monotone_penalty)
    lat = eval_split_lattice(
        hist, num_bins_per_feat, nan_bin, is_cat, params,
        feature_mask=feature_mask, mono_type=mono_type,
        leaf_lo=leaf_lo, leaf_hi=leaf_hi, parent_output=parent_output,
        mono_pen=mono_pen, rand_bin=rand_bin,
        cat_sorted_mask=cat_sorted_mask, gain_scale=gain_scale,
        gain_penalty=gain_penalty, adv_bounds=adv_bounds,
        quant_scales=quant_scales)
    net, left, right = lat["net"], lat["left"], lat["right"]
    out_l, out_r, pg, cat2 = (lat["out_l"], lat["out_r"], lat["pg"],
                              lat["is_cat2"])
    bins_iota = jnp.arange(B, dtype=jnp.int32)

    # ---- argmax over (F, B, 2) per leaf
    flat = net.reshape(L, F * B * 2)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feat = (best // (B * 2)).astype(jnp.int32)
    thr = ((best // 2) % B).astype(jnp.int32)
    opt = (best % 2).astype(jnp.int32)
    default_left = opt == 1
    feature_gain = net.max(axis=(2, 3)) if return_feature_gain else None

    def take3(a):
        af = a.reshape(L, F * B * 2, 3)
        return jnp.take_along_axis(af, best[:, None, None], axis=1)[:, 0, :]

    def take1(a):
        af = a.reshape(L, F * B * 2)
        return jnp.take_along_axis(af, best[:, None], axis=1)[:, 0]

    out = {
        "gain": best_gain,
        "feature": feat,
        "threshold": thr,
        "default_left": default_left,
        "left_sum": take3(left),
        "right_sum": take3(right),
        "left_out": take1(out_l),
        "right_out": take1(out_r),
        "is_cat_split": jnp.take_along_axis(
            jnp.broadcast_to(cat2, (L, F)), feat[:, None], axis=1)[:, 0],
    }
    if return_feature_gain:
        out["feature_gain"] = feature_gain

    # one-hot winners' membership mask (single bin goes left)
    member = ((bins_iota[None, :] == thr[:, None])
              & out["is_cat_split"][:, None]
              & jnp.isfinite(best_gain)[:, None])               # [L, B]

    if cat_sorted_mask is not None:
        from .cat_split import find_best_cat_sorted
        srt = find_best_cat_sorted(
            hist, num_bins_per_feat, cat_sorted_mask, params, pg,
            feature_mask=feature_mask, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
            parent_output=parent_output, rand_bin=rand_bin)
        # sorted-cat candidates compete against scaled/penalized gains —
        # charge them the same feature_contri scale and CEGB penalty
        if gain_scale is not None or gain_penalty is not None:
            sg = srt["gain"]
            sf = srt["feature"][:, None]
            if gain_scale is not None:
                gs2b = jnp.broadcast_to(
                    gain_scale if gain_scale.ndim == 2
                    else gain_scale[None, :], (L, F))
                sg = jnp.where(jnp.isfinite(sg), sg * jnp.take_along_axis(
                    gs2b, sf, axis=1)[:, 0], sg)
            if gain_penalty is not None:
                sg = jnp.where(jnp.isfinite(sg), sg - jnp.take_along_axis(
                    gain_penalty, sf, axis=1)[:, 0], sg)
            srt["gain"] = jnp.where(sg > 1e-10, sg, NEG_INF)
        if return_feature_gain:
            out["feature_gain"] = jnp.maximum(out["feature_gain"],
                                              srt["feature_gain"])
        pick = srt["gain"] > out["gain"]
        out["gain"] = jnp.where(pick, srt["gain"], out["gain"])
        out["feature"] = jnp.where(pick, srt["feature"], out["feature"])
        out["threshold"] = jnp.where(pick, 0, out["threshold"])
        out["default_left"] = jnp.where(pick, False, out["default_left"])
        out["left_sum"] = jnp.where(pick[:, None], srt["left_sum"],
                                    out["left_sum"])
        out["right_sum"] = jnp.where(pick[:, None], srt["right_sum"],
                                     out["right_sum"])
        out["left_out"] = jnp.where(pick, srt["left_out"], out["left_out"])
        out["right_out"] = jnp.where(pick, srt["right_out"],
                                     out["right_out"])
        out["is_cat_split"] = jnp.where(pick, True, out["is_cat_split"])
        member = jnp.where(pick[:, None], srt["member"], member)

    out["cat_bitset"] = pack_member_bitset(member)
    return out
