"""Best-split search over histograms.

TPU-native analog of the reference split finder (LightGBM
``src/treelearner/feature_histogram.hpp:165`` ``FindBestThreshold``,
``cuda/cuda_best_split_finder.cu``): for each (leaf, feature) scan bin
thresholds in both missing-direction variants and keep the max-gain split.

Design: the reference scans each histogram twice (missing-left /
missing-right) in scalar loops. Here the whole search is one vectorized
cumsum + gain evaluation over a dense [leaves, features, bins, 2] lattice —
an argmax XLA reduces on-device; no data-dependent control flow.

Gain math mirrors feature_histogram.hpp exactly:
  ThresholdL1(s, l1) = sign(s) * max(|s| - l1, 0)
  leaf_gain(G, H)    = ThresholdL1(G)^2 / (H + l2)
  split_gain         = leaf_gain(GL) + leaf_gain(GR)  (parent part constant)
  leaf_output(G, H)  = -ThresholdL1(G) / (H + l2)
Validity: counts >= min_data_in_leaf, hessians >= min_sum_hessian_in_leaf on
both sides; gain must exceed leaf_gain(parent) + min_gain_to_split
(the reference's gain_shift).

Categorical features use the one-hot split path (bin == t goes left) with
cat_l2 regularization — feature_histogram.hpp FindBestThresholdCategorical's
one-hot branch; sorted-subset categorical splits are a planned follow-up.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["SplitParams", "find_best_splits", "leaf_output", "leaf_gain"]

NEG_INF = -jnp.inf


class SplitParams(NamedTuple):
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_delta_step: float = 0.0


def _threshold_l1(s, l1):
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(g, h, l1, l2):
    t = _threshold_l1(g, l1)
    return jnp.where(h + l2 > 0, t * t / (h + l2), 0.0)


def leaf_output(g, h, l1, l2, max_delta_step=0.0):
    out = jnp.where(h + l2 > 0, -_threshold_l1(g, l1) / (h + l2), 0.0)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def find_best_splits(hist: jax.Array, num_bins_per_feat: jax.Array,
                     nan_bin: jax.Array, is_cat: jax.Array,
                     params: SplitParams) -> Dict[str, jax.Array]:
    """Vectorized best split per leaf.

    Args:
      hist: [L, F, B, 3] (sum_grad, sum_hess, count) per (leaf, feature, bin).
      num_bins_per_feat: [F] int32 — valid bins per feature (<= B).
      nan_bin: [F] int32 — NaN bin index per feature, -1 if none.
      is_cat: [F] bool — categorical feature flags.
      params: SplitParams.

    Returns dict with per-leaf arrays:
      gain [L] (-inf when no valid split), feature [L], threshold [L],
      default_left [L] bool, left_sum/right_sum [L, 3], is_cat_split [L].
    """
    L, F, B, _ = hist.shape
    l1, l2 = params.lambda_l1, params.lambda_l2
    bins_iota = jnp.arange(B, dtype=jnp.int32)

    has_nan = nan_bin >= 0                                     # [F]
    # zero out the nan bin so cumsums cover non-missing rows only
    nan_mask = (bins_iota[None, :] == nan_bin[:, None]) & has_nan[:, None]
    hist_nonan = jnp.where(nan_mask[None, :, :, None], 0.0, hist)
    nan_sum = jnp.einsum("lfbc,fb->lfc", hist, nan_mask.astype(hist.dtype))

    totals = hist_nonan.sum(axis=2) + nan_sum                  # [L, F, 3]
    cum = jnp.cumsum(hist_nonan, axis=2)                       # [L, F, B, 3]

    # ---- numerical thresholds: left = {bin <= t}, two missing directions
    # option 0: missing right (default_left=False); option 1: missing left
    gl0 = cum
    gl1 = cum + nan_sum[:, :, None, :]
    tot = totals[:, :, None, :]
    num_left = jnp.stack([gl0, gl1], axis=3)                   # [L,F,B,2,3]
    num_right = tot[:, :, :, None, :] - num_left

    nnb = num_bins_per_feat - has_nan.astype(jnp.int32)        # non-nan bins
    t_valid = bins_iota[None, :] < (nnb[:, None] - 1)          # [F, B]
    # when the feature has no nan, option 1 duplicates option 0 — mask it
    opt_valid = jnp.stack(
        [jnp.ones_like(has_nan), has_nan], axis=-1)            # [F, 2]
    num_valid = (t_valid[:, :, None] & opt_valid[:, None, :]
                 & (~is_cat)[:, None, None])[None]             # [1, F, B, 2]

    # ---- categorical one-hot: left = {bin == t}
    cat_left = hist[:, :, :, None, :]                           # reuse lattice
    cat_right = tot[:, :, :, None, :] - cat_left
    cat_ok = (bins_iota[None, :] < nnb[:, None]) & is_cat[:, None]
    cat_valid = (cat_ok[:, :, None]
                 & jnp.array([True, False])[None, None, :])[None]

    left = jnp.where(is_cat[None, :, None, None, None], cat_left, num_left)
    right = jnp.where(is_cat[None, :, None, None, None], cat_right, num_right)
    valid = jnp.where(is_cat[None, :, None, None], cat_valid, num_valid)

    gL, hL, nL = left[..., 0], left[..., 1], left[..., 2]
    gR, hR, nR = right[..., 0], right[..., 1], right[..., 2]

    l2_eff = jnp.where(is_cat, l2 + params.cat_l2, l2)[None, :, None, None]
    gain = (_threshold_l1(gL, l1) ** 2 / (hL + l2_eff)
            + _threshold_l1(gR, l1) ** 2 / (hR + l2_eff))

    md, mh = params.min_data_in_leaf, params.min_sum_hessian_in_leaf
    ok = (valid & (nL >= md) & (nR >= md) & (hL >= mh) & (hR >= mh))
    gain = jnp.where(ok, gain, NEG_INF)

    # parent gain + min_gain_to_split: the reference's gain_shift
    pg = leaf_gain(totals[..., 0], totals[..., 1], l1, l2)      # [L, F]
    gain_shift = pg[:, :, None, None] + params.min_gain_to_split
    real_gain = gain - gain_shift
    gain = jnp.where(real_gain > 1e-10, gain, NEG_INF)

    # ---- argmax over (F, B, 2) per leaf
    flat = gain.reshape(L, F * B * 2)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feat = (best // (B * 2)).astype(jnp.int32)
    thr = ((best // 2) % B).astype(jnp.int32)
    opt = (best % 2).astype(jnp.int32)
    default_left = opt == 1

    def take(a):
        # a: [L, F, B, 2, ...] -> per-leaf best entry
        af = a.reshape(L, F * B * 2, 3)
        return jnp.take_along_axis(af, best[:, None, None], axis=1)[:, 0, :]

    left_sum = take(left)
    right_sum = take(right)
    pgain_best = jnp.take_along_axis(pg, feat[:, None], axis=1)[:, 0]

    return {
        "gain": jnp.where(jnp.isfinite(best_gain),
                          best_gain - pgain_best, NEG_INF),
        "feature": feat,
        "threshold": thr,
        "default_left": default_left,
        "left_sum": left_sum,
        "right_sum": right_sum,
        "is_cat_split": jnp.take_along_axis(
            is_cat[None, :].repeat(L, 0), feat[:, None], axis=1)[:, 0],
    }
