"""Sorted-subset categorical split search.

TPU-native analog of the reference's many-category split finder
(``src/treelearner/feature_histogram.cpp:239-360``
``FindBestThresholdCategoricalInner``, sorted-subset branch): bins with
enough data are sorted by gradient/hessian ratio (the CTR trick of
Fisher's optimal-partition result), then prefix subsets from BOTH ends of
the order are scanned, grouped so every evaluated subset adds at least
``min_data_per_group`` rows, capped at ``max_cat_threshold`` categories.

Vectorization: the reference runs a stateful scalar loop per feature.
Here, per (leaf, feature):
- candidate filter + CTR sort are a masked ``argsort`` over the bin axis,
- subset sums are prefix sums over the sorted order (backward direction =
  total minus a shifted prefix),
- the sequential ``cnt_cur_group`` accumulate-and-reset rule is the one
  genuinely serial piece — a ``lax.scan`` over the (<=256-step) bin axis
  carrying a [2, L, F] counter, negligible next to the histogram matmuls,
- gains for every (position, direction) evaluate in one vectorized batch
  with the same output-based gain math as ops/split.py (cat_l2-regularized,
  monotone-clamped, path-smoothed).

The winning subset is materialized as a bin-space bitmask [L, B] for the
tree's bitset storage (tree.py cat_threshold serialization).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .split import (SplitParams, calc_output, gain_given_output, NEG_INF)

__all__ = ["find_best_cat_sorted"]


def find_best_cat_sorted(hist: jax.Array, num_bins_per_feat: jax.Array,
                         cat_sorted_mask: jax.Array, params: SplitParams,
                         pg: jax.Array,
                         feature_mask: Optional[jax.Array] = None,
                         leaf_lo: Optional[jax.Array] = None,
                         leaf_hi: Optional[jax.Array] = None,
                         parent_output: Optional[jax.Array] = None,
                         rand_bin: Optional[jax.Array] = None
                         ) -> Dict[str, jax.Array]:
    """Best sorted-subset categorical split per leaf.

    Args:
      hist: [L, F, B, 3] histograms.
      num_bins_per_feat: [F] or per-slot [L, F] int32 (voting-parallel
        passes per-slot elected-column metadata).
      cat_sorted_mask: [F] or [L, F] bool — categorical features on the
        sorted path (num_bin > max_cat_to_onehot).
      params: SplitParams (cat_l2/cat_smooth/max_cat_threshold/
        min_data_per_group are read here).
      pg: [L, F] parent gain (gain_shift), shared with the main finder.
      feature_mask: optional [F] or [L, F] bool.
      leaf_lo/leaf_hi: optional [L] monotone bounds (outputs are clamped;
        categorical splits never carry a monotone direction).
      parent_output: optional [L] (path smoothing).
      rand_bin: optional [L, F] int32 — extra-trees; reduced modulo the
        per-feature position count to pick one subset size.

    Returns per-leaf dict: gain [L] (net; -inf if none), feature [L],
      left_sum/right_sum [L, 3], left_out/right_out [L],
      member [L, B] bool (bin-space subset that goes LEFT).
    """
    L, F, B, _ = hist.shape
    l1 = params.lambda_l1
    l2c = params.lambda_l2 + params.cat_l2
    mds = params.max_delta_step
    use_smooth = params.path_smooth > 0.0
    use_mono = leaf_lo is not None
    mdl = params.min_data_in_leaf
    msh = params.min_sum_hessian_in_leaf
    mdpg = params.min_data_per_group
    iota = jnp.arange(B, dtype=jnp.int32)

    g = hist[..., 0]
    h = hist[..., 1]
    n = hist[..., 2]

    # candidate bins: enough data (feature_histogram.cpp:240-245 uses the
    # hessian-estimated count >= cat_smooth) and within the feature's range
    nb2 = (num_bins_per_feat if num_bins_per_feat.ndim == 2
           else num_bins_per_feat[None, :])                      # [M, F]
    cs2 = (cat_sorted_mask if cat_sorted_mask.ndim == 2
           else cat_sorted_mask[None, :])
    cand = ((n >= params.cat_smooth)
            & (iota[None, None, :] < nb2[:, :, None])
            & cs2[:, :, None])                                   # [L, F, B]
    used_bin = cand.sum(axis=2).astype(jnp.int32)                # [L, F]

    # CTR sort ascending; non-candidates sink to the end
    ctr = g / (h + params.cat_smooth)
    key = jnp.where(cand, ctr, jnp.inf)
    order = jnp.argsort(key, axis=2)                             # pos -> bin
    inv = jnp.argsort(order, axis=2)                             # bin -> pos

    def by_pos(a):
        return jnp.take_along_axis(a, order, axis=2)

    g_s = by_pos(jnp.where(cand, g, 0.0))
    h_s = by_pos(jnp.where(cand, h, 0.0))
    n_s = by_pos(jnp.where(cand, n, 0.0))
    P_g = jnp.cumsum(g_s, axis=2)
    P_h = jnp.cumsum(h_s, axis=2)
    P_n = jnp.cumsum(n_s, axis=2)
    # totals over ALL bins of the feature (subset splits against the whole
    # leaf population, not just candidate bins)
    tot = hist.sum(axis=2)                                       # [L, F, 3]
    T_g, T_h, T_n = tot[..., 0], tot[..., 1], tot[..., 2]

    # position i (0-based) takes i+1 bins from the low end (dir 0) or the
    # high end of the candidate order (dir 1)
    def left_sums(i_arr, dir_hi):
        if not dir_hi:
            lg = jnp.take_along_axis(P_g, i_arr, axis=2)
            lh = jnp.take_along_axis(P_h, i_arr, axis=2)
            lc = jnp.take_along_axis(P_n, i_arr, axis=2)
        else:
            # bins at positions [used_bin-1-i, used_bin-1]
            j = used_bin[:, :, None] - 2 - i_arr                 # prefix end
            jc = jnp.clip(j, 0, B - 1)
            pg_ = jnp.where(j >= 0, jnp.take_along_axis(P_g, jc, axis=2), 0.0)
            ph_ = jnp.where(j >= 0, jnp.take_along_axis(P_h, jc, axis=2), 0.0)
            pn_ = jnp.where(j >= 0, jnp.take_along_axis(P_n, jc, axis=2), 0.0)
            ub1 = jnp.clip(used_bin[:, :, None] - 1, 0, B - 1)
            vg = jnp.take_along_axis(P_g, ub1, axis=2)
            vh = jnp.take_along_axis(P_h, ub1, axis=2)
            vn = jnp.take_along_axis(P_n, ub1, axis=2)
            lg, lh, lc = vg - pg_, vh - ph_, vn - pn_
        return lg, lh, lc

    iexp = jnp.broadcast_to(iota[None, None, :], (L, F, B))
    lg0, lh0, lc0 = left_sums(iexp, False)
    lg1, lh1, lc1 = left_sums(iexp, True)
    lg = jnp.stack([lg0, lg1], axis=3)                           # [L,F,B,2]
    lh = jnp.stack([lh0, lh1], axis=3)
    lc = jnp.stack([lc0, lc1], axis=3)
    rg = T_g[:, :, None, None] - lg
    rh = T_h[:, :, None, None] - lh
    rc = T_n[:, :, None, None] - lc

    # --- sequential group rule (cnt_cur_group, feature_histogram.cpp:276-316)
    max_num_cat = jnp.minimum(params.max_cat_threshold,
                              (used_bin + 1) // 2)               # [L, F]
    in_range = (iexp[..., None] < used_bin[:, :, None, None]) \
        & (iexp[..., None] < max_num_cat[:, :, None, None])
    left_ok = (lc >= mdl) & (lh >= msh)        # "continue" class: no reset
    right_fail = (rc < mdl) | (rc < mdpg) | (rh < msh)   # "break" class

    # scan over positions; state: group counter + broken flag per (dir,L,F)
    lc2 = jnp.stack([lc0, lc1], axis=0)                          # [2,L,F,B]
    cnt_steps = jnp.moveaxis(
        lc2 - jnp.pad(lc2[:, :, :, :B - 1],
                      ((0, 0), (0, 0), (0, 0), (1, 0))), 3, 0)   # [B,2,L,F]
    to_scan = lambda a: jnp.transpose(a, (2, 3, 0, 1))   # [L,F,B,2]->[B,2,L,F]
    left_ok_t = to_scan(left_ok)
    rfail_t = to_scan(right_fail)
    inr_t = to_scan(in_range)

    def scan_body(carry, xs):
        cnt_cur, broken = carry
        c_i, lok, rfl, inr = xs
        cnt_cur = cnt_cur + c_i
        broken = broken | (rfl & inr)
        elig = lok & inr & ~broken & (cnt_cur >= mdpg)
        cnt_cur = jnp.where(elig, 0.0, cnt_cur)
        return (cnt_cur, broken), elig

    # carry derived FROM the data (not fresh zeros) so its varying
    # manual axes match the xs under shard_map (voting's psum'd
    # elected histograms are device-varying)
    zeros2 = lc2[:, :, :, 0] * 0.0
    (_, _), elig_t = jax.lax.scan(
        scan_body, (zeros2, zeros2.astype(bool)),
        (cnt_steps, left_ok_t, rfail_t, inr_t))
    elig = jnp.transpose(elig_t, (2, 3, 0, 1))                   # [L,F,B,2]

    if rand_bin is not None:  # extra_trees: one subset size per feature
        rpos = rand_bin % jnp.maximum(max_num_cat, 1)            # [L, F]
        elig = elig & (iexp[..., None] == rpos[:, :, None, None])

    # --- gains (output-based; cat_l2-regularized like the reference's
    # sorted branch, parent gain pg uses plain l2 — shared with caller)
    sm_l = {}
    sm_r = {}
    if use_smooth:
        po = parent_output[:, None, None, None]
        sm_l = dict(path_smooth=params.path_smooth, count=lc,
                    parent_output=po)
        sm_r = dict(path_smooth=params.path_smooth, count=rc,
                    parent_output=po)
    out_l = calc_output(lg, lh, l1, l2c, mds, **sm_l)
    out_r = calc_output(rg, rh, l1, l2c, mds, **sm_r)
    if use_mono:
        lo = leaf_lo[:, None, None, None]
        hi = leaf_hi[:, None, None, None]
        out_l = jnp.clip(out_l, lo, hi)
        out_r = jnp.clip(out_r, lo, hi)
    gain = (gain_given_output(lg, lh, l1, l2c, out_l)
            + gain_given_output(rg, rh, l1, l2c, out_r))
    net = gain - pg[:, :, None, None] - params.min_gain_to_split
    net = jnp.where(elig & (net > 1e-10), net, NEG_INF)
    if feature_mask is not None:
        fm = (feature_mask[None, :] if feature_mask.ndim == 1
              else feature_mask)
        net = jnp.where(fm[:, :, None, None], net, NEG_INF)

    # --- argmax over (F, B, 2)
    flat = net.reshape(L, F * B * 2)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    feat = (best // (B * 2)).astype(jnp.int32)
    pos = ((best // 2) % B).astype(jnp.int32)
    dir_hi = (best % 2).astype(jnp.int32)

    def take(a):
        return jnp.take_along_axis(
            a.reshape(L, F * B * 2), best[:, None], axis=1)[:, 0]

    l_sum = jnp.stack([take(lg), take(lh), take(lc)], axis=1)
    r_sum = jnp.stack([take(rg), take(rh), take(rc)], axis=1)

    # --- winning subset as a bin-space membership mask
    fsel = feat[:, None, None]                                   # [L,1,1]
    inv_f = jnp.take_along_axis(inv, jnp.broadcast_to(
        fsel, (L, 1, B)), axis=1)[:, 0, :]                       # [L, B]
    cand_f = jnp.take_along_axis(cand, jnp.broadcast_to(
        fsel, (L, 1, B)), axis=1)[:, 0, :]
    ub_f = jnp.take_along_axis(used_bin, feat[:, None], axis=1)[:, 0]
    member_lo = inv_f <= pos[:, None]
    member_hi = inv_f >= (ub_f[:, None] - 1 - pos[:, None])
    member = cand_f & jnp.where(dir_hi[:, None] == 1, member_hi, member_lo)
    member = member & jnp.isfinite(best_gain)[:, None]

    return {
        "gain": best_gain,
        "feature": feat,
        "left_sum": l_sum,
        "right_sum": r_sum,
        "left_out": take(out_l),
        "right_out": take(out_r),
        "member": member,
        # per-feature best sorted gain — merged into the main finder's
        # feature_gain so voting ballots see sorted-subset candidates
        "feature_gain": net.max(axis=(2, 3)),
    }
