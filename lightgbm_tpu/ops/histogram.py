"""Histogram construction — the one true hot loop.

TPU-native analog of the reference histogram kernels (LightGBM
``src/io/dense_bin.hpp`` ``ConstructHistogram``,
``src/treelearner/cuda/cuda_histogram_constructor.cu``): accumulate
(sum_grad, sum_hess, count) per (leaf, feature, bin).

Design (TPU-first, NOT a translation):
- CPUs/GPUs scatter-add into per-thread/shared-memory histograms. TPUs have
  no fast scatter; the MXU wants matmuls. We therefore compute the histogram
  as a single dense matmul per row-block:

      onehot[r, f*B + b]  = (bins[r, f] == b)                 (bf16, exact)
      ghl   [r, l*3 + c]  = (row_leaf[r] == leaf_ids[l]) * gh[r, c]
      hist  [f*B, l*3]   += onehot^T @ ghl                    (f32 accumulate)

  The leaf axis rides in the matmul N dimension: computing one leaf's
  histogram (N=3) would waste the 128-wide MXU tile, so the tree builder
  batches `leaf_batch` leaves per round and gets their histograms in the
  same pass (see boosting/tree_builder.py). This replaces the reference's
  smaller-leaf-first scheduling (serial_tree_learner.cpp:341) as the way to
  keep the hot loop saturated.
- Rows are processed in fixed-size blocks via lax.scan so the bf16 one-hot
  temporary stays bounded; all shapes static for XLA.
- Padded rows carry row_leaf == -1 and never match a leaf id.
- A Pallas kernel generating the one-hot in VMEM (skipping the HBM
  round-trip) is the planned round-2 upgrade; this XLA formulation is the
  portable baseline and the semantics oracle for it.
- Class batching (``class_batch``, boosting/tree_builder.py
  ``_build_tree_class_batched``): the multiclass trainer vmaps the whole
  build over the class axis, so these kernels run under a batching
  trace. The matmul path's ``ghl`` gains a leading K and the contraction
  becomes one batched matmul — effectively folding class into the
  leaf-slot (N) dimension, hist [K, F·B, S·3] from ONE dispatch with K×
  the MXU work per dispatch instead of K sequential calls. The scatter
  path batches the same way (one scatter-add with a class index axis).
  The ``native`` FFI kernel has no vmap rule — the class-batched entry
  remaps native→scatter (bit-identical; see tests/test_histogram.py
  native↔scatter parity). ``merge_histograms`` collectives batch too:
  psum / psum_scatter carry [K, ...] operands in one collective, so
  cross-chip bytes per class are unchanged while the dispatch count
  drops K×.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..native import jax_ffi as _jax_ffi
import numpy as np

__all__ = ["build_histograms", "resolve_impl", "merge_histograms",
           "HIST_CH"]

# channels per histogram cell: (sum_grad, sum_hess, count)
HIST_CH = 3


def merge_histograms(hist: jax.Array, axis_name: Optional[str],
                     merge="allreduce", n_shards: int = 1) -> jax.Array:
    """Cross-shard merge of a ``[L, F, B, CH]`` histogram — the
    ``Network::ReduceScatter`` analog (data_parallel_tree_learner.cpp:284),
    factored out so every kernel path (matmul/scatter/native/pallas) and
    the tree builder's EFB-unbundled merge share ONE implementation.

    ``merge`` selects the collective:
    - ``False`` / ``"none"``: no collective — the histogram stays
      shard-local (feature/voting-parallel merge selectively later).
    - ``True`` / ``"allreduce"``: ``lax.psum`` — every shard receives the
      full merged histogram (replicated split finding; ~2x the wire
      bytes of reduce-scatter and n-redundant downstream work).
    - ``"reduce_scatter"``: ``lax.psum_scatter`` along the feature axis
      (dim 1, padded to a multiple of ``n_shards``): shard k receives
      ONLY its ``F_pad/n`` feature-slot block ``[k*F_pad/n, (k+1)*F_pad/n)``
      of the merged histogram — the reference's true per-worker
      feature-block merge. Split finding then runs on the local block
      and winners sync SplitInfo-sized (see tree_builder._sync_best).

    The collective is wrapped in the ``hist_merge`` profiler phase, so
    trace viewers group its device time and the collective-traffic
    auditor (parallel/comms.py) can attribute histogram collectives by
    the ``hist_merge`` op-name prefix.
    """
    if axis_name is None or merge in (False, "none", None):
        return hist
    from .. import profiler
    with profiler.phase("hist_merge"):
        if merge == "reduce_scatter":
            F = hist.shape[1]
            F_pad = -(-F // n_shards) * n_shards
            if F_pad != F:
                cfg = [(0, 0)] * hist.ndim
                cfg[1] = (0, F_pad - F)
                hist = jnp.pad(hist, cfg)
            return jax.lax.psum_scatter(hist, axis_name,
                                        scatter_dimension=1, tiled=True)
        return jax.lax.psum(hist, axis_name)


def _pick_block_rows(num_rows: int, fb: int, dtype_bytes: int = 2,
                     budget_bytes: int = 1 << 26) -> int:
    """Row-block size so the one-hot temp stays ~<= budget (64MB)."""
    blk = budget_bytes // max(1, fb * dtype_bytes)
    blk = int(2 ** np.floor(np.log2(max(blk, 256))))
    blk = min(blk, 1 << 16)
    # avoid degenerate tiny blocks
    return max(blk, 256)


def block_rows_for(num_rows: int, num_features: int, num_bins: int) -> int:
    return _pick_block_rows(num_rows, num_features * num_bins)


def _pvary(x, axis_name):
    """Mark a scan carry as varying over a shard_map axis (no-op when
    it already is — pcast rejects varying->varying)."""
    vma = getattr(getattr(x, "aval", None), "vma", None)
    if vma is not None and axis_name in vma:
        return x
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_name)
    return x  # 0.4.x shard_map: no varying-mark concept — no-op


# Pallas training-path survivability: the fused kernel has never met a
# given chip's Mosaic toolchain until first hardware contact, and the
# reference's equivalent defense is a GPU->CPU treelearner fallback
# (gpu_tree_learner.cpp logs and degrades rather than aborting). The
# verdict is probed ONCE, eagerly, and cached for the process.
_PALLAS_TRAIN_OK: Optional[bool] = None


def _reset_pallas_probe() -> None:
    """Forget the cached Pallas probe verdicts (tests only) — both the
    training-path verdict here and the fused build+split verdict in
    ops.pallas_histogram (they gate independently: a chip can run the
    histogram kernel yet reject the fused epilogue)."""
    global _PALLAS_TRAIN_OK
    _PALLAS_TRAIN_OK = None
    from . import pallas_histogram
    pallas_histogram._FUSED_PROBE.clear()


def _probe_pallas_training() -> bool:
    """Compile + run a tiny Pallas histogram eagerly, once; cache verdict.

    Mosaic may reject the kernel on a chip/toolchain this code has never
    met; default-params training must degrade to the matmul formulation
    instead of crashing. Runs eagerly so the verdict exists before any
    outer jit traces ``build_histograms``.
    """
    global _PALLAS_TRAIN_OK
    if _PALLAS_TRAIN_OK is None:
        try:
            from . import pallas_histogram
            # F=2, B=64 resolves to the lane-ALIGNED kernel plan
            # (fc*Bp = 128) — the same shape class production configs
            # take; a tiny unaligned probe would validate the wrong path
            r, l = 256, 2
            out = pallas_histogram.build_histograms_pallas(
                jnp.zeros((r, 2), jnp.uint8),
                jnp.ones((r, HIST_CH), jnp.float32),
                jnp.zeros((r,), jnp.int32),
                jnp.arange(l, dtype=jnp.int32),
                num_bins=64, hist_dtype="bfloat16")
            jax.block_until_ready(out)
            _PALLAS_TRAIN_OK = True
        except Exception as e:  # Mosaic lowering / runtime rejection
            from .. import log as _log
            # default to caching the False verdict (an unrecognized
            # failure repeating the doomed probe compile on EVERY
            # booster setup would stall each one for seconds); only a
            # known-TRANSIENT class — momentary device OOM / device
            # busy — leaves the cache unset so the next resolve retries
            msg = f"{type(e).__name__}: {e}"
            transient = any(s in msg for s in (
                "RESOURCE_EXHAUSTED", "Resource exhausted",
                "out of memory", "OOM", "DEADLINE_EXCEEDED",
                "UNAVAILABLE", "ABORTED"))
            _log.warning(
                "Pallas histogram kernel unavailable on this backend "
                f"({msg}); falling back to the XLA matmul formulation"
                + (" (transient error — will re-probe on next resolve)"
                   if transient else ""))
            if transient:
                return False
            _PALLAS_TRAIN_OK = False
    return _PALLAS_TRAIN_OK


def _trace_state_clean() -> bool:
    try:
        return jax.core.trace_state_clean()
    except Exception:
        return True


def resolve_impl(impl: str) -> str:
    """Resolve ``hist_impl='auto'`` to a concrete kernel for this backend.

    Call eagerly (GBDT setup does) before any tracing: on TPU the Pallas
    kernel is the default but only after a one-time probe compile proves
    Mosaic accepts it — otherwise the matmul formulation. When invoked
    mid-trace with the probe not yet run (a direct jitted caller), the
    probe CANNOT run meaningfully — its ops would be staged into the
    ambient trace and the try/except would pass vacuously, poisoning the
    cache — so resolution stays conservatively on matmul instead.
    """
    if impl != "auto":
        return impl
    backend = jax.default_backend()
    if backend == "cpu":
        # the runtime-compiled C kernel (native/hist.c — dense_bin.hpp
        # ConstructHistogram cache locality) beats the XLA scatter by
        # ~5x; scatter remains the no-toolchain fallback
        from .. import native as _native
        if _native.hist_lib() is not None:
            return "native"
        return "scatter"     # XLA lowers the scatter to per-row adds
    if backend == "tpu":
        if _PALLAS_TRAIN_OK is None and not _trace_state_clean():
            return "matmul"
        return "pallas" if _probe_pallas_training() else "matmul"
    return "matmul"


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "block_rows", "axis_name", "hist_dtype",
                     "impl", "merge", "n_shards"))
def build_histograms(bins: jax.Array, gh: jax.Array, row_leaf: jax.Array,
                     leaf_ids: jax.Array, *, num_bins: int,
                     block_rows: int = 0, axis_name: Optional[str] = None,
                     hist_dtype: str = "bfloat16",
                     impl: str = "auto", merge=True,
                     n_shards: int = 1,
                     row_gather: Optional[jax.Array] = None,
                     num_rows: Optional[jax.Array] = None,
                     init: Optional[jax.Array] = None) -> jax.Array:
    """Accumulate per-(leaf, feature, bin) sums of (grad, hess, count).

    Args:
      bins: [R, F] integer bin matrix (uint8/int32). R must be divisible by
        block_rows (caller pads; padded rows have row_leaf == -1).
      gh: [R, 3] float32 — (grad, hess, 1.0) per row; zeros for padded rows.
      row_leaf: [R] int32 current leaf slot per row (-1 = padded/dead).
      leaf_ids: [L] int32 leaf slots to build histograms for. Use a negative
        sentinel (-2) for unused slots — matches nothing.
      num_bins: static B (max bins over features).
      axis_name: if inside shard_map over a row-sharded mesh axis, the
        mapped axis name; histograms are merged over it per ``merge``
        (see :func:`merge_histograms`) — ``True``/``"allreduce"`` is the
        replicated psum, ``"reduce_scatter"`` the feature-slot-scattered
        ``lax.psum_scatter`` (the reference's true
        ``Network::ReduceScatter`` per-worker feature-block merge,
        data_parallel_tree_learner.cpp:284; result is ``[L, F_pad/n, B,
        CH]`` with ``n = n_shards``). With ``merge=False`` the result
        stays shard-LOCAL (feature/voting-parallel modes merge
        selectively later) but scan carries are still marked varying.
      impl: "matmul" (MXU one-hot formulation), "scatter" (XLA
        scatter-add), "native" (the C kernel as an XLA FFI custom call
        on CPU — the true dense_bin.hpp:105 sequential pass; bit-equal
        to scatter), "pallas" (fused TPU kernel), or "auto" (backend
        default: pallas on tpu after a probe, native on cpu when a
        toolchain exists, else scatter; matmul elsewhere). All produce
        identical histograms up to f32 accumulation order.

    Quantized mode (gradient_discretizer.hpp:22 + the packed int16/int32
    histograms of cuda_histogram_constructor.cu): when ``gh`` is int8
    (stochastically-rounded grid values from GBDT._quantize_impl), the
    matmul runs as an int8 x int8 -> int32 MXU dot and the returned
    histogram is **int32** — exact integer accumulation (deterministic
    psum merge as a bonus). The caller descales the tiny [L, F, B, 3]
    result once before split finding (FindBestThresholdInt,
    feature_histogram.hpp:177, does the same descale during its bin
    scan). The bandwidth win lands where it matters: the one-hot temp
    drops bf16->int8 (2x) and gh f32->int8 (4x) in the R-sized hot
    stream. int32 accumulation bounds: |sum| <= R_leaf * nb/2 — checked
    host-side in GBDT (the analog of the reference's per-leaf
    int16->int32 escalation, which the MXU makes unnecessary).

    Dynamic row stream (the histogram-subtraction companion, VERDICT r3
    #2 — the analog of dense_bin.hpp:105 iterating ``data_indices``
    only): ``row_gather`` [R] int32 is a compacted row-index order for
    ``bins`` — ``gh`` and ``row_leaf`` are passed ALREADY compacted by
    the caller (they are narrow; bins is the wide stream whose gather is
    deferred to per-block, so unprocessed blocks never touch it).
    ``num_rows`` (traced scalar) bounds the stream: only
    ``ceil(num_rows / block_rows)`` blocks are processed via a
    dynamically-bounded loop — rows past ``num_rows`` must carry
    ``row_leaf == -1``. Works inside shard_map: each shard bounds its
    own stream; the psum after the loop re-syncs. The Pallas path
    honors ``row_gather`` by materializing the gathered bins (correct
    but not yet a bandwidth win; its grid is static).

    Carried accumulation (out-of-core, data/chunked.py): ``init``
    [L, F, B, CH] seeds the accumulator, so a row stream too large for
    device memory can be fed chunk by chunk — chunk k's result becomes
    chunk k+1's ``init``. On the matmul and scatter paths the seed IS
    the internal scan carry (re-laid-out, not post-added), so chunked
    accumulation over aligned block boundaries is bit-identical to one
    resident pass: both already reduce block-sequentially, the seed
    just replaces the zeros block. ``block_rows`` is independent of R
    (:func:`_pick_block_rows` sizes by F*B only), so a caller that pads
    every chunk to the same ``block_rows`` multiple gets identical
    block shapes — and identical addition order — in both regimes.
    Native/pallas add ``init`` after their kernel (exact for int32
    histograms, order-shifted for f32 — the chunked driver pins
    matmul/scatter). With ``axis_name`` set, ``init`` must be the
    shard-local PRE-merge accumulator (it is added before the
    collective); the chunked driver is serial-only so this does not
    arise in practice.

    Returns: [L, F, B, 3] float32 (int32 when gh is int8).
    """
    R, F = bins.shape
    L = leaf_ids.shape[0]
    B = num_bins
    quant = gh.dtype == jnp.int8
    if block_rows <= 0:
        block_rows = _pick_block_rows(R, F * B)
    if R % block_rows != 0:
        # fall back: single block (caller should pad; keeps jit legal)
        block_rows = R
    nb = R // block_rows
    cdt = jnp.dtype(hist_dtype)
    if impl == "auto":
        # resolves at trace time (impl is static); the Pallas probe cache
        # is normally warmed eagerly by GBDT setup via resolve_impl
        impl = resolve_impl(impl)

    if impl == "pallas":
        from .pallas_histogram import build_histograms_pallas
        bins_p = (jnp.take(bins, row_gather, axis=0)
                  if row_gather is not None else bins)
        hist = build_histograms_pallas(
            bins_p, gh, row_leaf, leaf_ids, num_bins=B,
            hist_dtype=hist_dtype, num_rows=num_rows)
        if init is not None:
            hist = hist + init
        # honor merge=False: feature-parallel slots are feature-disjoint
        # and voting merges elected columns itself — an unconditional
        # psum here was a pure-waste no-op for the former and would
        # double-count for the latter
        return merge_histograms(hist, axis_name, merge, n_shards)

    if impl == "native":
        # the C kernel as an XLA FFI custom call (CPU backend): one
        # sequential pass over the row stream at memory speed — the
        # exact dense_bin.hpp:105 shape the XLA scatter can't reach —
        # executed on XLA's compute thread (no Python, no GIL; legal
        # inside jit/while_loop/shard_map). Honors the compacted
        # dynamic row stream natively: row_gather indexes bins per
        # stream position and the loop stops at num_rows.
        from .. import native as _native
        if _native.hist_lib() is None:     # trace-time check, cached
            from .. import log as _log
            _log.warning("hist_impl='native' requested but the C "
                         "toolchain is unavailable; using 'scatter'")
            impl = "scatter"
        else:
            acc_dt_n = jnp.int32 if quant else jnp.float32
            bf16_round = bool((not quant) and cdt == jnp.bfloat16)
            has_rg = row_gather is not None
            rg_in = row_gather if has_rg else jnp.zeros((1,), jnp.int32)
            nr_in = (num_rows if num_rows is not None
                     else jnp.asarray(R, jnp.int32))
            nr_in = jnp.asarray(nr_in, jnp.int32).reshape((1,))
            out_sds = jax.ShapeDtypeStruct((L, F, B, HIST_CH), acc_dt_n)
            target = "lgbtpu_hist_i8" if quant else "lgbtpu_hist_f32"
            hist = _jax_ffi().ffi_call(target, out_sds)(
                bins, gh, row_leaf.astype(jnp.int32),
                leaf_ids.astype(jnp.int32), rg_in, nr_in,
                bf16_round=bf16_round, use_gather=has_rg)
            if init is not None:
                hist = hist + init
            if axis_name is not None:
                # custom-call results come back unvarying; restore the
                # manual-axis type before the merge / loop carry
                hist = _pvary(hist, axis_name)
                hist = merge_histograms(hist, axis_name, merge, n_shards)
            return hist

    # quantized addend/accumulator dtypes: int8 operands, exact int32 sums
    adt = jnp.int8 if quant else cdt
    acc_dt = jnp.int32 if quant else jnp.float32

    # dynamically-bounded stream: process only the blocks that hold live
    # rows, via fori_loop; otherwise a full static scan (cheapest trace)
    dyn = (num_rows is not None) or (row_gather is not None)
    if num_rows is not None:
        nb_used = jnp.clip((num_rows + block_rows - 1) // block_rows, 0, nb)
    else:
        nb_used = nb

    def _block(i):
        s = i * block_rows
        if row_gather is not None:
            idx = jax.lax.dynamic_slice(row_gather, (s,), (block_rows,))
            bb = jnp.take(bins, idx, axis=0)
        else:
            bb = jax.lax.dynamic_slice(bins, (s, 0), (block_rows, F))
        ghb = jax.lax.dynamic_slice(gh, (s, 0), (block_rows, HIST_CH))
        lb = jax.lax.dynamic_slice(row_leaf, (s,), (block_rows,))
        return bb, ghb, lb

    iota_b = jnp.arange(B, dtype=jnp.int32)

    if impl == "scatter":
        iota_f = jnp.arange(F, dtype=jnp.int32)

        def accum_scatter(acc, bb, ghb, lb):
            eq = lb[:, None] == leaf_ids[None, :]
            li = jnp.argmax(eq, axis=1)
            li = jnp.where(jnp.any(eq, axis=1), li, L)  # L = spill slot
            flat = ((li[:, None] * F + iota_f[None, :]) * B
                    + bb.astype(jnp.int32))              # [blk, F]
            # round addends exactly like the matmul path's cast chain
            if quant:
                vals = ghb.astype(jnp.int32)
            else:
                vals = ghb.astype(cdt).astype(jnp.float32)
            vals = jnp.broadcast_to(
                vals[:, None, :], (block_rows, F, HIST_CH))
            return acc.at[flat.reshape(-1)].add(
                vals.reshape(block_rows * F, HIST_CH))

        if init is not None:
            # seed the real slots, keep the spill slot zeroed — spill
            # rows are dropped below so their stale sums never surface
            acc0 = jnp.concatenate(
                [init.astype(acc_dt).reshape(L * F * B, HIST_CH),
                 jnp.zeros((F * B, HIST_CH), dtype=acc_dt)], axis=0)
        else:
            acc0 = jnp.zeros(((L + 1) * F * B, HIST_CH), dtype=acc_dt)
        if axis_name is not None:
            acc0 = _pvary(acc0, axis_name)
        if dyn:
            acc = jax.lax.fori_loop(
                0, nb_used,
                lambda i, a: accum_scatter(a, *_block(i)), acc0)
        else:
            acc, _ = jax.lax.scan(
                lambda a, xs: (accum_scatter(a, *xs), None), acc0,
                (bins.reshape(nb, block_rows, F),
                 gh.reshape(nb, block_rows, HIST_CH),
                 row_leaf.reshape(nb, block_rows)))
        hist = acc[:L * F * B].reshape(L, F, B, HIST_CH)
        return merge_histograms(hist, axis_name, merge, n_shards)

    def accum(acc, bb, ghb, lb):
        onehot = (bb.astype(jnp.int32)[:, :, None] == iota_b).astype(adt)
        onehot = onehot.reshape(block_rows, F * B)
        mask = (lb[:, None] == leaf_ids[None, :]).astype(adt)
        ghl = (mask[:, :, None] * ghb.astype(adt)[:, None, :]).reshape(
            block_rows, L * HIST_CH)
        # float32 mode must not silently drop to the MXU's bf16 passes
        prec = (jax.lax.Precision.HIGHEST if cdt == jnp.float32
                else jax.lax.Precision.DEFAULT)
        return acc + jax.lax.dot(
            onehot.T, ghl,
            precision=None if quant else prec,
            preferred_element_type=acc_dt)

    if init is not None:
        # inverse of the output layout transform below: [L,F,B,CH] ->
        # [F*B, L*CH] so the seed IS the matmul accumulator carry
        acc0 = init.astype(acc_dt).transpose(1, 2, 0, 3).reshape(
            F * B, L * HIST_CH)
    else:
        acc0 = jnp.zeros((F * B, L * HIST_CH), dtype=acc_dt)
    if axis_name is not None:
        # inside shard_map the blocked inputs vary over the mapped axis;
        # the loop carry must carry the same varying-axis type
        acc0 = _pvary(acc0, axis_name)
    if dyn:
        acc = jax.lax.fori_loop(
            0, nb_used, lambda i, a: accum(a, *_block(i)), acc0)
    else:
        acc, _ = jax.lax.scan(
            lambda a, xs: (accum(a, *xs), None), acc0,
            (bins.reshape(nb, block_rows, F),
             gh.reshape(nb, block_rows, HIST_CH),
             row_leaf.reshape(nb, block_rows)))
    hist = acc.reshape(F, B, L, HIST_CH).transpose(2, 0, 1, 3)
    # cross-chip merge over ICI — Network::ReduceScatter analog; with
    # merge="reduce_scatter" this IS a reduce-scatter and each chip
    # keeps only its feature-slot block.
    return merge_histograms(hist, axis_name, merge, n_shards)


def build_histograms_reference(bins: np.ndarray, gh: np.ndarray,
                               row_leaf: np.ndarray, leaf_ids: np.ndarray,
                               num_bins: int) -> np.ndarray:
    """NumPy oracle for tests (slow, exact)."""
    R, F = bins.shape
    L = len(leaf_ids)
    out = np.zeros((L, F, num_bins, HIST_CH), dtype=np.float64)
    for li, leaf in enumerate(leaf_ids):
        rows = np.nonzero(row_leaf == leaf)[0]
        for f in range(F):
            for r in rows:
                out[li, f, bins[r, f]] += gh[r]
    return out.astype(np.float32)
