"""Histogram construction — the one true hot loop.

TPU-native analog of the reference histogram kernels (LightGBM
``src/io/dense_bin.hpp`` ``ConstructHistogram``,
``src/treelearner/cuda/cuda_histogram_constructor.cu``): accumulate
(sum_grad, sum_hess, count) per (leaf, feature, bin).

Design (TPU-first, NOT a translation):
- CPUs/GPUs scatter-add into per-thread/shared-memory histograms. TPUs have
  no fast scatter; the MXU wants matmuls. We therefore compute the histogram
  as a single dense matmul per row-block:

      onehot[r, f*B + b]  = (bins[r, f] == b)                 (bf16, exact)
      ghl   [r, l*3 + c]  = (row_leaf[r] == leaf_ids[l]) * gh[r, c]
      hist  [f*B, l*3]   += onehot^T @ ghl                    (f32 accumulate)

  The leaf axis rides in the matmul N dimension: computing one leaf's
  histogram (N=3) would waste the 128-wide MXU tile, so the tree builder
  batches `leaf_batch` leaves per round and gets their histograms in the
  same pass (see boosting/tree_builder.py). This replaces the reference's
  smaller-leaf-first scheduling (serial_tree_learner.cpp:341) as the way to
  keep the hot loop saturated.
- Rows are processed in fixed-size blocks via lax.scan so the bf16 one-hot
  temporary stays bounded; all shapes static for XLA.
- Padded rows carry row_leaf == -1 and never match a leaf id.
- A Pallas kernel generating the one-hot in VMEM (skipping the HBM
  round-trip) is the planned round-2 upgrade; this XLA formulation is the
  portable baseline and the semantics oracle for it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["build_histograms", "HIST_CH"]

# channels per histogram cell: (sum_grad, sum_hess, count)
HIST_CH = 3


def _pick_block_rows(num_rows: int, fb: int, dtype_bytes: int = 2,
                     budget_bytes: int = 1 << 26) -> int:
    """Row-block size so the one-hot temp stays ~<= budget (64MB)."""
    blk = budget_bytes // max(1, fb * dtype_bytes)
    blk = int(2 ** np.floor(np.log2(max(blk, 256))))
    blk = min(blk, 1 << 16)
    # avoid degenerate tiny blocks
    return max(blk, 256)


def block_rows_for(num_rows: int, num_features: int, num_bins: int) -> int:
    return _pick_block_rows(num_rows, num_features * num_bins)


def _pvary(x, axis_name):
    """Mark a scan carry as varying over a shard_map axis."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    return jax.lax.pvary(x, axis_name)  # older jax


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "block_rows", "axis_name", "hist_dtype",
                     "impl", "merge"))
def build_histograms(bins: jax.Array, gh: jax.Array, row_leaf: jax.Array,
                     leaf_ids: jax.Array, *, num_bins: int,
                     block_rows: int = 0, axis_name: Optional[str] = None,
                     hist_dtype: str = "bfloat16",
                     impl: str = "auto", merge: bool = True) -> jax.Array:
    """Accumulate per-(leaf, feature, bin) sums of (grad, hess, count).

    Args:
      bins: [R, F] integer bin matrix (uint8/int32). R must be divisible by
        block_rows (caller pads; padded rows have row_leaf == -1).
      gh: [R, 3] float32 — (grad, hess, 1.0) per row; zeros for padded rows.
      row_leaf: [R] int32 current leaf slot per row (-1 = padded/dead).
      leaf_ids: [L] int32 leaf slots to build histograms for. Use a negative
        sentinel (-2) for unused slots — matches nothing.
      num_bins: static B (max bins over features).
      axis_name: if inside shard_map over a row-sharded mesh axis, the
        mapped axis name; histograms are psum-merged over it — the analog of
        the reference's ReduceScatter+Allgather histogram merge
        (data_parallel_tree_learner.cpp:284). With ``merge=False`` the
        result stays shard-LOCAL (feature/voting-parallel modes merge
        selectively later) but scan carries are still marked varying.
      impl: "matmul" (MXU one-hot formulation), "scatter" (XLA scatter-add
        — the dense_bin.hpp:105 shape, fast on CPU where XLA lowers it to
        per-row adds, pathological on TPU), or "auto" (backend default:
        scatter on cpu, matmul elsewhere). Both produce identical
        histograms up to f32 accumulation order.

    Quantized mode (gradient_discretizer.hpp:22 + the packed int16/int32
    histograms of cuda_histogram_constructor.cu): when ``gh`` is int8
    (stochastically-rounded grid values from GBDT._quantize_impl), the
    matmul runs as an int8 x int8 -> int32 MXU dot and the returned
    histogram is **int32** — exact integer accumulation (deterministic
    psum merge as a bonus). The caller descales the tiny [L, F, B, 3]
    result once before split finding (FindBestThresholdInt,
    feature_histogram.hpp:177, does the same descale during its bin
    scan). The bandwidth win lands where it matters: the one-hot temp
    drops bf16->int8 (2x) and gh f32->int8 (4x) in the R-sized hot
    stream. int32 accumulation bounds: |sum| <= R_leaf * nb/2 — checked
    host-side in GBDT (the analog of the reference's per-leaf
    int16->int32 escalation, which the MXU makes unnecessary).

    Returns: [L, F, B, 3] float32 (int32 when gh is int8).
    """
    R, F = bins.shape
    L = leaf_ids.shape[0]
    B = num_bins
    quant = gh.dtype == jnp.int8
    if block_rows <= 0:
        block_rows = _pick_block_rows(R, F * B)
    if R % block_rows != 0:
        # fall back: single block (caller should pad; keeps jit legal)
        block_rows = R
    nb = R // block_rows
    cdt = jnp.dtype(hist_dtype)
    if impl == "auto":
        backend = jax.default_backend()
        if backend == "tpu":
            impl = "pallas"      # fused VMEM one-hot (pallas_histogram)
        elif backend == "cpu":
            impl = "scatter"     # XLA lowers to per-row adds
        else:
            impl = "matmul"

    if impl == "pallas":
        from .pallas_histogram import build_histograms_pallas
        hist = build_histograms_pallas(
            bins, gh, row_leaf, leaf_ids, num_bins=B,
            hist_dtype=hist_dtype)
        if axis_name is not None:
            hist = jax.lax.psum(hist, axis_name)
        return hist

    # quantized addend/accumulator dtypes: int8 operands, exact int32 sums
    adt = jnp.int8 if quant else cdt
    acc_dt = jnp.int32 if quant else jnp.float32

    bins_b = bins.reshape(nb, block_rows, F)
    gh_b = gh.reshape(nb, block_rows, HIST_CH)
    leaf_b = row_leaf.reshape(nb, block_rows)

    iota_b = jnp.arange(B, dtype=jnp.int32)

    if impl == "scatter":
        iota_f = jnp.arange(F, dtype=jnp.int32)

        def body_scatter(acc, inputs):
            bb, ghb, lb = inputs
            eq = lb[:, None] == leaf_ids[None, :]
            li = jnp.argmax(eq, axis=1)
            li = jnp.where(jnp.any(eq, axis=1), li, L)  # L = spill slot
            flat = ((li[:, None] * F + iota_f[None, :]) * B
                    + bb.astype(jnp.int32))              # [blk, F]
            # round addends exactly like the matmul path's cast chain
            if quant:
                vals = ghb.astype(jnp.int32)
            else:
                vals = ghb.astype(cdt).astype(jnp.float32)
            vals = jnp.broadcast_to(
                vals[:, None, :], (block_rows, F, HIST_CH))
            acc = acc.at[flat.reshape(-1)].add(
                vals.reshape(block_rows * F, HIST_CH))
            return acc, None

        acc0 = jnp.zeros(((L + 1) * F * B, HIST_CH), dtype=acc_dt)
        if axis_name is not None:
            acc0 = _pvary(acc0, axis_name)
        acc, _ = jax.lax.scan(body_scatter, acc0, (bins_b, gh_b, leaf_b))
        hist = acc[:L * F * B].reshape(L, F, B, HIST_CH)
        if axis_name is not None and merge:
            hist = jax.lax.psum(hist, axis_name)
        return hist

    def body(acc, inputs):
        bb, ghb, lb = inputs
        onehot = (bb.astype(jnp.int32)[:, :, None] == iota_b).astype(adt)
        onehot = onehot.reshape(block_rows, F * B)
        mask = (lb[:, None] == leaf_ids[None, :]).astype(adt)
        ghl = (mask[:, :, None] * ghb.astype(adt)[:, None, :]).reshape(
            block_rows, L * HIST_CH)
        # float32 mode must not silently drop to the MXU's bf16 passes
        prec = (jax.lax.Precision.HIGHEST if cdt == jnp.float32
                else jax.lax.Precision.DEFAULT)
        acc = acc + jax.lax.dot(
            onehot.T, ghl,
            precision=None if quant else prec,
            preferred_element_type=acc_dt)
        return acc, None

    acc0 = jnp.zeros((F * B, L * HIST_CH), dtype=acc_dt)
    if axis_name is not None:
        # inside shard_map the blocked inputs vary over the mapped axis;
        # the scan carry must carry the same varying-axis type
        acc0 = _pvary(acc0, axis_name)
    acc, _ = jax.lax.scan(body, acc0, (bins_b, gh_b, leaf_b))
    hist = acc.reshape(F, B, L, HIST_CH).transpose(2, 0, 1, 3)
    if axis_name is not None and merge:
        # cross-chip merge over ICI — replaces Network::ReduceScatter +
        # best-split Allgather of the reference data-parallel learner.
        hist = jax.lax.psum(hist, axis_name)
    return hist


def build_histograms_reference(bins: np.ndarray, gh: np.ndarray,
                               row_leaf: np.ndarray, leaf_ids: np.ndarray,
                               num_bins: int) -> np.ndarray:
    """NumPy oracle for tests (slow, exact)."""
    R, F = bins.shape
    L = len(leaf_ids)
    out = np.zeros((L, F, num_bins, HIST_CH), dtype=np.float64)
    for li, leaf in enumerate(leaf_ids):
        rows = np.nonzero(row_leaf == leaf)[0]
        for f in range(F):
            for r in rows:
                out[li, f, bins[r, f]] += gh[r]
    return out.astype(np.float32)
