"""Pallas TPU histogram kernel — the fused hot loop.

The XLA formulation in ops/histogram.py materializes a ``[block, F*B]``
bf16 one-hot in HBM and feeds it to the MXU; at Higgs scale that is ~14GB
of HBM traffic per histogram build, and HBM bandwidth — not MXU FLOPs —
is the TPU bottleneck (reference hot loop analog:
``src/io/dense_bin.hpp:105`` ConstructHistogram,
``src/treelearner/cuda/cuda_histogram_constructor.cu`` shared-memory
kernels). This kernel builds the one-hot *in VMEM* per (row-block,
feature-chunk) grid step, multiplies on the MXU, and accumulates into a
VMEM-resident output block — the one-hot never touches HBM. HBM traffic
drops to the irreducible streams: bins [R, Fc] uint8 + gh [R, 3] in,
hist [F*B, L*3] out.

Grid: ``(feature_chunks, row_blocks)`` with rows innermost, so each
feature chunk's accumulator stays pinned in VMEM across the whole row
stream (TPU grids execute sequentially; revisiting the same out block is
the standard reduction pattern).

Numerics match ops/histogram.py's matmul path: addends cast to
``hist_dtype`` (bf16 default), accumulation in f32 on the MXU.

Class batching: the multiclass class-batched build
(boosting/tree_builder.py ``_build_tree_class_batched``) vmaps the
whole tree build, so ``pallas_call`` here lowers through its batching
rule — ONE kernel launch whose grid gains the class axis, bit-equal to
K sequential launches (validated in interpret mode for both the plain
and scalar-prefetch paths). Caveat: vmap batches EVERY operand, so the
bins matrix — logically shared across classes — is presented K× to the
root-histogram launch ([K, R, Fc] view). XLA keeps it as a broadcast
(no HBM copy), but the kernel's block streams read it per class: the
root build's bins traffic is K× the sequential path's single pass.
In-loop builds index per-class rows anyway, so only the root round
pays; the K× MXU utilization win dominates on every measured shape.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import HIST_CH

__all__ = ["build_histograms_pallas", "pallas_available"]


def pallas_available() -> bool:
    """True when the Pallas TPU lowering path can run (a TPU backend)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _kernel(bins_ref, gh_ref, leaf_ref, lids_ref, out_ref, *,
            num_bins: int, cdt, fb_pad: int, lb3_pad: int, acc_dt,
            nr_ref=None, blk_rows: int = 0):
    """One (feature-chunk, row-block) grid step.

    bins_ref: [blk, Fc] int32 (pre-padded; out-of-range bin == no match)
    gh_ref:   [blk, 8] f32   (grad, hess, in-bag count, 5 zero lanes)
              — or int8 quantized grid values (see ops/histogram.py)
    leaf_ref: [blk, 8] int32 current leaf per row broadcast (-1 dead)
    lids_ref: [8, L_pad] int32 leaf slots this build targets (-2 pad)
    out_ref:  [fb_pad, lb3_pad] f32 (int32 when quantized) accumulator
              (same block every row step; both dims padded to MXU/VPU
              tile multiples)
    nr_ref:   scalar-prefetch [1] int32 live-row bound, or None — row
              blocks at or past ceil(nr / blk) are SKIPPED entirely (the
              index maps also clamp their DMAs to an already-fetched
              block), so a compacted stream pays only for its live
              prefix — the dense_bin.hpp:105 data_indices bound.
    """
    j = pl.program_id(1)
    blk, fc = bins_ref.shape
    l_pad = lids_ref.shape[1]

    def compute():
        bb = bins_ref[:]                                  # [blk, Fc] int32
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (blk, fc, num_bins), 2)
        onehot = (bb[:, :, None] == iota_b).astype(cdt).reshape(
            blk, fc * num_bins)
        if fb_pad != fc * num_bins:
            onehot = jnp.pad(onehot,
                             ((0, 0), (0, fb_pad - fc * num_bins)))

        # leaf mask: [blk, L_pad]; pad slots are -2 and never match
        mask = (leaf_ref[:, 0:1] == lids_ref[0:1, :]).astype(cdt)
        ghb = gh_ref[:].astype(cdt)                       # [blk, 8]
        # NOTE: ghb[:, None, :HIST_CH] (newaxis + partial slice in one
        # index) lowers via lax.gather, which Mosaic rejects at this
        # shape ("Shape mismatch in input, indices and output" — first
        # real-hardware finding, r5). A static slice + expand_dims is
        # the same math with no gather.
        gh3 = jnp.expand_dims(ghb[:, :HIST_CH], 1)        # [blk, 1, 3]
        ghl = (jnp.expand_dims(mask, 2) * gh3).reshape(
            blk, l_pad * HIST_CH)
        if lb3_pad != l_pad * HIST_CH:
            ghl = jnp.pad(ghl,
                          ((0, 0), (0, lb3_pad - l_pad * HIST_CH)))

        return jax.lax.dot_general(
            onehot, ghl, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)            # [fb_pad, lb3_pad]

    if nr_ref is None:
        @pl.when(j == 0)
        def _():
            out_ref[:] = compute()

        @pl.when(j > 0)
        def _():
            out_ref[:] = out_ref[:] + compute()
    else:
        nb_used = (nr_ref[0] + blk_rows - 1) // blk_rows
        # the first step must still initialize the accumulator (zero
        # when even block 0 is past the bound)
        @pl.when(j == 0)
        def _():
            out_ref[:] = jnp.where(nb_used > 0, compute(),
                                   jnp.zeros_like(out_ref))

        @pl.when((j > 0) & (j < nb_used))
        def _():
            out_ref[:] = out_ref[:] + compute()


try:  # pallas imports kept optional so CPU-only installs never pay for them
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _plan_chunks(F: int, B: int, L: int, vmem_budget: int = 10 << 20):
    """Pick (row_block, feature_chunk, padded_bins, padded_leaves).

    Mosaic-friendliness: the one-hot is built at ``Bp`` bins (power of
    two >= B; bins >= B simply never match) and ``fc`` is chosen so
    ``fc * Bp`` is a multiple of the 128-lane tile — then the kernel's
    reshape/matmul operands are exactly lane-aligned and its pads
    compile away. ``l_pad`` is lifted to a multiple of 128 for the same
    reason (ghl width l_pad*3 is then 128-aligned). Shapes with no
    aligned divisor fall back to in-kernel padding (still correct)."""
    Bp = 1 << int(np.ceil(np.log2(max(B, 2))))
    l_pad = max(128, -(-L // 128) * 128)
    out_cap = 4 << 20      # resident accumulator block budget
    # feature chunk: fc | F, fc * Bp ≡ 0 (mod 128), fc * Bp <= 4096,
    # and the [fc*Bp, l_pad*3] f32 accumulator under its own cap (it
    # stays VMEM-resident across the whole row stream)
    fc = 0
    for cand in range(min(F, max(1, 4096 // Bp)), 0, -1):
        if F % cand == 0 and (cand * Bp) % 128 == 0 \
                and cand * Bp * l_pad * HIST_CH * 4 <= out_cap:
            fc = cand
            break
    if fc == 0:
        # no aligned divisor (e.g. odd tiny F): legacy padding path,
        # with the cheap narrow leaf pad (alignment can't compile away
        # here anyway)
        Bp = B
        l_pad = max(8, -(-L // 8) * 8)
        fc = max(1, min(F, 4096 // max(B, 1)))
        while F % fc != 0 or (fc > 1 and -(-(fc * B) // 128) * 128
                              * -(-(l_pad * HIST_CH) // 128) * 128 * 4
                              > out_cap):
            fc -= 1
    out_b = (-(-(fc * Bp) // 128) * 128
             * -(-(l_pad * HIST_CH) // 128) * 128 * 4)
    # row block: onehot (cdt bytes, estimate 2) + double-buffered bins
    # int32 + ghl row width, inside what the accumulator leaves free
    per_row = fc * Bp * 2 + fc * 4 * 2 + l_pad * HIST_CH * 4
    blk = max(256, (vmem_budget - out_b) // max(1, per_row))
    blk = int(2 ** np.floor(np.log2(blk)))
    blk = min(blk, 4096)
    return blk, fc, Bp, l_pad


def _compiler_params(**kw):
    """pltpu.CompilerParams across jax versions (TPUCompilerParams
    before the rename)."""
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "hist_dtype", "interpret"))
def build_histograms_pallas(bins: jax.Array, gh: jax.Array,
                            row_leaf: jax.Array, leaf_ids: jax.Array, *,
                            num_bins: int, hist_dtype: str = "bfloat16",
                            interpret: bool = False,
                            num_rows: Optional[jax.Array] = None
                            ) -> jax.Array:
    """Pallas analog of ops.histogram.build_histograms.

    Same contract: bins [R, F] uint/int, gh [R, 3] f32, row_leaf [R]
    int32, leaf_ids [L] int32 -> [L, F, B, 3] f32. R is padded up to the
    row block internally (padded rows get leaf -1).
    int8 ``gh`` selects the quantized path (int8 MXU dot, exact int32
    output — see ops/histogram.py docstring).
    ``num_rows`` (traced int32 scalar): dynamic live-row bound for a
    COMPACTED stream (VERDICT r4 #3) — it rides in as a scalar-prefetch
    operand, row blocks at or past ``ceil(num_rows / blk)`` are skipped
    by ``pl.when`` and their index maps clamp to an already-fetched
    block (no fresh DMA), so histogram subtraction's row-stream savings
    survive on the chip. Rows past ``num_rows`` must carry
    ``row_leaf == -1`` (they are never read when the bound is exact,
    but the trailing partial block is still masked by leaf ids).
    ``interpret=True`` runs the kernel in the Pallas interpreter —
    CPU-testable parity with the real TPU lowering.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable in this jax build")
    R, F = bins.shape
    L = int(leaf_ids.shape[0])
    B = int(num_bins)
    quant = gh.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.dtype(hist_dtype)
    acc_dt = jnp.int32 if quant else jnp.float32
    blk, fc, Bp, l_pad = _plan_chunks(F, B, L)

    r_pad = ((R + blk - 1) // blk) * blk
    if r_pad != R:
        bins = jnp.pad(bins, ((0, r_pad - R), (0, 0)))
        gh = jnp.pad(gh, ((0, r_pad - R), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, r_pad - R), constant_values=-1)

    n_fb = F // fc
    n_rb = r_pad // blk
    # with an aligned plan these equal fc*Bp / l_pad*3 exactly and the
    # kernel's pads compile away; otherwise they round up to the tile
    fb_pad = -(-(fc * Bp) // 128) * 128
    lb3_pad = -(-(l_pad * HIST_CH) // 128) * 128

    gh8 = jnp.pad(gh, ((0, 0), (0, 8 - HIST_CH)))
    leaf8 = jnp.broadcast_to(row_leaf[:, None].astype(jnp.int32),
                             (r_pad, 8))
    lids8 = jnp.broadcast_to(
        jnp.pad(leaf_ids.astype(jnp.int32), (0, l_pad - L),
                constant_values=-2)[None, :], (8, l_pad))

    kern = functools.partial(_kernel, num_bins=Bp, cdt=cdt,
                             fb_pad=fb_pad, lb3_pad=lb3_pad,
                             acc_dt=acc_dt)
    if num_rows is None:
        out = pl.pallas_call(
            kern,
            grid=(n_fb, n_rb),
            in_specs=[
                pl.BlockSpec((blk, fc), lambda i, j: (j, i)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
                pl.BlockSpec((8, l_pad), lambda i, j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((fb_pad, lb3_pad),
                                   lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_fb * fb_pad, lb3_pad),
                                           acc_dt),
            # feature chunks are independent; the row dim revisits the
            # same accumulator block and must stay sequential
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(bins.astype(jnp.int32), gh8, leaf8, lids8)
    else:
        nr = jnp.reshape(jnp.asarray(num_rows, jnp.int32), (1,))

        def _row_clamp(s, j):
            # last live block; skipped steps revisit it (no new DMA)
            jmax = jnp.maximum((s[0] + blk - 1) // blk - 1, 0)
            return jnp.minimum(j, jmax)

        def kern_nr(s_ref, *refs):
            kern(*refs, nr_ref=s_ref, blk_rows=blk)

        out = pl.pallas_call(
            kern_nr,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_fb, n_rb),
                in_specs=[
                    pl.BlockSpec((blk, fc),
                                 lambda i, j, s: (_row_clamp(s, j), i)),
                    pl.BlockSpec((blk, 8),
                                 lambda i, j, s: (_row_clamp(s, j), 0)),
                    pl.BlockSpec((blk, 8),
                                 lambda i, j, s: (_row_clamp(s, j), 0)),
                    pl.BlockSpec((8, l_pad), lambda i, j, s: (0, 0)),
                ],
                out_specs=pl.BlockSpec((fb_pad, lb3_pad),
                                       lambda i, j, s: (i, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((n_fb * fb_pad, lb3_pad),
                                           acc_dt),
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(nr, bins.astype(jnp.int32), gh8, leaf8, lids8)

    hist = out.reshape(n_fb, fb_pad, lb3_pad)[:, :fc * Bp,
                                              :l_pad * HIST_CH]
    hist = hist.reshape(n_fb, fc, Bp, l_pad, HIST_CH)[:, :, :B, :L, :]
    return hist.reshape(F, B, L, HIST_CH).transpose(2, 0, 1, 3)
