"""Pallas TPU histogram kernel — the fused hot loop.

The XLA formulation in ops/histogram.py materializes a ``[block, F*B]``
bf16 one-hot in HBM and feeds it to the MXU; at Higgs scale that is ~14GB
of HBM traffic per histogram build, and HBM bandwidth — not MXU FLOPs —
is the TPU bottleneck (reference hot loop analog:
``src/io/dense_bin.hpp:105`` ConstructHistogram,
``src/treelearner/cuda/cuda_histogram_constructor.cu`` shared-memory
kernels). This kernel builds the one-hot *in VMEM* per (row-block,
feature-chunk) grid step, multiplies on the MXU, and accumulates into a
VMEM-resident output block — the one-hot never touches HBM. HBM traffic
drops to the irreducible streams: bins [R, Fc] uint8 + gh [R, 3] in,
hist [F*B, L*3] out.

Grid: ``(feature_chunks, row_blocks)`` with rows innermost, so each
feature chunk's accumulator stays pinned in VMEM across the whole row
stream (TPU grids execute sequentially; revisiting the same out block is
the standard reduction pattern).

Numerics match ops/histogram.py's matmul path: addends cast to
``hist_dtype`` (bf16 default), accumulation in f32 on the MXU.

Class batching: the multiclass class-batched build
(boosting/tree_builder.py ``_build_tree_class_batched``) vmaps the
whole tree build, so ``pallas_call`` here lowers through its batching
rule — ONE kernel launch whose grid gains the class axis, bit-equal to
K sequential launches (validated in interpret mode for both the plain
and scalar-prefetch paths). Caveat: vmap batches EVERY operand, so the
bins matrix — logically shared across classes — is presented K× to the
root-histogram launch ([K, R, Fc] view). XLA keeps it as a broadcast
(no HBM copy), but the kernel's block streams read it per class: the
root build's bins traffic is K× the sequential path's single pass.
In-loop builds index per-class rows anyway, so only the root round
pays; the K× MXU utilization win dominates on every measured shape.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import HIST_CH
from . import split as _split

__all__ = ["build_histograms_pallas", "pallas_available",
           "fused_build_best_splits", "fused_plan_ok", "fused_probe_ok",
           "fused_candidate_bytes", "build_root_histograms_classes"]


def pallas_available() -> bool:
    """True when the Pallas TPU lowering path can run (a TPU backend)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _kernel(bins_ref, gh_ref, leaf_ref, lids_ref, out_ref, *,
            num_bins: int, cdt, fb_pad: int, lb3_pad: int, acc_dt,
            nr_ref=None, blk_rows: int = 0):
    """One (feature-chunk, row-block) grid step.

    bins_ref: [blk, Fc] int32 (pre-padded; out-of-range bin == no match)
    gh_ref:   [blk, 8] f32   (grad, hess, in-bag count, 5 zero lanes)
              — or int8 quantized grid values (see ops/histogram.py)
    leaf_ref: [blk, 8] int32 current leaf per row broadcast (-1 dead)
    lids_ref: [8, L_pad] int32 leaf slots this build targets (-2 pad)
    out_ref:  [fb_pad, lb3_pad] f32 (int32 when quantized) accumulator
              (same block every row step; both dims padded to MXU/VPU
              tile multiples)
    nr_ref:   scalar-prefetch [1] int32 live-row bound, or None — row
              blocks at or past ceil(nr / blk) are SKIPPED entirely (the
              index maps also clamp their DMAs to an already-fetched
              block), so a compacted stream pays only for its live
              prefix — the dense_bin.hpp:105 data_indices bound.
    """
    j = pl.program_id(1)
    blk, fc = bins_ref.shape
    l_pad = lids_ref.shape[1]

    def compute():
        bb = bins_ref[:]                                  # [blk, Fc] int32
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (blk, fc, num_bins), 2)
        onehot = (bb[:, :, None] == iota_b).astype(cdt).reshape(
            blk, fc * num_bins)
        if fb_pad != fc * num_bins:
            onehot = jnp.pad(onehot,
                             ((0, 0), (0, fb_pad - fc * num_bins)))

        # leaf mask: [blk, L_pad]; pad slots are -2 and never match
        mask = (leaf_ref[:, 0:1] == lids_ref[0:1, :]).astype(cdt)
        ghb = gh_ref[:].astype(cdt)                       # [blk, 8]
        # NOTE: ghb[:, None, :HIST_CH] (newaxis + partial slice in one
        # index) lowers via lax.gather, which Mosaic rejects at this
        # shape ("Shape mismatch in input, indices and output" — first
        # real-hardware finding, r5). A static slice + expand_dims is
        # the same math with no gather.
        gh3 = jnp.expand_dims(ghb[:, :HIST_CH], 1)        # [blk, 1, 3]
        ghl = (jnp.expand_dims(mask, 2) * gh3).reshape(
            blk, l_pad * HIST_CH)
        if lb3_pad != l_pad * HIST_CH:
            ghl = jnp.pad(ghl,
                          ((0, 0), (0, lb3_pad - l_pad * HIST_CH)))

        return jax.lax.dot_general(
            onehot, ghl, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)            # [fb_pad, lb3_pad]

    if nr_ref is None:
        @pl.when(j == 0)
        def _():
            out_ref[:] = compute()

        @pl.when(j > 0)
        def _():
            out_ref[:] = out_ref[:] + compute()
    else:
        nb_used = (nr_ref[0] + blk_rows - 1) // blk_rows
        # the first step must still initialize the accumulator (zero
        # when even block 0 is past the bound)
        @pl.when(j == 0)
        def _():
            out_ref[:] = jnp.where(nb_used > 0, compute(),
                                   jnp.zeros_like(out_ref))

        @pl.when((j > 0) & (j < nb_used))
        def _():
            out_ref[:] = out_ref[:] + compute()


try:  # pallas imports kept optional so CPU-only installs never pay for them
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _plan_chunks(F: int, B: int, L: int, vmem_budget: int = 10 << 20):
    """Pick (row_block, feature_chunk, padded_bins, padded_leaves).

    Mosaic-friendliness: the one-hot is built at ``Bp`` bins (power of
    two >= B; bins >= B simply never match) and ``fc`` is chosen so
    ``fc * Bp`` is a multiple of the 128-lane tile — then the kernel's
    reshape/matmul operands are exactly lane-aligned and its pads
    compile away. ``l_pad`` is lifted to a multiple of 128 for the same
    reason (ghl width l_pad*3 is then 128-aligned). Shapes with no
    aligned divisor fall back to in-kernel padding (still correct)."""
    Bp = 1 << int(np.ceil(np.log2(max(B, 2))))
    l_pad = max(128, -(-L // 128) * 128)
    out_cap = 4 << 20      # resident accumulator block budget
    # feature chunk: fc | F, fc * Bp ≡ 0 (mod 128), fc * Bp <= 4096,
    # and the [fc*Bp, l_pad*3] f32 accumulator under its own cap (it
    # stays VMEM-resident across the whole row stream)
    fc = 0
    for cand in range(min(F, max(1, 4096 // Bp)), 0, -1):
        if F % cand == 0 and (cand * Bp) % 128 == 0 \
                and cand * Bp * l_pad * HIST_CH * 4 <= out_cap:
            fc = cand
            break
    if fc == 0:
        # no aligned divisor (e.g. odd tiny F): legacy padding path,
        # with the cheap narrow leaf pad (alignment can't compile away
        # here anyway)
        Bp = B
        l_pad = max(8, -(-L // 8) * 8)
        fc = max(1, min(F, 4096 // max(B, 1)))
        while F % fc != 0 or (fc > 1 and -(-(fc * B) // 128) * 128
                              * -(-(l_pad * HIST_CH) // 128) * 128 * 4
                              > out_cap):
            fc -= 1
    out_b = (-(-(fc * Bp) // 128) * 128
             * -(-(l_pad * HIST_CH) // 128) * 128 * 4)
    # row block: onehot (cdt bytes, estimate 2) + double-buffered bins
    # int32 + ghl row width, inside what the accumulator leaves free
    per_row = fc * Bp * 2 + fc * 4 * 2 + l_pad * HIST_CH * 4
    blk = max(256, (vmem_budget - out_b) // max(1, per_row))
    blk = int(2 ** np.floor(np.log2(blk)))
    blk = min(blk, 4096)
    return blk, fc, Bp, l_pad


def _compiler_params(**kw):
    """pltpu.CompilerParams across jax versions (TPUCompilerParams
    before the rename)."""
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "hist_dtype", "interpret"))
def build_histograms_pallas(bins: jax.Array, gh: jax.Array,
                            row_leaf: jax.Array, leaf_ids: jax.Array, *,
                            num_bins: int, hist_dtype: str = "bfloat16",
                            interpret: bool = False,
                            num_rows: Optional[jax.Array] = None
                            ) -> jax.Array:
    """Pallas analog of ops.histogram.build_histograms.

    Same contract: bins [R, F] uint/int, gh [R, 3] f32, row_leaf [R]
    int32, leaf_ids [L] int32 -> [L, F, B, 3] f32. R is padded up to the
    row block internally (padded rows get leaf -1).
    int8 ``gh`` selects the quantized path (int8 MXU dot, exact int32
    output — see ops/histogram.py docstring).
    ``num_rows`` (traced int32 scalar): dynamic live-row bound for a
    COMPACTED stream (VERDICT r4 #3) — it rides in as a scalar-prefetch
    operand, row blocks at or past ``ceil(num_rows / blk)`` are skipped
    by ``pl.when`` and their index maps clamp to an already-fetched
    block (no fresh DMA), so histogram subtraction's row-stream savings
    survive on the chip. Rows past ``num_rows`` must carry
    ``row_leaf == -1`` (they are never read when the bound is exact,
    but the trailing partial block is still masked by leaf ids).
    ``interpret=True`` runs the kernel in the Pallas interpreter —
    CPU-testable parity with the real TPU lowering.
    """
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable in this jax build")
    R, F = bins.shape
    L = int(leaf_ids.shape[0])
    B = int(num_bins)
    quant = gh.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.dtype(hist_dtype)
    acc_dt = jnp.int32 if quant else jnp.float32
    blk, fc, Bp, l_pad = _plan_chunks(F, B, L)

    r_pad = ((R + blk - 1) // blk) * blk
    if r_pad != R:
        bins = jnp.pad(bins, ((0, r_pad - R), (0, 0)))
        gh = jnp.pad(gh, ((0, r_pad - R), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, r_pad - R), constant_values=-1)

    n_fb = F // fc
    n_rb = r_pad // blk
    # with an aligned plan these equal fc*Bp / l_pad*3 exactly and the
    # kernel's pads compile away; otherwise they round up to the tile
    fb_pad = -(-(fc * Bp) // 128) * 128
    lb3_pad = -(-(l_pad * HIST_CH) // 128) * 128

    gh8 = jnp.pad(gh, ((0, 0), (0, 8 - HIST_CH)))
    leaf8 = jnp.broadcast_to(row_leaf[:, None].astype(jnp.int32),
                             (r_pad, 8))
    lids8 = jnp.broadcast_to(
        jnp.pad(leaf_ids.astype(jnp.int32), (0, l_pad - L),
                constant_values=-2)[None, :], (8, l_pad))

    kern = functools.partial(_kernel, num_bins=Bp, cdt=cdt,
                             fb_pad=fb_pad, lb3_pad=lb3_pad,
                             acc_dt=acc_dt)
    if num_rows is None:
        out = pl.pallas_call(
            kern,
            grid=(n_fb, n_rb),
            in_specs=[
                pl.BlockSpec((blk, fc), lambda i, j: (j, i)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
                pl.BlockSpec((8, l_pad), lambda i, j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((fb_pad, lb3_pad),
                                   lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n_fb * fb_pad, lb3_pad),
                                           acc_dt),
            # feature chunks are independent; the row dim revisits the
            # same accumulator block and must stay sequential
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(bins.astype(jnp.int32), gh8, leaf8, lids8)
    else:
        nr = jnp.reshape(jnp.asarray(num_rows, jnp.int32), (1,))

        def _row_clamp(s, j):
            # last live block; skipped steps revisit it (no new DMA)
            jmax = jnp.maximum((s[0] + blk - 1) // blk - 1, 0)
            return jnp.minimum(j, jmax)

        def kern_nr(s_ref, *refs):
            kern(*refs, nr_ref=s_ref, blk_rows=blk)

        out = pl.pallas_call(
            kern_nr,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_fb, n_rb),
                in_specs=[
                    pl.BlockSpec((blk, fc),
                                 lambda i, j, s: (_row_clamp(s, j), i)),
                    pl.BlockSpec((blk, 8),
                                 lambda i, j, s: (_row_clamp(s, j), 0)),
                    pl.BlockSpec((blk, 8),
                                 lambda i, j, s: (_row_clamp(s, j), 0)),
                    pl.BlockSpec((8, l_pad), lambda i, j, s: (0, 0)),
                ],
                out_specs=pl.BlockSpec((fb_pad, lb3_pad),
                                       lambda i, j, s: (i, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((n_fb * fb_pad, lb3_pad),
                                           acc_dt),
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(nr, bins.astype(jnp.int32), gh8, leaf8, lids8)

    hist = out.reshape(n_fb, fb_pad, lb3_pad)[:, :fc * Bp,
                                              :l_pad * HIST_CH]
    hist = hist.reshape(n_fb, fc, Bp, l_pad, HIST_CH)[:, :, :B, :L, :]
    return hist.reshape(F, B, L, HIST_CH).transpose(2, 0, 1, 3)


# ---------------------------------------------------------------------------
# Fused histogram → split-find kernel (ISSUE 14 / ROADMAP item 1).
#
# Same accumulation grid as `_kernel`; on the LAST row step of each
# feature chunk an epilogue runs ops/split.py's dense gain lattice
# (`eval_split_lattice`) on the VMEM-resident accumulator and emits one
# [l_pad, 128] candidate record block per chunk — gain, global feature,
# bin, missing-direction, winner left/right (G, H, count), constrained
# outputs, and the chunk's leaf totals. A tiny XLA argmax over chunks
# (`fused_build_best_splits` postlude) then replaces the full-lattice
# scan: the [L, F, B, 3] histogram never round-trips through HBM unless
# the caller asks for it (`emit_hist=True`, which feeds the histogram
# subtraction cache).
#
# Candidate record lanes (f32):
#   0 gain   1 feature(global)  2 bin  3 dir(1=missing-left)
#   4..6 left (G, H, count)     7..9 right (G, H, count)
#   10 left_out  11 right_out   12..14 leaf totals (G, H, count)
#
# Quantized path: int8 gh → int32 accumulators, scanned EXACTLY in the
# epilogue with the grid-value rescale applied at gain time
# (`eval_split_lattice(quant_scales=...)`) — no dequantized histogram is
# ever materialized.
# ---------------------------------------------------------------------------

_REC_LANES = 128


def fused_plan_ok(F: int, B: int, L: int) -> bool:
    """True when `_plan_chunks` yields a lane-aligned plan — the fused
    epilogue reshapes the accumulator [fb_pad, lb3_pad] into
    [fc, Bp, l_pad, 3], which is only exact when the pads compile away."""
    _, fc, Bp, l_pad = _plan_chunks(F, B, L)
    return (fc * Bp) % 128 == 0 and (l_pad * HIST_CH) % 128 == 0


def fused_candidate_bytes(F: int, B: int, L: int) -> int:
    """HBM bytes of the fused kernel's candidate-record output stream.

    This is the only lattice-sized traffic the fused build pass writes:
    one [l_pad, _REC_LANES] f32 record block per feature chunk, in place
    of the two-pass path's [F, B, L, 3] histogram write + re-read. Used
    by the telemetry cost model's analytical byte counts."""
    _, fc, _, l_pad = _plan_chunks(F, B, L)
    n_fb = -(-F // fc)
    return n_fb * l_pad * _REC_LANES * 4


def _split_epilogue(acc, chunk_idx, fmeta, lmeta, fmask, *, params,
                    fc: int, Bp: int, l_pad: int, use_mono: bool,
                    use_smooth: bool, pen_on: bool, quant: bool):
    """Gain lattice + per-chunk argmax over the VMEM-resident accumulator.

    acc:   [fc*Bp, l_pad*3] (f32, or int32 quantized)
    fmeta: [8, fc] int32 — rows 0 num_bins_pf, 1 nan_bin, 2 is_cat,
           3 mono_type (this chunk's feature slice)
    lmeta: [8, l_pad] f32 — rows 0 parent_output, 1 leaf_lo, 2 leaf_hi,
           3 mono_pen, 4 g_scale, 5 h_scale
    fmask: [l_pad, fc] int32 candidate-feature mask
    Returns the [l_pad, _REC_LANES] candidate record block.
    """
    hist = acc.reshape(fc, Bp, l_pad, HIST_CH).transpose(2, 0, 1, 3)
    lat = _split.eval_split_lattice(
        hist, fmeta[0], fmeta[1], fmeta[2] != 0, params,
        feature_mask=(fmask != 0),
        mono_type=fmeta[3] if use_mono else None,
        leaf_lo=lmeta[1] if use_mono else None,
        leaf_hi=lmeta[2] if use_mono else None,
        parent_output=lmeta[0] if use_smooth else None,
        mono_pen=lmeta[3] if pen_on else None,
        quant_scales=(jnp.stack([lmeta[4], lmeta[5]], axis=1)
                      if quant else None))
    N = fc * Bp * 2
    flat = lat["net"].reshape(l_pad, N)
    best = jnp.argmax(flat, axis=1)
    # gather-free winner select (Mosaic rejects lax.gather): one-hot the
    # argmax and reduce. where() keeps -inf/0 products out of the sum.
    sel = (jax.lax.broadcasted_iota(jnp.int32, (l_pad, N), 1)
           == best[:, None])

    def pick1(a):
        return jnp.sum(jnp.where(sel, a.reshape(l_pad, N), 0.0), axis=1)

    def pick3(a):
        return jnp.sum(jnp.where(sel[:, :, None], a.reshape(l_pad, N, 3),
                                 0.0), axis=1)

    gain = pick1(flat)
    lsum = pick3(lat["left"])
    rsum = pick3(lat["right"])
    f_loc = (best // (Bp * 2)).astype(jnp.int32)
    feat_g = chunk_idx * fc + f_loc
    thr = ((best // 2) % Bp).astype(jnp.int32)
    opt = (best % 2).astype(jnp.int32)
    tot0 = lat["totals"][:, 0, :]          # any feature's totals = leaf's
    rec = jnp.stack([
        gain, feat_g.astype(jnp.float32), thr.astype(jnp.float32),
        opt.astype(jnp.float32),
        lsum[:, 0], lsum[:, 1], lsum[:, 2],
        rsum[:, 0], rsum[:, 1], rsum[:, 2],
        pick1(lat["out_l"]), pick1(lat["out_r"]),
        tot0[:, 0], tot0[:, 1], tot0[:, 2],
    ], axis=1)                              # [l_pad, 15]
    return jnp.pad(rec, ((0, 0), (0, _REC_LANES - rec.shape[1])))


def _fused_kernel(bins_ref, gh_ref, leaf_ref, lids_ref, fmeta_ref,
                  lmeta_ref, fmask_ref, *refs, num_bins: int, cdt,
                  fb_pad: int, lb3_pad: int, acc_dt, n_rb: int,
                  emit_hist: bool, params, fc: int, Bp: int, l_pad: int,
                  use_mono: bool, use_smooth: bool, pen_on: bool,
                  quant: bool, nr_ref=None, blk_rows: int = 0):
    """Accumulation grid step + last-row-step split epilogue.

    Output refs: emit_hist → (hist_out, cand_out) with the histogram
    block doubling as the accumulator; else (cand_out, acc_scratch) with
    the accumulator in VMEM scratch — the histogram never leaves the
    chip."""
    if emit_hist:
        acc_ref, cand_ref = refs
    else:
        cand_ref, acc_ref = refs
    _kernel(bins_ref, gh_ref, leaf_ref, lids_ref, acc_ref,
            num_bins=num_bins, cdt=cdt, fb_pad=fb_pad, lb3_pad=lb3_pad,
            acc_dt=acc_dt, nr_ref=nr_ref, blk_rows=blk_rows)
    # program_id must be read at kernel top level (inside a pl.when body
    # it misses the interpret-mode grid-env substitution)
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == n_rb - 1)
    def _():
        cand_ref[:] = _split_epilogue(
            acc_ref[:], i, fmeta_ref[:], lmeta_ref[:],
            fmask_ref[:], params=params, fc=fc, Bp=Bp, l_pad=l_pad,
            use_mono=use_mono, use_smooth=use_smooth, pen_on=pen_on,
            quant=quant)


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "params", "hist_dtype", "interpret",
                     "emit_hist"))
def fused_build_best_splits(bins: jax.Array, gh: jax.Array,
                            row_leaf: jax.Array, leaf_ids: jax.Array, *,
                            num_bins: int, params,
                            num_bins_pf: jax.Array, nan_bin_pf: jax.Array,
                            is_cat_pf: jax.Array,
                            feature_mask: Optional[jax.Array] = None,
                            mono_type: Optional[jax.Array] = None,
                            leaf_lo: Optional[jax.Array] = None,
                            leaf_hi: Optional[jax.Array] = None,
                            parent_output: Optional[jax.Array] = None,
                            mono_pen: Optional[jax.Array] = None,
                            quant_scales: Optional[jax.Array] = None,
                            hist_dtype: str = "bfloat16",
                            interpret: bool = False,
                            num_rows: Optional[jax.Array] = None,
                            emit_hist: bool = False):
    """One VMEM-resident pass: build histograms AND find best splits.

    Contract mirrors `build_histograms_pallas` for the row-stream
    operands plus `ops.split.find_best_splits` for the metadata; returns
    ``(best, hist)`` where ``best`` is the find_best_splits dict (gain,
    feature, threshold, default_left, left_sum, right_sum, left_out,
    right_out, is_cat_split, cat_bitset — plus "slot_totals" [L, 3], the
    per-leaf (G, H, count) totals for root-sum bootstrapping) and
    ``hist`` is the [L, F, B, 3] histogram when ``emit_hist=True``
    (feeds the subtraction cache) or ``None`` (pure mode — the histogram
    never touches HBM; only [n_chunks * l_pad, 128] candidate records do).

    Winners are bit-equal to ``find_best_splits`` over the scatter-path
    histogram: the epilogue runs the identical `eval_split_lattice` ops
    on the identical accumulator block, per-chunk/within-chunk first-max
    argmaxes compose to the same global first-max tie-break, and the
    postlude's cross-chunk argmax runs over feature-contiguous chunks.

    Gates the caller must respect (`find_best_splits` fallback):
    sorted-subset categoricals, extra-trees random thresholds,
    gain scale/penalty (feature_contri, CEGB), advanced monotone bounds,
    and unaligned chunk plans (check `fused_plan_ok`).
    """
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable in this jax build")
    R, F = bins.shape
    L = int(leaf_ids.shape[0])
    B = int(num_bins)
    quant = gh.dtype == jnp.int8
    if quant and quant_scales is None:
        raise ValueError("int8 gh requires quant_scales")
    cdt = jnp.int8 if quant else jnp.dtype(hist_dtype)
    acc_dt = jnp.int32 if quant else jnp.float32
    blk, fc, Bp, l_pad = _plan_chunks(F, B, L)
    fb_pad = -(-(fc * Bp) // 128) * 128
    lb3_pad = -(-(l_pad * HIST_CH) // 128) * 128
    if fb_pad != fc * Bp or lb3_pad != l_pad * HIST_CH:
        raise ValueError(
            "fused split kernel needs an aligned chunk plan "
            f"(F={F}, B={B}, L={L}); gate on fused_plan_ok() first")

    r_pad = ((R + blk - 1) // blk) * blk
    if r_pad != R:
        bins = jnp.pad(bins, ((0, r_pad - R), (0, 0)))
        gh = jnp.pad(gh, ((0, r_pad - R), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, r_pad - R), constant_values=-1)
    n_fb = F // fc
    n_rb = r_pad // blk

    gh8 = jnp.pad(gh, ((0, 0), (0, 8 - HIST_CH)))
    leaf8 = jnp.broadcast_to(row_leaf[:, None].astype(jnp.int32),
                             (r_pad, 8))
    lids8 = jnp.broadcast_to(
        jnp.pad(leaf_ids.astype(jnp.int32), (0, l_pad - L),
                constant_values=-2)[None, :], (8, l_pad))

    use_mono = mono_type is not None
    use_smooth = params.path_smooth > 0.0
    pen_on = use_mono and params.monotone_penalty > 0.0

    zi = jnp.zeros((F,), jnp.int32)
    fmeta = jnp.stack([
        num_bins_pf.astype(jnp.int32), nan_bin_pf.astype(jnp.int32),
        is_cat_pf.astype(jnp.int32),
        mono_type.astype(jnp.int32) if use_mono else zi,
        zi, zi, zi, zi], axis=0)                          # [8, F]

    zf = jnp.zeros((l_pad,), jnp.float32)

    def _lrow(a, fill=0.0):
        if a is None:
            return zf
        return jnp.pad(a.astype(jnp.float32), (0, l_pad - L),
                       constant_values=fill)

    if quant:
        qsf = quant_scales.astype(jnp.float32)
        srow_g = jnp.broadcast_to(qsf[0], (l_pad,))
        srow_h = jnp.broadcast_to(qsf[1], (l_pad,))
    else:
        srow_g = srow_h = zf
    lmeta = jnp.stack([
        _lrow(parent_output), _lrow(leaf_lo), _lrow(leaf_hi),
        _lrow(mono_pen, fill=1.0), srow_g, srow_h, zf, zf],
        axis=0)                                           # [8, l_pad]

    if feature_mask is None:
        fmask = jnp.ones((l_pad, F), jnp.int32)
    else:
        fm2 = (feature_mask if feature_mask.ndim == 2
               else jnp.broadcast_to(feature_mask[None, :], (L, F)))
        fmask = jnp.pad(fm2.astype(jnp.int32), ((0, l_pad - L), (0, 0)),
                        constant_values=1)

    kern = functools.partial(
        _fused_kernel, num_bins=Bp, cdt=cdt, fb_pad=fb_pad,
        lb3_pad=lb3_pad, acc_dt=acc_dt, n_rb=n_rb, emit_hist=emit_hist,
        params=params, fc=fc, Bp=Bp, l_pad=l_pad, use_mono=use_mono,
        use_smooth=use_smooth, pen_on=pen_on, quant=quant)

    cand_shape = jax.ShapeDtypeStruct((n_fb * l_pad, _REC_LANES),
                                      jnp.float32)
    hist_shape = jax.ShapeDtypeStruct((n_fb * fb_pad, lb3_pad), acc_dt)
    if emit_hist:
        out_shape = (hist_shape, cand_shape)
        scratch = []
    else:
        out_shape = (cand_shape,)
        scratch = [pltpu.VMEM((fb_pad, lb3_pad), acc_dt)]
    operands = (bins.astype(jnp.int32), gh8, leaf8, lids8, fmeta, lmeta,
                fmask)

    if num_rows is None:
        def _specs(w):
            row = [
                pl.BlockSpec((blk, fc), lambda i, j: (j, i)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
                pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
            ]
            meta = [
                pl.BlockSpec((8, l_pad), lambda i, j: (0, 0)),
                pl.BlockSpec((8, fc), lambda i, j: (0, i)),
                pl.BlockSpec((8, l_pad), lambda i, j: (0, 0)),
                pl.BlockSpec((l_pad, fc), lambda i, j: (0, i)),
            ]
            hist_o = [pl.BlockSpec((fb_pad, lb3_pad), lambda i, j: (i, 0))]
            cand_o = [pl.BlockSpec((l_pad, _REC_LANES),
                                   lambda i, j: (i, 0))]
            return row + meta, (hist_o + cand_o if w else cand_o)

        in_specs, out_specs = _specs(emit_hist)
        outs = pl.pallas_call(
            kern,
            grid=(n_fb, n_rb),
            in_specs=in_specs,
            out_specs=tuple(out_specs) if emit_hist else out_specs[0],
            out_shape=out_shape if emit_hist else out_shape[0],
            scratch_shapes=scratch,
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(*operands)
    else:
        nr = jnp.reshape(jnp.asarray(num_rows, jnp.int32), (1,))

        def _row_clamp(s, j):
            jmax = jnp.maximum((s[0] + blk - 1) // blk - 1, 0)
            return jnp.minimum(j, jmax)

        def kern_nr(s_ref, *refs):
            kern(*refs, nr_ref=s_ref, blk_rows=blk)

        in_specs = [
            pl.BlockSpec((blk, fc), lambda i, j, s: (_row_clamp(s, j), i)),
            pl.BlockSpec((blk, 8), lambda i, j, s: (_row_clamp(s, j), 0)),
            pl.BlockSpec((blk, 8), lambda i, j, s: (_row_clamp(s, j), 0)),
            pl.BlockSpec((8, l_pad), lambda i, j, s: (0, 0)),
            pl.BlockSpec((8, fc), lambda i, j, s: (0, i)),
            pl.BlockSpec((8, l_pad), lambda i, j, s: (0, 0)),
            pl.BlockSpec((l_pad, fc), lambda i, j, s: (0, i)),
        ]
        hist_o = pl.BlockSpec((fb_pad, lb3_pad), lambda i, j, s: (i, 0))
        cand_o = pl.BlockSpec((l_pad, _REC_LANES), lambda i, j, s: (i, 0))
        outs = pl.pallas_call(
            kern_nr,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_fb, n_rb),
                in_specs=in_specs,
                out_specs=((hist_o, cand_o) if emit_hist else cand_o),
                scratch_shapes=tuple(scratch),
            ),
            out_shape=out_shape if emit_hist else out_shape[0],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(nr, *operands)

    if emit_hist:
        hist_raw, cand = outs
        hist = hist_raw.reshape(n_fb, fb_pad, lb3_pad)
        hist = hist.reshape(n_fb, fc, Bp, l_pad, HIST_CH)[:, :, :B, :L, :]
        hist = hist.reshape(F, B, L, HIST_CH).transpose(2, 0, 1, 3)
    else:
        hist, cand = None, outs

    # ---- XLA postlude: tiny argmax over chunks replaces the full scan
    cand = cand.reshape(n_fb, l_pad, _REC_LANES)[:, :L, :]
    bc = jnp.argmax(cand[:, :, 0], axis=0)                # [L] first-max
    rec = jnp.take_along_axis(cand, bc[None, :, None], axis=0)[0]
    gain = rec[:, 0]
    feat = rec[:, 1].astype(jnp.int32)
    thr = rec[:, 2].astype(jnp.int32)
    is_cat_split = jnp.take(is_cat_pf.astype(bool), feat)
    member = ((jnp.arange(B, dtype=jnp.int32)[None, :] == thr[:, None])
              & is_cat_split[:, None] & jnp.isfinite(gain)[:, None])
    best = {
        "gain": gain,
        "feature": feat,
        "threshold": thr,
        "default_left": rec[:, 3] == 1.0,
        "left_sum": rec[:, 4:7],
        "right_sum": rec[:, 7:10],
        "left_out": rec[:, 10],
        "right_out": rec[:, 11],
        "is_cat_split": is_cat_split,
        "cat_bitset": _split.pack_member_bitset(member),
        "slot_totals": rec[:, 12:15],
    }
    return best, hist


_FUSED_PROBE: dict = {}


def fused_probe_ok() -> bool:
    """One-time compile-and-run probe of the fused kernel on the real
    backend (mirrors ops.histogram's pallas training probe); always True
    caching aside. CPU/interpret callers skip this (fused_split="on")."""
    if "ok" in _FUSED_PROBE:
        return _FUSED_PROBE["ok"]
    if not pallas_available():
        _FUSED_PROBE["ok"] = False
        return False
    try:
        F, B, L, R = 16, 8, 4, 256
        bins = jnp.zeros((R, F), jnp.int32)
        gh = jnp.ones((R, HIST_CH), jnp.float32)
        rl = jnp.zeros((R,), jnp.int32)
        best, _ = fused_build_best_splits(
            bins, gh, rl, jnp.arange(L, dtype=jnp.int32), num_bins=B,
            params=_split.SplitParams(),
            num_bins_pf=jnp.full((F,), B, jnp.int32),
            nan_bin_pf=jnp.full((F,), -1, jnp.int32),
            is_cat_pf=jnp.zeros((F,), bool))
        jax.block_until_ready(best["gain"])
        _FUSED_PROBE["ok"] = True
    except Exception:  # pragma: no cover - only on real hardware quirks
        _FUSED_PROBE["ok"] = False
    return _FUSED_PROBE["ok"]


def _reset_fused_probe():
    _FUSED_PROBE.clear()


# ---------------------------------------------------------------------------
# Class-shared root histogram (ISSUE-14 satellite): the class-batched
# multiclass build vmaps the whole tree build, which batches EVERY
# pallas operand — the bins matrix, logically shared across classes, is
# presented K× to the root launch. This kernel instead streams bins ONCE
# and reduces all K classes' (g, h, count) lanes against the same
# one-hot: ghl is [blk, K*3] with the root-leaf row mask applied
# elementwise, so the MXU emits [fc*Bp, K*3] per chunk.
# ---------------------------------------------------------------------------


def _class_kernel(bins_ref, ghk_ref, leaf_ref, out_ref, *, num_bins: int,
                  cdt, fb_pad: int, kc_pad: int, acc_dt,
                  root_slot: int):
    j = pl.program_id(1)
    blk, fc = bins_ref.shape

    def compute():
        bb = bins_ref[:]
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (blk, fc, num_bins), 2)
        onehot = (bb[:, :, None] == iota_b).astype(cdt).reshape(
            blk, fc * num_bins)
        if fb_pad != fc * num_bins:
            onehot = jnp.pad(onehot,
                             ((0, 0), (0, fb_pad - fc * num_bins)))
        mask = (leaf_ref[:, 0:1] == root_slot).astype(cdt)  # [blk, 1]
        ghl = mask * ghk_ref[:].astype(cdt)                 # [blk, kc_pad]
        return jax.lax.dot_general(
            onehot, ghl, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)                  # [fb_pad, kc_pad]

    @pl.when(j == 0)
    def _():
        out_ref[:] = compute()

    @pl.when(j > 0)
    def _():
        out_ref[:] = out_ref[:] + compute()


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "hist_dtype", "interpret", "root_slot"))
def build_root_histograms_classes(bins: jax.Array, gh_k: jax.Array,
                                  row_leaf: jax.Array, *, num_bins: int,
                                  hist_dtype: str = "bfloat16",
                                  interpret: bool = False,
                                  root_slot: int = 0) -> jax.Array:
    """Root histograms for all K classes with ONE pass over bins.

    bins [R, F], gh_k [K, R, 3] (f32 or int8 quantized), row_leaf [R]
    int32 → [K, F, B, 3] (f32; int32 when quantized). Bit-equal to K
    independent `build_histograms_pallas` root launches: the per-class
    lanes hit the same MXU contraction against the same one-hot, in the
    same row-block order."""
    if not _HAS_PALLAS:
        raise RuntimeError("pallas unavailable in this jax build")
    R, F = bins.shape
    K = int(gh_k.shape[0])
    B = int(num_bins)
    quant = gh_k.dtype == jnp.int8
    cdt = jnp.int8 if quant else jnp.dtype(hist_dtype)
    acc_dt = jnp.int32 if quant else jnp.float32
    blk, fc, Bp, _ = _plan_chunks(F, B, max(K, 1))
    fb_pad = -(-(fc * Bp) // 128) * 128
    kc = K * HIST_CH
    kc_pad = -(-kc // 128) * 128

    r_pad = ((R + blk - 1) // blk) * blk
    if r_pad != R:
        bins = jnp.pad(bins, ((0, r_pad - R), (0, 0)))
        gh_k = jnp.pad(gh_k, ((0, 0), (0, r_pad - R), (0, 0)))
        row_leaf = jnp.pad(row_leaf, (0, r_pad - R), constant_values=-1)
    n_fb = F // fc
    n_rb = r_pad // blk

    ghk = gh_k.transpose(1, 0, 2).reshape(r_pad, kc)      # [R, K*3]
    if kc_pad != kc:
        ghk = jnp.pad(ghk, ((0, 0), (0, kc_pad - kc)))
    leaf8 = jnp.broadcast_to(row_leaf[:, None].astype(jnp.int32),
                             (r_pad, 8))

    out = pl.pallas_call(
        functools.partial(_class_kernel, num_bins=Bp, cdt=cdt,
                          fb_pad=fb_pad, kc_pad=kc_pad, acc_dt=acc_dt,
                          root_slot=root_slot),
        grid=(n_fb, n_rb),
        in_specs=[
            pl.BlockSpec((blk, fc), lambda i, j: (j, i)),
            pl.BlockSpec((blk, kc_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((blk, 8), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((fb_pad, kc_pad), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_fb * fb_pad, kc_pad), acc_dt),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bins.astype(jnp.int32), ghk, leaf8)

    hist = out.reshape(n_fb, fb_pad, kc_pad)[:, :fc * Bp, :kc]
    hist = hist.reshape(n_fb, fc, Bp, K, HIST_CH)[:, :, :B, :, :]
    return hist.reshape(F, B, K, HIST_CH).transpose(2, 0, 1, 3)
