"""Micro-batching scheduler: coalesce concurrent predict requests into
one kernel call.

The per-call fixed cost of a prediction (HTTP handling, Python dispatch,
native-handle entry, device launch) dwarfs the marginal per-row cost —
the same amortize-fixed-cost argument the batched GPU tree-walk
literature makes for trees (PAPERS.md: "GPU-acceleration for
Large-scale Tree Boosting") applied to *request aggregation*: N
concurrent 16-row requests as one 16N-row kernel call run at nearly the
cost of one.

Scheduling contract:

- A batch closes when the queue holds ``max_batch_rows`` rows, or
  ``max_wait_us`` after its OLDEST pending request arrived, whichever
  comes first. A lone request therefore waits out the deadline — tune
  ``max_wait_us`` down for latency-sensitive single-stream traffic.
- Requests are never split across batches; a request larger than
  ``max_batch_rows`` becomes its own (bucket-padded) oversized batch.
- Batches are padded up a fixed power-of-two bucket ladder before the
  kernel call, so the jitted device path sees at most
  ``log2(max_batch_rows) + 1`` distinct shapes and never retraces on a
  novel request mix (tree walks are row-independent, so padding rows
  never changes real rows' results; pad rows are sliced off before
  scatter).
- Admission control: the queue is bounded at ``max_queue_rows``. A
  request that would overflow it fast-fails with :class:`Overloaded`
  (retriable) instead of queuing unbounded latency — the caller (or the
  HTTP layer, as 429 + Retry-After) decides whether to retry.

Whole-model guarantee: the batcher issues ONE ``predict_fn`` call per
batch, and ``predict_fn`` (``ModelRegistry.predict`` in the server)
resolves the active model exactly once per call — so every request's
result comes from exactly one model version, never a mix, even while a
hot-swap lands mid-burst (see ``registry.py`` and the
``PredictSession`` snapshot contract in ``engine.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .metrics import ServingMetrics

__all__ = ["MicroBatcher", "Overloaded", "bucket_rows"]


class Overloaded(RuntimeError):
    """Admission-control fast-fail; the request was NOT enqueued.

    ``retriable`` is True by definition: nothing about the request was
    wrong, the queue was full — retry after backoff.
    """

    retriable = True

    def __init__(self, queued_rows: int, max_queue_rows: int):
        super().__init__(
            f"serving queue full ({queued_rows}/{max_queue_rows} rows); "
            "retriable")
        self.queued_rows = queued_rows
        self.max_queue_rows = max_queue_rows


def bucket_rows(n: int, min_bucket: int, max_batch_rows: int) -> int:
    """Pad target for an ``n``-row batch: next power of two in
    ``[min_bucket, max_batch_rows]``; oversized batches (a single
    request above ``max_batch_rows``) pad to the next power of two so
    even they reuse ladder shapes."""
    b = max(int(min_bucket), 1)
    while b < n:
        b <<= 1
    return b if n > max_batch_rows else min(b, int(max_batch_rows))


class _Pending:
    __slots__ = ("X", "done", "result", "error", "tag", "t_enqueue",
                 "abandoned", "callback")

    def __init__(self, X: np.ndarray):
        self.X = X
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.tag = None
        self.t_enqueue = time.monotonic()
        self.abandoned = False
        self.callback = None

    def fire(self):
        self.done.set()
        cb = self.callback
        if cb is not None:
            cb(self.result, self.error, self.tag)


class MicroBatcher:
    """Coalesce concurrent ``submit()`` calls into batched
    ``predict_fn`` calls.

    ``predict_fn(X) -> result`` or ``(result, tag)``: called with a
    2-D float64 matrix whose row count is a ladder bucket; must return
    per-row results (1-D, or 2-D with rows first). ``tag`` (e.g. the
    serving model version) is handed back to every request of the
    batch.
    """

    def __init__(self, predict_fn: Callable, *,
                 max_batch_rows: int = 1024,
                 max_wait_us: int = 2000,
                 max_queue_rows: Optional[int] = None,
                 min_bucket: int = 16,
                 metrics: Optional[ServingMetrics] = None,
                 model: str = "default"):
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        if max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        self._predict = predict_fn
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = max_wait_us / 1e6
        self.max_queue_rows = int(max_queue_rows
                                  if max_queue_rows is not None
                                  else 8 * max_batch_rows)
        self.min_bucket = int(min_bucket)
        self.metrics = metrics or ServingMetrics()
        self.model = model
        self._cond = threading.Condition()
        self._queue: List[_Pending] = []
        self._queued_rows = 0
        self._closed = False
        self._worker = threading.Thread(target=self._loop,
                                        name=f"batcher[{model}]",
                                        daemon=True)
        self._worker.start()

    # -- client side ---------------------------------------------------
    def submit(self, X, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the batched prediction for ``X`` is ready.

        Raises :class:`Overloaded` (without enqueueing) when admission
        control rejects, ``TimeoutError`` past ``timeout``, or whatever
        the model raised for this batch.
        """
        res, _tag = self.submit_tagged(X, timeout=timeout)
        return res

    def submit_tagged(self, X, timeout: Optional[float] = None
                      ) -> Tuple[np.ndarray, object]:
        """`submit`, also returning the batch's model tag (version)."""
        p = self._enqueue(X)
        if not p.done.wait(timeout):
            # unregister the abandoned promise: if it is still queued,
            # remove it (its rows must stop counting against admission
            # control); if a worker already took the batch, mark it so
            # _run_batch won't fill a slot nobody reads
            with self._cond:
                p.abandoned = True
                if p in self._queue:
                    self._queue.remove(p)
                    self._queued_rows -= len(p.X)
            raise TimeoutError("prediction did not complete in time")
        if p.error is not None:
            raise p.error
        return p.result, p.tag

    def submit_async(self, X, callback: Callable) -> None:
        """Enqueue ``X`` and return immediately; ``callback(result,
        error, tag)`` fires exactly once, on the batcher worker thread,
        when the batch lands. The async front-end's entry point: no
        thread parks per request. Admission failures (:class:
        `Overloaded`, closed, bad shape) still raise synchronously —
        the caller holds the connection and maps them itself.
        """
        self._enqueue(X, callback)

    def _enqueue(self, X, callback: Optional[Callable] = None
                 ) -> _Pending:
        X = np.ascontiguousarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError("submit expects a nonempty 1-D row or "
                             "2-D [rows, features] matrix")
        p = _Pending(X)
        p.callback = callback   # attach BEFORE the worker can see it
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._queued_rows + len(X) > self.max_queue_rows:
                self.metrics.on_overload()
                raise Overloaded(self._queued_rows, self.max_queue_rows)
            self._queue.append(p)
            self._queued_rows += len(X)
            self._cond.notify_all()
        self.metrics.on_request(self.model, len(X))
        return p

    def load(self) -> int:
        """Rows queued right now — the replica router's depth signal."""
        with self._cond:
            return self._queued_rows

    def close(self, drain: bool = True):
        """Stop the worker; ``drain`` runs queued requests first, else
        they fail with a closed error."""
        with self._cond:
            self._closed = True
            if not drain:
                for p in self._queue:
                    p.error = RuntimeError("batcher closed")
                    p.fire()
                self._queue.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        self._worker.join(timeout=30)

    # -- worker side ---------------------------------------------------
    def _take_batch(self) -> List[_Pending]:
        """Pop whole requests up to ``max_batch_rows`` (at least one)."""
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            if batch and rows + len(nxt.X) > self.max_batch_rows:
                break
            batch.append(self._queue.pop(0))
            rows += len(nxt.X)
        self._queued_rows -= rows
        return batch

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                # deadline anchored at the OLDEST pending request
                deadline = self._queue[0].t_enqueue + self.max_wait_s
                while (self._queued_rows < self.max_batch_rows
                       and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                    if not self._queue:   # drained by close(drain=False)
                        break
                if not self._queue:
                    continue
                batch = self._take_batch()
            self._run_batch(batch)

    def _run_batch(self, batch: List[_Pending]):
        t0 = time.monotonic()
        rows = sum(len(p.X) for p in batch)
        X = batch[0].X if len(batch) == 1 else np.concatenate(
            [p.X for p in batch])
        target = bucket_rows(rows, self.min_bucket, self.max_batch_rows)
        if target > rows:
            X = np.concatenate(
                [X, np.zeros((target - rows, X.shape[1]), X.dtype)])
        try:
            out = self._predict(X)
            tag = None
            if isinstance(out, tuple):
                out, tag = out
            out = np.asarray(out)
            if out.shape[0] != len(X):
                raise RuntimeError(
                    f"predict_fn returned {out.shape[0]} rows for a "
                    f"{len(X)}-row batch")
        except BaseException as e:  # noqa: BLE001 — forwarded per request
            for p in batch:
                self.metrics.on_error(self.model)
                p.error = e
                p.fire()
            return
        compute_s = time.monotonic() - t0
        self.metrics.on_batch(rows, t0 - batch[0].t_enqueue, compute_s)
        for p in batch:
            # each request's own wait, row-weighted — the per-batch
            # observation above only sees the oldest request, which
            # under-weights coalesced bursts (ISSUE 15)
            self.metrics.on_request_wait(t0 - p.t_enqueue, len(p.X))
        off = 0
        for p in batch:
            if not p.abandoned:   # timed-out caller left; don't fill
                p.result = out[off:off + len(p.X)]
                p.tag = tag
            off += len(p.X)
            p.fire()
