"""Serving observability: lock-cheap counters + ring-buffer latency
histograms, rendered in the Prometheus text exposition format.

No reference analog — LightGBM stops at the C API boundary
(src/c_api.cpp) and ships no service layer; the field set follows what
the micro-batching scheduler needs to be tuned in production: queue-wait
vs compute split (is latency admission or the kernel?), batch-size
distribution (is coalescing happening?), and per-model request/error
counts (is a deploy failing?).

The primitives (Counter, RingHistogram) and the text renderer live in
``telemetry/core.py`` now — they started here and were generalized so
training shares them; this module keeps the serving-specific field set
and its exact render bytes (pinned by tests). A ``PredictionServer``
mounts this set onto its :class:`~lightgbm_tpu.telemetry.core.
MetricsRegistry` as a collector, so ``/metrics`` is one registry render
on both the training and serving sides.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from ..telemetry.core import (Counter, RingHistogram, render_counter,
                              render_summary)

__all__ = ["Counter", "RingHistogram", "ServingMetrics"]


class ServingMetrics:
    """The metric set of the serving subsystem, one instance per server.

    Exported families (``render()``, Prometheus text format):

    ========================================  =============================
    field                                     meaning
    ========================================  =============================
    serve_requests_total{model=}              requests accepted per model
    serve_errors_total{model=}                requests that raised
    serve_overload_total                      fast-failed at admission
    serve_rows_total                          rows predicted (pre-padding)
    serve_batches_total                       kernel calls issued
    serve_batch_rows{quantile=} / _mean       coalesced batch size
    serve_queue_wait_seconds{quantile=}       enqueue -> batch start
    serve_compute_seconds{quantile=}          kernel call duration
    serve_rows_per_s                          window throughput gauge
    serve_swaps_total / serve_rollbacks_total registry movements
    serve_uptime_seconds                      since metrics creation
    serve_request_wait_seconds{quantile=}     per-REQUEST enqueue wait
    serve_row_wait_p99                        row-weighted wait p99
    serve_budget_rejected_total{model=}       QPS-budget admission fails
    ========================================  =============================

    ``serve_queue_wait_seconds`` observes once per BATCH (the oldest
    request's wait) — under a coalesced burst that under-weights the
    many requests that joined late. ``serve_request_wait_seconds``
    observes every request, and ``serve_row_wait_p99`` weights each
    request's wait by its row count, so a 1000-row straggler moves the
    tail the way 1000 single-row stragglers would (ISSUE 15 satellite).
    """

    def __init__(self, hist_size: int = 4096):
        self._lock = threading.Lock()        # label-map creation only
        self.requests_total: Dict[str, Counter] = {}
        self.errors_total: Dict[str, Counter] = {}
        self.overload_total = Counter()
        self.rows_total = Counter()
        self.batches_total = Counter()
        self.swaps_total = Counter()
        self.rollbacks_total = Counter()
        self.budget_rejected_total: Dict[str, Counter] = {}
        self.batch_rows = RingHistogram(hist_size)
        self.queue_wait_s = RingHistogram(hist_size)
        self.compute_s = RingHistogram(hist_size)
        self.request_wait_s = RingHistogram(hist_size)
        # paired rings (same observe cadence): each request's wait next
        # to its row count, so the row-weighted percentile can be
        # recomputed over the retained window at render time
        self._req_wait = RingHistogram(hist_size)
        self._req_rows = RingHistogram(hist_size)
        # (monotonic_ts, rows) per batch: windowed rows/s gauge
        self._thru = RingHistogram(hist_size)
        self._thru_ts = RingHistogram(hist_size)
        self._t0 = time.monotonic()

    # -- recording hooks (called by batcher/registry/server) -----------
    def _labelled(self, family: Dict[str, Counter], model: str) -> Counter:
        c = family.get(model)
        if c is None:
            with self._lock:
                c = family.setdefault(model, Counter())
        return c

    def on_request(self, model: str, rows: int):
        self._labelled(self.requests_total, model).inc()

    def on_error(self, model: str):
        self._labelled(self.errors_total, model).inc()

    def on_overload(self):
        self.overload_total.inc()

    def on_batch(self, rows: int, queue_wait_s: float, compute_s: float):
        now = time.monotonic()
        self.batches_total.inc()
        self.rows_total.inc(rows)
        self.batch_rows.observe(float(rows))
        self.queue_wait_s.observe(queue_wait_s)
        self.compute_s.observe(compute_s)
        self._thru.observe(float(rows))
        self._thru_ts.observe(now)

    def on_request_wait(self, wait_s: float, rows: int):
        """Per-request wait at batch start (one call per request of the
        batch, row count attached for the weighted tail)."""
        self.request_wait_s.observe(wait_s)
        self._req_wait.observe(wait_s)
        self._req_rows.observe(float(rows))

    def on_budget_rejected(self, model: str):
        self._labelled(self.budget_rejected_total, model).inc()

    def row_wait_p99(self) -> float:
        """Row-weighted p99 of request wait over the retained window:
        the wait below which 99% of ROWS (not requests) started."""
        w = self._req_wait.window()
        r = self._req_rows.window()
        m = min(w.size, r.size)      # rings race by at most one slot
        if m == 0:
            return 0.0
        w, r = w[:m], r[:m]
        order = w.argsort()
        w, r = w[order], r[order]
        cum = r.cumsum()
        total = cum[-1]
        if total <= 0:
            return float(w[-1])
        idx = int((cum >= 0.99 * total).argmax())
        return float(w[idx])

    def mean_batch_rows(self) -> float:
        return self.batch_rows.summary()[2]

    def rows_per_s(self) -> float:
        """Throughput over the retained batch window."""
        ts = self._thru_ts.window()
        if ts.size < 2:
            return 0.0
        span = float(ts.max() - ts.min())
        if span <= 0:
            return 0.0
        return float(self._thru.window().sum()) / span

    # -- export --------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4)."""
        out: List[str] = []

        render_counter(out, "serve_requests_total",
                       "Accepted predict requests",
                       [(f'{{model="{m}"}}', c.value)
                        for m, c in sorted(self.requests_total.items())] or
                       [("", 0)])
        render_counter(out, "serve_errors_total", "Requests that raised",
                       [(f'{{model="{m}"}}', c.value)
                        for m, c in sorted(self.errors_total.items())] or
                       [("", 0)])
        render_counter(out, "serve_overload_total",
                       "Requests fast-failed at admission control",
                       [("", self.overload_total.value)])
        render_counter(out, "serve_rows_total",
                       "Rows predicted (pre-padding)",
                       [("", self.rows_total.value)])
        render_counter(out, "serve_batches_total", "Coalesced kernel calls",
                       [("", self.batches_total.value)])
        render_counter(out, "serve_swaps_total", "Model hot-swaps",
                       [("", self.swaps_total.value)])
        render_counter(out, "serve_rollbacks_total", "Model rollbacks",
                       [("", self.rollbacks_total.value)])
        render_summary(out, "serve_batch_rows", "Rows per coalesced batch",
                       self.batch_rows)
        render_summary(out, "serve_queue_wait_seconds",
                       "Enqueue to batch start", self.queue_wait_s)
        render_summary(out, "serve_compute_seconds",
                       "Kernel call duration", self.compute_s)
        out.append("# HELP serve_rows_per_s Window throughput")
        out.append("# TYPE serve_rows_per_s gauge")
        out.append(f"serve_rows_per_s {self.rows_per_s():.9g}")
        out.append("# HELP serve_uptime_seconds Seconds since start")
        out.append("# TYPE serve_uptime_seconds gauge")
        out.append(
            f"serve_uptime_seconds {time.monotonic() - self._t0:.3f}")
        render_summary(out, "serve_request_wait_seconds",
                       "Per-request enqueue to batch start",
                       self.request_wait_s)
        out.append("# HELP serve_row_wait_p99 Row-weighted wait p99")
        out.append("# TYPE serve_row_wait_p99 gauge")
        out.append(f"serve_row_wait_p99 {self.row_wait_p99():.9g}")
        render_counter(out, "serve_budget_rejected_total",
                       "Requests rejected by per-model QPS budgets",
                       [(f'{{model="{m}"}}', c.value)
                        for m, c in
                        sorted(self.budget_rejected_total.items())] or
                       [("", 0)])
        return "\n".join(out) + "\n"
