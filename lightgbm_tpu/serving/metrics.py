"""Serving observability: lock-cheap counters + ring-buffer latency
histograms, rendered in the Prometheus text exposition format.

No reference analog — LightGBM stops at the C API boundary
(src/c_api.cpp) and ships no service layer; the field set follows what
the micro-batching scheduler needs to be tuned in production: queue-wait
vs compute split (is latency admission or the kernel?), batch-size
distribution (is coalescing happening?), and per-model request/error
counts (is a deploy failing?).

The primitives (Counter, RingHistogram) and the text renderer live in
``telemetry/core.py`` now — they started here and were generalized so
training shares them; this module keeps the serving-specific field set
and its exact render bytes (pinned by tests). A ``PredictionServer``
mounts this set onto its :class:`~lightgbm_tpu.telemetry.core.
MetricsRegistry` as a collector, so ``/metrics`` is one registry render
on both the training and serving sides.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from ..telemetry.core import (Counter, RingHistogram, render_counter,
                              render_summary)

__all__ = ["Counter", "RingHistogram", "ServingMetrics"]


class ServingMetrics:
    """The metric set of the serving subsystem, one instance per server.

    Exported families (``render()``, Prometheus text format):

    ========================================  =============================
    field                                     meaning
    ========================================  =============================
    serve_requests_total{model=}              requests accepted per model
    serve_errors_total{model=}                requests that raised
    serve_overload_total                      fast-failed at admission
    serve_rows_total                          rows predicted (pre-padding)
    serve_batches_total                       kernel calls issued
    serve_batch_rows{quantile=} / _mean       coalesced batch size
    serve_queue_wait_seconds{quantile=}       enqueue -> batch start
    serve_compute_seconds{quantile=}          kernel call duration
    serve_rows_per_s                          window throughput gauge
    serve_swaps_total / serve_rollbacks_total registry movements
    serve_uptime_seconds                      since metrics creation
    ========================================  =============================
    """

    def __init__(self, hist_size: int = 4096):
        self._lock = threading.Lock()        # label-map creation only
        self.requests_total: Dict[str, Counter] = {}
        self.errors_total: Dict[str, Counter] = {}
        self.overload_total = Counter()
        self.rows_total = Counter()
        self.batches_total = Counter()
        self.swaps_total = Counter()
        self.rollbacks_total = Counter()
        self.batch_rows = RingHistogram(hist_size)
        self.queue_wait_s = RingHistogram(hist_size)
        self.compute_s = RingHistogram(hist_size)
        # (monotonic_ts, rows) per batch: windowed rows/s gauge
        self._thru = RingHistogram(hist_size)
        self._thru_ts = RingHistogram(hist_size)
        self._t0 = time.monotonic()

    # -- recording hooks (called by batcher/registry/server) -----------
    def _labelled(self, family: Dict[str, Counter], model: str) -> Counter:
        c = family.get(model)
        if c is None:
            with self._lock:
                c = family.setdefault(model, Counter())
        return c

    def on_request(self, model: str, rows: int):
        self._labelled(self.requests_total, model).inc()

    def on_error(self, model: str):
        self._labelled(self.errors_total, model).inc()

    def on_overload(self):
        self.overload_total.inc()

    def on_batch(self, rows: int, queue_wait_s: float, compute_s: float):
        now = time.monotonic()
        self.batches_total.inc()
        self.rows_total.inc(rows)
        self.batch_rows.observe(float(rows))
        self.queue_wait_s.observe(queue_wait_s)
        self.compute_s.observe(compute_s)
        self._thru.observe(float(rows))
        self._thru_ts.observe(now)

    def mean_batch_rows(self) -> float:
        return self.batch_rows.summary()[2]

    def rows_per_s(self) -> float:
        """Throughput over the retained batch window."""
        ts = self._thru_ts.window()
        if ts.size < 2:
            return 0.0
        span = float(ts.max() - ts.min())
        if span <= 0:
            return 0.0
        return float(self._thru.window().sum()) / span

    # -- export --------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4)."""
        out: List[str] = []

        render_counter(out, "serve_requests_total",
                       "Accepted predict requests",
                       [(f'{{model="{m}"}}', c.value)
                        for m, c in sorted(self.requests_total.items())] or
                       [("", 0)])
        render_counter(out, "serve_errors_total", "Requests that raised",
                       [(f'{{model="{m}"}}', c.value)
                        for m, c in sorted(self.errors_total.items())] or
                       [("", 0)])
        render_counter(out, "serve_overload_total",
                       "Requests fast-failed at admission control",
                       [("", self.overload_total.value)])
        render_counter(out, "serve_rows_total",
                       "Rows predicted (pre-padding)",
                       [("", self.rows_total.value)])
        render_counter(out, "serve_batches_total", "Coalesced kernel calls",
                       [("", self.batches_total.value)])
        render_counter(out, "serve_swaps_total", "Model hot-swaps",
                       [("", self.swaps_total.value)])
        render_counter(out, "serve_rollbacks_total", "Model rollbacks",
                       [("", self.rollbacks_total.value)])
        render_summary(out, "serve_batch_rows", "Rows per coalesced batch",
                       self.batch_rows)
        render_summary(out, "serve_queue_wait_seconds",
                       "Enqueue to batch start", self.queue_wait_s)
        render_summary(out, "serve_compute_seconds",
                       "Kernel call duration", self.compute_s)
        out.append("# HELP serve_rows_per_s Window throughput")
        out.append("# TYPE serve_rows_per_s gauge")
        out.append(f"serve_rows_per_s {self.rows_per_s():.9g}")
        out.append("# HELP serve_uptime_seconds Seconds since start")
        out.append("# TYPE serve_uptime_seconds gauge")
        out.append(
            f"serve_uptime_seconds {time.monotonic() - self._t0:.3f}")
        return "\n".join(out) + "\n"
