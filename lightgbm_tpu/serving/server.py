"""Async prediction server over the micro-batcher, replica fleet and
model registry — ``python -m lightgbm_tpu serve model=<file>``.

Stdlib-only, selector-based: ONE event-loop thread owns every socket
(accept, parse, write), and a ``/predict`` body is handed to the
per-model :class:`~.batcher.MicroBatcher` (or the active version's
:class:`~.replica.ReplicaSet`) via ``submit_async`` — the response is
written when the batch completion fires, so a thousand in-flight
requests cost a thousand small buffers, not a thousand parked threads.
This replaces the thread-per-request ``ThreadingHTTPServer`` front end:
at 64+ concurrent clients the old model spent its time context-
switching readers that were all blocked on the same batcher condvar.

Request routing:

- model has a replica fleet (``replicas=N``): the request goes straight
  to the least-loaded replica's batcher — per-device queues, one
  in-flight kernel per device, results tagged with the fleet's pinned
  ModelVersion.
- otherwise: the classic per-model batcher whose ``predict_fn`` is
  ``registry.predict`` (resolves the active version once per BATCH —
  the whole-model guarantee under hot-swap).
- per-model QPS budgets (``qps_budget=``) gate admission before either
  queue: 429 with ``status="budget_exceeded"``, so one tenant's burst
  cannot occupy another's batcher capacity.

Endpoints (unchanged contract):

- ``POST /predict[?model=name]`` — body either JSON
  ``{"data": [[...], ...]}`` (``"rows"`` accepted as an alias) or a raw
  ``.npy`` matrix (``Content-Type: application/x-npy`` or
  ``application/octet-stream``). JSON in -> JSON
  ``{"predictions": ..., "model": ..., "version": ...}`` out; npy in ->
  npy float64 out with the model identity in ``X-Model-Name`` /
  ``X-Model-Version`` headers (bit-exact round-trip). Overload ->
  ``429`` + ``Retry-After`` with ``{"status": "overloaded",
  "retriable": true}``; budget -> ``429`` with
  ``{"status": "budget_exceeded", "retriable": true}``.
- ``GET /models`` — active versions (now incl. compiled/replica
  state); ``POST /models/swap`` ``{"name", "file"}`` hot-swaps (load +
  full-ladder warm off-path on a helper thread, then atomic publish);
  ``POST /models/rollback`` ``{"name"?}`` republishes the previous
  version. Control ops never run on the event loop.
- ``GET /healthz/alive`` — 200 while the process serves HTTP at all
  (liveness); ``GET /healthz`` / ``GET /healthz/ready`` — 200 once a
  model serves AND the server is not draining, 503 otherwise.
- ``GET /metrics`` — Prometheus text (field reference: metrics.py).

Graceful drain: ``drain()`` (wired to SIGTERM by the CLI ``serve``
path) flips readiness, stops accepting connections, finishes queued
batcher work (``MicroBatcher.close(drain=True)``, replica fleets
included), flushes the responses those completions produce, then
returns — a rolling restart loses no accepted request.
"""

from __future__ import annotations

import io
import json
import selectors
import socket
import threading
from collections import deque
from http.client import responses as _REASONS
from typing import Dict, List, Optional, Tuple, Union
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..telemetry.core import MetricsRegistry
from .batcher import MicroBatcher, Overloaded
from .metrics import ServingMetrics
from .registry import ModelRegistry
from .replica import BudgetExceeded, QpsBudget

__all__ = ["PredictionServer"]

_NPY_TYPES = ("application/x-npy", "application/octet-stream")
_MAX_HEADER = 64 * 1024
_MAX_BODY = 1 << 30


class _Conn:
    """One client connection's state, owned by the event loop."""

    __slots__ = ("sock", "inbuf", "outbuf", "busy", "close_after",
                 "open")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.busy = False          # a request is in flight; don't parse
        self.close_after = False
        self.open = True


_Resp = Tuple[int, bytes, str, Optional[dict]]


class PredictionServer:
    """Own the registry, the per-model batchers/replica fleets and the
    async HTTP front end.

    ``start()`` binds (port 0 picks a free port) and runs the event
    loop from a daemon thread; ``serve_forever()`` runs it on the
    calling thread.

    ``replicas=N`` + ``compiled_predict=True`` configure the registry
    so every subsequently registered model is tensorized
    (``codegen.CompiledEnsemble``) and fanned out across mesh devices;
    ``qps_budget`` is a per-model requests/s cap (one float applied to
    every model, or a ``{name: qps}`` dict).
    """

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 host: str = "127.0.0.1", port: int = 8080,
                 max_batch_rows: int = 1024, max_wait_us: int = 2000,
                 max_queue_rows: Optional[int] = None,
                 min_bucket: int = 16,
                 metrics: Optional[ServingMetrics] = None,
                 telemetry: Optional[MetricsRegistry] = None,
                 replicas: int = 0, compiled_predict: bool = False,
                 qps_budget: Union[None, float, Dict[str, float]] = None,
                 replica_devices=None):
        self.metrics = metrics or ServingMetrics()
        self.registry = registry or ModelRegistry(metrics=self.metrics)
        if registry is not None and registry.metrics is not self.metrics:
            registry.metrics = self.metrics
        # the unified registry (telemetry/core.py): serving's families
        # mount as a collector, so /metrics here is one registry render
        # — identical bytes when no other families are registered, and
        # a shared registry (e.g. in-process training) composes both
        self.telemetry = telemetry or MetricsRegistry()
        self.telemetry.register_collector("serving", self.metrics.render)
        self.host, self.port = host, int(port)
        self._batcher_opts = dict(max_batch_rows=int(max_batch_rows),
                                  max_wait_us=int(max_wait_us),
                                  max_queue_rows=max_queue_rows,
                                  min_bucket=int(min_bucket))
        self._batchers: Dict[str, MicroBatcher] = {}
        self._block = threading.Lock()
        self._stop_lock = threading.Lock()
        self.draining = False
        self._fleet = int(replicas) > 0 or bool(compiled_predict)
        # every rung the bucket ladder can produce is warmed off-path
        # at register time (registry._load) — publish means zero
        # compiles on the serving path, at ANY rung, on ANY replica
        self.registry.configure_serving(
            warm_ladder=self._ladder(),
            compiled_predict=(bool(compiled_predict)
                              if self._fleet else None),
            replicas=int(replicas) if replicas else None,
            devices=replica_devices,
            batcher_opts=self._batcher_opts if self._fleet else None)
        if isinstance(qps_budget, dict):
            self._budgets: Dict[str, QpsBudget] = {
                m: QpsBudget(q) for m, q in qps_budget.items()}
            self._default_qps = None
        else:
            self._budgets = {}
            self._default_qps = (float(qps_budget)
                                 if qps_budget is not None else None)
        # event-loop state
        self._sel: Optional[selectors.BaseSelector] = None
        self._listen: Optional[socket.socket] = None
        self._wake_r: Optional[socket.socket] = None
        self._wake_w: Optional[socket.socket] = None
        self._conns: Dict[socket.socket, _Conn] = {}
        self._completions: deque = deque()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _ladder(self) -> List[int]:
        """Every batch shape ``bucket_rows`` can emit below the cap."""
        rungs: List[int] = []
        b = max(int(self._batcher_opts["min_bucket"]), 1)
        mx = int(self._batcher_opts["max_batch_rows"])
        while b < mx:
            rungs.append(b)
            b <<= 1
        rungs.append(mx)
        return rungs

    # -- predict plumbing ---------------------------------------------
    def _batcher(self, name: str) -> MicroBatcher:
        b = self._batchers.get(name)
        if b is None:
            with self._block:
                b = self._batchers.get(name)
                if b is None:
                    b = MicroBatcher(
                        lambda X, _n=name: self.registry.predict(X, _n),
                        metrics=self.metrics, model=name,
                        **self._batcher_opts)
                    self._batchers[name] = b
        return b

    def _budget(self, name: str) -> Optional[QpsBudget]:
        q = self._budgets.get(name)
        if q is None and self._default_qps is not None:
            with self._block:
                q = self._budgets.setdefault(
                    name, QpsBudget(self._default_qps))
        return q

    def _admit(self, name: str):
        q = self._budget(name)
        if q is not None and not q.try_admit():
            self.metrics.on_budget_rejected(name)
            raise BudgetExceeded(name, q.qps)

    def _replica_set(self, name: str):
        try:
            return self.registry.resolve(name).replicas
        except LookupError:
            return None   # the batcher path surfaces the LookupError

    def predict(self, X, model: Optional[str] = None):
        """(result, ModelVersion) through the replica fleet when the
        active version has one, else the per-model micro-batcher."""
        name = model or self.registry.default_name
        if name is None:
            raise LookupError("no model registered")
        self._admit(name)
        rs = self._replica_set(name)
        if rs is not None:
            return rs.submit_tagged(X)
        return self._batcher(name).submit_tagged(X)

    def predict_async(self, X, model: Optional[str],
                      callback) -> None:
        """``callback(result, error, version)`` fires off-loop when the
        batch lands; admission errors raise synchronously."""
        name = model or self.registry.default_name
        if name is None:
            raise LookupError("no model registered")
        self._admit(name)
        rs = self._replica_set(name)
        if rs is not None:
            rs.submit_async(X, callback)
        else:
            self._batcher(name).submit_async(X, callback)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> int:
        """Bind + run the event loop from a daemon thread; returns the
        bound port."""
        self._bind()
        self._thread = threading.Thread(
            target=self._run_loop, name="serving-http", daemon=True)
        self._thread.start()
        return self.port

    def serve_forever(self):
        self._bind()
        try:
            self._run_loop()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def _bind(self):
        if self._listen is not None:
            return
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(128)
        s.setblocking(False)
        self.port = s.getsockname()[1]
        self._listen = s
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(s, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")

    def _wakeup(self):
        w = self._wake_w
        if w is not None:
            try:
                w.send(b"x")
            except OSError:
                pass

    def stop(self):
        """Idempotent shutdown: stop accepting, drain batcher work
        (replica fleets included), flush the responses it produced,
        then exit the loop. Safe to call concurrently and from any
        thread — including the loop thread via ``serve_forever``'s
        ``finally``."""
        with self._stop_lock:
            batchers = list(self._batchers.values())
            self._batchers = {}
            thread, self._thread = self._thread, None
            fleet, self._fleet = self._fleet, False
        for b in batchers:
            # drain=True: queued requests are answered before the
            # worker exits — accepted work is never dropped
            b.close(drain=True)
        if fleet:
            self.registry.close()   # replica batchers drain the same way
        self._shutdown.set()
        self._wakeup()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=10)
        # the loop tears its own sockets down on exit (_teardown); the
        # serve_forever path reaches here after that already happened

    def drain(self) -> None:
        """Graceful drain (SIGTERM path): flip readiness so load
        balancers route away, then stop — finishing in-flight batcher
        work before returning."""
        self.draining = True
        self.stop()

    # -- event loop ----------------------------------------------------
    def _run_loop(self):
        sel = self._sel
        try:
            while not self._shutdown.is_set():
                for key, mask in sel.select(timeout=0.5):
                    data = key.data
                    if data == "accept":
                        self._accept()
                    elif data == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    else:
                        if mask & selectors.EVENT_READ:
                            self._on_read(data)
                        if data.open and mask & selectors.EVENT_WRITE:
                            self._on_write(data)
                self._flush_completions()
        finally:
            self._teardown()

    def _teardown(self):
        # answer whatever completed during the drain, then close
        self._flush_completions()
        for conn in list(self._conns.values()):
            if conn.outbuf and conn.open:
                try:
                    conn.sock.settimeout(2.0)
                    conn.sock.sendall(bytes(conn.outbuf))
                except OSError:
                    pass
            self._close_conn(conn)
        for s in (self._listen, self._wake_r, self._wake_w):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._listen = self._wake_r = self._wake_w = None
        if self._sel is not None:
            self._sel.close()
            self._sel = None

    def _accept(self):
        while True:
            try:
                sock, _addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _close_conn(self, conn: _Conn):
        if not conn.open:
            return
        conn.open = False
        self._conns.pop(conn.sock, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _interest(self, conn: _Conn):
        if not conn.open:
            return
        ev = selectors.EVENT_READ
        if conn.outbuf:
            ev |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, ev, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _on_read(self, conn: _Conn):
        try:
            chunk = conn.sock.recv(65536)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if not chunk:
            self._close_conn(conn)
            return
        conn.inbuf += chunk
        if not conn.busy:
            self._try_parse(conn)

    def _on_write(self, conn: _Conn):
        try:
            n = conn.sock.send(bytes(conn.outbuf))
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        del conn.outbuf[:n]
        if not conn.outbuf:
            if conn.close_after:
                self._close_conn(conn)
                return
            self._interest(conn)
            if conn.busy:
                conn.busy = False
                self._try_parse(conn)   # a pipelined request may wait

    # -- HTTP parsing / dispatch --------------------------------------
    def _try_parse(self, conn: _Conn):
        while conn.open and not conn.busy:
            head_end = conn.inbuf.find(b"\r\n\r\n")
            if head_end < 0:
                if len(conn.inbuf) > _MAX_HEADER:
                    self._queue_resp(conn, (431, json.dumps(
                        {"error": "headers too large"}).encode(),
                        "application/json", None), close=True)
                return
            head = bytes(conn.inbuf[:head_end]).decode(
                "latin-1").split("\r\n")
            try:
                method, target, version = head[0].split(" ", 2)
            except ValueError:
                self._queue_resp(conn, (400, json.dumps(
                    {"error": "malformed request line"}).encode(),
                    "application/json", None), close=True)
                return
            headers = {}
            for ln in head[1:]:
                k, _, v = ln.partition(":")
                headers[k.strip().lower()] = v.strip()
            try:
                clen = int(headers.get("content-length") or 0)
            except ValueError:
                clen = 0
            if clen < 0 or clen > _MAX_BODY:
                self._queue_resp(conn, (413, json.dumps(
                    {"error": "body too large"}).encode(),
                    "application/json", None), close=True)
                return
            if len(conn.inbuf) < head_end + 4 + clen:
                return                      # body still in flight
            body = bytes(conn.inbuf[head_end + 4:head_end + 4 + clen])
            del conn.inbuf[:head_end + 4 + clen]
            conn.close_after = (
                headers.get("connection", "").lower() == "close"
                or version == "HTTP/1.0")
            conn.busy = True
            self._dispatch(conn, method, target, headers, body)

    def _dispatch(self, conn: _Conn, method: str, target: str,
                  headers: dict, body: bytes):
        parts = urlsplit(target)
        path = parts.path.rstrip("/") or "/"
        if method == "GET":
            self._queue_resp(conn, self._guard(
                lambda: self._handle_get(path)))
        elif method == "POST":
            if path == "/predict":
                resp = self._guard(lambda: self._start_predict(
                    conn, parts.query, headers, body))
                if resp is not None:        # admission failed in-line
                    self._queue_resp(conn, resp)
            elif path in ("/models/swap", "/models/rollback"):
                # control ops block (load + full-ladder warm): never on
                # the event loop
                op = (self._do_swap if path == "/models/swap"
                      else self._do_rollback)
                threading.Thread(
                    target=lambda: self._complete(conn, self._guard(
                        lambda: op(body))),
                    name="serve-control", daemon=True).start()
            else:
                self._queue_resp(conn, (404, json.dumps(
                    {"error": f"unknown path {path}"}).encode(),
                    "application/json", None))
        else:
            self._queue_resp(conn, (405, json.dumps(
                {"error": f"method {method} not allowed"}).encode(),
                "application/json", None))

    def _guard(self, fn) -> Optional[_Resp]:
        """Run ``fn`` under the endpoint error mapping; ``fn`` returns
        a response tuple or None (async completion pending)."""
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — mapped below
            return self._error_resp(e)

    def _error_resp(self, e: BaseException) -> _Resp:
        if isinstance(e, Overloaded):
            return (429, json.dumps(
                {"status": "overloaded", "retriable": True,
                 "error": str(e)}).encode(),
                "application/json", {"Retry-After": "1"})
        if isinstance(e, BudgetExceeded):
            return (429, json.dumps(
                {"status": "budget_exceeded", "retriable": True,
                 "error": str(e)}).encode(),
                "application/json", {"Retry-After": "1"})
        if isinstance(e, (ValueError, TypeError, KeyError, LookupError,
                          json.JSONDecodeError)):
            return (400, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(),
                "application/json", None)
        return (500, json.dumps(
            {"error": f"{type(e).__name__}: {e}"}).encode(),
            "application/json", None)

    # -- GET endpoints -------------------------------------------------
    def _handle_get(self, path: str) -> _Resp:
        if path == "/healthz/alive":
            # liveness: the process answers HTTP — even while draining
            return (200, json.dumps({"status": "alive"}).encode(),
                    "application/json", None)
        if path in ("/healthz", "/healthz/ready"):
            if self.draining:
                return (503, json.dumps(
                    {"status": "draining"}).encode(),
                    "application/json", None)
            try:
                mv = self.registry.resolve()
                return (200, json.dumps(
                    {"status": "ok", "model": mv.name,
                     "version": mv.version}).encode(),
                    "application/json", None)
            except LookupError:
                return (503, json.dumps(
                    {"status": "no model registered"}).encode(),
                    "application/json", None)
        if path == "/metrics":
            return (200, self.telemetry.render().encode(),
                    "text/plain; version=0.0.4", None)
        if path == "/models":
            return (200, json.dumps(
                {"models": self.registry.models(),
                 "default": self.registry.default_name}).encode(),
                "application/json", None)
        return (404, json.dumps(
            {"error": f"unknown path {path}"}).encode(),
            "application/json", None)

    # -- POST endpoints ------------------------------------------------
    def _start_predict(self, conn: _Conn, query: str, headers: dict,
                       body: bytes) -> Optional[_Resp]:
        q = parse_qs(query)
        model = (q.get("model") or [None])[0]
        ctype = (headers.get("content-type") or "").split(";")[0]
        is_npy = ctype in _NPY_TYPES or body[:6] == b"\x93NUMPY"
        if is_npy:
            X = np.load(io.BytesIO(body), allow_pickle=False)
        else:
            req = json.loads(body.decode() or "{}")
            model = req.get("model", model)
            data = req.get("data", req.get("rows"))
            if data is None:
                raise ValueError('JSON body needs "data" (or "rows"): '
                                 'a row or list of rows')
            X = np.asarray(data, np.float64)

        def on_done(result, error, mv):
            if error is not None:
                self._complete(conn, self._error_resp(error))
                return
            self._complete(conn, self._guard(
                lambda: self._format_predict(result, mv, is_npy)))

        self.predict_async(X, model, on_done)
        return None                  # response comes via _complete

    def _format_predict(self, result, mv, is_npy: bool) -> _Resp:
        result = np.asarray(result, np.float64)
        if is_npy:
            buf = io.BytesIO()
            np.save(buf, result, allow_pickle=False)
            return (200, buf.getvalue(), "application/x-npy",
                    {"X-Model-Name": mv.name,
                     "X-Model-Version": mv.version})
        return (200, json.dumps(
            {"predictions": result.tolist(), "model": mv.name,
             "version": mv.version}).encode(),
            "application/json", None)

    def _do_swap(self, body: bytes) -> _Resp:
        req = json.loads(body.decode() or "{}")
        name = req.get("name") or self.registry.default_name or "default"
        source = req.get("file") or req.get("path")
        if not source:
            raise ValueError('swap needs "file": path to a model file')
        mv = self.registry.swap(name, source)
        return (200, json.dumps(
            {"status": "swapped", **mv.describe()}).encode(),
            "application/json", None)

    def _do_rollback(self, body: bytes) -> _Resp:
        req = json.loads(body.decode() or "{}")
        mv = self.registry.rollback(req.get("name"))
        return (200, json.dumps(
            {"status": "rolled back", **mv.describe()}).encode(),
            "application/json", None)

    # -- response plumbing ---------------------------------------------
    def _complete(self, conn: _Conn, resp: _Resp):
        """Queue a response from ANY thread; the loop writes it."""
        self._completions.append((conn, resp))
        self._wakeup()

    def _flush_completions(self):
        while True:
            try:
                conn, resp = self._completions.popleft()
            except IndexError:
                return
            if conn.open:
                self._queue_resp(conn, resp)

    def _queue_resp(self, conn: _Conn, resp: _Resp,
                    close: bool = False):
        code, body, ctype, headers = resp
        if close:
            conn.close_after = True
        reason = _REASONS.get(code, "")
        lines = [f"HTTP/1.1 {code} {reason}",
                 f"Content-Type: {ctype}",
                 f"Content-Length: {len(body)}"]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        lines.append("Connection: close" if conn.close_after
                     else "Connection: keep-alive")
        conn.outbuf += ("\r\n".join(lines) + "\r\n\r\n").encode(
            "latin-1")
        conn.outbuf += body
        self._interest(conn)
        # opportunistic immediate write (loop thread): most responses
        # fit the socket buffer, saving one selector round-trip
        self._on_write(conn)
