"""Metrics-instrumented prediction server over the micro-batcher and
model registry — ``python -m lightgbm_tpu serve model=<file>``.

Stdlib-only (``http.server.ThreadingHTTPServer``): each connection gets
a thread, every ``/predict`` body lands in the per-model
:class:`~lightgbm_tpu.serving.batcher.MicroBatcher`, so concurrent
clients coalesce into shared kernel calls regardless of transport.

Endpoints:

- ``POST /predict[?model=name]`` — body either JSON
  ``{"data": [[...], ...]}`` (``"rows"`` accepted as an alias) or a raw
  ``.npy`` matrix (``Content-Type: application/x-npy`` or
  ``application/octet-stream``). JSON in -> JSON
  ``{"predictions": ..., "model": ..., "version": ...}`` out; npy in ->
  npy float64 out with the model identity in ``X-Model-Name`` /
  ``X-Model-Version`` headers (bit-exact round-trip, no text
  formatting loss). Overload -> ``429`` + ``Retry-After`` with
  ``{"status": "overloaded", "retriable": true}``.
- ``GET /models`` — active versions; ``POST /models/swap``
  ``{"name", "file"}`` hot-swaps (load + warmup off-path, then atomic
  publish); ``POST /models/rollback`` ``{"name"?}`` republishes the
  previous version.
- ``GET /healthz/alive`` — 200 while the process serves HTTP at all
  (liveness); ``GET /healthz`` / ``GET /healthz/ready`` — 200 once a
  model serves AND the server is not draining, 503 otherwise
  (readiness; a SIGTERM-draining server keeps answering alive=200 /
  ready=503 until in-flight batcher work finishes).
- ``GET /metrics`` — Prometheus text (field reference: metrics.py).

Graceful drain: ``drain()`` (wired to SIGTERM by the CLI ``serve``
path) flips readiness, stops accepting connections, finishes queued
batcher work (``MicroBatcher.close(drain=True)``), then returns — so a
rolling restart loses no accepted request.
"""

from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..telemetry.core import MetricsRegistry
from .batcher import MicroBatcher, Overloaded
from .metrics import ServingMetrics
from .registry import ModelRegistry

__all__ = ["PredictionServer"]

_NPY_TYPES = ("application/x-npy", "application/octet-stream")


class PredictionServer:
    """Own the registry, the per-model batchers and the HTTP front end.

    ``start()`` binds (port 0 picks a free port) and serves from a
    daemon thread; ``serve_forever()`` serves on the calling thread.
    """

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 host: str = "127.0.0.1", port: int = 8080,
                 max_batch_rows: int = 1024, max_wait_us: int = 2000,
                 max_queue_rows: Optional[int] = None,
                 min_bucket: int = 16,
                 metrics: Optional[ServingMetrics] = None,
                 telemetry: Optional[MetricsRegistry] = None):
        self.metrics = metrics or ServingMetrics()
        self.registry = registry or ModelRegistry(metrics=self.metrics)
        if registry is not None and registry.metrics is not self.metrics:
            registry.metrics = self.metrics
        # the unified registry (telemetry/core.py): serving's families
        # mount as a collector, so /metrics here is one registry render
        # — identical bytes when no other families are registered, and
        # a shared registry (e.g. in-process training) composes both
        self.telemetry = telemetry or MetricsRegistry()
        self.telemetry.register_collector("serving", self.metrics.render)
        self.host, self.port = host, int(port)
        self._batcher_opts = dict(max_batch_rows=int(max_batch_rows),
                                  max_wait_us=int(max_wait_us),
                                  max_queue_rows=max_queue_rows,
                                  min_bucket=int(min_bucket))
        self._batchers: Dict[str, MicroBatcher] = {}
        self._block = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_lock = threading.Lock()
        self.draining = False

    # -- predict plumbing ---------------------------------------------
    def _batcher(self, name: str) -> MicroBatcher:
        b = self._batchers.get(name)
        if b is None:
            with self._block:
                b = self._batchers.get(name)
                if b is None:
                    b = MicroBatcher(
                        lambda X, _n=name: self.registry.predict(X, _n),
                        metrics=self.metrics, model=name,
                        **self._batcher_opts)
                    self._batchers[name] = b
        return b

    def predict(self, X, model: Optional[str] = None):
        """(result, ModelVersion) through the micro-batcher."""
        name = model or self.registry.default_name
        if name is None:
            raise LookupError("no model registered")
        return self._batcher(name).submit_tagged(X)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> int:
        """Bind + serve from a daemon thread; returns the bound port."""
        self._bind()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._thread.start()
        return self.port

    def serve_forever(self):
        self._bind()
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def _bind(self):
        if self._httpd is not None:
            return
        app = self

        class Handler(_Handler):
            server_app = app

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # default backlog (5) RSTs bursts of simultaneous connects
            # well below the concurrency the batcher is built for
            request_queue_size = 128

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]

    def stop(self):
        """Idempotent shutdown: stop accepting, then close batchers.

        Must not run on the thread inside ``serve_forever`` —
        ``httpd.shutdown()`` blocks until that loop exits (deadlock);
        the CLI's SIGTERM path calls ``drain()`` from a helper thread
        for exactly this reason. Safe to call concurrently: state is
        claimed under a lock, so the drain thread and
        ``serve_forever``'s ``finally`` compose."""
        with self._stop_lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
            batchers = list(self._batchers.values())
            self._batchers = {}
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=10)
        for b in batchers:
            # drain=True: queued requests are answered before the
            # worker exits — accepted work is never dropped
            b.close(drain=True)

    def drain(self) -> None:
        """Graceful drain (SIGTERM path): flip readiness so load
        balancers route away, then stop — finishing in-flight batcher
        work before returning."""
        self.draining = True
        self.stop()


class _Handler(BaseHTTPRequestHandler):
    server_app: PredictionServer = None  # bound per-server subclass
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------
    def log_message(self, fmt, *args):  # route through our logger
        from .. import log
        log.debug(f"serve: {self.address_string()} {fmt % args}")

    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj, headers=None):
        self._send(code, json.dumps(obj).encode(), "application/json",
                   headers)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n > 0 else b""

    # -- GET -----------------------------------------------------------
    def do_GET(self):  # noqa: N802 (http.server API)
        app = self.server_app
        path = urlparse(self.path).path.rstrip("/") or "/"
        if path == "/healthz/alive":
            # liveness: the process answers HTTP — even while draining
            self._send_json(200, {"status": "alive"})
        elif path in ("/healthz", "/healthz/ready"):
            if app.draining:
                self._send_json(503, {"status": "draining"})
                return
            try:
                mv = app.registry.resolve()
                self._send_json(200, {"status": "ok",
                                      "model": mv.name,
                                      "version": mv.version})
            except LookupError:
                self._send_json(503, {"status": "no model registered"})
        elif path == "/metrics":
            self._send(200, app.telemetry.render().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/models":
            self._send_json(200, {"models": app.registry.models(),
                                  "default": app.registry.default_name})
        else:
            self._send_json(404, {"error": f"unknown path {path}"})

    # -- POST ----------------------------------------------------------
    def do_POST(self):  # noqa: N802
        app = self.server_app
        parsed = urlparse(self.path)
        path = parsed.path.rstrip("/")
        try:
            if path == "/predict":
                self._predict(app, parsed)
            elif path == "/models/swap":
                self._swap(app)
            elif path == "/models/rollback":
                self._rollback(app)
            else:
                self._send_json(404, {"error": f"unknown path {path}"})
        except Overloaded as e:
            self._send_json(429, {"status": "overloaded",
                                  "retriable": True, "error": str(e)},
                            headers={"Retry-After": "1"})
        except (ValueError, TypeError, KeyError, LookupError,
                json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001 — a request must not kill
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

    def _predict(self, app: PredictionServer, parsed):
        q = parse_qs(parsed.query)
        model = (q.get("model") or [None])[0]
        body = self._read_body()
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        is_npy = ctype in _NPY_TYPES or body[:6] == b"\x93NUMPY"
        if is_npy:
            X = np.load(io.BytesIO(body), allow_pickle=False)
        else:
            req = json.loads(body.decode() or "{}")
            model = req.get("model", model)
            data = req.get("data", req.get("rows"))
            if data is None:
                raise ValueError('JSON body needs "data" (or "rows"): '
                                 'a row or list of rows')
            X = np.asarray(data, np.float64)
        result, mv = app.predict(X, model)
        result = np.asarray(result, np.float64)
        if is_npy:
            buf = io.BytesIO()
            np.save(buf, result, allow_pickle=False)
            self._send(200, buf.getvalue(), "application/x-npy",
                       headers={"X-Model-Name": mv.name,
                                "X-Model-Version": mv.version})
        else:
            self._send_json(200, {"predictions": result.tolist(),
                                  "model": mv.name,
                                  "version": mv.version})

    def _swap(self, app: PredictionServer):
        req = json.loads(self._read_body().decode() or "{}")
        name = req.get("name") or app.registry.default_name or "default"
        source = req.get("file") or req.get("path")
        if not source:
            raise ValueError('swap needs "file": path to a model file')
        mv = app.registry.swap(name, source)
        self._send_json(200, {"status": "swapped", **mv.describe()})

    def _rollback(self, app: PredictionServer):
        req = json.loads(self._read_body().decode() or "{}")
        mv = app.registry.rollback(req.get("name"))
        self._send_json(200, {"status": "rolled back", **mv.describe()})
