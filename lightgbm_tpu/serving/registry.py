"""Named + versioned model registry with atomic hot-swap and one-step
rollback.

Deploy contract (the reason this exists — LightGBM's C API loads a
model once per handle and has no swap story):

1. ``swap()`` loads the incoming model and ``warmup()``s its
   :class:`~lightgbm_tpu.engine.PredictSession` entirely OFF the
   serving path — native handle built, device ensemble packed, jit
   executables compiled — while live traffic keeps reading the old
   version untouched.
2. Only then is the active slot CAS'd: publishing is a single
   reference assignment (atomic under the GIL), so a reader holding
   yesterday's reference finishes on yesterday's model and the next
   ``resolve()`` sees the new one. No request ever observes a cold or
   half-loaded model.
3. The replaced version stays in the history ring; ``rollback()``
   republishes it with the same single-assignment CAS (its session
   caches are still warm, so rollback is instant).

Whole-model guarantee: ``predict()`` resolves the active
:class:`ModelVersion` exactly once and serves the entire call from that
snapshot's session — combined with the ``PredictSession`` snapshot
contract (engine.py) a result can never mix trees of two versions. The
micro-batcher calls ``predict()`` once per coalesced batch, extending
the guarantee to every request in the batch.

Registered models are SERVING-ONLY: training, ``rollback_one_iter`` or
leaf surgery on a registered Booster is outside the contract (swap in a
new version instead — that is the point of the registry).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .metrics import ServingMetrics

__all__ = ["ModelRegistry", "ModelVersion"]


class ModelVersion:
    """One immutable (booster, warmed session[, compiled, replicas])
    snapshot. The registry hands these out by reference; holders may
    predict on them at any time, even after the version was
    superseded. ``compiled`` / ``replicas`` are populated off-path by
    ``_load`` when serving is configured — publishing the version
    publishes all three in the same single reference store."""

    __slots__ = ("name", "version", "source", "booster", "session",
                 "loaded_at", "num_features", "compiled", "replicas",
                 "compiled_fallback")

    def __init__(self, name: str, version: int, source: str,
                 booster, session):
        self.name = name
        self.version = version
        self.source = source
        self.booster = booster
        self.session = session
        self.loaded_at = time.time()
        self.num_features = booster.num_feature()
        self.compiled = None          # codegen.CompiledEnsemble | None
        self.replicas = None          # replica.ReplicaSet | None
        self.compiled_fallback = None  # why compiled is None (str)

    def close_replicas(self, drain: bool = True):
        """Retire this version's replica fleet (history eviction /
        unregister); the session path stays usable."""
        rs, self.replicas = self.replicas, None
        if rs is not None:
            rs.close(drain=drain)

    def describe(self) -> dict:
        d = {"name": self.name, "version": self.version,
             "source": self.source, "loaded_at": self.loaded_at,
             "num_features": self.num_features,
             "num_trees": self.booster.num_trees()}
        if self.compiled is not None:
            d["compiled"] = self.compiled.describe()
        elif self.compiled_fallback is not None:
            d["compiled_fallback"] = self.compiled_fallback
        if self.replicas is not None:
            d["replicas"] = self.replicas.describe()
        return d


class ModelRegistry:
    """Thread-safe model store: writers serialize on a lock, readers
    are lock-free (one attribute load resolves the active version)."""

    def __init__(self, *, warmup_rows: int = 256, history: int = 4,
                 metrics: Optional[ServingMetrics] = None,
                 compiled_predict: bool = False, replicas: int = 0):
        self.warmup_rows = int(warmup_rows)
        self.history = int(history)
        self.metrics = metrics or ServingMetrics()
        self.compiled_predict = bool(compiled_predict)
        self.replicas = int(replicas)
        self.warm_ladder: Optional[List[int]] = None
        self.replica_devices = None
        self.replica_batcher_opts: Dict[str, object] = {}
        self._lock = threading.Lock()          # writers only
        self._active: Dict[str, ModelVersion] = {}
        self._history: Dict[str, List[ModelVersion]] = {}
        self._next_version: Dict[str, int] = {}
        self._default: Optional[str] = None

    def configure_serving(self, *, compiled_predict: Optional[bool] = None,
                          replicas: Optional[int] = None,
                          warm_ladder: Optional[List[int]] = None,
                          devices=None,
                          batcher_opts: Optional[Dict] = None):
        """Set the serving shape applied to every subsequent ``_load``
        (already-published versions are not rebuilt — swap to apply).

        ``warm_ladder`` is the full batch-bucket ladder; every rung is
        compiled per replica OFF the serving path so publish means
        ZERO compiles on live traffic (ISSUE 15 satellite — warming
        only the max rung left every smaller first-request paying
        compile latency in-band)."""
        if compiled_predict is not None:
            self.compiled_predict = bool(compiled_predict)
        if replicas is not None:
            self.replicas = int(replicas)
        if warm_ladder is not None:
            self.warm_ladder = [int(r) for r in warm_ladder]
        if devices is not None:
            self.replica_devices = list(devices)
        if batcher_opts is not None:
            self.replica_batcher_opts = dict(batcher_opts)

    # -- loading / swapping -------------------------------------------
    def _load(self, name: str, source, **session_kwargs) -> ModelVersion:
        """Build + warm a ModelVersion OFF the serving path."""
        from ..engine import Booster
        if isinstance(source, Booster):
            booster, src = source, "<booster>"
        elif isinstance(source, (str, os.PathLike)):
            booster, src = Booster(model_file=str(source)), str(source)
        else:
            raise TypeError("model source must be a Booster or a model "
                            f"file path, got {type(source).__name__}")
        session = booster.predict_session(**session_kwargs)
        # warm the WHOLE batch ladder, not just one rung: executables
        # cache per shape, so a single-rung warmup still left the first
        # live request at every other rung paying compile in-band
        ladder = self.warm_ladder or [self.warmup_rows]
        if self.warmup_rows > 0:
            for rows in sorted(set(ladder)):
                session.warmup(rows)
        with self._lock:
            v = self._next_version.get(name, 0) + 1
            self._next_version[name] = v
        mv = ModelVersion(name, v, src, booster, session)
        if self.compiled_predict or self.replicas > 0:
            from ..codegen import CompiledEnsemble
            try:
                mv.compiled = CompiledEnsemble(booster,
                                               **session_kwargs)
            except (ValueError, TypeError) as e:
                # named fallback, same discipline as fused_split=auto:
                # the session path serves, /models says why
                mv.compiled_fallback = str(e)
        if mv.compiled is not None:
            if self.replicas > 0:
                from .replica import ReplicaSet
                mv.replicas = ReplicaSet(
                    mv.compiled, mv, replicas=self.replicas,
                    devices=self.replica_devices,
                    metrics=self.metrics, model=name,
                    **self.replica_batcher_opts)
                if self.warmup_rows > 0:
                    mv.replicas.warm(ladder)
            elif self.warmup_rows > 0:
                mv.compiled.warm(sorted(set(ladder)))
        return mv

    def register(self, name: str, source,
                 **session_kwargs) -> ModelVersion:
        """Load, warm, then atomically publish ``source`` as the active
        version of ``name``. The first registered name becomes the
        default model."""
        mv = self._load(name, source, **session_kwargs)
        evicted: List[ModelVersion] = []
        with self._lock:
            old = self._active.get(name)
            if old is not None:
                hist = self._history.setdefault(name, [])
                hist.append(old)
                evicted = hist[:-self.history]
                del hist[:-self.history]
                self.metrics.swaps_total.inc()
                from ..telemetry.events import record_serving
                record_serving("swap", name, mv.version)
            # the publish: one reference store, atomic under the GIL —
            # in-flight readers keep `old`, new resolves see `mv`.
            # `mv` already carries its compiled program and warmed
            # replica fleet, so (version, compiled, replicas) is ONE
            # atomic snapshot
            self._active[name] = mv
            if self._default is None:
                self._default = name
        for ev in evicted:
            # aged past the rollback ring: its replica batchers are
            # unreachable — retire them (outside the lock; drain)
            ev.close_replicas()
        return mv

    # a swap IS a register on an existing name; the alias keeps the
    # deploy runbook's vocabulary honest
    swap = register

    def rollback(self, name: Optional[str] = None) -> ModelVersion:
        """One-step rollback: republish the previous version of
        ``name`` (still warm — its session caches survived the swap)."""
        name = name or self._default
        with self._lock:
            hist = self._history.get(name or "")
            if not hist:
                raise LookupError(f"no previous version of {name!r} "
                                  "to roll back to")
            mv = hist.pop()
            self._active[name] = mv
            self.metrics.rollbacks_total.inc()
            from ..telemetry.events import record_serving
            record_serving("rollback", name, mv.version)
        return mv

    def unregister(self, name: str):
        with self._lock:
            dropped = [self._active.pop(name, None)]
            dropped += self._history.pop(name, [])
            if self._default == name:
                self._default = next(iter(self._active), None)
        for mv in dropped:
            if mv is not None:
                mv.close_replicas()

    def close(self):
        """Retire every version's replica fleet (server shutdown)."""
        with self._lock:
            all_mv = list(self._active.values())
            for hist in self._history.values():
                all_mv += hist
        for mv in all_mv:
            mv.close_replicas()

    # -- serving side (lock-free) -------------------------------------
    def resolve(self, name: Optional[str] = None) -> ModelVersion:
        """Active version snapshot — ONE dict read, no lock. Everything
        reachable from the returned object is immutable."""
        mv = self._active.get(name or self._default or "")
        if mv is None:
            raise LookupError(f"no model registered as "
                              f"{name or self._default!r}")
        return mv

    def predict(self, X, name: Optional[str] = None
                ) -> Tuple[np.ndarray, ModelVersion]:
        """Predict entirely on one resolved version; returns
        ``(result, version)`` so callers (the batcher) can tag results
        with the model that produced them. Prefers the tensorized
        program when the version carries one (bit-identical by the
        CompiledEnsemble contract; replicated routing lives in the
        server, which talks to ``mv.replicas`` directly)."""
        mv = self.resolve(name)
        if mv.compiled is not None:
            return mv.compiled.predict(X), mv
        return mv.session.predict(X), mv

    # -- introspection -------------------------------------------------
    def models(self) -> List[dict]:
        with self._lock:
            out = []
            for name, mv in sorted(self._active.items()):
                d = mv.describe()
                d["default"] = name == self._default
                hist = self._history.get(name)
                d["rollback_to"] = hist[-1].version if hist else None
                out.append(d)
            return out

    @property
    def default_name(self) -> Optional[str]:
        return self._default
