"""Named + versioned model registry with atomic hot-swap and one-step
rollback.

Deploy contract (the reason this exists — LightGBM's C API loads a
model once per handle and has no swap story):

1. ``swap()`` loads the incoming model and ``warmup()``s its
   :class:`~lightgbm_tpu.engine.PredictSession` entirely OFF the
   serving path — native handle built, device ensemble packed, jit
   executables compiled — while live traffic keeps reading the old
   version untouched.
2. Only then is the active slot CAS'd: publishing is a single
   reference assignment (atomic under the GIL), so a reader holding
   yesterday's reference finishes on yesterday's model and the next
   ``resolve()`` sees the new one. No request ever observes a cold or
   half-loaded model.
3. The replaced version stays in the history ring; ``rollback()``
   republishes it with the same single-assignment CAS (its session
   caches are still warm, so rollback is instant).

Whole-model guarantee: ``predict()`` resolves the active
:class:`ModelVersion` exactly once and serves the entire call from that
snapshot's session — combined with the ``PredictSession`` snapshot
contract (engine.py) a result can never mix trees of two versions. The
micro-batcher calls ``predict()`` once per coalesced batch, extending
the guarantee to every request in the batch.

Registered models are SERVING-ONLY: training, ``rollback_one_iter`` or
leaf surgery on a registered Booster is outside the contract (swap in a
new version instead — that is the point of the registry).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .metrics import ServingMetrics

__all__ = ["ModelRegistry", "ModelVersion"]


class ModelVersion:
    """One immutable (booster, warmed session) pair. The registry hands
    these out by reference; holders may predict on them at any time,
    even after the version was superseded."""

    __slots__ = ("name", "version", "source", "booster", "session",
                 "loaded_at", "num_features")

    def __init__(self, name: str, version: int, source: str,
                 booster, session):
        self.name = name
        self.version = version
        self.source = source
        self.booster = booster
        self.session = session
        self.loaded_at = time.time()
        self.num_features = booster.num_feature()

    def describe(self) -> dict:
        return {"name": self.name, "version": self.version,
                "source": self.source, "loaded_at": self.loaded_at,
                "num_features": self.num_features,
                "num_trees": self.booster.num_trees()}


class ModelRegistry:
    """Thread-safe model store: writers serialize on a lock, readers
    are lock-free (one attribute load resolves the active version)."""

    def __init__(self, *, warmup_rows: int = 256, history: int = 4,
                 metrics: Optional[ServingMetrics] = None):
        self.warmup_rows = int(warmup_rows)
        self.history = int(history)
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()          # writers only
        self._active: Dict[str, ModelVersion] = {}
        self._history: Dict[str, List[ModelVersion]] = {}
        self._next_version: Dict[str, int] = {}
        self._default: Optional[str] = None

    # -- loading / swapping -------------------------------------------
    def _load(self, name: str, source, **session_kwargs) -> ModelVersion:
        """Build + warm a ModelVersion OFF the serving path."""
        from ..engine import Booster
        if isinstance(source, Booster):
            booster, src = source, "<booster>"
        elif isinstance(source, (str, os.PathLike)):
            booster, src = Booster(model_file=str(source)), str(source)
        else:
            raise TypeError("model source must be a Booster or a model "
                            f"file path, got {type(source).__name__}")
        session = booster.predict_session(**session_kwargs)
        if self.warmup_rows > 0:
            session.warmup(self.warmup_rows)
        with self._lock:
            v = self._next_version.get(name, 0) + 1
            self._next_version[name] = v
        return ModelVersion(name, v, src, booster, session)

    def register(self, name: str, source,
                 **session_kwargs) -> ModelVersion:
        """Load, warm, then atomically publish ``source`` as the active
        version of ``name``. The first registered name becomes the
        default model."""
        mv = self._load(name, source, **session_kwargs)
        with self._lock:
            old = self._active.get(name)
            if old is not None:
                hist = self._history.setdefault(name, [])
                hist.append(old)
                del hist[:-self.history]
                self.metrics.swaps_total.inc()
                from ..telemetry.events import record_serving
                record_serving("swap", name, mv.version)
            # the publish: one reference store, atomic under the GIL —
            # in-flight readers keep `old`, new resolves see `mv`
            self._active[name] = mv
            if self._default is None:
                self._default = name
        return mv

    # a swap IS a register on an existing name; the alias keeps the
    # deploy runbook's vocabulary honest
    swap = register

    def rollback(self, name: Optional[str] = None) -> ModelVersion:
        """One-step rollback: republish the previous version of
        ``name`` (still warm — its session caches survived the swap)."""
        name = name or self._default
        with self._lock:
            hist = self._history.get(name or "")
            if not hist:
                raise LookupError(f"no previous version of {name!r} "
                                  "to roll back to")
            mv = hist.pop()
            self._active[name] = mv
            self.metrics.rollbacks_total.inc()
            from ..telemetry.events import record_serving
            record_serving("rollback", name, mv.version)
        return mv

    def unregister(self, name: str):
        with self._lock:
            self._active.pop(name, None)
            self._history.pop(name, None)
            if self._default == name:
                self._default = next(iter(self._active), None)

    # -- serving side (lock-free) -------------------------------------
    def resolve(self, name: Optional[str] = None) -> ModelVersion:
        """Active version snapshot — ONE dict read, no lock. Everything
        reachable from the returned object is immutable."""
        mv = self._active.get(name or self._default or "")
        if mv is None:
            raise LookupError(f"no model registered as "
                              f"{name or self._default!r}")
        return mv

    def predict(self, X, name: Optional[str] = None
                ) -> Tuple[np.ndarray, ModelVersion]:
        """Predict entirely on one resolved version; returns
        ``(result, version)`` so callers (the batcher) can tag results
        with the model that produced them."""
        mv = self.resolve(name)
        return mv.session.predict(X), mv

    # -- introspection -------------------------------------------------
    def models(self) -> List[dict]:
        with self._lock:
            out = []
            for name, mv in sorted(self._active.items()):
                d = mv.describe()
                d["default"] = name == self._default
                hist = self._history.get(name)
                d["rollback_to"] = hist[-1].version if hist else None
                out.append(d)
            return out

    @property
    def default_name(self) -> Optional[str]:
        return self._default
