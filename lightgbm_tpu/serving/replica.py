"""Sharded replica fleet: one compiled ensemble, N device-resident
copies, least-queue-depth routing — plus per-model QPS budgets.

The tensorized predict program (``codegen.CompiledEnsemble``) makes a
model's serving state a handful of dense arrays, so replicating it
across mesh devices is a ``device_put`` per table, not a process per
copy. Each replica owns its OWN :class:`~.batcher.MicroBatcher` (its
queue IS the device's queue — one in-flight kernel per device, no
cross-device convoy), and the router picks the replica with the fewest
queued rows at submit time. That is the power-of-one-choice degenerate
case of least-loaded routing: with a handful of replicas, scanning all
queue depths is cheaper than maintaining anything smarter.

Version affinity: a ``ReplicaSet`` is constructed FOR one
:class:`~.registry.ModelVersion` and every replica's ``predict_fn``
tags results with that version — a request routed anywhere in the set
can never observe a different version. Hot-swap publishes a whole new
set (built and warmed off-path by the registry) in the same atomic
snapshot as the version itself.

Admission has two independent gates:

- per-replica queue bounds (``Overloaded``, inherited from the
  batcher) — protects the DEVICE;
- per-model token-bucket QPS budgets (:class:`QpsBudget`,
  :class:`BudgetExceeded`) — protects the TENANT mix: one model's
  burst cannot starve the others' batcher capacity. The HTTP layer
  maps both to 429, distinguished by ``status``.

Runbook — draining one device's replica (e.g. before a host swap)::

    rs = registry.resolve("m").replicas
    rs.drain_replica(i)     # router skips it; queued work finishes
    ...maintenance...
    rs.restore_replica(i)   # fresh batcher, back in rotation
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .batcher import MicroBatcher, Overloaded
from .metrics import ServingMetrics

__all__ = ["ReplicaSet", "QpsBudget", "BudgetExceeded"]


class BudgetExceeded(RuntimeError):
    """Per-model QPS budget exhausted; the request was NOT enqueued.

    Retriable by definition (nothing about the request was wrong) —
    the HTTP layer answers 429 + Retry-After with
    ``status="budget_exceeded"`` so clients can tell tenant throttling
    from queue overload.
    """

    retriable = True

    def __init__(self, model: str, qps: float):
        super().__init__(
            f"model {model!r} exceeded its {qps:g} req/s budget; "
            "retriable")
        self.model = model
        self.qps = qps


class QpsBudget:
    """Token bucket: ``qps`` tokens/s refill, ``burst`` capacity
    (default ``max(qps, 1)`` — a one-second burst). Thread-safe;
    ``try_admit`` never blocks."""

    def __init__(self, qps: float, burst: Optional[float] = None):
        if qps <= 0:
            raise ValueError("qps budget must be > 0")
        self.qps = float(qps)
        self.burst = float(burst) if burst is not None else max(
            self.qps, 1.0)
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def try_admit(self, tokens: float = 1.0) -> bool:
        now = time.monotonic()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.qps)
            self._t_last = now
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True
            return False


class _Replica:
    __slots__ = ("index", "device", "batcher", "draining")

    def __init__(self, index: int, device, batcher: MicroBatcher):
        self.index = index
        self.device = device
        self.batcher = batcher
        self.draining = False


class ReplicaSet:
    """N device-resident copies of one compiled model version behind a
    least-queue-depth router.

    ``compiled`` is a :class:`~lightgbm_tpu.codegen.CompiledEnsemble`;
    ``tag`` is handed back with every result (the registry passes the
    owning ``ModelVersion``). ``devices`` defaults to the local mesh;
    with more replicas than devices they wrap round-robin (useful on a
    single-device host to exercise fleet behavior).
    """

    def __init__(self, compiled, tag=None, *, replicas: int = 1,
                 devices: Optional[Sequence] = None,
                 metrics: Optional[ServingMetrics] = None,
                 model: str = "default", **batcher_opts):
        if replicas < 1:
            raise ValueError("a ReplicaSet needs >= 1 replicas")
        if devices is None:
            import jax
            devices = jax.devices()
        self.compiled = compiled
        self.tag = tag
        self.model = model
        self.metrics = metrics or ServingMetrics()
        self._batcher_opts = dict(batcher_opts)
        self._lock = threading.Lock()
        self._closed = False
        self.replicas: List[_Replica] = [
            self._spawn(i, devices[i % len(devices)])
            for i in range(int(replicas))]

    def _spawn(self, index: int, device) -> _Replica:
        def predict_fn(X, _d=device):
            return self.compiled.predict(X, device=_d), self.tag

        b = MicroBatcher(predict_fn, metrics=self.metrics,
                         model=self.model, **self._batcher_opts)
        return _Replica(index, device, b)

    # -- routing -------------------------------------------------------
    def pick(self) -> _Replica:
        """Replica with the fewest queued rows among those in
        rotation."""
        best = None
        best_load = None
        for r in self.replicas:
            if r.draining:
                continue
            load = r.batcher.load()
            if best is None or load < best_load:
                best, best_load = r, load
        if best is None:
            raise Overloaded(0, 0)   # every replica draining: retriable
        return best

    def submit(self, X, timeout: Optional[float] = None) -> np.ndarray:
        return self.pick().batcher.submit(X, timeout=timeout)

    def submit_tagged(self, X, timeout: Optional[float] = None
                      ) -> Tuple[np.ndarray, object]:
        return self.pick().batcher.submit_tagged(X, timeout=timeout)

    def submit_async(self, X, callback: Callable) -> None:
        self.pick().batcher.submit_async(X, callback)

    def loads(self) -> List[int]:
        return [r.batcher.load() for r in self.replicas]

    # -- lifecycle -----------------------------------------------------
    def warm(self, rungs: Sequence[int]) -> "ReplicaSet":
        """Compile every ladder rung on every replica's device — jit
        executables cache per (shape, device), so one replica's warmth
        does not transfer."""
        for r in self.replicas:
            for rows in sorted(set(int(x) for x in rungs)):
                Z = np.zeros((rows, self.compiled.num_features))
                self.compiled.predict(Z, device=r.device)
        return self

    def drain_replica(self, index: int):
        """Take one replica out of rotation and finish its queued work
        (the device-maintenance runbook step). Refuses to drain the
        last live replica — that is a model drain, not a device
        drain."""
        with self._lock:
            live = [r for r in self.replicas if not r.draining]
            r = self.replicas[index]
            if not r.draining and len(live) <= 1:
                raise RuntimeError(
                    "refusing to drain the last live replica; "
                    "swap or unregister the model instead")
            r.draining = True
        r.batcher.close(drain=True)

    def restore_replica(self, index: int):
        """Return a drained replica to rotation with a fresh batcher
        (its device tables are still resident — restore is instant)."""
        with self._lock:
            old = self.replicas[index]
            if not old.draining:
                return
            self.replicas[index] = self._spawn(old.index, old.device)

    def close(self, drain: bool = True):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            reps = list(self.replicas)
        for r in reps:
            if not r.draining:
                r.batcher.close(drain=drain)

    def describe(self) -> dict:
        return {"replicas": len(self.replicas),
                "devices": [str(r.device) for r in self.replicas],
                "draining": [r.index for r in self.replicas
                             if r.draining],
                "loads": self.loads(),
                "compiled_signatures":
                    self.compiled.compiled_signatures()}
