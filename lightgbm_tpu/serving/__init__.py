"""Serving subsystem: micro-batching scheduler, versioned model
registry with hot-swap, and a metrics-instrumented prediction server.

Layered on :class:`~lightgbm_tpu.engine.PredictSession` (the fast
per-process primitive of PR 1) — this package is what turns it into a
service: request coalescing under a latency deadline (``batcher``),
zero-downtime deploys (``registry``), request-level observability
(``metrics``), and an HTTP front end (``server``,
``python -m lightgbm_tpu serve``).
"""

from .batcher import MicroBatcher, Overloaded, bucket_rows
from .metrics import Counter, RingHistogram, ServingMetrics
from .registry import ModelRegistry, ModelVersion
from .replica import BudgetExceeded, QpsBudget, ReplicaSet
from .server import PredictionServer

__all__ = ["MicroBatcher", "Overloaded", "bucket_rows", "Counter",
           "RingHistogram", "ServingMetrics", "ModelRegistry",
           "ModelVersion", "PredictionServer", "ReplicaSet",
           "QpsBudget", "BudgetExceeded"]
