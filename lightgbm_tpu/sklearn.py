"""scikit-learn estimator API.

Analog of the reference Python wrapper (``python-package/lightgbm/
sklearn.py`` — ``LGBMModel`` :486, ``LGBMClassifier`` :1314,
``LGBMRegressor`` :1424, ``LGBMRanker`` :1678): the same constructor
surface (sklearn-style aliases like ``n_estimators``/``min_child_samples``
resolve through the Config alias table), fit/predict contract, fitted
attributes, and eval-set/early-stopping behavior, driving the JAX Booster
directly instead of a ctypes C API.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
from sklearn.preprocessing import LabelEncoder

from .callback import record_evaluation
from .dataset import Dataset
from .engine import Booster, train

__all__ = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]


def _to_array(X):
    if hasattr(X, "values") and hasattr(X, "columns"):
        return X.values
    return np.asarray(X)


class LGBMModel(BaseEstimator):
    """Base estimator (sklearn.py:486 analog)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None,
                 n_jobs: Optional[int] = None,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- sklearn plumbing ---------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep)
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for k, v in params.items():
            setattr(self, k, v)
            if k not in self._sk_ctor_names():
                self._other_params[k] = v
        return self

    @classmethod
    def _sk_ctor_names(cls):
        import inspect
        return set(inspect.signature(LGBMModel.__init__).parameters) - \
            {"self", "kwargs"}

    def _process_params(self, default_objective: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        obj = params.pop("objective", None) or default_objective
        if callable(obj):
            self._fobj_callable = obj
            obj = "custom"
        else:
            self._fobj_callable = None
        params["objective"] = obj
        if params.pop("n_jobs", None) is not None:
            pass  # threading is XLA's business on TPU
        rs = params.pop("random_state", None)
        if rs is not None:
            if isinstance(rs, np.random.RandomState):
                params["seed"] = int(rs.randint(2 ** 31))
            elif isinstance(rs, getattr(np.random, "Generator", ())):
                params["seed"] = int(rs.integers(2 ** 31))
            else:
                params["seed"] = int(rs)
        params["boosting"] = params.pop("boosting_type", "gbdt")
        params.setdefault("verbosity", -1)
        # sklearn names that Config resolves via aliases: subsample,
        # colsample_bytree, reg_alpha, reg_lambda, min_child_samples,
        # min_child_weight, min_split_gain, subsample_for_bin pass through
        return {k: v for k, v in params.items() if v is not None}

    # -- fit ----------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None):
        params = self._process_params(self._default_objective())
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        y_arr = self._prepare_targets(np.asarray(_to_array(y)).reshape(-1),
                                      params)

        sw = sample_weight
        if getattr(self, "_class_weight_arr", None) is not None:
            cw = self._class_weight_arr[self._le.transform(
                np.asarray(_to_array(y)).reshape(-1))]
            sw = cw if sw is None else np.asarray(sw) * cw

        train_set = Dataset(X, label=y_arr, weight=sw, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=dict(params), free_raw_data=False)
        valid_sets: List[Dataset] = []
        names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vy_arr = np.asarray(_to_array(vy)).reshape(-1)
                if hasattr(self, "_le"):
                    vy_arr = self._le.transform(vy_arr)
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vis = (eval_init_score[i]
                       if eval_init_score is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                valid_sets.append(Dataset(
                    vx, label=vy_arr, weight=vw, group=vg, init_score=vis,
                    reference=train_set))
                names.append(eval_names[i] if eval_names and
                             i < len(eval_names) else f"valid_{i}")

        callbacks = list(callbacks or [])
        self._evals_result: Dict = {}
        if valid_sets:
            callbacks.append(record_evaluation(self._evals_result))

        feval = None
        if callable(eval_metric):
            feval = _wrap_sklearn_metric(eval_metric)

        fobj = None
        if self._fobj_callable is not None:
            fobj = _wrap_sklearn_objective(self._fobj_callable)

        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=names or None,
            callbacks=callbacks, feval=feval, fobj=fobj,
            init_model=init_model)
        self._n_features = train_set.num_total_features
        self._feature_name = list(train_set.feature_name)
        self.fitted_ = True
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _prepare_targets(self, y: np.ndarray, params: Dict) -> np.ndarray:
        return np.asarray(y, np.float64)

    # -- predict ------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        self._check_fitted()
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)

    def _check_fitted(self):
        if not getattr(self, "fitted_", False):
            raise _NotFittedError(
                f"This {type(self).__name__} instance is not fitted yet. "
                "Call 'fit' with appropriate arguments before using this "
                "estimator.")

    # -- fitted attributes (sklearn.py:940-1030 analog) ---------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._Booster.best_iteration

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._Booster.best_score

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._feature_name

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._Booster.current_iteration()

    @property
    def n_iter_(self) -> int:
        return self.n_estimators_


class _NotFittedError(ValueError, AttributeError):
    """sklearn.exceptions.NotFittedError-compatible."""


try:
    from sklearn.exceptions import NotFittedError as _NotFittedError  # noqa
except ImportError:  # pragma: no cover
    pass


def _wrap_sklearn_metric(func):
    """Adapt sklearn-style feval(y_true, y_pred) -> engine feval."""
    def feval(preds, dataset):
        y_true = dataset.get_label()
        res = func(y_true, preds)
        if isinstance(res, tuple) and len(res) == 3:
            return res
        return [r for r in res]
    return feval


def _wrap_sklearn_objective(func):
    """Adapt sklearn-style fobj(y_true, y_pred) -> engine fobj."""
    def fobj(preds, dataset):
        return func(dataset.get_label(), preds)
    return fobj


class LGBMRegressor(RegressorMixin, LGBMModel):
    """sklearn.py:1424 analog."""

    def _default_objective(self) -> str:
        return "regression"

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)


class LGBMClassifier(ClassifierMixin, LGBMModel):
    """sklearn.py:1314 analog: label encoding, predict_proba, classes_."""

    def _default_objective(self) -> str:
        return "binary"

    def _prepare_targets(self, y: np.ndarray, params: Dict) -> np.ndarray:
        self._le = LabelEncoder().fit(y)
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if params.get("objective") in (None, "binary", "custom"):
                if params.get("objective") != "custom":
                    params["objective"] = "multiclass"
            params["num_class"] = self._n_classes
        elif params.get("objective") not in ("custom",):
            params.setdefault("objective", "binary")
        # class_weight='balanced' or dict -> per-class sample weights
        cw = self.class_weight
        if cw is not None:
            from sklearn.utils.class_weight import compute_class_weight
            if isinstance(cw, str):
                arr = compute_class_weight(cw, classes=self._classes, y=y)
            else:
                arr = np.asarray([cw.get(c, 1.0) for c in self._classes],
                                 np.float64)
            self._class_weight_arr = arr
        else:
            self._class_weight_arr = None
        return self._le.transform(y).astype(np.float64)

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:  # binary probabilities of class 1
            idx = (result >= 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        self._check_fitted()
        res = self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return res
        if res.ndim == 1:
            return np.stack([1.0 - res, res], axis=1) \
                if self._n_classes <= 2 else res
        return res

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """sklearn.py:1678 analog (lambdarank)."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is "
                             "not None")
        self._other_params["eval_at"] = list(eval_at)
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            group=group, eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_group=eval_group,
            eval_metric=eval_metric, feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)
