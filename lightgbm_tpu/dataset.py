"""Dataset: binned feature matrix + metadata, resident in HBM.

TPU-native analog of the reference data layer (LightGBM
``include/LightGBM/dataset.h:487`` ``Dataset``, ``dataset.h:48`` ``Metadata``,
``src/io/dataset_loader.cpp`` ``DatasetLoader``).

Design differences (TPU-first):
- The reference stores per-feature-group packed columns (dense/sparse bins,
  EFB bundles) tuned for CPU cache behavior. On TPU the histogram kernel
  wants one dense row-major bin matrix in HBM (uint8 when bins <= 256)
  feeding the MXU one-hot matmul — sparse storage would force gathers.
  For high-dimensional sparse data, EFB (efb.py) packs mutually-exclusive
  features into shared columns so the matrix (and the matmul lattice)
  scales with bundles, not features.
- Rows are padded to a multiple of the histogram row-block so every shape
  under jit is static; padded rows carry ``row_leaf = -1`` and zero
  grad/hess weight so they never contribute.
- Binning runs on host NumPy over a sample (``bin_construct_sample_cnt``,
  config.h analog) exactly like DatasetLoader's two-round sampling load.
"""

from __future__ import annotations

import os
import numpy as np
from typing import Dict, List, Optional

from .binning import BinMapper
from .config import Config

__all__ = ["Dataset", "Sequence", "estimate_device_bytes",
           "check_device_capacity"]


def estimate_device_bytes(num_rows: int, width: int, itemsize: int,
                          num_leaves: int, max_bin: int,
                          hist_cache: bool, n_row_shards: int = 1) -> int:
    """Per-device bytes of the training working set (capacity model,
    VERDICT r4 #5). Device storage is the DENSE bundled bin matrix
    sharded over data-parallel rows — the reference instead has
    per-feature sparse storage (src/io/sparse_bin.hpp:1,
    multi_val_sparse_bin.hpp:1) so its footprint scales with non-zeros.
    Dominant terms per chip:
      bins [R/shards, width] itemsize   (the matrix itself)
      gh/scores/row_leaf ~ 4 x [R/shards] f32
      hist cache [(L+1), width*B', 3] f32 when hist_subtraction is on
    """
    r_local = -(-num_rows // max(1, n_row_shards))
    bins_b = r_local * width * itemsize
    per_row = 4 * 4 * r_local                    # gh(3) + scores/row_leaf
    cache_b = ((num_leaves + 1) * width * max_bin * 3 * 4
               if hist_cache else 0)
    return int(bins_b + per_row + cache_b)


def check_device_capacity(num_rows: int, width: int, itemsize: int,
                          num_leaves: int, max_bin: int,
                          hist_cache: bool, n_row_shards: int = 1,
                          headroom: float = 0.85) -> None:
    """Raise MemoryError with sized guidance when the dense working set
    cannot fit a device (instead of an opaque device OOM mid-training).

    The budget comes from the backend's per-device memory when the
    runtime reports one (TPU HBM), else from
    ``LIGHTGBM_TPU_DEVICE_MEM_GB`` (also the test hook); with neither,
    the check is skipped (CPU hosts page).
    """
    budget = None
    env = os.environ.get("LIGHTGBM_TPU_DEVICE_MEM_GB")
    if env:
        budget = float(env) * (1 << 30)
    else:
        try:
            import jax
            stats = jax.devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                budget = float(stats["bytes_limit"])
        except Exception:
            budget = None
    if not budget:
        return
    need = estimate_device_bytes(num_rows, width, itemsize, num_leaves,
                                 max_bin, hist_cache, n_row_shards)
    if need <= budget * headroom:
        return
    gib = 1 << 30
    raise MemoryError(
        f"training working set ~{need / gib:.1f} GiB per device exceeds "
        f"{budget * headroom / gib:.1f} GiB available "
        f"({num_rows:,} rows x {width:,} stored columns x {itemsize} B "
        f"over {n_row_shards} row shard(s)). Device storage is the "
        "DENSE bundled bin matrix — wide sparse data fits only when its "
        "columns are mutually exclusive enough to bundle (EFB). "
        "Options: enable_bundle=true with a larger max_conflict_rate; "
        "max_bin<=255 keeps columns uint8; shard rows over more "
        "devices/hosts (tree_learner=data); shard COLUMNS over devices "
        "(tree_learner=feature with feature_shard_storage=true — each "
        "chip then stores only width/devices columns); or reduce "
        "features up-front. The reference's sparse_bin.hpp per-feature "
        "sparse storage maps to the column-sharded mode here (README "
        "'Sparse data').")


class Sequence:
    """Generic batched-row data access (basic.py:915 Sequence analog).

    Subclass and implement ``__getitem__`` (int -> 1-D row, slice -> 2-D
    batch) and ``__len__``. Dataset streams rows through it in
    ``batch_size`` chunks — the raw matrix never materializes, the analog
    of the reference's two-round loading + LGBM_DatasetPushRows
    streaming ingestion (c_api).
    """

    batch_size = 4096

    def __getitem__(self, idx):
        raise NotImplementedError("Sequence must implement __getitem__")

    def __len__(self):
        raise NotImplementedError("Sequence must implement __len__")


def _is_sequence_input(data) -> bool:
    if isinstance(data, Sequence):
        return True
    return (isinstance(data, list) and len(data) > 0
            and all(isinstance(s, Sequence) for s in data))


def _is_sparse(data) -> bool:
    return hasattr(data, "tocsc") and hasattr(data, "tocsr")


def _is_arrow(data) -> bool:
    return hasattr(data, "column_names") and hasattr(data, "num_rows")


def _is_pandas_df(data) -> bool:
    return (hasattr(data, "dtypes") and hasattr(data, "columns")
            and hasattr(data, "values") and not _is_arrow(data))


def _data_from_pandas(df, align_categories=None):
    """DataFrame -> (f64 matrix, category column indices, category
    lists). The reference's ``_data_from_pandas``
    (python-package/lightgbm/basic.py): ``category``-dtype columns map
    to their codes (missing -> NaN), every other column must be
    int/float/bool, and at valid/predict time the codes are ALIGNED to
    the training category lists (``align_categories``)."""
    import pandas as pd

    def _is_cat(dt):
        return isinstance(dt, pd.CategoricalDtype) or str(dt) == "category"

    cat_idx = [i for i, dt in enumerate(df.dtypes) if _is_cat(dt)]
    bad = [str(c) for c, dt in zip(df.columns, df.dtypes)
           if not _is_cat(dt) and getattr(dt, "kind", "O") not in "iufb"]
    if bad:
        raise ValueError(
            "DataFrame.dtypes for data must be int, float or bool.\n"
            "Did not expect the data types in the following fields: "
            + ", ".join(bad))
    if align_categories is not None and len(align_categories) != len(
            cat_idx):
        raise ValueError(
            "train and valid dataset categorical_feature do not match.")
    out = np.empty(df.shape, np.float64)
    cats_out = []
    cat_set = set(cat_idx)
    j = 0
    for i, col in enumerate(df.columns):
        s = df[col]
        if i in cat_set:
            if align_categories is not None:
                s = s.cat.set_categories(align_categories[j])
            cats_out.append(list(s.cat.categories))
            codes = np.asarray(s.cat.codes, np.float64)
            codes[codes < 0] = np.nan
            out[:, i] = codes
            j += 1
        else:
            out[:, i] = np.asarray(s, np.float64)
    return out, cat_idx, cats_out


def _to_2d_float(data) -> np.ndarray:
    if _is_arrow(data):
        # pyarrow Table (arrow.h ArrowChunkedArray ingestion analog):
        # column-wise conversion; chunked arrays concatenate
        cols = [np.asarray(data.column(i).to_numpy(zero_copy_only=False),
                           dtype=np.float64)
                for i in range(data.num_columns)]
        return np.ascontiguousarray(np.column_stack(cols))
    if hasattr(data, "values") and hasattr(data, "columns"):  # DataFrame
        arr = data.values
    else:
        arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr[:, None]
    return np.ascontiguousarray(arr, dtype=np.float64)


class Dataset:
    """Binned training data.

    Mirrors the construction flow of DatasetLoader::ConstructFromSampleData
    (dataset_loader.cpp:593): sample rows -> fit BinMappers -> map all rows.
    """

    def __init__(self, data, label=None, weight=None, group=None,
                 init_score=None, feature_name="auto",
                 categorical_feature="auto", params: Optional[Dict] = None,
                 reference: Optional["Dataset"] = None,
                 free_raw_data: bool = True, position=None):
        self.params = dict(params or {})
        self.config = Config(self.params)
        self._raw_data = data
        self.label = None if label is None else np.asarray(
            label, dtype=np.float64).reshape(-1)
        self.weight = None if weight is None else np.asarray(
            weight, dtype=np.float64).reshape(-1)
        self.group = None if group is None else np.asarray(
            group, dtype=np.int64).reshape(-1)
        self.init_score = None if init_score is None else np.asarray(
            init_score, dtype=np.float64)
        # per-row result positions for unbiased lambdarank
        # (Metadata::positions, src/io/metadata.cpp; ids or names)
        self.position = (None if position is None
                         else np.asarray(position).reshape(-1))
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.reference = reference
        self.free_raw_data = free_raw_data

        self.bin_mappers: List[BinMapper] = []
        self.pandas_categorical = None   # per-cat-column category lists
        self.raw_values: Optional[np.ndarray] = None  # kept for linear_tree
        self.bundle_plan = None                     # EFB layout (efb.py)
        self.bins = None                            # [num_data, F|G] int
        self.chunk_source = None   # shard-backed row stream (data/)
        self.num_data: int = 0
        # True once the multi-host loader kept only this process's row
        # block (learners that need FULL rows per worker check this)
        self.auto_partitioned = False
        self.num_total_features: int = 0
        self.used_features: Optional[np.ndarray] = None  # indices of
        # non-trivial features actually trained on
        self._constructed = False

    # ------------------------------------------------------------------
    @property
    def bins(self) -> Optional[np.ndarray]:
        """[num_data, F|G] binned matrix. Shard-backed datasets keep it
        on disk (``chunk_source``) and materialize HERE, lazily, only
        when a resident consumer (save_binary, subset, a non-chunked
        trainer fallback) actually reads it — the chunked trainer never
        does."""
        if self._bins is None and self.chunk_source is not None:
            src = self.chunk_source
            step = 1 << 16
            self._bins = np.concatenate(
                [np.asarray(src.read_rows(lo, min(lo + step,
                                                  src.num_rows)))
                 for lo in range(0, src.num_rows, step)])
        return self._bins

    @bins.setter
    def bins(self, value) -> None:
        self._bins = value

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._constructed:
            return self
        # params may have been merged from the Booster since __init__
        # (reference _update_params flow, basic.py) — refresh the config
        self.config = Config(self.params)
        if self.reference is not None:
            # a valid set needs its train set's bin mappers (and, for
            # LibSVM, its width) before anything else happens
            self.reference.construct()
        if _is_sequence_input(self._raw_data):
            return self._construct_from_sequences()
        file_names: Optional[List[str]] = None
        from_file = isinstance(self._raw_data, (str, os.PathLike))
        if from_file:
            from .data.shardfile import is_shard_path
            if is_shard_path(self._raw_data):
                # pre-binned .lgbtpu shard dataset (`python -m
                # lightgbm_tpu ingest` output): metadata restores from
                # the shard headers, rows stream from the mmaps
                return self._construct_from_shards(self._raw_data)
        if from_file and self._is_binary_file(self._raw_data):
            # binary dataset cache (LoadFromBinFile analog): restores
            # the constructed state directly, no parsing or re-binning
            self._load_binary(self._raw_data)
            sl = self._auto_partition_slice(self.bins.shape[0])
            if sl is not None:
                self.bins = self.bins[sl]
                self.num_data = len(sl)
                self._apply_partition(sl)
            if self.label is None and not self.params.get("_allow_no_label"):
                raise ValueError("Dataset has no label")
            return self
        if from_file:
            # text-file path: CSV/TSV/LibSVM autodetect + sidecars
            # (DatasetLoader::LoadFromFile, dataset_loader.cpp:203)
            from .io import load_data_file
            hint = (self.reference.num_total_features
                    if self.reference is not None else 0)
            loaded = load_data_file(self._raw_data, self.config,
                                    num_features_hint=hint)
            self._raw_data = loaded.X
            file_names = loaded.feature_names
            if self.label is None and loaded.label is not None:
                self.label = loaded.label
            if self.weight is None and loaded.weight is not None:
                self.weight = loaded.weight
            if self.group is None and loaded.group is not None:
                self.group = loaded.group
            if self.init_score is None and loaded.init_score is not None:
                self.init_score = loaded.init_score
            if self.position is None and loaded.position is not None:
                self.position = loaded.position
        sparse = _is_sparse(self._raw_data)
        pd_cat_idx = None
        if sparse:
            # scipy CSR/CSC input: binning samples densify per-row, full
            # extraction streams per-column — the dense [R, F] matrix
            # never materializes (SparseBin/CSR ingestion analog)
            data = self._raw_data.tocsr()
            data_csc = None
        elif _is_pandas_df(self._raw_data):
            # a valid set aligns to its train set's category lists; a
            # train set trained WITHOUT pandas gets [] so a categorical
            # frame against it raises the reference's mismatch error
            ref_cats = None
            if self.reference is not None:
                ref_cats = self.reference.pandas_categorical
                if ref_cats is None:
                    ref_cats = []
            data, pd_cat_idx, cats = _data_from_pandas(
                self._raw_data, ref_cats)
            self.pandas_categorical = cats
        else:
            data = _to_2d_float(self._raw_data)
        if (self.reference is not None
                and data.shape[1] != self.reference.num_total_features):
            if from_file and data.shape[1] < \
                    self.reference.num_total_features:
                # LibSVM valid file whose max feature index is below the
                # train set's: right-pad with zeros to align (CreateValid
                # semantics — absent sparse entries are zero)
                pad = self.reference.num_total_features - data.shape[1]
                data = np.concatenate(
                    [data, np.zeros((data.shape[0], pad))], axis=1)
            else:
                raise ValueError(
                    f"validation data has {data.shape[1]} features but "
                    f"training data has "
                    f"{self.reference.num_total_features}")
        sl = self._auto_partition_slice(data.shape[0])
        if sl is not None:
            data = data[sl]
            self._apply_partition(sl)
        self.num_data, self.num_total_features = data.shape
        cfg = self.config

        if isinstance(self.feature_name, (list, tuple)) and self.feature_name:
            names = list(self.feature_name)
        elif _is_arrow(self._raw_data):
            names = [str(c) for c in self._raw_data.column_names]
        elif hasattr(self._raw_data, "columns"):
            names = [str(c) for c in self._raw_data.columns]
        elif file_names and len(file_names) == self.num_total_features:
            names = file_names
        else:
            names = [f"Column_{i}" for i in range(self.num_total_features)]
        self.feature_name = names

        cat_idx = self._resolve_categoricals(names)
        if pd_cat_idx and self.categorical_feature in ("auto", None):
            # categorical_feature='auto': pandas category dtypes become
            # categorical features (basic.py _data_from_pandas)
            cat_idx = cat_idx | set(pd_cat_idx)

        if self.reference is not None:
            # validation set: reuse the training bin mappers
            # (dataset.h CreateValid / align-with-train semantics)
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.max_num_bin = ref.max_num_bin
        else:
            sample_cnt = min(cfg.bin_construct_sample_cnt, self.num_data)
            if sample_cnt < self.num_data:
                rng = np.random.RandomState(cfg.data_random_seed)
                sample_idx = rng.choice(self.num_data, sample_cnt,
                                        replace=False)
                sample = data[sample_idx]
            else:
                sample = data
            if sparse:
                sample = np.asarray(sample.todense(), dtype=np.float64)
            self._fit_mappers(sample, cat_idx, cfg)

        F = len(self.used_features)

        if sparse:
            # one CSR->CSC conversion; column slices are then O(nnz_col)
            data_csc = data.tocsc()

        def col_of(f):
            if sparse:
                return np.asarray(data_csc[:, [f]].todense(),
                                  dtype=np.float64).ravel()
            return data[:, f]

        # -- EFB: pack mutually-exclusive sparse features (efb.py) ----
        if self.reference is not None:
            self.bundle_plan = self.reference.bundle_plan
        elif self._multi_process():
            # pre-partitioned multi-host: a bundle plan built from the
            # LOCAL sample would differ across hosts (different conflict
            # counts -> different column layouts); skip EFB until the
            # plan itself is synced like the mappers are
            self.bundle_plan = None
        elif cfg.enable_bundle and F > 4:
            from .efb import plan_bundles
            uf = self.used_features
            sample_bins = np.stack(
                [self.bin_mappers[f].values_to_bins(sample[:, f])
                 for f in uf], axis=1)
            plan = plan_bundles(
                sample_bins,
                [self.bin_mappers[f].num_bin for f in uf],
                [self.bin_mappers[f].most_freq_bin for f in uf],
                max_conflict_rate=cfg.max_conflict_rate,
                max_bundle_bins=cfg.max_bundle_bins)
            # bundle only when it genuinely shrinks the matrix
            self.bundle_plan = (plan if plan.num_bundles <= int(0.75 * F)
                                else None)
        else:
            self.bundle_plan = None

        if self.bundle_plan is not None:
            from .efb import encode_bundles

            def cols():
                for j, f in enumerate(self.used_features):
                    yield j, self.bin_mappers[f].values_to_bins(
                        col_of(f)).astype(np.int64)
            self.bins = encode_bundles(self.bundle_plan, cols(),
                                       self.num_data)
        else:
            dtype = np.uint8 if self.max_num_bin <= 256 else np.int32
            fast = None
            if not sparse:
                # accelerator fast path: one jitted searchsorted over the
                # whole [R, F] matrix (ops/binning_device.py)
                from .ops.binning_device import (device_bin_dense,
                                                 want_device_binning)
                if want_device_binning(self.num_data, F):
                    fast = device_bin_dense(
                        data, self.bin_mappers, self.used_features, dtype)
            if fast is not None:
                self.bins = fast
            else:
                self.bins = np.empty((self.num_data, F), dtype=dtype)
                for j, f in enumerate(self.used_features):
                    self.bins[:, j] = self.bin_mappers[f].values_to_bins(
                        col_of(f)).astype(dtype)

        if self.label is None and not self.params.get("_allow_no_label"):
            raise ValueError("Dataset has no label")
        # linear trees regress on raw feature values; keep them resident
        # (the reference keeps raw data when linear_tree, dataset.cpp)
        self.raw_values = None
        ref_cfg = (self.reference.config if self.reference is not None
                   else None)
        if self.config.linear_tree or (
                ref_cfg is not None and ref_cfg.linear_tree):
            if sparse:
                raise ValueError(
                    "linear_tree needs dense raw feature values; sparse "
                    "input is not supported with linear trees")
            self.raw_values = np.ascontiguousarray(data, np.float32)
        if self.free_raw_data:
            self._raw_data = None
        self._constructed = True
        return self

    def _construct_from_shards(self, path) -> "Dataset":
        """Construct from a ``.lgbtpu`` shard directory: every shard is
        validated (checksum + set completeness), BinMappers restore from
        the shard headers, and the binned rows stay mmap-backed behind
        ``chunk_source`` for the chunked trainer."""
        from .data.chunked import ShardSource
        from .data.shardfile import open_shard_dir
        if self._multi_process():
            raise NotImplementedError(
                "shard datasets load single-host (the chunked trainer "
                "is serial; pre-partition shards per host instead)")
        readers, h0 = open_shard_dir(str(path))
        self.bin_mappers = readers[0].mappers()
        self.num_total_features = int(h0["num_total_features"])
        self.used_features = np.asarray(h0["used_features"], np.int64)
        self.max_num_bin = int(h0["max_num_bin"])
        if not (isinstance(self.feature_name, (list, tuple))
                and self.feature_name):
            self.feature_name = list(h0["feature_names"])
        self.num_data = int(h0["total_rows"])
        if self.label is None and h0.get("has_label"):
            self.label = np.concatenate(
                [np.asarray(r.label, np.float64) for r in readers])
        if self.weight is None and h0.get("has_weight"):
            self.weight = np.concatenate(
                [np.asarray(r.weight, np.float64) for r in readers])
        self.bundle_plan = None   # shards store unbundled feature space
        self.chunk_source = ShardSource(readers)
        if self.label is None and not self.params.get("_allow_no_label"):
            raise ValueError("Dataset has no label")
        if self.config.linear_tree:
            raise ValueError(
                "linear_tree needs dense raw feature values; shard "
                "datasets carry only binned rows")
        self.raw_values = None
        if self.free_raw_data:
            self._raw_data = None
        self._constructed = True
        return self

    def _construct_from_sequences(self) -> "Dataset":
        """Two-round streaming load from Sequence objects: a sampled
        pass fits BinMappers, then blocks stream through the shared
        chunked reader (:class:`lightgbm_tpu.data.reader.
        SequenceChunkReader`) and are binned row-block by row-block —
        the full raw matrix never exists in memory (basic.py
        _init_from_sample + _push_rows flow)."""
        cfg = self.config
        if self._multi_process() and not bool(cfg.pre_partition):
            raise NotImplementedError(
                "multi-host Sequence ingestion requires pre-partitioned "
                "sequences per host (pre_partition=true)")
        from .data.reader import DEFAULT_CHUNK_ROWS, SequenceChunkReader
        reader = SequenceChunkReader(self._raw_data)
        self.num_data = int(reader.num_rows)
        self.num_total_features = int(reader.num_features)
        if self.reference is not None:
            ref = self.reference
            if self.num_total_features != ref.num_total_features:
                raise ValueError(
                    f"validation data has {self.num_total_features} "
                    f"features but training data has "
                    f"{ref.num_total_features}")
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.max_num_bin = ref.max_num_bin
            self.bundle_plan = ref.bundle_plan
            names = list(ref.feature_name)
        else:
            names = [f"Column_{i}" for i in range(self.num_total_features)]
        self.feature_name = names
        cat_idx = self._resolve_categoricals(names)

        if self.reference is None:
            sample_cnt = min(cfg.bin_construct_sample_cnt, self.num_data)
            rng = np.random.RandomState(cfg.data_random_seed)
            sample_idx = np.sort(rng.choice(self.num_data, sample_cnt,
                                            replace=False))
            sample = reader.read_rows_at(sample_idx)
            self._fit_mappers(sample, cat_idx, cfg)
            self.bundle_plan = None  # streaming path stays unbundled

        F = len(self.used_features)
        if self.bundle_plan is not None:
            # valid set against an EFB-bundled train set: encode into
            # the same bundle layout so the trainer's decode matches
            from .efb import encode_rows
            dtype = (np.uint8 if self.bundle_plan.max_bundle_bins <= 256
                     else np.int32)
            self.bins = np.zeros(
                (self.num_data, self.bundle_plan.num_bundles), dtype)
        else:
            dtype = np.uint8 if self.max_num_bin <= 256 else np.int32
            self.bins = np.empty((self.num_data, F), dtype=dtype)
        row0 = 0
        for chunk in reader.iter_chunks(DEFAULT_CHUNK_ROWS):
            batch = chunk.X
            r = batch.shape[0]
            batch_bins = np.empty((r, F), np.int64)
            for j, f in enumerate(self.used_features):
                batch_bins[:, j] = self.bin_mappers[f].values_to_bins(
                    batch[:, f])
            if self.bundle_plan is not None:
                from .efb import encode_rows
                encode_rows(self.bundle_plan, batch_bins, self.bins,
                            row0)
            else:
                self.bins[row0:row0 + r] = batch_bins.astype(dtype)
            row0 += r
        assert row0 == self.num_data

        if self.label is None and not self.params.get("_allow_no_label"):
            raise ValueError("Dataset has no label")
        if self.config.linear_tree:
            raise ValueError(
                "linear_tree needs dense raw feature values; Sequence "
                "streaming input is not supported with linear trees")
        self.raw_values = None
        if self.free_raw_data:
            self._raw_data = None
        self._constructed = True
        return self

    def _fit_mappers(self, sample: np.ndarray, cat_idx: set, cfg) -> None:
        """Fit per-feature BinMappers from a row sample
        (ConstructBinMappersFromTextData / ConstructFromSampleData
        analog), honoring max_bin_by_feature and forcedbins_filename
        (dataset_loader.cpp:619-653)."""
        mbf = list(cfg.max_bin_by_feature or [])
        if mbf and len(mbf) != self.num_total_features:
            raise ValueError(
                f"max_bin_by_feature has {len(mbf)} entries but the "
                f"dataset has {self.num_total_features} features")
        forced: Dict[int, list] = {}
        if cfg.forcedbins_filename:
            import json as _json
            with open(cfg.forcedbins_filename) as fh:
                for item in _json.load(fh):
                    forced[int(item["feature"])] = [
                        float(x) for x in item["bin_upper_bound"]]
        self.bin_mappers = []
        # pre-partitioned multi-host: each process fits only its OWNED
        # feature block (the reference fits len/num_machines features per
        # machine, dataset_loader.cpp:1070); sync_bin_mappers fills the
        # rest from the other hosts' blocks
        owned = None
        if self._sync_mappers_needed:
            import jax
            from .parallel.distributed import feature_blocks
            blocks = feature_blocks(self.num_total_features,
                                    jax.process_count())
            owned = set(int(f) for f in blocks[jax.process_index()])
        for f in range(self.num_total_features):
            if owned is not None and f not in owned:
                self.bin_mappers.append(BinMapper())  # filled by sync
                continue
            bt = "categorical" if f in cat_idx else "numerical"
            m = BinMapper.from_values(
                sample[:, f],
                max_bin=int(mbf[f]) if mbf else cfg.max_bin,
                min_data_in_bin=cfg.min_data_in_bin, bin_type=bt,
                use_missing=cfg.use_missing,
                zero_as_missing=cfg.zero_as_missing,
                forced_bounds=forced.get(f))
            self.bin_mappers.append(m)
        if self._sync_mappers_needed:
            # pre-partitioned multi-host loading: every process holds a
            # DIFFERENT row shard, so mappers fitted from local samples
            # would disagree; merge the per-process feature blocks
            # (ConstructBinMappersFromTextData's Allgather,
            # dataset_loader.cpp:1070).
            from .parallel.distributed import sync_bin_mappers
            self.bin_mappers = sync_bin_mappers(self.bin_mappers)
        self.used_features = np.asarray(
            [f for f, m in enumerate(self.bin_mappers)
             if not m.is_trivial], dtype=np.int32)
        if len(self.used_features) == 0:
            raise ValueError("Cannot construct Dataset: all features are "
                             "trivial (single value)")
        self.max_num_bin = max(
            self.bin_mappers[f].num_bin for f in self.used_features)

    def _resolve_categoricals(self, names) -> set:
        cat = self.categorical_feature
        if cat == "auto" or cat is None:
            cfg_cat = self.config.categorical_feature
            if not cfg_cat:
                return set()
            cat = [tok for tok in str(cfg_cat).split(",") if tok]
        out = set()
        for c in cat:
            if isinstance(c, str) and not c.lstrip("-").isdigit():
                if c in names:
                    out.add(names.index(c))
            else:
                out.add(int(c))
        return out

    # ------------------------------------------------------------------
    # accessors used by the trainer
    def _multi_process(self) -> bool:
        """True under a multi-host runtime: this Dataset holds (or will
        hold) one row shard — bin mappers must be synced, EFB skipped."""
        try:
            import jax
            return jax.process_count() > 1
        except Exception:
            return False

    @property
    def _sync_mappers_needed(self) -> bool:
        return self._multi_process()

    def _auto_partition_slice(self, n: int):
        """Rows this process keeps when the caller did NOT pre-partition:
        the loader's rank/num_machines row split
        (DatasetLoader::LoadFromFile, dataset_loader.cpp:203). With
        pre_partition=true the caller's data is already this host's
        shard and no slicing happens."""
        if not self._multi_process() or bool(self.config.pre_partition):
            return None
        self.auto_partitioned = True
        if self.group is not None:
            raise NotImplementedError(
                "multi-host auto-partition does not support query/group "
                "data; pre-partition queries per host and set "
                "pre_partition=true")
        import jax
        from .parallel.distributed import feature_blocks as _blocks
        return _blocks(n, jax.process_count())[jax.process_index()]

    def _apply_partition(self, sl) -> None:
        for fld in ("label", "weight", "position"):
            v = getattr(self, fld)
            if v is not None:
                setattr(self, fld, v[sl])
        if self.init_score is not None:
            isc = np.asarray(self.init_score)
            self.init_score = isc[sl] if isc.ndim == 1 else isc[sl, :]

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def per_feature_num_bins(self) -> np.ndarray:
        return np.asarray([self.bin_mappers[f].num_bin
                           for f in self.used_features], dtype=np.int32)

    def unbundled_bins(self) -> np.ndarray:
        """Per-feature [R, F] bin matrix decoded from EFB bundle storage
        (decode_feature_bins applied column-wise); ``self.bins`` itself
        when no bundling. tree_learner=feature uses this: it shards
        FEATURES and replicates rows, so it needs per-feature columns
        and gives up nothing (each worker holds the full dataset in the
        reference too, feature_parallel_tree_learner.cpp:38)."""
        bp = self.bundle_plan
        if bp is None:
            return self.bins
        from .efb import decode_feature_bins
        nb = self.per_feature_num_bins()
        # int32 (not uint16) above 256 bins: every downstream bins
        # consumer — including the native FFI dispatch, which reads
        # "uint8 else int32" (native/hist_ffi.cc) — handles exactly
        # those two dtypes
        dt = np.uint8 if int(nb.max()) <= 256 else np.int32
        R, F = self.bins.shape[0], len(nb)
        out = np.empty((R, F), dt)
        # decode in row blocks: the int32 gather/compare intermediates
        # are ~8 bytes/cell, so a whole-matrix pass would spike host
        # memory ~10x over the final matrix at EFB-wide shapes
        blk = max(1, (64 << 20) // max(1, 8 * F))
        for r0 in range(0, R, blk):
            raw = self.bins[r0:r0 + blk, bp.feat_bundle].astype(np.int32)
            out[r0:r0 + blk] = decode_feature_bins(
                raw, bp.feat_offset[None, :], nb[None, :],
                bp.feat_mfb[None, :])
        return out

    def per_feature_nan_bins(self) -> np.ndarray:
        """nan bin index per used feature; -1 when the feature has none."""
        return np.asarray([self.bin_mappers[f].nan_bin
                           for f in self.used_features], dtype=np.int32)

    def per_feature_is_categorical(self) -> np.ndarray:
        return np.asarray(
            [self.bin_mappers[f].bin_type == "categorical"
             for f in self.used_features], dtype=bool)

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def query_boundaries(self) -> Optional[np.ndarray]:
        """Cumulative query boundaries from per-query sizes (Metadata
        query_boundaries_, dataset.h:48)."""
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)]).astype(np.int64)

    def set_field(self, name, value):
        if name == "label":
            self.label = np.asarray(value, dtype=np.float64).reshape(-1)
        elif name == "weight":
            self.weight = None if value is None else np.asarray(
                value, dtype=np.float64).reshape(-1)
        elif name == "group":
            self.group = None if value is None else np.asarray(
                value, dtype=np.int64).reshape(-1)
        elif name == "init_score":
            self.init_score = None if value is None else np.asarray(
                value, dtype=np.float64)
        elif name == "position":
            self.position = (None if value is None
                             else np.asarray(value).reshape(-1))
        else:
            raise ValueError(f"Unknown field {name}")

    def __len__(self):
        return self.num_data

    def subset(self, used_indices, params: Optional[Dict] = None
               ) -> "Dataset":
        """Row-subset view sharing this dataset's bin mappers
        (Dataset::CopySubrow, dataset.cpp:836 / basic.py subset): the
        child is already constructed — no re-binning."""
        self.construct()
        idx = np.sort(np.asarray(used_indices, np.int64))
        child = Dataset.__new__(Dataset)
        child.params = {**self.params, **(params or {})}
        child.config = Config(child.params)
        child._raw_data = None
        child.feature_name = list(self.feature_name)
        child.categorical_feature = self.categorical_feature
        child.reference = self
        child.free_raw_data = True
        child.bin_mappers = self.bin_mappers
        child.bundle_plan = self.bundle_plan
        child.used_features = self.used_features
        child.max_num_bin = self.max_num_bin
        child.num_total_features = self.num_total_features
        child.bins = self.bins[idx]
        child.num_data = len(idx)
        child.label = None if self.label is None else self.label[idx]
        child.weight = None if self.weight is None else self.weight[idx]
        child.init_score = None
        if self.init_score is not None:
            isc = np.asarray(self.init_score)
            child.init_score = (isc[idx] if isc.ndim == 1
                                else isc[idx, :])
        child.group = None
        if self.group is not None:
            # rows of a query stay together or the subset is per-row;
            # recompute sizes from membership (used_indices sorted)
            bounds = self.query_boundaries()
            qid = np.searchsorted(bounds, idx, side="right") - 1
            change = np.nonzero(np.diff(qid))[0] + 1
            child.group = np.diff(np.concatenate(
                [[0], change, [len(idx)]])).astype(np.int64)
        child.raw_values = (None if self.raw_values is None
                            else self.raw_values[idx])
        child.position = (None if self.position is None
                          else self.position[idx])
        child.pandas_categorical = self.pandas_categorical
        child._constructed = True
        return child

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append ``other``'s features to this dataset in place
        (Dataset::AddFeaturesFrom, dataset.cpp:1586). Both datasets must
        be constructed with the same ``num_data``; ``other``'s metadata
        (label/weight/group) is discarded, matching the reference."""
        self.construct()
        other.construct()
        if self.num_data != other.num_data:
            raise ValueError(
                f"cannot add features: num_data differs "
                f"({self.num_data} vs {other.num_data})")
        if self.bundle_plan is not None or other.bundle_plan is not None:
            raise ValueError(
                "add_features_from does not support EFB-bundled datasets "
                "(set enable_bundle=false on both)")
        if self.bins.dtype != other.bins.dtype:
            wide = np.int32
            self.bins = self.bins.astype(wide)
            other_bins = other.bins.astype(wide)
        else:
            other_bins = other.bins
        base = self.num_total_features
        self.bins = np.concatenate([self.bins, other_bins], axis=1)
        self.bin_mappers = list(self.bin_mappers) + list(other.bin_mappers)
        self.used_features = np.concatenate(
            [self.used_features, other.used_features + base])
        # de-duplicate colliding names the way pandas would
        names = list(self.feature_name)
        taken = set(names)
        for nm in other.feature_name:
            new = nm
            i = 1
            while new in taken:
                new = f"{nm}_{i}"
                i += 1
            taken.add(new)
            names.append(new)
        self.feature_name = names
        self.num_total_features = base + other.num_total_features
        self.max_num_bin = max(self.max_num_bin, other.max_num_bin)
        if self.raw_values is not None and other.raw_values is not None:
            self.raw_values = np.concatenate(
                [self.raw_values, other.raw_values], axis=1)
        else:
            self.raw_values = None
        return self

    # ------------------------------------------------------------------
    # binary dataset cache (Dataset::SaveBinaryFile dataset.cpp:1018 /
    # DatasetLoader::LoadFromBinFile dataset_loader.cpp:417): persist the
    # CONSTRUCTED state — binned matrix + mappers + metadata — so reloads
    # skip parsing and re-binning entirely.
    _BINARY_KEY = "lightgbm_tpu_dataset_v1"

    def save_binary(self, filename) -> "Dataset":
        self.construct()
        payload = {
            self._BINARY_KEY: np.asarray(1),
            "bins": self.bins,
            "used_features": self.used_features,
            "max_num_bin": np.asarray(self.max_num_bin),
            "feature_name": np.asarray(self.feature_name),
        }
        for field in ("label", "weight", "group", "init_score",
                      "position"):
            v = getattr(self, field)
            if v is not None:
                payload[field] = v
        if self.pandas_categorical is not None:
            import json as _json

            def _py(o):
                if isinstance(o, np.integer):
                    return int(o)
                if isinstance(o, np.floating):
                    return float(o)
                if isinstance(o, np.bool_):
                    return bool(o)
                return str(o)
            payload["pandas_categorical"] = np.asarray(_json.dumps(
                self.pandas_categorical, default=_py))
        scal, ubs, cats = [], [], []
        ub_off, cat_off = [0], [0]
        for m in self.bin_mappers:
            s, ub, ct = m.state_arrays()
            scal.append(s)
            ubs.append(ub)
            cats.append(ct)
            ub_off.append(ub_off[-1] + len(ub))
            cat_off.append(cat_off[-1] + len(ct))
        payload.update(
            mapper_scalars=np.stack(scal),
            mapper_ub=np.concatenate(ubs) if ubs else np.empty(0),
            mapper_ub_off=np.asarray(ub_off, np.int64),
            mapper_cats=np.concatenate(cats) if cats else np.empty(0,
                                                                   np.int64),
            mapper_cat_off=np.asarray(cat_off, np.int64))
        if self.bundle_plan is not None:
            fb, fo, fm, bnb, bscal = self.bundle_plan.state_arrays()
            payload.update(efb_feat_bundle=fb, efb_feat_offset=fo,
                           efb_feat_mfb=fm, efb_bundle_bins=bnb,
                           efb_scalars=bscal)
        with open(filename, "wb") as f:
            np.savez_compressed(f, **payload)
        return self

    @staticmethod
    def _is_binary_file(path) -> bool:
        try:
            with open(path, "rb") as f:
                return f.read(2) == b"PK"  # npz = zip container
        except OSError:
            return False

    def _load_binary(self, path):
        from .binning import BinMapper
        with np.load(path, allow_pickle=False) as z:
            if self._BINARY_KEY not in z:
                raise ValueError(
                    f"{path} is not a lightgbm_tpu binary dataset")
            self.bins = z["bins"]
            self.used_features = z["used_features"]
            self.max_num_bin = int(z["max_num_bin"])
            self.feature_name = [str(s) for s in z["feature_name"]]
            for field in ("label", "weight", "group", "init_score",
                          "position"):
                if field in z and getattr(self, field) is None:
                    setattr(self, field, z[field])
            if "pandas_categorical" in z:
                import json as _json
                self.pandas_categorical = _json.loads(
                    str(z["pandas_categorical"]))
            scal = z["mapper_scalars"]
            ub, ub_off = z["mapper_ub"], z["mapper_ub_off"]
            cats, cat_off = z["mapper_cats"], z["mapper_cat_off"]
            if "efb_scalars" in z:
                from .efb import BundlePlan
                self.bundle_plan = BundlePlan.from_state_arrays(
                    z["efb_feat_bundle"], z["efb_feat_offset"],
                    z["efb_feat_mfb"], z["efb_bundle_bins"],
                    z["efb_scalars"])
        self.bin_mappers = [
            BinMapper.from_state_arrays(
                scal[i], ub[ub_off[i]:ub_off[i + 1]],
                cats[cat_off[i]:cat_off[i + 1]])
            for i in range(scal.shape[0])]
        self.num_data, _ = self.bins.shape
        self.num_total_features = len(self.bin_mappers)
        self._raw_data = None
        self._constructed = True
