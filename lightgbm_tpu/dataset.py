"""Dataset: binned feature matrix + metadata, resident in HBM.

TPU-native analog of the reference data layer (LightGBM
``include/LightGBM/dataset.h:487`` ``Dataset``, ``dataset.h:48`` ``Metadata``,
``src/io/dataset_loader.cpp`` ``DatasetLoader``).

Design differences (TPU-first):
- The reference stores per-feature-group packed columns (dense/sparse bins,
  EFB bundles) tuned for CPU cache behavior. On TPU the histogram kernel
  wants one dense row-major ``[num_data, num_features]`` bin matrix in HBM
  (uint8 when max_bin <= 256) feeding the MXU one-hot matmul — sparse
  storage would force gathers. EFB is unnecessary for the same reason.
- Rows are padded to a multiple of the histogram row-block so every shape
  under jit is static; padded rows carry ``row_leaf = -1`` and zero
  grad/hess weight so they never contribute.
- Binning runs on host NumPy over a sample (``bin_construct_sample_cnt``,
  config.h analog) exactly like DatasetLoader's two-round sampling load.
"""

from __future__ import annotations

import os
import numpy as np
from typing import Any, Dict, List, Optional, Sequence, Union

from .binning import BinMapper
from .config import Config

__all__ = ["Dataset"]


def _to_2d_float(data) -> np.ndarray:
    if hasattr(data, "values") and hasattr(data, "columns"):  # DataFrame
        arr = data.values
    else:
        arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr[:, None]
    return np.ascontiguousarray(arr, dtype=np.float64)


class Dataset:
    """Binned training data.

    Mirrors the construction flow of DatasetLoader::ConstructFromSampleData
    (dataset_loader.cpp:593): sample rows -> fit BinMappers -> map all rows.
    """

    def __init__(self, data, label=None, weight=None, group=None,
                 init_score=None, feature_name="auto",
                 categorical_feature="auto", params: Optional[Dict] = None,
                 reference: Optional["Dataset"] = None,
                 free_raw_data: bool = True):
        self.params = dict(params or {})
        self.config = Config(self.params)
        self._raw_data = data
        self.label = None if label is None else np.asarray(
            label, dtype=np.float64).reshape(-1)
        self.weight = None if weight is None else np.asarray(
            weight, dtype=np.float64).reshape(-1)
        self.group = None if group is None else np.asarray(
            group, dtype=np.int64).reshape(-1)
        self.init_score = None if init_score is None else np.asarray(
            init_score, dtype=np.float64)
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.reference = reference
        self.free_raw_data = free_raw_data

        self.bin_mappers: List[BinMapper] = []
        self.raw_values: Optional[np.ndarray] = None  # kept for linear_tree
        self.bins: Optional[np.ndarray] = None      # [num_data, F] int
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.used_features: Optional[np.ndarray] = None  # indices of
        # non-trivial features actually trained on
        self._constructed = False

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._constructed:
            return self
        # params may have been merged from the Booster since __init__
        # (reference _update_params flow, basic.py) — refresh the config
        self.config = Config(self.params)
        if self.reference is not None:
            # a valid set needs its train set's bin mappers (and, for
            # LibSVM, its width) before anything else happens
            self.reference.construct()
        file_names: Optional[List[str]] = None
        from_file = isinstance(self._raw_data, (str, os.PathLike))
        if from_file and self._is_binary_file(self._raw_data):
            # binary dataset cache (LoadFromBinFile analog): restores
            # the constructed state directly, no parsing or re-binning
            self._load_binary(self._raw_data)
            if self.label is None and not self.params.get("_allow_no_label"):
                raise ValueError("Dataset has no label")
            return self
        if from_file:
            # text-file path: CSV/TSV/LibSVM autodetect + sidecars
            # (DatasetLoader::LoadFromFile, dataset_loader.cpp:203)
            from .io import load_data_file
            hint = (self.reference.num_total_features
                    if self.reference is not None else 0)
            loaded = load_data_file(self._raw_data, self.config,
                                    num_features_hint=hint)
            self._raw_data = loaded.X
            file_names = loaded.feature_names
            if self.label is None and loaded.label is not None:
                self.label = loaded.label
            if self.weight is None and loaded.weight is not None:
                self.weight = loaded.weight
            if self.group is None and loaded.group is not None:
                self.group = loaded.group
            if self.init_score is None and loaded.init_score is not None:
                self.init_score = loaded.init_score
        data = _to_2d_float(self._raw_data)
        if (self.reference is not None
                and data.shape[1] != self.reference.num_total_features):
            if from_file and data.shape[1] < \
                    self.reference.num_total_features:
                # LibSVM valid file whose max feature index is below the
                # train set's: right-pad with zeros to align (CreateValid
                # semantics — absent sparse entries are zero)
                pad = self.reference.num_total_features - data.shape[1]
                data = np.concatenate(
                    [data, np.zeros((data.shape[0], pad))], axis=1)
            else:
                raise ValueError(
                    f"validation data has {data.shape[1]} features but "
                    f"training data has "
                    f"{self.reference.num_total_features}")
        self.num_data, self.num_total_features = data.shape
        cfg = self.config

        if isinstance(self.feature_name, (list, tuple)) and self.feature_name:
            names = list(self.feature_name)
        elif hasattr(self._raw_data, "columns"):
            names = [str(c) for c in self._raw_data.columns]
        elif file_names and len(file_names) == self.num_total_features:
            names = file_names
        else:
            names = [f"Column_{i}" for i in range(self.num_total_features)]
        self.feature_name = names

        cat_idx = self._resolve_categoricals(names)

        if self.reference is not None:
            # validation set: reuse the training bin mappers
            # (dataset.h CreateValid / align-with-train semantics)
            ref = self.reference.construct()
            self.bin_mappers = ref.bin_mappers
            self.used_features = ref.used_features
            self.max_num_bin = ref.max_num_bin
        else:
            sample_cnt = min(cfg.bin_construct_sample_cnt, self.num_data)
            if sample_cnt < self.num_data:
                rng = np.random.RandomState(cfg.data_random_seed)
                sample_idx = rng.choice(self.num_data, sample_cnt,
                                        replace=False)
                sample = data[sample_idx]
            else:
                sample = data
            # per-feature bin caps + forced boundaries
            # (max_bin_by_feature, forcedbins_filename —
            # dataset_loader.cpp:619-653 GetForcedBins)
            mbf = list(cfg.max_bin_by_feature or [])
            if mbf and len(mbf) != self.num_total_features:
                raise ValueError(
                    f"max_bin_by_feature has {len(mbf)} entries but the "
                    f"dataset has {self.num_total_features} features")
            forced: Dict[int, list] = {}
            if cfg.forcedbins_filename:
                import json as _json
                with open(cfg.forcedbins_filename) as fh:
                    for item in _json.load(fh):
                        forced[int(item["feature"])] = [
                            float(x) for x in item["bin_upper_bound"]]
            self.bin_mappers = []
            for f in range(self.num_total_features):
                bt = "categorical" if f in cat_idx else "numerical"
                m = BinMapper.from_values(
                    sample[:, f],
                    max_bin=int(mbf[f]) if mbf else cfg.max_bin,
                    min_data_in_bin=cfg.min_data_in_bin, bin_type=bt,
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    forced_bounds=forced.get(f))
                self.bin_mappers.append(m)
            self.used_features = np.asarray(
                [f for f, m in enumerate(self.bin_mappers)
                 if not m.is_trivial], dtype=np.int32)
            if len(self.used_features) == 0:
                raise ValueError("Cannot construct Dataset: all features are "
                                 "trivial (single value)")
            self.max_num_bin = max(
                self.bin_mappers[f].num_bin for f in self.used_features)

        F = len(self.used_features)
        dtype = np.uint8 if self.max_num_bin <= 256 else np.int32
        self.bins = np.empty((self.num_data, F), dtype=dtype)
        for j, f in enumerate(self.used_features):
            self.bins[:, j] = self.bin_mappers[f].values_to_bins(
                data[:, f]).astype(dtype)

        if self.label is None and not self.params.get("_allow_no_label"):
            raise ValueError("Dataset has no label")
        # linear trees regress on raw feature values; keep them resident
        # (the reference keeps raw data when linear_tree, dataset.cpp)
        self.raw_values = None
        ref_cfg = (self.reference.config if self.reference is not None
                   else None)
        if self.config.linear_tree or (
                ref_cfg is not None and ref_cfg.linear_tree):
            self.raw_values = np.ascontiguousarray(data, np.float32)
        if self.free_raw_data:
            self._raw_data = None
        self._constructed = True
        return self

    def _resolve_categoricals(self, names) -> set:
        cat = self.categorical_feature
        if cat == "auto" or cat is None:
            cfg_cat = self.config.categorical_feature
            if not cfg_cat:
                return set()
            cat = [tok for tok in str(cfg_cat).split(",") if tok]
        out = set()
        for c in cat:
            if isinstance(c, str) and not c.lstrip("-").isdigit():
                if c in names:
                    out.add(names.index(c))
            else:
                out.add(int(c))
        return out

    # ------------------------------------------------------------------
    # accessors used by the trainer
    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def per_feature_num_bins(self) -> np.ndarray:
        return np.asarray([self.bin_mappers[f].num_bin
                           for f in self.used_features], dtype=np.int32)

    def per_feature_nan_bins(self) -> np.ndarray:
        """nan bin index per used feature; -1 when the feature has none."""
        return np.asarray([self.bin_mappers[f].nan_bin
                           for f in self.used_features], dtype=np.int32)

    def per_feature_is_categorical(self) -> np.ndarray:
        return np.asarray(
            [self.bin_mappers[f].bin_type == "categorical"
             for f in self.used_features], dtype=bool)

    def get_label(self):
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def query_boundaries(self) -> Optional[np.ndarray]:
        """Cumulative query boundaries from per-query sizes (Metadata
        query_boundaries_, dataset.h:48)."""
        if self.group is None:
            return None
        return np.concatenate([[0], np.cumsum(self.group)]).astype(np.int64)

    def set_field(self, name, value):
        if name == "label":
            self.label = np.asarray(value, dtype=np.float64).reshape(-1)
        elif name == "weight":
            self.weight = None if value is None else np.asarray(
                value, dtype=np.float64).reshape(-1)
        elif name == "group":
            self.group = None if value is None else np.asarray(
                value, dtype=np.int64).reshape(-1)
        elif name == "init_score":
            self.init_score = None if value is None else np.asarray(
                value, dtype=np.float64)
        else:
            raise ValueError(f"Unknown field {name}")

    def __len__(self):
        return self.num_data

    # ------------------------------------------------------------------
    # binary dataset cache (Dataset::SaveBinaryFile dataset.cpp:1018 /
    # DatasetLoader::LoadFromBinFile dataset_loader.cpp:417): persist the
    # CONSTRUCTED state — binned matrix + mappers + metadata — so reloads
    # skip parsing and re-binning entirely.
    _BINARY_KEY = "lightgbm_tpu_dataset_v1"

    def save_binary(self, filename) -> "Dataset":
        self.construct()
        payload = {
            self._BINARY_KEY: np.asarray(1),
            "bins": self.bins,
            "used_features": self.used_features,
            "max_num_bin": np.asarray(self.max_num_bin),
            "feature_name": np.asarray(self.feature_name),
        }
        for field in ("label", "weight", "group", "init_score"):
            v = getattr(self, field)
            if v is not None:
                payload[field] = v
        scal, ubs, cats = [], [], []
        ub_off, cat_off = [0], [0]
        for m in self.bin_mappers:
            s, ub, ct = m.state_arrays()
            scal.append(s)
            ubs.append(ub)
            cats.append(ct)
            ub_off.append(ub_off[-1] + len(ub))
            cat_off.append(cat_off[-1] + len(ct))
        payload.update(
            mapper_scalars=np.stack(scal),
            mapper_ub=np.concatenate(ubs) if ubs else np.empty(0),
            mapper_ub_off=np.asarray(ub_off, np.int64),
            mapper_cats=np.concatenate(cats) if cats else np.empty(0,
                                                                   np.int64),
            mapper_cat_off=np.asarray(cat_off, np.int64))
        with open(filename, "wb") as f:
            np.savez_compressed(f, **payload)
        return self

    @staticmethod
    def _is_binary_file(path) -> bool:
        try:
            with open(path, "rb") as f:
                return f.read(2) == b"PK"  # npz = zip container
        except OSError:
            return False

    def _load_binary(self, path):
        from .binning import BinMapper
        with np.load(path, allow_pickle=False) as z:
            if self._BINARY_KEY not in z:
                raise ValueError(
                    f"{path} is not a lightgbm_tpu binary dataset")
            self.bins = z["bins"]
            self.used_features = z["used_features"]
            self.max_num_bin = int(z["max_num_bin"])
            self.feature_name = [str(s) for s in z["feature_name"]]
            for field in ("label", "weight", "group", "init_score"):
                if field in z and getattr(self, field) is None:
                    setattr(self, field, z[field])
            scal = z["mapper_scalars"]
            ub, ub_off = z["mapper_ub"], z["mapper_ub_off"]
            cats, cat_off = z["mapper_cats"], z["mapper_cat_off"]
        self.bin_mappers = [
            BinMapper.from_state_arrays(
                scal[i], ub[ub_off[i]:ub_off[i + 1]],
                cats[cat_off[i]:cat_off[i + 1]])
            for i in range(scal.shape[0])]
        self.num_data, _ = self.bins.shape
        self.num_total_features = len(self.bin_mappers)
        self._raw_data = None
        self._constructed = True
