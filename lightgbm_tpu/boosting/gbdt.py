"""GBDT training loop.

TPU-native analog of the reference boosting layer
(``src/boosting/gbdt.cpp``: ``Train`` :237, ``TrainOneIter`` :344,
``BoostFromAverage`` :319, ``UpdateScore`` :491; sampling strategies
``bagging.hpp`` / ``goss.hpp``).

Structure (TPU-first):
- Scores live on device as [num_class, padded_rows] f32; the default
  driver is the FUSED step (_fused_step_impl): grad/hess -> sampling ->
  quantize -> per-class build_tree -> score update chained into ONE
  jitted program per iteration, score buffers donated, and the built
  TreeArrays kept on device in a pending ring. Host materialization
  (Tree.from_device) happens in batches at sync points only — eval
  cadence boundaries and end of training — so the steady-state loop
  dispatches ahead with zero host syncs between eval points. Configs
  that need per-iteration host work (custom fobj, linear trees, CEGB,
  multi-process meshes, position-bias ranking) fall back to the legacy
  loop (_train_one_iter_legacy: ~5 dispatches + a per-tree sync,
  mirroring the CUDA learner's scalars-only host boundary,
  cuda_single_gpu_tree_learner.cpp:246-273); LIGHTGBM_TPU_FUSED_TRAIN=0
  or fused_train=false pin the legacy loop everywhere.
- Bagging/GOSS produce a row mask/scale, never a data subset: fixed shapes
  keep one compiled program alive. The mask rides in the histogram count
  channel so min_data_in_leaf counts in-bag rows like the reference.
- The init score (BoostFromAverage) is added into the first tree per class
  via AddBias, exactly like gbdt.cpp:416 — saved models are self-contained.
- Validation sets are co-partitioned during growth (see tree_builder), so
  validation scores update with a gather, no full predict pass.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..objectives import Objective
from ..ops import pallas_histogram as PH
from ..ops.histogram import block_rows_for, resolve_impl
from ..ops.split import SplitParams
from ..tree import Tree
from .tree_builder import build_tree, TreeArrays

__all__ = ["GBDT"]

kEpsilon = 1e-15


def _pad_rows(arr: np.ndarray, r_pad: int, fill=0):
    if arr.shape[0] == r_pad:
        return arr
    pad = [(0, r_pad - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, constant_values=fill)


class _DeviceData:
    """Device-resident binned matrix + co-partition state for one dataset.

    With a data-parallel plan, rows are sharded across the mesh's data axis
    (the per-machine row partition of data_parallel_tree_learner.cpp, done
    by jax.sharding instead of pre_partition'd files)."""

    def __init__(self, ds: Dataset, block: int, plan=None,
                 unbundle: bool = False):
        # num_data is PER-PROCESS under pre-partitioned multi-host
        # loading (each host's Dataset holds its own row shard); r_pad is
        # the GLOBAL padded row count, r_local this process's slice of it
        self.num_data = ds.num_data
        if plan is not None:
            self.r_pad = plan.pad_to(ds.num_data, block)
            self.r_local = plan.local_rows(self.r_pad)
        else:
            self.r_pad = ((ds.num_data + block - 1) // block) * block
            self.r_local = self.r_pad
        src = ds.unbundled_bins() if unbundle else ds.bins
        bins = _pad_rows(src, self.r_local)
        row_leaf0 = np.where(np.arange(self.r_local) < ds.num_data, 0, -1) \
            .astype(np.int32)
        if plan is not None:
            self.bins = plan.shard_bins(bins)
            self.row_leaf0 = plan.shard_rows(row_leaf0)
        else:
            self.bins = jnp.asarray(bins)
            self.row_leaf0 = jnp.asarray(row_leaf0)


class _ChunkedDeviceData:
    """Device-data stand-in for the out-of-core chunked driver: the
    row bookkeeping of :class:`_DeviceData` without a resident matrix
    (``bins`` stays None — the prefetcher streams it). Geometry follows
    the prefetcher's chunk lattice so the [R]-shaped score/gradient
    arrays line up with the streamed chunks."""

    def __init__(self, ds: Dataset, prefetcher):
        self.num_data = ds.num_data
        self.r_pad = int(prefetcher.padded_rows)
        self.r_local = self.r_pad
        self.bins = None
        self.row_leaf0 = jnp.asarray(
            np.where(np.arange(self.r_pad) < ds.num_data, 0, -1)
            .astype(np.int32))


class GBDT:
    # subclasses that replay past trees (DART) keep them on device;
    # plain gbdt/rf retain only the host Tree models
    keep_device_trees = False

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[Objective],
                 valid_sets: Sequence[Dataset] = (),
                 init_row_scores: Optional[np.ndarray] = None,
                 valid_init_row_scores: Sequence[np.ndarray] = (),
                 num_init_iteration: int = 0):
        self.config = config
        self.train_set = train_set.construct()
        self.objective = objective
        self.iter_ = 0
        self.num_init_iteration = num_init_iteration  # gbdt.h analog
        self.models: List[Tree] = []
        # (TreeArrays, weight) per trained tree, kept on device for DART
        # drop/restore, rollback and refit (HistogramPool-sized: ~KBs/tree)
        self.device_trees: List[Tuple[TreeArrays, float]] = []
        self.num_class = config.num_class
        self.K = (objective.num_model_per_iteration
                  if objective is not None else max(1, config.num_class))
        self.shrinkage = config.learning_rate
        self._init_scores = np.zeros(self.K)
        self._boosted_from_average = False

        F = self.train_set.num_features
        self.B = int(self.train_set.max_num_bin)
        # resolve hist_impl='auto' EAGERLY, before any jit traces the
        # tree builder: on TPU this probe-compiles the Pallas kernel once
        # and falls back to matmul if Mosaic rejects it, so first
        # hardware contact degrades instead of crashing
        config._values["hist_impl"] = resolve_impl(config.hist_impl)
        # EFB: bins are bundled [R, G]; histogram sizing follows the
        # bundle lattice, split finding stays in feature space
        bp = self.train_set.bundle_plan
        self._bundle_meta = None
        self._bundle_bins = 0
        self._unbundle_feature = False   # tree_learner=feature w/ EFB
        if bp is not None:
            self._bundle_meta = (jnp.asarray(bp.feat_bundle),
                                 jnp.asarray(bp.feat_offset),
                                 jnp.asarray(bp.feat_mfb))
            self._bundle_bins = int(bp.max_bundle_bins)
            self.block = block_rows_for(
                self.train_set.num_data, bp.num_bundles,
                bp.max_bundle_bins)
        else:
            self.block = block_rows_for(self.train_set.num_data, F, self.B)
        # histogram-subtraction gate: the per-leaf raw cache (the
        # HistogramPool analog) must fit the pool budget
        pool_budget = (config.histogram_pool_size
                       if config.histogram_pool_size > 0 else 512.0)

        def _hist_sub_gate(lattice: int) -> bool:
            cache_mb = ((config.num_leaves + 1) * lattice * 3 * 4
                        / 2 ** 20)
            ok = bool(config.hist_subtraction) and cache_mb <= pool_budget
            if bool(config.hist_subtraction) and not ok:
                from .. import log as _log
                _log.warning(
                    f"per-leaf histogram cache would need {cache_mb:.0f}"
                    f" MB (> histogram_pool_size budget "
                    f"{pool_budget:.0f} MB); disabling histogram "
                    "subtraction")
            return ok
        # gate evaluated ONCE, below, after the tree_learner plan is
        # known (tree_learner=feature may unbundle and change the
        # lattice; gating here first would warn for the wrong one)
        # data-parallel over every local device (tree_learner param,
        # tree_learner.cpp:15 factory analog; "serial" pins one device)
        if bool(config.linear_tree):
            for ds_ in (self.train_set, *[v.construct()
                                          for v in valid_sets]):
                if getattr(ds_, "raw_values", None) is None:
                    raise ValueError(
                        "linear_tree needs raw feature values for every "
                        "dataset; binary dataset caches do not retain "
                        "them — construct Datasets from arrays or text "
                        "files")
        if int(config.num_machines) > 1:
            # multi-host bootstrap (Network::Init analog): after this,
            # jax.devices() spans every host and the mesh plans below
            # cover DCN transparently
            from ..parallel.distributed import maybe_init_distributed
            maybe_init_distributed(config)
        n_dev = len(jax.devices())
        self.plan = None
        # CEGB and feature_contri run on the serial learner only — the
        # reference ties CEGB to SerialTreeLearner; we follow its
        # force-serial-with-warning pattern (config.cpp:434-437 style)
        needs_serial = bool(
            config.cegb_tradeoff < 1.0 or config.cegb_penalty_split > 0.0
            or config.cegb_penalty_feature_coupled
            or config.cegb_penalty_feature_lazy or config.feature_contri)
        if needs_serial and n_dev > 1 and config.tree_learner != "serial":
            from .. import log as _log
            _log.warning("CEGB/feature_contri require the serial tree "
                         "learner; forcing tree_learner=serial")
        if not needs_serial and n_dev > 1 \
                and config.tree_learner != "serial":
            from ..parallel.data_parallel import (
                DataParallelPlan, FeatureParallelPlan, VotingParallelPlan)
            plan_cls = {"feature": FeatureParallelPlan,
                        "voting": VotingParallelPlan}.get(
                            config.tree_learner, DataParallelPlan)
            if self._bundle_meta is not None and \
                    plan_cls is FeatureParallelPlan:
                # feature mode shards FEATURES, so the bundled storage
                # is decoded back to per-feature columns (bundle
                # histograms unbundled == per-feature histograms, so
                # training is identical). Rows are replicated on every
                # chip in this mode anyway — the reference's model
                # (feature_parallel_tree_learner.cpp:38: each worker
                # holds the full dataset) — so the width saving EFB
                # gave up is the mode's own storage model.
                self._bundle_meta = None
                self._bundle_bins = 0
                self._unbundle_feature = True
                self.block = block_rows_for(
                    self.train_set.num_data, F, self.B)
            plan_kw = {}
            if plan_cls is FeatureParallelPlan:
                plan_kw["shard_storage"] = bool(
                    config.feature_shard_storage)
            elif config.feature_shard_storage:
                from .. import log as _log
                _log.warning("feature_shard_storage only applies with "
                             "tree_learner=feature; ignoring")
            if plan_cls is not FeatureParallelPlan:
                hm = str(config.dp_hist_merge)
                if config.forcedsplits_filename and hm != "allreduce":
                    # the forced-split gather reads full-feature
                    # histogram rows from the per-leaf cache, which the
                    # scattered layout shards by feature slot
                    from .. import log as _log
                    if hm == "reduce_scatter":
                        _log.warning(
                            "forced splits need the full-histogram "
                            "merge; pinning dp_hist_merge=allreduce")
                    hm = "allreduce"
                plan_kw["hist_merge"] = hm
            self.plan = plan_cls(top_k=int(config.top_k), **plan_kw)
            if (plan_cls is FeatureParallelPlan
                    and getattr(self.plan, "multi_process", False)):
                # feature-parallel needs the FULL dataset replicated on
                # every worker (feature_parallel_tree_learner.cpp:38).
                # Two ways a worker's copy can silently differ: the
                # loader auto-partitioned rows, or the caller fed each
                # host its own shard under pre_partition=true. Both
                # produce diverging replicas (or a cross-process trace
                # mismatch), so verify the copies agree up front.
                for ds_ in (train_set, *[v.construct()
                                         for v in valid_sets]):
                    if getattr(ds_, "auto_partitioned", False):
                        raise ValueError(
                            "tree_learner=feature across machines "
                            "requires every worker to load the FULL "
                            "dataset: pass the whole data on each "
                            "machine with pre_partition=true (the "
                            "loader auto-partitioned rows because "
                            "pre_partition was false)")
                from ..parallel.distributed import \
                    check_replicas_identical
                check_replicas_identical(
                    [train_set] + [v for v in valid_sets])
            if self.plan.rows_sharded:
                # keep the scan block well under the per-shard row count
                # so shard-granular padding stays a small fraction
                per_shard = -(-self.train_set.num_data // n_dev)
                cap = max(256, 1 << int(np.floor(np.log2(
                    max(1, per_shard // 4)))))
                self.block = min(self.block, cap)
        elif config.feature_shard_storage:
            from .. import log as _log
            _log.warning(
                "feature_shard_storage needs tree_learner=feature and "
                "more than one device "
                f"({n_dev} visible); storing the matrix unsharded")
        # column-sharded storage keeps only the local feature slice of
        # the matrix AND the hist cache per device: one divisor feeds
        # both the hist-sub gate and the capacity gate below
        n_fs = (self.plan.num_shards
                if self.plan is not None
                and getattr(self.plan, "shard_storage", False) else 1)
        # single hist-sub gate on the FINAL device lattice (bundle
        # lattice, or F*B after the feature-mode unbundle above)
        _lattice = (self._bundle_bins * bp.num_bundles
                    if self._bundle_meta is not None else F * self.B)
        # reduce-scatter data-parallel slot-shards the per-leaf raw
        # cache by feature slot (and stores it in UNBUNDLED feature
        # space): each chip budgets 1/n of the feature lattice
        self._dp_rs = bool(
            self.plan is not None and self.plan.parallel_mode == "data"
            and getattr(self.plan, "hist_merge", "") == "reduce_scatter"
            and self.plan.num_shards > 1)
        if self._dp_rs:
            _lattice = -(-(F * self.B) // self.plan.num_shards)
        self._hist_sub = _hist_sub_gate(-(-_lattice // n_fs))
        # capacity gate BEFORE the device transfer (VERDICT r4 #5):
        # fail with sized guidance, not a mid-training device OOM — or,
        # when the chunked out-of-core driver can take the run, degrade
        # to streaming row chunks instead of failing (PR 13)
        from ..dataset import check_device_capacity
        self.chunked = False
        self._chunk_source = None
        self._prefetcher = None
        self._chunked_builder = None       # built at the end of __init__
        oc = str(getattr(config, "out_of_core", "auto"))
        chunk_reason = self._chunked_gate_reason()
        shard_src = getattr(self.train_set, "chunk_source", None)
        if oc == "on" or (oc == "auto" and shard_src is not None):
            if chunk_reason:
                if oc == "on":
                    raise ValueError(
                        "out_of_core=on but chunked training cannot "
                        f"drive this run: {chunk_reason}")
                # shard-backed dataset with a feature the chunked
                # builder gates out: fall through to the resident path
                # (Dataset.bins materializes the matrix lazily)
            else:
                self.chunked = True
                self._chunk_source = shard_src
        # multi-process: num_data is this process's LOCAL rows and they
        # spread over the process's own devices only — dividing by the
        # GLOBAL device count would understate the per-chip footprint
        if self.plan is not None and self.plan.rows_sharded:
            n_row_shards = max(1, self.plan.num_shards
                               // getattr(self.plan, "num_processes", 1))
        else:
            n_row_shards = 1
        if not self.chunked:
            if self._unbundle_feature:
                # the device holds the UNBUNDLED matrix: per-feature
                # width and the (possibly narrower) per-feature dtype
                cap_width = F
                cap_itemsize = 1 if self.B <= 256 else 4  # unbundled dtype
            else:
                cap_width = self.train_set.bins.shape[1]
                cap_itemsize = self.train_set.bins.dtype.itemsize
            # feature_shard_storage: each device stores only its own
            # column slice of the (padded) matrix
            cap_width = -(-cap_width // n_fs)
            try:
                check_device_capacity(
                    self.train_set.num_data, cap_width, cap_itemsize,
                    config.num_leaves, self._bundle_bins or self.B,
                    self._hist_sub, n_row_shards=n_row_shards)
            except MemoryError:
                if oc == "off" or chunk_reason:
                    raise
                # the resident matrix does not fit but the run is
                # chunkable: degrade transparently (shard-backed data
                # keeps its mmap stream; in-memory data streams the
                # host matrix)
                from .. import log as _log
                _log.warning(
                    "binned matrix exceeds device capacity; streaming "
                    "it in row chunks (out_of_core) instead")
                self.chunked = True
                self._chunk_source = shard_src
        if self.chunked:
            from ..data.chunked import ArraySource
            from ..data.prefetch import ChunkPrefetcher, chunk_rows_for
            if self._chunk_source is None:
                self._chunk_source = ArraySource(
                    np.asarray(self.train_set.bins))
            itemsize = int(
                self._chunk_source.read_rows(0, 1).dtype.itemsize)
            c_rows = chunk_rows_for(
                self.train_set.num_data,
                self._chunk_source.num_features, itemsize,
                config.chunk_budget_mb, self.block)
            self._prefetcher = ChunkPrefetcher(self._chunk_source, c_rows)
            self.train_dd = _ChunkedDeviceData(self.train_set,
                                               self._prefetcher)
        else:
            self.train_dd = _DeviceData(self.train_set, self.block,
                                        self.plan,
                                        unbundle=self._unbundle_feature)
        self._bins_cm = None            # lazy column-major copy (native)
        self.valid_dd = [
            _DeviceData(v.construct(), self.block, self.plan,
                        unbundle=self._unbundle_feature)
            for v in valid_sets]
        self.valid_sets = list(valid_sets)

        R = self.train_dd.r_pad
        R_loc = self.train_dd.r_local
        lbl = self.train_set.get_label()
        self._mp = bool(self.plan is not None
                        and getattr(self.plan, "multi_process", False))
        if self._mp and bool(config.linear_tree):
            # reference parity: "linear tree learner must be serial
            # type" (config.cpp:429-437 forces tree_learner=serial), so
            # distributed linear trees do not exist there either
            raise NotImplementedError(
                "linear_tree requires single-host training (the "
                "reference forces tree_learner=serial for linear trees "
                "too, config.cpp:429)")
        # multi-host ranking (VERDICT r4 #4): the padded-query lattice
        # holds LOCAL row ids, so ranking gradients are computed PER
        # PROCESS on the host's own score block (each host owns whole
        # queries under pre-partitioned loading — the reference
        # pre-partitions lambdarank by query the same way,
        # src/io/metadata.cpp partitioned loading) and re-placed into
        # the sharded global array. The reference's objective also runs
        # host-side per machine; only histogram/split sync crosses hosts.
        self._mp_ranking = bool(self._mp and objective is not None
                                and objective.is_ranking)

        def _row_put(a):
            return (self.plan.shard_rows(a) if self.plan is not None
                    else jnp.asarray(a))
        self.label_dev = _row_put(
            _pad_rows(np.asarray(lbl, np.float32), R_loc))
        # global row count for GOSS's top-k over the global score sort
        self._num_data_global = self.train_dd.num_data
        if self._mp:
            from jax.experimental import multihost_utils
            self._num_data_global = int(multihost_utils.process_allgather(
                np.asarray([self.train_dd.num_data], np.int64)).sum())
        w = self.train_set.get_weight()
        self.weight_dev = None if w is None else _row_put(
            _pad_rows(np.asarray(w, np.float32), R_loc))
        if self._mp_ranking:
            # per-process gradient computation needs LOCAL label/weight
            # blocks next to the local score slice (see _grads)
            self._label_local = jnp.asarray(
                _pad_rows(np.asarray(lbl, np.float32), R_loc))
            self._weight_local = None if w is None else jnp.asarray(
                _pad_rows(np.asarray(w, np.float32), R_loc))

        if objective is not None:
            okw = {}
            if (objective.is_ranking
                    and getattr(self.train_set, "position", None) is not None):
                okw["position"] = self.train_set.position
            objective.init(lbl, w, self.train_set.query_boundaries(), **okw)
            if objective.label is not lbl:
                # init() may retarget training to a transformed label
                # space (reg_sqrt trains on sign(y)*sqrt(|y|),
                # regression_objective.hpp sqrt_); gradients must see
                # the SAME label the init score was derived from
                self.label_dev = _row_put(_pad_rows(
                    np.asarray(objective.label, np.float32), R_loc))
            self._init_scores = np.asarray(objective.boost_from_score(),
                                           dtype=np.float64).reshape(-1)
            if len(self._init_scores) != self.K:
                self._init_scores = np.resize(self._init_scores, self.K)
            if self._mp:
                # per-process automatic init scores are averaged across
                # hosts — Network::GlobalSyncUpByMean in BoostFromAverage
                # (gbdt.cpp:313)
                from ..parallel.distributed import global_mean_init_scores
                self._init_scores = global_mean_init_scores(
                    self._init_scores)

        def _put_scores(local_kr):
            return (self.plan.shard_scores(local_kr)
                    if self.plan is not None
                    else jnp.asarray(local_kr))

        if init_row_scores is not None:
            # continued training (init_model): scores resume from the
            # loaded model's per-row predictions; no BoostFromAverage
            # (gbdt.cpp only boosts from average when models_.empty()).
            # Multi-host: each host predicted its own pre-partitioned
            # rows with the base model, so the [K, R_loc] block shards
            # into the global score array like any other score field.
            def to_kr(a, r_loc):
                a = np.asarray(a, np.float32)
                if a.ndim == 1:
                    a = a[:, None]
                return _pad_rows(a, r_loc).T  # [K, R_loc]
            self.scores = _put_scores(to_kr(init_row_scores, R_loc))
            self.valid_scores = [
                _put_scores(to_kr(v, dd.r_local))
                for v, dd in zip(valid_init_row_scores, self.valid_dd)]
            self._init_scores = np.zeros(self.K)
        # NOTE: when init_row_scores (init_model) is present it takes
        # precedence over Dataset.init_score — same as the reference,
        # where the predictor path overrides a user init_score
        # (basic.py:2219-2223 `elif init_score is not None`).
        elif self.train_set.get_init_score() is not None:
            # Metadata init_score: per-row base offsets added to scores
            # before any boosting (ScoreUpdater ctor / dataset.h:126);
            # BoostFromAverage is skipped (gbdt.cpp:319 has_init_score
            # guard) and no AddBias folds into the first tree, so
            # prediction excludes the offset exactly like the reference.
            # Under multi-process each host's Metadata holds its LOCAL
            # rows; the local block is placed into the sharded array.
            self.scores = _put_scores(self._field_init_scores(
                self.train_set.get_init_score(), self.train_set.num_data,
                self.train_dd.r_local))
            self.valid_scores = []
            for v, dd in zip(self.valid_sets, self.valid_dd):
                vi = v.get_init_score()
                if vi is not None:
                    self.valid_scores.append(_put_scores(
                        self._field_init_scores(vi, v.num_data,
                                                dd.r_local)))
                else:
                    self.valid_scores.append(_put_scores(
                        np.zeros((self.K, dd.r_local), np.float32)))
            self._init_scores = np.zeros(self.K)
        else:
            if not (self.config.boost_from_average
                    and objective is not None):
                self._init_scores = np.zeros(self.K)
            else:
                self._boosted_from_average = True
            base = (self._init_scores.astype(np.float32)[:, None]
                    if self._boosted_from_average else 0.0)

            def _mk_scores(dd):
                local = np.zeros((self.K, dd.r_local), np.float32) + base
                return (self.plan.shard_scores(local)
                        if self.plan is not None else jnp.asarray(local))
            self.scores = _mk_scores(self.train_dd)
            self.valid_scores = [_mk_scores(dd) for dd in self.valid_dd]

        # static metadata for the tree builder
        # multi-process jit rejects committed single-device inputs next
        # to global-mesh arrays; plain numpy inputs are auto-replicated
        _meta_put = np.asarray if self._mp else jnp.asarray
        self.num_bins_pf = _meta_put(self.train_set.per_feature_num_bins())
        self.nan_bin_pf = _meta_put(self.train_set.per_feature_nan_bins())
        self.is_cat_pf = _meta_put(
            self.train_set.per_feature_is_categorical())
        # sorted-subset categorical splits: features with more than
        # max_cat_to_onehot bins leave the one-hot path
        # (feature_histogram.cpp:172 `num_bin <= max_cat_to_onehot`)
        self._cat_sorted_mask = None
        _csm = (np.asarray(self.train_set.per_feature_is_categorical())
                & (np.asarray(self.train_set.per_feature_num_bins())
                   > int(config.max_cat_to_onehot)))
        if _csm.any():
            self._cat_sorted_mask = _meta_put(_csm)
        self.split_params = SplitParams(
            lambda_l1=float(config.lambda_l1),
            lambda_l2=float(config.lambda_l2),
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(config.min_sum_hessian_in_leaf),
            min_gain_to_split=float(config.min_gain_to_split),
            cat_l2=float(config.cat_l2),
            cat_smooth=float(config.cat_smooth),
            max_delta_step=float(config.max_delta_step),
            path_smooth=float(config.path_smooth),
            monotone_penalty=float(config.monotone_penalty),
            extra_trees=bool(config.extra_trees),
            max_cat_threshold=int(config.max_cat_threshold),
            max_cat_to_onehot=int(config.max_cat_to_onehot),
            min_data_per_group=float(config.min_data_per_group))

        self.mono_type_pf = self._parse_monotone_constraints()
        self.interaction_groups = self._parse_interaction_constraints()
        # replicated PRNG driving per-node feature sampling (ColSampler,
        # feature_fraction_seed) and extra-trees thresholds (extra_seed)
        self._ffbn = float(config.feature_fraction_bynode)
        if self._ffbn < 1.0 or config.extra_trees:
            seed = (int(config.feature_fraction_seed) * 2654435761
                    + int(config.extra_seed)) & 0x7FFFFFFF
            self._tree_key = jax.random.PRNGKey(seed)
        else:
            self._tree_key = None

        self._rng_feature = np.random.RandomState(config.feature_fraction_seed)
        self._rng_bagging = np.random.RandomState(config.bagging_seed)
        self._bag_mask = None  # device [R] f32, regenerated per bagging_freq
        self._goss = (config.data_sample_strategy == "goss")
        if self._goss:
            if config.top_rate + config.other_rate > 1.0:
                raise ValueError("top_rate + other_rate must be <= 1")

        self._update_score_jit = jax.jit(self._update_score_impl)
        self._goss_jit = jax.jit(self._goss_impl)

        # fused boosting step state (see train_one_iter): the pending
        # ring of (iteration, shrinkage, device TreeArrays per class,
        # device should_continue flag), materialized in batches by
        # sync(); host_sync_count instruments the bench's
        # host_syncs_per_iter field
        self._pending: List[Tuple] = []
        self._fused_jit = None
        self._full_mask_cache: Optional[Tuple] = None
        self.host_sync_count = 0

        # numeric-divergence guard (resilience subsystem): the fused
        # step ALWAYS computes the finiteness flag (one program shape
        # regardless of policy — the flag is ignored when off, so the
        # default stays bit-identical); sync()/the legacy driver act on
        # it only when the policy arms it
        self._nan_guard = str(getattr(config, "nan_guard", "off"))

        # quantized-gradient training (GradientDiscretizer,
        # gradient_discretizer.hpp:22/.cpp:55-140): gradients are
        # stochastically rounded onto an int8 grid and the histogram runs
        # as an int8 x int8 -> int32 MXU matmul (ops/histogram.py quant
        # path — the analog of the packed int16/int32 histograms of
        # cuda_histogram_constructor.cu, with the MXU's native int32
        # accumulation replacing the per-leaf bit-width escalation).
        # Split finding descales the tiny integer histogram once
        # (FindBestThresholdInt, feature_histogram.hpp:177).
        self._quant = bool(config.use_quantized_grad)
        if self._quant:
            nbq = int(config.num_grad_quant_bins)
            if not 2 <= nbq <= 127:
                raise ValueError(
                    "num_grad_quant_bins must be in [2, 127] (int8 grid)")
            # int32 accumulator bound: the hessian channel quantizes onto
            # [0, nb] (hs = max|h|/nb), so a leaf's bin sum can reach
            # rows * nb — the binding constraint (grads only reach nb/2).
            # GLOBAL rows: the per-shard int32 histograms are psum-merged
            # in int32, so sharding does not relieve the bound.
            if self._num_data_global * nbq >= 2 ** 31:
                raise ValueError(
                    "use_quantized_grad: num_data * num_grad_quant_bins "
                    "overflows the int32 histogram accumulator; lower "
                    "num_grad_quant_bins")
            self._quant_key = jax.random.PRNGKey(
                (int(config.data_random_seed) * 65537 + 17) & 0x7FFFFFFF)
            self._quantize_jit = jax.jit(self._quantize_impl)
            self._renew_jit = jax.jit(self._renew_leaf_impl)
            # class-batched legacy driver: renew all K trees in one
            # dispatch (vmap over the class axis; see ISSUE 8)
            self._renew_batch_jit = jax.jit(
                jax.vmap(self._renew_leaf_impl))

        # feature_contri: per-feature split-gain multiplier
        # (feature_histogram.hpp:174)
        self._gain_scale = None
        fc = config.feature_contri
        if fc:
            fc = np.asarray(fc, np.float32)
            ntf = self.train_set.num_total_features
            if len(fc) != ntf:
                raise ValueError(
                    f"feature_contri has {len(fc)} entries but the "
                    f"dataset has {ntf} features")
            # plan is always None here: needs_serial forced serial
            self._gain_scale = jnp.asarray(
                fc[self.train_set.used_features])

        # forced splits (forcedsplits_filename;
        # SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:636):
        # BFS over the JSON tree, thresholds mapped to bins, slots
        # assigned under our numbering (round r: left keeps the slot,
        # right becomes slot r+1)
        self._forced_splits = None
        if config.forcedsplits_filename:
            self._forced_splits = self._parse_forced_splits(
                config.forcedsplits_filename)

        # CEGB (cost_effective_gradient_boosting.hpp IsEnable)
        self._cegb = None
        self._cegb_feat_used = None
        self._cegb_used_rows = None
        coupled_in = config.cegb_penalty_feature_coupled
        lazy_in = config.cegb_penalty_feature_lazy
        if (config.cegb_tradeoff < 1.0 or config.cegb_penalty_split > 0.0
                or coupled_in or lazy_in):
            F_used = self.train_set.num_features
            uf = self.train_set.used_features

            def per_feat(vals, name):
                if not vals:
                    return None
                vals = np.asarray(vals, np.float32)
                if len(vals) != self.train_set.num_total_features:
                    raise ValueError(
                        f"{name} should be the same size as feature "
                        "number")
                return jnp.asarray(vals[uf])
            coupled = per_feat(coupled_in, "cegb_penalty_feature_coupled")
            lazy = per_feat(lazy_in, "cegb_penalty_feature_lazy")
            self._cegb = (float(config.cegb_tradeoff),
                          float(config.cegb_penalty_split), coupled, lazy)
            self._cegb_feat_used = jnp.zeros((F_used,), bool)
            if lazy is not None:
                self._cegb_used_rows = jnp.zeros(
                    (self.train_dd.r_pad, F_used), bool)

        # class-batched multiclass build (ISSUE 8): decided before the
        # driver gate, because BOTH drivers route the per-iteration K
        # tree builds through the batched builder when it clears
        self.class_batch_reason = self._class_batch_reason()
        self.class_batch_ok = not self.class_batch_reason
        if self.class_batch_ok and self.K > 1 and self._hist_sub:
            # the vmapped builder carries the per-leaf histogram cache
            # PER CLASS ([K, L+1, lattice, 3]): re-gate the pool budget
            # at K x the lattice (falls back to no-subtraction, not to
            # the sequential path — subtraction is an optimization, the
            # batched build stays bit-identical without it)
            self._hist_sub = _hist_sub_gate(
                self.K * (-(-_lattice // n_fs)))

        # fused Pallas build+split (ISSUE 14): decided eagerly (the
        # probe compiles outside any trace) so telemetry can name the
        # binding gate; both tree builders read the flag
        self.fused_split_reason = self._fused_split_reason()
        self.fused_split_ok = not self.fused_split_reason

        # decide the iteration driver LAST (the gate reads _cegb/_mp/...)
        self.fused_reason = self._fused_gate_reason()
        self.fused_ok = not self.fused_reason

        if self.chunked:
            # built HERE (not at the capacity gate) because it consumes
            # the per-feature metadata and split params assembled above;
            # one builder per booster — its four jitted round programs
            # cache their compilations across trees and iterations
            from ..data.chunked import ChunkedTreeBuilder
            self._chunked_builder = ChunkedTreeBuilder(
                num_bins_pf=self.num_bins_pf,
                nan_bin_pf=self.nan_bin_pf,
                is_cat_pf=self.is_cat_pf,
                num_leaves=config.num_leaves,
                leaf_batch=config.leaf_batch,
                max_depth=config.max_depth,
                num_bins=self.B,
                split_params=self.split_params,
                hist_dtype=config.hist_dtype,
                hist_impl=config.hist_impl,
                block_rows=self.block,
                cat_sorted_mask=self._cat_sorted_mask,
                hist_sub=self._hist_sub)

    # ------------------------------------------------------------------
    def _field_init_scores(self, init, n: int, r_pad: int) -> np.ndarray:
        """Metadata init_score -> [K, r_pad] f32.

        Accepts [n], [n, K], or flat [n*K] laid out class-major (the
        reference's per-class contiguous blocks, metadata.cpp:120-129)."""
        a = np.asarray(init, np.float32)
        if a.ndim == 2:
            a = a.T  # [K, n]
        elif a.size == n * self.K and self.K > 1:
            a = a.reshape(self.K, n)
        else:
            if a.size != n:
                raise ValueError(
                    f"init_score size {a.size} does not match num_data {n}"
                    f" (num_model_per_iteration={self.K})")
            a = np.broadcast_to(a.reshape(1, n), (self.K, n))
        return _pad_rows(np.ascontiguousarray(a.T), r_pad).T

    # ------------------------------------------------------------------
    def _parse_monotone_constraints(self) -> Optional[jax.Array]:
        """[F_used] int32 in {-1,0,1} or None (config.h monotone_constraints;
        applied via BasicLeafConstraints semantics — basic mode only)."""
        mc = self.config.monotone_constraints
        if not mc:
            return None
        if isinstance(mc, str):
            mc = [int(x) for x in mc.replace("(", "").replace(")", "")
                  .split(",")]
        mc = np.asarray(list(mc), np.int32)
        ntf = self.train_set.num_total_features
        if len(mc) != ntf:
            raise ValueError(
                f"monotone_constraints has {len(mc)} entries but the "
                f"dataset has {ntf} features")
        if not np.isin(mc, (-1, 0, 1)).all():
            raise ValueError("monotone_constraints values must be in "
                             "{-1, 0, 1}")
        used = mc[self.train_set.used_features]
        if not used.any():
            return None
        is_cat = np.asarray(self.train_set.per_feature_is_categorical())
        if (used != 0)[is_cat].any():
            raise ValueError("monotone_constraints cannot be used with "
                             "categorical features (config.cpp check)")
        method = self.config.monotone_constraints_method
        if method not in ("basic", "intermediate", "advanced"):
            raise ValueError(f"unknown monotone_constraints_method {method}")
        return jnp.asarray(used)

    def _parse_interaction_constraints(self) -> Optional[jax.Array]:
        """[G, F_used] bool group matrix or None (col_sampler.hpp:28
        interaction_constraints_vector)."""
        ic = self.config.interaction_constraints
        if not ic:
            return None
        if isinstance(ic, str):
            import json
            s = ic.strip().replace("(", "[").replace(")", "]")
            try:
                parsed = json.loads(s)
            except json.JSONDecodeError:
                parsed = json.loads("[" + s + "]")
            if parsed and all(isinstance(x, (int, float)) for x in parsed):
                parsed = [parsed]  # single flat group
            ic = parsed
        groups = [list(g) for g in ic]
        ntf = self.train_set.num_total_features
        F = self.train_set.num_features
        used_pos = {f: i for i, f in enumerate(self.train_set.used_features)}
        mat = np.zeros((len(groups), F), bool)
        for gi, g in enumerate(groups):
            for f in g:
                f = int(f)
                if f < 0 or f >= ntf:
                    raise ValueError(
                        f"interaction_constraints feature index {f} out of "
                        f"range [0, {ntf})")
                if f in used_pos:
                    mat[gi, used_pos[f]] = True
        return jnp.asarray(mat)

    # ------------------------------------------------------------------
    def _grads(self, it: int,
               scores: Optional[jax.Array] = None
               ) -> Tuple[jax.Array, jax.Array]:
        """[K, R] grad and hess from the objective at ``scores``
        (defaults to the live training scores; the fused step passes its
        traced score carry instead)."""
        obj = self.objective
        if scores is None:
            scores = self.scores
        if obj.num_model_per_iteration > 1:
            g, h = obj.get_gradients(scores.T, self.label_dev,
                                     self.weight_dev)
            return g.T, h.T
        kwargs = {}
        if obj.is_ranking:
            kwargs["it"] = jnp.asarray(it, jnp.int32)
        if self._mp_ranking:
            # per-process: the padded-query lattice indexes LOCAL rows,
            # so gather the host's own score block, compute there, and
            # re-place the result into the sharded global array (the
            # reference's objective is likewise machine-local)
            loc = self.plan.host_local_cols(scores,
                                            self.train_dd.r_local)
            g, h = obj.get_gradients(jnp.asarray(loc[0]),
                                     self._label_local,
                                     self._weight_local, **kwargs)
            return (self.plan.shard_scores(
                        np.asarray(g, np.float32)[None, :]),
                    self.plan.shard_scores(
                        np.asarray(h, np.float32)[None, :]))
        g, h = obj.get_gradients(scores[0], self.label_dev,
                                 self.weight_dev, **kwargs)
        return g[None, :], h[None, :]

    @staticmethod
    def _update_score_impl(scores_k, leaf_values, row_leaf, lr):
        rlc = jnp.where(row_leaf >= 0, row_leaf, leaf_values.shape[0] - 1)
        add = jnp.take(leaf_values, rlc) * lr
        return scores_k + jnp.where(row_leaf >= 0, add, 0.0)

    def _goss_impl(self, g, h, key):
        """GOSS mask+amplify (goss.hpp Helper): keep top `top_rate` rows by
        sum_k |g*h|, sample `other_rate` of the rest, amplify their grads."""
        cfg = self.config
        R = g.shape[1]
        n_real = self._num_data_global
        real = (self.train_dd.row_leaf0 >= 0).astype(jnp.float32)
        # padded rows DO carry gradients (label 0 vs init score) — mask them
        # out of the ranking or they displace real rows from the top set
        # padded rows must be UNSELECTABLE, not merely zero-scored: a
        # real row tied at 0 could otherwise lose its top slot to a
        # lower-index padded row (multi-host padding sits at each
        # host's local tail, below later hosts' real rows)
        score = jnp.where(real > 0,
                          jnp.sum(jnp.abs(g * h), axis=0), -jnp.inf)
        top_k = max(1, int(n_real * cfg.top_rate))
        other_k = max(1, int(n_real * cfg.other_rate))
        # exact arg-partition (goss.hpp:30 ArgMaxAtK): lax.top_k keeps
        # exactly top_k rows even on tied scores
        _, top_idx = jax.lax.top_k(score, top_k)
        is_top = jnp.zeros((R,), bool).at[top_idx].set(True)
        u = jax.random.uniform(key, (R,))
        rest = ~is_top & (self.train_dd.row_leaf0 >= 0)
        p_keep = other_k / max(1, n_real - top_k)
        sampled = rest & (u < p_keep)
        amp = (1.0 - cfg.top_rate) / cfg.other_rate
        mask = is_top.astype(jnp.float32) + sampled.astype(jnp.float32)
        scale = jnp.where(sampled, amp, 1.0) * mask
        return g * scale[None, :], h * scale[None, :], mask

    def _bagging_active(self) -> bool:
        cfg = self.config
        balanced = (cfg.pos_bagging_fraction < 1.0
                    or cfg.neg_bagging_fraction < 1.0)
        return (not self._goss and cfg.bagging_freq > 0
                and (cfg.bagging_fraction < 1.0 or balanced))

    def _host_bag_mask(self, it: int) -> Optional[jax.Array]:
        """Regenerate/return the device bagging mask for iteration
        ``it`` (host RNG draws, no device sync), or None when bagging is
        off. Shared by the legacy loop and the fused dispatcher so both
        consume the identical ``_rng_bagging`` stream."""
        cfg = self.config
        if not self._bagging_active():
            return None
        if it % cfg.bagging_freq == 0 or self._bag_mask is None:
            R = self.train_dd.r_local
            balanced = (cfg.pos_bagging_fraction < 1.0
                        or cfg.neg_bagging_fraction < 1.0)
            n = self.train_dd.num_data
            m = np.zeros(R, np.float32)
            if balanced:
                # balanced bagging (bagging.hpp:146-165): positives
                # and negatives subsampled at their own rates
                lbl = np.asarray(self.train_set.get_label())[:n]
                pos = np.nonzero(lbl > 0)[0]
                neg = np.nonzero(lbl <= 0)[0]
                for rows, frac in ((pos, cfg.pos_bagging_fraction),
                                   (neg, cfg.neg_bagging_fraction)):
                    if len(rows) == 0:
                        continue
                    cnt = max(1, int(len(rows) * frac))
                    m[self._rng_bagging.choice(rows, cnt,
                                               replace=False)] = 1.0
            elif cfg.bagging_by_query:
                if self.train_set.group is None:
                    raise ValueError(
                        "bagging_by_query needs query/group data on "
                        "the training Dataset")
                # sample whole queries (bagging_by_query,
                # bagging.hpp:36,169) so ranking lists stay intact
                bounds = self.train_set.query_boundaries()
                nq = len(bounds) - 1
                cnt = max(1, int(nq * cfg.bagging_fraction))
                qs = self._rng_bagging.choice(nq, cnt, replace=False)
                for q in qs:
                    m[bounds[q]:bounds[q + 1]] = 1.0
            else:
                cnt = max(1, int(n * cfg.bagging_fraction))
                idx = self._rng_bagging.choice(n, cnt, replace=False)
                m[idx] = 1.0
            self._bag_mask = (self.plan.shard_rows(m)
                              if self.plan is not None
                              else jnp.asarray(m))
        return self._bag_mask

    def _sampling(self, it: int, g: jax.Array, h: jax.Array):
        """Returns (g, h, count_mask [R] f32). Bagging masks are built
        per process over local rows (the reference's bagging runs on
        each machine's own partition too)."""
        cfg = self.config
        real = self.train_dd.row_leaf0 >= 0
        base_mask = real.astype(jnp.float32)
        if self._goss:
            # reference skips GOSS for the first 1/learning_rate iterations
            if it >= int(1.0 / cfg.learning_rate):
                key = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.bagging_seed), it)
                return self._goss_jit(g, h, key)
            return g, h, base_mask
        mask = self._host_bag_mask(it)
        if mask is not None:
            return g * mask, h * mask, mask
        return g, h, base_mask

    def _feature_mask(self) -> jax.Array:
        cfg = self.config
        F = self.train_set.num_features
        put = np.asarray if self._mp else jnp.asarray
        if cfg.feature_fraction >= 1.0:
            return put(np.ones((F,), bool))
        k = max(1, int(F * cfg.feature_fraction))
        idx = self._rng_feature.choice(F, k, replace=False)
        m = np.zeros(F, bool)
        m[idx] = True
        return put(m)

    # ------------------------------------------------------------------
    def _prep_custom_gh(self, gradients, hessians):
        """Custom fobj arrays: flat [K*num_data] class-major
        (LGBM_BoosterUpdateOneIterCustom layout) or [num_data, K].
        Multi-host: the caller supplies THIS process's rows; placement
        goes through the plan so the global array assembles from the
        per-host blocks."""
        R_loc = self.train_dd.r_local

        def prep(a):
            a = np.asarray(a, np.float32)
            n = self.train_dd.num_data
            if a.ndim == 1:
                a = a.reshape(self.K, n)
            else:
                a = a.T
            kr = _pad_rows(a.T, R_loc).T
            return (self.plan.shard_scores(kr) if self.plan is not None
                    else jnp.asarray(kr))
        return prep(gradients), prep(hessians)

    def _build_one_tree(self, gh: jax.Array, fmask: jax.Array, k: int = 0,
                        quant_scales: Optional[jax.Array] = None,
                        it=None, traced: bool = False):
        """One tree on the current gradients; returns device results.
        ``it`` overrides the iteration index (the fused step passes a
        traced scalar); ``traced`` inlines the builder into an ambient
        trace instead of dispatching its jit."""
        cfg = self.config
        if it is None:
            it = self.iter_
        if self.chunked:
            # out-of-core: stream the bin matrix through the chunked
            # builder. Its gate already pinned every feature the kw
            # plumbing below would add (quant/gain_scale ride through).
            kwc = {}
            if quant_scales is not None:
                kwc["quant_scales"] = quant_scales
            if self._gain_scale is not None:
                kwc["gain_scale"] = self._gain_scale
            return self._chunked_builder.build(
                self._prefetcher, gh, self.train_dd.row_leaf0, fmask,
                valid_bins=tuple(dd.bins for dd in self.valid_dd),
                valid_row_leaf0=tuple(dd.row_leaf0
                                      for dd in self.valid_dd), **kwc)
        builder = (self.plan.build_tree if self.plan is not None
                   else functools.partial(build_tree, traced=traced))
        # fold both iteration and class index: multiclass trees of one
        # iteration must sample independently (the reference's shared RNG
        # advances per tree)
        key = (jax.random.fold_in(
            jax.random.fold_in(self._tree_key, it), k)
            if self._tree_key is not None else None)
        kw = {}
        if quant_scales is not None:
            kw["quant_scales"] = quant_scales
        if self._cat_sorted_mask is not None:
            kw["cat_sorted_mask"] = self._cat_sorted_mask
        if self._bundle_meta is not None:
            kw["bundle_meta"] = self._bundle_meta
            kw["bundle_bins"] = self._bundle_bins
        if self.plan is None:
            # single-device extras (reference ties CEGB to the serial
            # learner; feature_contri follows for simplicity)
            if self._gain_scale is not None:
                kw["gain_scale"] = self._gain_scale
            if self._cegb is not None:
                t, ps, coupled, lazy = self._cegb
                kw["cegb"] = (t, ps, coupled, lazy,
                              self._cegb_feat_used, self._cegb_used_rows)
        if (self.plan is None and self._bundle_meta is None
                and resolve_impl(cfg.hist_impl) == "native"):
            # column-major copy of the bin matrix for the native
            # PARTITION custom call (dense_bin.hpp stores per-feature
            # columns for the same reason: the split feature's column is
            # read contiguously); built once, reused every tree
            if self._bins_cm is None:
                self._bins_cm = jnp.asarray(self.train_dd.bins.T)
            kw["bins_cm"] = self._bins_cm
        if self.fused_split_ok:
            kw["fused_split"] = True
        mono_method = (cfg.monotone_constraints_method
                       if self.mono_type_pf is not None else "basic")
        leaf_batch = cfg.leaf_batch
        if mono_method in ("intermediate", "advanced"):
            # cross-leaf bound propagation is only sound one split at a
            # time (see tree_builder.py); the reference learner is
            # sequential here anyway
            leaf_batch = 1
        kw["mono_method"] = mono_method
        if self._forced_splits is not None:
            kw["forced"] = self._forced_splits
            leaf_batch = 1
        out = builder(
            self.train_dd.bins, gh, self.train_dd.row_leaf0,
            self.num_bins_pf, self.nan_bin_pf, self.is_cat_pf, fmask,
            num_leaves=cfg.num_leaves, leaf_batch=leaf_batch,
            max_depth=cfg.max_depth, num_bins=self.B,
            split_params=self.split_params,
            hist_dtype=cfg.hist_dtype, hist_impl=cfg.hist_impl,
            hist_sub=self._hist_sub, block_rows=self.block,
            valid_bins=tuple(dd.bins for dd in self.valid_dd),
            valid_row_leaf0=tuple(dd.row_leaf0 for dd in self.valid_dd),
            mono_type_pf=self.mono_type_pf,
            interaction_groups=self.interaction_groups,
            rng_key=key, feature_fraction_bynode=self._ffbn, **kw)
        if "cegb" in kw:
            tree_arrays, row_leaf, valid_rls, cegb_state = out
            self._cegb_feat_used, self._cegb_used_rows = cegb_state
            return tree_arrays, row_leaf, valid_rls
        return out

    # -- out-of-core chunked training gate (ISSUE 13) ------------------

    def _chunked_gate_reason(self) -> str:
        """Why the out-of-core chunked driver cannot grow this run's
        trees ('' = it can). The chunked builder replays the serial
        builder's simple round body over streamed row chunks; anything
        that bends that body — whole-matrix device state, per-node host
        coordination, cross-leaf bound propagation — pins the resident
        path. Evaluated at the capacity gate, so it reads raw config
        (``_cegb``/``_forced_splits`` are assembled later)."""
        cfg = self.config
        if type(self) is not GBDT:
            return "boosting mode replays resident device trees"
        if self.plan is not None:
            return "parallel plans place the full device matrix"
        if self._bundle_meta is not None:
            return "EFB bundles bin in device bundle space"
        if bool(cfg.linear_tree):
            return "linear leaves read resident raw feature values"
        if cfg.monotone_constraints:
            return "monotone constraints propagate cross-leaf bounds"
        if cfg.interaction_constraints:
            return "interaction constraints thread per-node ancestry"
        if cfg.forcedsplits_filename:
            return "forced splits assign node slots sequentially"
        if (cfg.cegb_tradeoff < 1.0 or cfg.cegb_penalty_split > 0.0
                or cfg.cegb_penalty_feature_coupled
                or cfg.cegb_penalty_feature_lazy):
            return "CEGB tracks per-row feature-use device state"
        if float(cfg.feature_fraction_bynode) < 1.0:
            return "per-node feature sampling draws inside the builder"
        if bool(cfg.extra_trees):
            return "extra-trees thresholds draw inside the builder"
        return ""

    # -- fused Pallas build+split (ISSUE 14) ---------------------------

    def _fused_split_reason(self) -> str:
        """Why the fused histogram+split-find Pallas kernel cannot
        drive this run's split search ('' = it can). The kernel's
        epilogue evaluates the gain lattice on the VMEM-resident
        accumulator block and emits only per-(leaf, chunk) candidate
        records, so anything that needs the full [F, B, 3] histogram
        in HBM — merge collectives, EFB unbundling, sorted-subset
        categorical reordering, gain rescaling, random thresholds —
        pins the two-pass kernel + ``find_best_splits`` path. Mirrors
        tree_builder's trace-time ``use_fused`` gate (which still
        falls back silently if a traced shape disagrees)."""
        import os
        cfg = self.config
        env = os.environ.get("LIGHTGBM_TPU_FUSED_SPLIT", "")
        if env == "0":
            return "LIGHTGBM_TPU_FUSED_SPLIT=0"
        mode = "on" if env == "1" else str(cfg.fused_split)
        if mode == "off":
            return "fused_split=off"
        impl = resolve_impl(cfg.hist_impl)
        if impl != "pallas":
            return f"hist_impl resolves to {impl} (epilogue is Pallas)"
        if self.chunked:
            return "chunked rounds accumulate histograms across chunks"
        if self.plan is not None:
            return "parallel plans merge full histograms"
        if self._bundle_meta is not None:
            return "EFB bundles unbundle the full histogram"
        if bool(cfg.extra_trees):
            return "extra-trees thresholds sample the full lattice"
        if self._forced_splits is not None:
            return "forced splits gather arbitrary (feature, bin) cells"
        if self._cegb is not None:
            return "CEGB rescales gains outside the kernel"
        if self._gain_scale is not None:
            return "feature_contri rescales gains outside the kernel"
        if self._cat_sorted_mask is not None:
            return "sorted-subset categoricals reorder histogram bins"
        if (self.mono_type_pf is not None
                and cfg.monotone_constraints_method == "advanced"):
            return "advanced monotone re-reads sibling histograms"
        F = self.train_set.num_features
        W = max(1, min(int(cfg.leaf_batch), int(cfg.num_leaves) - 1))
        if not (PH.fused_plan_ok(F, self.B, 2 * W)
                and PH.fused_plan_ok(F, self.B, W)):
            return (f"chunk plan unaligned for (F={F}, B={self.B}, "
                    f"W={W})")
        if mode != "on" and not PH.fused_probe_ok():
            return "fused probe failed to compile on this backend"
        return ""

    # -- class-batched multiclass build (ISSUE 8) ----------------------

    def _class_batch_reason(self) -> str:
        """Why the class-batched build cannot drive this run ('' = it
        can). Unlike the fused gate this applies to BOTH drivers: when
        it clears, the legacy loop and the fused step each grow all K
        per-class trees of an iteration through ONE
        :func:`tree_builder._build_tree_class_batched` program instead
        of K sequential builds. Anything threading per-class host state
        between builds, or assigning tree structure sequentially, pins
        the per-class loop."""
        import os
        cfg = self.config
        env = os.environ.get("LIGHTGBM_TPU_CLASS_BATCH", "")
        if env == "0":
            return "LIGHTGBM_TPU_CLASS_BATCH=0"
        if self.chunked:
            return "out-of-core training streams row chunks per tree"
        mode = "on" if env == "1" else str(cfg.class_batch)
        if mode == "off":
            return "class_batch=off"
        if self.K <= 1 and mode != "on":
            # one model per iteration: nothing to batch (class_batch=on
            # still exercises the K=1 batched path — the bench ablation
            # and parity tests rely on that)
            return "single model per iteration"
        if type(self) is not GBDT:
            return "boosting mode overrides the iteration loop"
        if bool(cfg.linear_tree):
            return "linear leaves solve per-class on host raw values"
        if self._forced_splits is not None:
            return "forced splits assign node slots sequentially"
        if self._cegb is not None:
            return "CEGB threads per-class model state across builds"
        if self.plan is not None and self.plan.parallel_mode == "feature":
            return "feature-parallel plan builds per-class"
        if self._mp:
            return "multi-process meshes place per-host blocks"
        return ""

    def _class_batch_keys(self, it):
        """[K, 2] per-class builder PRNG keys — fold_in(it) then
        fold_in(k), bit-identical to the keys the sequential loop's
        ``_build_one_tree(.., k)`` consumes — or None when per-node
        sampling and extra-trees are off."""
        if self._tree_key is None:
            return None
        it_key = jax.random.fold_in(self._tree_key, it)
        return jax.vmap(lambda k: jax.random.fold_in(it_key, k))(
            jnp.arange(self.K, dtype=jnp.int32))

    def _build_one_tree_batched(self, gh_k: jax.Array, fmask: jax.Array,
                                quant_scales_k: Optional[jax.Array] = None,
                                it=None, traced: bool = False):
        """All K trees of one iteration in ONE class-batched build.
        ``gh_k`` is [K, R, 3] (grad/hess/count channels per class);
        ``quant_scales_k`` is [K, 2]. Returns (stacked TreeArrays with
        a leading K axis, row_leaf [K, R], valid_row_leafs tuple of
        [K, Rv]). Only reachable when :meth:`_class_batch_reason`
        cleared, so the forced/CEGB/linear extras of
        :meth:`_build_one_tree` never arise here."""
        cfg = self.config
        if it is None:
            it = self.iter_
        if self.plan is not None:
            builder = functools.partial(self.plan.build_tree,
                                        class_batched=True)
        else:
            builder = functools.partial(build_tree, traced=traced,
                                        class_batched=True)
        kw = {}
        if quant_scales_k is not None:
            kw["quant_scales"] = quant_scales_k
        if self._cat_sorted_mask is not None:
            kw["cat_sorted_mask"] = self._cat_sorted_mask
        if self._bundle_meta is not None:
            kw["bundle_meta"] = self._bundle_meta
            kw["bundle_bins"] = self._bundle_bins
        if self.plan is None and self._gain_scale is not None:
            kw["gain_scale"] = self._gain_scale
        if self.fused_split_ok:
            kw["fused_split"] = True
        mono_method = (cfg.monotone_constraints_method
                       if self.mono_type_pf is not None else "basic")
        leaf_batch = cfg.leaf_batch
        if mono_method in ("intermediate", "advanced"):
            leaf_batch = 1
        kw["mono_method"] = mono_method
        return builder(
            self.train_dd.bins, gh_k, self.train_dd.row_leaf0,
            self.num_bins_pf, self.nan_bin_pf, self.is_cat_pf, fmask,
            num_leaves=cfg.num_leaves, leaf_batch=leaf_batch,
            max_depth=cfg.max_depth, num_bins=self.B,
            split_params=self.split_params,
            hist_dtype=cfg.hist_dtype, hist_impl=cfg.hist_impl,
            hist_sub=self._hist_sub, block_rows=self.block,
            valid_bins=tuple(dd.bins for dd in self.valid_dd),
            valid_row_leaf0=tuple(dd.row_leaf0 for dd in self.valid_dd),
            mono_type_pf=self.mono_type_pf,
            interaction_groups=self.interaction_groups,
            rng_key=self._class_batch_keys(it),
            feature_fraction_bynode=self._ffbn, **kw)

    def _stack_gh_k(self, g, h, count_mask):
        """[K, R, 3] batched gh for the class-batched build — the
        per-class analog of the sequential loop's
        ``jnp.stack([g[k], h[k], count_mask], axis=1)``."""
        return jnp.stack([g, h, jnp.broadcast_to(count_mask, g.shape)],
                         axis=2)

    def _parse_forced_splits(self, path):
        """JSON forced-split tree -> (parents, isright, feats, thrs,
        is_cat) static tuples in BFS order (ForceSplits queue
        semantics). Each node records its parent's index in the list
        (-1 for the root) and which side it forces — slots resolve at
        runtime inside the builder so a dropped forced node drops its
        subtree. Feature indices are ORIGINAL column ids; thresholds
        are raw values mapped through the feature's BinMapper. A
        categorical node forces the one-hot split on its category
        (GatherInfoForThresholdCategoricalInner,
        feature_histogram.hpp:604: left = rows equal to the category,
        default_left=false)."""
        import json as _json
        from collections import deque
        with open(path) as fh:
            root = _json.load(fh)
        if self.plan is not None and self.plan.parallel_mode != "data":
            raise NotImplementedError(
                "forced splits support the serial/data tree learners")
        uf = list(self.train_set.used_features)
        parents, isright, feats, thrs, iscat = [], [], [], [], []
        q = deque([(root, -1, False)])
        while q:
            node, pj, is_r = q.popleft()
            if not node:
                continue
            f_orig = int(node["feature"])
            if f_orig not in uf:
                raise ValueError(
                    f"forced split feature {f_orig} is not a used "
                    "feature of the dataset")
            f_inner = uf.index(f_orig)
            m = self.train_set.bin_mappers[f_orig]
            if m.bin_type == "categorical":
                # reference: ValueToBin of an unseen/negative category
                # returns the reserved bin and the gather rejects it
                # ("Invalid categorical threshold split",
                # feature_histogram.hpp:613). Our bin 0 is the most
                # frequent REAL category, so the miss must be caught
                # here: thr_bin=-1 makes the builder drop the node.
                cv = int(float(node["threshold"]))
                thr_bin = m._cat_to_bin.get(cv, -1) if cv >= 0 else -1
                if thr_bin < 0:
                    from .. import log as _log
                    _log.warning(
                        "Invalid categorical threshold split: category "
                        f"{cv} of feature {f_orig} was not seen in "
                        "training; the forced node will be skipped")
            else:
                thr_bin = int(m.values_to_bins(
                    np.asarray([float(node["threshold"])]))[0])
            me = len(parents)
            parents.append(pj)
            isright.append(is_r)
            feats.append(f_inner)
            thrs.append(thr_bin)
            iscat.append(m.bin_type == "categorical")
            if node.get("left"):
                q.append((node["left"], me, False))
            if node.get("right"):
                q.append((node["right"], me, True))
        return (tuple(parents), tuple(isright), tuple(feats),
                tuple(thrs), tuple(iscat))

    def _quantize_impl(self, g, h, key):
        """Stochastic rounding onto the int8 quant grid
        (DiscretizeGradients, gradient_discretizer.cpp:68-140).
        g, h: [K, R] f32 -> int8 grid values [K, R] + per-class scales
        (gs, hs) [K]. The int8 values feed the integer MXU histogram; the
        scales descale histogram sums at split-find time."""
        cfg = self.config
        nb = int(cfg.num_grad_quant_bins)
        gs = jnp.maximum(jnp.max(jnp.abs(g), axis=1, keepdims=True),
                         1e-30) / (nb // 2)
        hs = jnp.maximum(jnp.max(jnp.abs(h), axis=1, keepdims=True),
                         1e-30) / nb
        if bool(cfg.stochastic_rounding):
            # the rounding stream is defined on LOGICAL rows, not the
            # padded layout: threefry output depends on the draw shape,
            # and r_pad differs between serial and mesh runs (the mesh
            # pads to block*num_shards) — drawing at [K, num_data] and
            # padding with the deterministic 0.5 offset makes every
            # real row consume identical randomness under any sharding,
            # the bit-parity precondition of serial-vs-data training.
            # (Multi-HOST runs interleave per-process pads, so only
            # same-process-count runs are bit-comparable there.)
            n = min(self._num_data_global, g.shape[1])

            def draws(salt, width):
                u = jax.random.uniform(jax.random.fold_in(key, salt),
                                       (g.shape[0], n))
                return jnp.pad(u, ((0, 0), (0, width - n)),
                               constant_values=0.5)
            u1 = draws(0, g.shape[1])
            u2 = draws(1, h.shape[1])
        else:
            u1 = jnp.full_like(g, 0.5)
            u2 = jnp.full_like(h, 0.5)
        # int8 cast truncates toward zero; the random offset is applied
        # away from zero (gradient_discretizer.cpp:124-131)
        qg = jnp.trunc(g / gs + jnp.where(g >= 0, u1, -u1))
        qh = jnp.trunc(h / hs + u2)
        return (qg.astype(jnp.int8), qh.astype(jnp.int8),
                gs[:, 0], hs[:, 0])

    def _renew_leaf_impl(self, tree_arrays: TreeArrays, row_leaf, g, h):
        """RenewIntGradTreeOutput (gradient_discretizer.cpp:208-258):
        after a quantized build, leaf outputs are recomputed from the
        TRUE float grad/hess sums per leaf."""
        from ..ops.split import calc_output
        sp = self.split_params
        L1 = tree_arrays.leaf_values.shape[0]      # L + 1 (dummy slot)
        rlc = jnp.clip(row_leaf, 0, L1 - 1)
        dead = row_leaf < 0
        gz = jnp.where(dead, 0.0, g)
        hz = jnp.where(dead, 0.0, h)
        sum_g = jnp.zeros((L1,), jnp.float32).at[rlc].add(gz)
        sum_h = jnp.zeros((L1,), jnp.float32).at[rlc].add(hz)
        cnt = jnp.zeros((L1,), jnp.float32).at[rlc].add(
            jnp.where(dead, 0.0, 1.0))
        # NOTE: no path smoothing here — the reference's renewal calls
        # CalculateSplittedLeafOutput<USE_L1=true, USE_MAX_OUTPUT=true,
        # USE_SMOOTHING=false> (gradient_discretizer.cpp:231,254)
        out = calc_output(sum_g, sum_h, sp.lambda_l1, sp.lambda_l2,
                          sp.max_delta_step)
        live = (jnp.arange(L1) < tree_arrays.num_leaves) & (sum_h > 0)
        new_leaf = jnp.where(live, out, tree_arrays.leaf_values)
        node_value = tree_arrays.node_value.at[tree_arrays.leaf2node].set(
            jnp.where(live, new_leaf, jnp.take(
                tree_arrays.node_value, tree_arrays.leaf2node)))
        return tree_arrays._replace(leaf_values=new_leaf,
                                    node_value=node_value)

    # ------------------------------------------------------------------
    def _fit_linear_leaves(self, tree, row_leaf, g, h, shrink: float):
        """Per-leaf ridge solve on raw feature values
        (LinearTreeLearner::CalculateLinear, linear_tree_learner.cpp:
        280-385): for each leaf, regress -g on the raw values of the
        features along its path, weighted by h, ridge linear_lambda.
        Host NumPy: the solves are tiny ((d+1)^2 per leaf); the heavy
        segment sums vectorize over rows per leaf."""
        raw = self.train_set.raw_values
        lam = float(self.config.linear_lambda)
        n = self.train_set.num_data
        rl = np.asarray(row_leaf)[:n]
        g = np.asarray(g)[:n].astype(np.float64)
        h = np.asarray(h)[:n].astype(np.float64)

        # path features per leaf (global ids, first-use order)
        paths = [[] for _ in range(tree.num_leaves)]
        if tree.num_leaves > 1:
            stack = [(0, [])]
            while stack:
                node, feats = stack.pop()
                if node < 0:
                    paths[~node] = feats
                    continue
                f = int(tree.split_feature[node])
                nf = feats if f in feats else feats + [f]
                stack.append((int(tree.left_child[node]), nf))
                stack.append((int(tree.right_child[node]), nf))

        tree.is_linear = True
        for s in range(tree.num_leaves):
            feats = paths[s]
            rows = np.nonzero(rl == s)[0]
            tree.leaf_features[s] = []
            tree.leaf_coeff[s] = []
            tree.leaf_const[s] = tree.leaf_value[s]
            if not feats or len(rows) == 0:
                continue
            vals = raw[np.ix_(rows, feats)].astype(np.float64)
            ok = ~np.isnan(vals).any(axis=1)
            if ok.sum() < len(feats) + 1:
                continue  # too few clean rows: constant leaf
            X = np.concatenate([vals[ok], np.ones((ok.sum(), 1))], axis=1)
            hw = h[rows][ok]
            gw = g[rows][ok]
            A = (X * hw[:, None]).T @ X
            d = len(feats)
            A[np.arange(d), np.arange(d)] += lam
            b = X.T @ gw
            try:
                beta = -np.linalg.solve(A, b)
            except np.linalg.LinAlgError:
                continue
            if not np.isfinite(beta).all():
                continue
            keep = np.abs(beta[:d]) > 1e-35   # kZeroThreshold
            tree.leaf_features[s] = [feats[i] for i in range(d) if keep[i]]
            tree.leaf_coeff[s] = [float(beta[i] * shrink)
                                  for i in range(d) if keep[i]]
            tree.leaf_const[s] = float(beta[d] * shrink)

    def _linear_score_delta(self, tree, raw, row_leaf, r_pad):
        """Per-row SHRUNK outputs of a linear tree (AddPredictionToScore
        linear path, tree.cpp:120-149) for the score update."""
        n = raw.shape[0]
        rl = np.asarray(row_leaf)[:n]
        out = np.zeros(r_pad, np.float32)
        for s in range(tree.num_leaves):
            rows = np.nonzero(rl == s)[0]
            if len(rows) == 0:
                continue
            feats = tree.leaf_features[s]
            if not feats:
                out[rows] = tree.leaf_const[s]
                continue
            vals = raw[np.ix_(rows, feats)].astype(np.float64)
            nan = np.isnan(vals).any(axis=1)
            lin = tree.leaf_const[s] + vals @ np.asarray(tree.leaf_coeff[s])
            out[rows] = np.where(nan, tree.leaf_value[s], lin)
        return out

    def _bias_adjust_device(self, tree_arrays: TreeArrays, bias: float,
                            shrink: float) -> TreeArrays:
        """Fold an output bias into the stored device tree so that
        weight * node_value includes it (AddBias, tree.h; keeps DART /
        rollback / init_model score arithmetic consistent with the
        host-side first-tree bias of gbdt.cpp:416)."""
        adj = jnp.float32(bias / shrink)
        return tree_arrays._replace(
            node_value=tree_arrays.node_value + adj,
            leaf_values=tree_arrays.leaf_values + adj)

    # -- fused boosting step (ISSUE 3) ---------------------------------
    # One jitted program per iteration: grads -> sampling -> quantize ->
    # K tree builds -> score updates, with donated score buffers. Built
    # TreeArrays stay ON DEVICE in the pending ring and materialize to
    # host Tree objects in batches at sync points only (engine.train's
    # eval cadence), so the steady-state inner loop runs dispatch-ahead
    # with zero host syncs between eval points — the whole-round
    # on-device shape of the CUDA learner, now including the outer loop.

    def _fused_gate_reason(self) -> str:
        """Why the fused single-dispatch step cannot drive this run
        ('' = it can). Anything needing per-iteration HOST work — host
        gradients, host leaf solves, cross-tree host state — pins the
        legacy loop; host-RNG sampling masks do NOT (they are generated
        sync-free at dispatch time and passed in)."""
        import os
        cfg = self.config
        if os.environ.get("LIGHTGBM_TPU_FUSED_TRAIN", "") == "0":
            return "LIGHTGBM_TPU_FUSED_TRAIN=0"
        if not bool(cfg.fused_train):
            return "fused_train=false"
        if self.chunked:
            return "out-of-core chunk sweeps are host-driven"
        if type(self) is not GBDT:
            return "boosting mode overrides the iteration loop"
        if self.objective is None:
            return "custom objective gradients are host-supplied"
        if bool(cfg.linear_tree):
            return "linear leaves solve on host raw values"
        if self._cegb is not None:
            return "CEGB threads model-level host state"
        if self._mp:
            return "multi-process meshes place per-host blocks"
        if self.plan is not None and not self.plan.supports_fused():
            return "parallel plan pins the legacy loop"
        if self.objective.is_ranking and getattr(
                self.objective, "num_position_ids", 0):
            return "position-bias estimation updates host state"
        return ""

    def _fused_step_impl(self, scores, valid_scores, bag_mask, fmask,
                         it, lr):
        """The traced iteration body. Pure function of its inputs plus
        static self state; numerically identical to the legacy loop
        (same ops, one program). Returns (scores, valid_scores, trees,
        should_continue flag, finite flag) — all on device. ``trees`` is
        one stacked TreeArrays (leading K axis) when the class-batched
        build drives the iteration, else the per-class [TreeArrays]*K
        list; sync() materializes both forms. The finite flag is the
        NaN guard's deferred device check (same mechanism as the
        no-split stop): NaN gradients produce -inf gains and a
        no-split tree, so without the explicit g/h check divergence
        would masquerade as a clean early stop."""
        from .. import profiler
        cfg = self.config
        with profiler.phase("grads"):
            g, h = self._grads(it, scores)
        with profiler.phase("sampling"):
            if self._goss:
                # GOSS starts after 1/learning_rate iterations
                # (goss.hpp); a traced-iteration cond replaces the
                # legacy host branch
                thresh = int(1.0 / cfg.learning_rate)
                key = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.bagging_seed), it)
                base = (self.train_dd.row_leaf0 >= 0).astype(jnp.float32)
                g, h, count_mask = jax.lax.cond(
                    it >= thresh,
                    lambda gg, hh: self._goss_impl(gg, hh, key),
                    lambda gg, hh: (gg, hh, base), g, h)
            elif self._bagging_active():
                g, h, count_mask = g * bag_mask, h * bag_mask, bag_mask
            else:
                count_mask = bag_mask    # base real-row mask
            g_true, h_true = g, h
            if self._quant:
                qg, qh, q_gs, q_hs = self._quantize_impl(
                    g, h, jax.random.fold_in(self._quant_key, it))
                count_i8 = count_mask.astype(jnp.int8)
        finite = jnp.all(jnp.isfinite(g)) & jnp.all(jnp.isfinite(h))
        new_scores = scores
        new_valid = list(valid_scores)
        if self.class_batch_ok:
            # class-batched build (ISSUE 8): ONE program grows all K
            # trees — the class axis rides the leaf-slot axis through
            # every kernel, so the staged equations and the histogram
            # dispatches per round stop scaling with K
            if self._quant:
                gh_k = self._stack_gh_k(qg, qh, count_i8)
                qsk_b = jnp.stack([q_gs, q_hs], axis=1)     # [K, 2]
            else:
                gh_k = self._stack_gh_k(g, h, count_mask)
                qsk_b = None
            with profiler.phase("build"):
                trees_k, row_leaf_k, valid_rls_k = \
                    self._build_one_tree_batched(
                        gh_k, fmask, quant_scales_k=qsk_b, it=it,
                        traced=self.plan is None)
                if self._quant and bool(cfg.quant_train_renew_leaf):
                    trees_k = jax.vmap(self._renew_leaf_impl)(
                        trees_k, row_leaf_k, g_true, h_true)
            grew_k = trees_k.num_leaves > 1                 # [K] bool
            with profiler.phase("update"):
                # per-class rows are independent, so the batched
                # where() equals the sequential .at[k].set chain
                upd = jax.vmap(self._update_score_impl,
                               in_axes=(0, 0, 0, None))(
                    new_scores, trees_k.leaf_values, row_leaf_k, lr)
                new_scores = jnp.where(grew_k[:, None], upd, new_scores)
                for vi, vrl_k in enumerate(valid_rls_k):
                    vupd = jax.vmap(self._update_score_impl,
                                    in_axes=(0, 0, 0, None))(
                        new_valid[vi], trees_k.leaf_values, vrl_k, lr)
                    new_valid[vi] = jnp.where(grew_k[:, None], vupd,
                                              new_valid[vi])
            finite = finite & jnp.all(jnp.isfinite(new_scores))
            return (new_scores, tuple(new_valid), trees_k,
                    jnp.any(grew_k), finite)
        trees = []
        grews = []
        for k in range(self.K):
            if self._quant:
                gh = jnp.stack([qg[k], qh[k], count_i8], axis=1)
                qsk = {"quant_scales": jnp.stack([q_gs[k], q_hs[k]])}
            else:
                gh = jnp.stack([g[k], h[k], count_mask], axis=1)
                qsk = {}
            with profiler.phase("build"):
                tree_arrays, row_leaf, valid_rls = self._build_one_tree(
                    gh, fmask, k, it=it, traced=self.plan is None, **qsk)
                if self._quant and bool(cfg.quant_train_renew_leaf):
                    tree_arrays = self._renew_leaf_impl(
                        tree_arrays, row_leaf, g_true[k], h_true[k])
            grew = tree_arrays.num_leaves > 1
            with profiler.phase("update"):
                # score updates apply only when the tree grew — the
                # device form of the legacy num_leaves>1 host check
                upd = self._update_score_impl(
                    new_scores[k], tree_arrays.leaf_values, row_leaf, lr)
                new_scores = new_scores.at[k].set(
                    jnp.where(grew, upd, new_scores[k]))
                for vi, vrl in enumerate(valid_rls):
                    vupd = self._update_score_impl(
                        new_valid[vi][k], tree_arrays.leaf_values, vrl,
                        lr)
                    new_valid[vi] = new_valid[vi].at[k].set(
                        jnp.where(grew, vupd, new_valid[vi][k]))
            trees.append(tree_arrays)
            grews.append(grew)
        cont = jnp.any(jnp.stack(grews))
        finite = finite & jnp.all(jnp.isfinite(new_scores))
        return new_scores, tuple(new_valid), trees, cont, finite

    def _fused_data_args(self):
        """The large per-instance device arrays the fused step reads,
        as a pytree jit ARGUMENT. On jax 0.4.x, closed-over concrete
        arrays are embedded into the lowered module as dense HLO
        constants — a multi-MB (at Higgs scale, multi-hundred-MB)
        constant per dataset that XLA then burns compile time
        constant-folding over. Passing them as arguments keeps the
        program data-free like the legacy build_tree jit."""
        return dict(
            bins=self.train_dd.bins,
            row_leaf0=self.train_dd.row_leaf0,
            label=self.label_dev,
            weight=self.weight_dev,
            bins_cm=self._bins_cm,
            valid_bins=tuple(dd.bins for dd in self.valid_dd),
            valid_rl0=tuple(dd.row_leaf0 for dd in self.valid_dd))

    def _fused_step_entry(self, scores, valid_scores, bag_mask, fmask,
                          it, lr, data):
        """jit entry point: rebinds ``data``'s tracers onto self for
        the duration of the trace (restored in finally), so every read
        the step body makes of the big arrays resolves to a program
        argument instead of a closure constant. Runs only while
        TRACING — steady-state dispatches hit the compiled cache and
        never re-enter Python here."""
        saved = (self.train_dd.bins, self.train_dd.row_leaf0,
                 self.label_dev, self.weight_dev, self._bins_cm,
                 [dd.bins for dd in self.valid_dd],
                 [dd.row_leaf0 for dd in self.valid_dd])
        try:
            self.train_dd.bins = data["bins"]
            self.train_dd.row_leaf0 = data["row_leaf0"]
            self.label_dev = data["label"]
            self.weight_dev = data["weight"]
            self._bins_cm = data["bins_cm"]
            for dd, b, rl in zip(self.valid_dd, data["valid_bins"],
                                 data["valid_rl0"]):
                dd.bins, dd.row_leaf0 = b, rl
            return self._fused_step_impl(scores, valid_scores, bag_mask,
                                         fmask, it, lr)
        finally:
            (self.train_dd.bins, self.train_dd.row_leaf0, self.label_dev,
             self.weight_dev, self._bins_cm, vb, vr) = saved
            for dd, b, rl in zip(self.valid_dd, vb, vr):
                dd.bins, dd.row_leaf0 = b, rl

    def _full_row_mask(self) -> jax.Array:
        """All-real-rows bagging mask, ``(row_leaf0 >= 0)`` as f32,
        cached by buffer identity — ``row_leaf0`` is static across
        iterations, and recomputing eagerly cost two extra device
        dispatches (greater_equal + convert) per fused iteration."""
        rl0 = self.train_dd.row_leaf0
        cached = self._full_mask_cache
        if cached is None or cached[0] is not rl0:
            self._full_mask_cache = (rl0, (rl0 >= 0).astype(jnp.float32))
        return self._full_mask_cache[1]

    def _fused_dispatch(self):
        """Enqueue one fused iteration: a single jit dispatch, no host
        sync. Host-RNG inputs (bagging mask, feature mask) are drawn
        here — pure host computation — so fused and legacy consume the
        identical RNG streams in the identical order."""
        it = self.iter_
        mask = self._host_bag_mask(it)
        if mask is None:
            mask = self._full_row_mask()
        fmask = self._feature_mask()
        if (self._bins_cm is None and self.plan is None
                and self._bundle_meta is None
                and resolve_impl(self.config.hist_impl) == "native"):
            # the lazy column-major copy must exist BEFORE tracing: a
            # trace-time build inside _build_one_tree would store a
            # tracer on self
            self._bins_cm = jnp.asarray(self.train_dd.bins.T)
        if self._fused_jit is None:
            # donate the score carries on accelerators: each iteration
            # writes into the previous buffers instead of allocating
            # K*R fresh. The CPU backend pins NO-donation: np.asarray
            # of a CPU jax array is zero-copy, so metric/eval code can
            # still hold views of the previous score buffers when the
            # next donated in-place write lands (observed as corrupted
            # valid metrics + runtime aborts).
            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            self._fused_jit = jax.jit(self._fused_step_entry,
                                      donate_argnums=donate)
        scores, valid_scores, trees, cont, ok = self._fused_jit(
            self.scores, tuple(self.valid_scores), mask, fmask,
            jnp.asarray(it, jnp.int32),
            jnp.asarray(self.shrinkage, jnp.float32),
            self._fused_data_args())
        self.scores = scores
        self.valid_scores = list(valid_scores)
        self._pending.append((it, float(self.shrinkage), trees, cont, ok))
        self.iter_ += 1

    def sync(self) -> bool:
        """Materialize every deferred iteration's device trees into host
        ``Tree`` models with ONE device transfer, and run the deferred
        stop check (the device should_continue flags of the pending
        ring). Returns True when training must stop — a no-split
        iteration was found; it and everything dispatched after it are
        dropped (their score updates were device no-ops, so the live
        scores are already correct). No-op False when nothing pends."""
        if not self._pending:
            return False
        pending, self._pending = self._pending, []
        try:
            host = jax.device_get([(trees, cont, ok)
                                   for (_, _, trees, cont, ok)
                                   in pending])
        except jax.errors.JaxRuntimeError as e:
            # an XLA execution error surfacing at the ring drain means
            # a device (or its collective partner) went away mid-step
            from ..resilience.guards import DeviceLossError
            raise DeviceLossError(pending[0][0], detail=str(e)) from e
        self.host_sync_count += 1
        bm = self.train_set.bin_mappers
        uf = self.train_set.used_features
        stop = False
        kept = 0
        for (it, shrink, _, _, _), (trees_h, cont, ok) in zip(pending,
                                                              host):
            if self._nan_guard != "off" and not bool(ok):
                # divergence check BEFORE the no-split stop: NaN grads
                # build a no-split tree, which would otherwise read as
                # a clean early stop. iter_ rewinds to the last good
                # iteration so a checkpoint restore / re-raise sees a
                # consistent counter.
                from ..resilience.guards import NumericDivergenceError
                self.iter_ = pending[0][0] + kept
                raise NumericDivergenceError(it)
            if not bool(cont) and it > 0:
                # drop the no-op iteration (and its dispatch-ahead
                # successors, which trained on unchanged scores),
                # reference gbdt.cpp:441-447
                stop = True
                break
            if isinstance(trees_h, TreeArrays):
                # class-batched iteration: ONE stacked TreeArrays with
                # a leading K axis; unstack into per-class host views
                # (zero-copy numpy slices)
                trees_h = [jax.tree.map(lambda a: a[k], trees_h)
                           for k in range(self.K)]
            for k, tree in enumerate(Tree.from_device_batch(
                    trees_h, bm, uf, shrink)):
                bias = self._init_scores[k]
                if it == 0 and abs(bias) > kEpsilon:
                    # AddBias (gbdt.cpp:416): fold init score into the
                    # first tree. Only the host model needs it here —
                    # the fused path never keeps device trees (DART,
                    # which does, is legacy-only).
                    tree.leaf_value += bias
                    tree.internal_value += bias
                self.models.append(tree)
            kept += 1
        self.iter_ = pending[0][0] + kept
        return stop

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None, *,
                       defer: bool = False):
        """One boosting iteration.

        Default (eager) contract: dispatch AND materialize, returning
        True when training should stop (no splits possible).

        ``defer=True`` with the fused step active: dispatch the whole
        iteration as one jitted program and return None with ZERO host
        syncs; trees stay on device until :meth:`sync` (engine.train
        syncs on its ``eval_period`` cadence). Custom gradients and
        fallback configs run the legacy loop eagerly either way.
        """
        self._maybe_chaos_poison()
        try:
            self._maybe_chaos_devloss()
            if gradients is not None or hessians is not None \
                    or not self.fused_ok:
                if self.sync():    # drain any deferred work first
                    return True
                return self._train_one_iter_legacy(gradients, hessians)
            self._fused_dispatch()
        except jax.errors.JaxRuntimeError as e:
            # runtime failures from collectives/XLA at the dispatch
            # site are device loss, not a bug in the traced program —
            # type them so the supervisor (on_device_loss=degrade) can
            # restore + re-plan instead of dying on a raw XLA error.
            # (NumericDivergenceError is a plain RuntimeError and
            # passes through untouched.)
            from ..resilience.guards import DeviceLossError
            raise DeviceLossError(self.iter_, detail=str(e)) from e
        if defer:
            return None
        return self.sync()

    def _maybe_chaos_poison(self) -> None:
        """Fault-injection hook (scripts/chaos_train.py): when armed via
        LIGHTGBM_TPU_CHAOS_POISON_ITER, overwrite one score entry with
        NaN before the matching iteration dispatches — the NaN
        propagates through the gradients so the divergence guard must
        catch it. A marker file (LIGHTGBM_TPU_CHAOS_POISON_ONCE) makes
        the fault transient: the rollback policy's re-run then
        succeeds. Inert (two env reads) outside the harness."""
        import os
        it_s = os.environ.get("LIGHTGBM_TPU_CHAOS_POISON_ITER")
        if it_s is None or self.iter_ != int(it_s):
            return
        marker = os.environ.get("LIGHTGBM_TPU_CHAOS_POISON_ONCE")
        if marker:
            if os.path.exists(marker):
                return      # already fired once; fault was transient
            with open(marker, "w") as f:
                f.write("poisoned\n")
        poisoned = np.asarray(self.scores).copy()
        poisoned[0, 0] = np.nan
        self.scores = (self.plan.shard_scores(poisoned)
                       if self.plan is not None else jnp.asarray(poisoned))

    def _maybe_chaos_devloss(self) -> None:
        """Fault-injection hook (scripts/chaos_train.py): when armed
        via LIGHTGBM_TPU_CHAOS_DEVLOSS_ITER, raise a real
        ``jax.errors.JaxRuntimeError`` at the matching iteration —
        exercising the same classify-and-retype path a genuine XLA
        collective failure takes. LIGHTGBM_TPU_CHAOS_DEVLOSS_ONCE
        (marker file) makes the fault transient; _DEVLOSS_MODE=mesh
        fires only while a parallel plan is active, so shrink-to-serial
        recovery can be proven. Inert (one env read) outside the
        harness."""
        import os
        it_s = os.environ.get("LIGHTGBM_TPU_CHAOS_DEVLOSS_ITER")
        if it_s is None or self.iter_ != int(it_s):
            return
        if (os.environ.get("LIGHTGBM_TPU_CHAOS_DEVLOSS_MODE") == "mesh"
                and self.plan is None):
            return
        marker = os.environ.get("LIGHTGBM_TPU_CHAOS_DEVLOSS_ONCE")
        if marker:
            if os.path.exists(marker):
                return      # already fired once; fault was transient
            with open(marker, "w") as f:
                f.write("device lost\n")
        raise jax.errors.JaxRuntimeError(
            "chaos: injected device loss (collective partner gone)")

    def _train_one_iter_legacy(self,
                               gradients: Optional[np.ndarray] = None,
                               hessians: Optional[np.ndarray] = None
                               ) -> bool:
        """Per-iteration host loop (~5 dispatches + a per-tree sync);
        returns True when training should stop (no splits possible)."""
        from .. import profiler
        with profiler.phase("grads"):
            if gradients is None or hessians is None:
                g, h = self._grads(self.iter_)
            else:
                g, h = self._prep_custom_gh(gradients, hessians)
        with profiler.phase("sampling"):
            g, h, count_mask = self._sampling(self.iter_, g, h)
            g_true, h_true = g, h
            if self._quant:
                qg, qh, q_gs, q_hs = self._quantize_jit(
                    g, h, jax.random.fold_in(self._quant_key, self.iter_))
                count_i8 = count_mask.astype(jnp.int8)
        if self._nan_guard != "off":
            # eager form of the fused step's deferred finite flag (the
            # legacy loop syncs every iteration anyway); checked BEFORE
            # the build so a corrupt tree is never appended
            if not (bool(jnp.all(jnp.isfinite(g)))
                    and bool(jnp.all(jnp.isfinite(h)))):
                from ..resilience.guards import NumericDivergenceError
                raise NumericDivergenceError(self.iter_)

        fmask = self._feature_mask()
        linear = bool(self.config.linear_tree)
        should_continue = False
        trees_k = None
        if self.class_batch_ok:
            # hoisted class-batched build (ISSUE 8 satellite): ONE
            # dispatch grows all K trees; the per-class loop below then
            # just slices host/device views out of the stacked result —
            # both drivers share the same build path
            if self._quant:
                gh_k = self._stack_gh_k(qg, qh, count_i8)
                qsk_b = jnp.stack([q_gs, q_hs], axis=1)     # [K, 2]
            else:
                gh_k = self._stack_gh_k(g, h, count_mask)
                qsk_b = None
            with profiler.phase("build"):
                trees_k, row_leaf_k, valid_rls_k = \
                    self._build_one_tree_batched(gh_k, fmask,
                                                 quant_scales_k=qsk_b)
                if self._quant and bool(self.config.quant_train_renew_leaf):
                    trees_k = self._renew_batch_jit(trees_k, row_leaf_k,
                                                    g_true, h_true)
            trees_k_host = jax.tree.map(np.asarray, trees_k)
        for k in range(self.K):
            if trees_k is not None:
                tree_arrays = jax.tree.map(lambda a: a[k], trees_k)
                host = jax.tree.map(lambda a: a[k], trees_k_host)
                row_leaf = row_leaf_k[k]
                valid_rls = tuple(v[k] for v in valid_rls_k)
            else:
                if self._quant:
                    gh = jnp.stack([qg[k], qh[k], count_i8], axis=1)
                    qsk = {"quant_scales": jnp.stack([q_gs[k], q_hs[k]])}
                else:
                    gh = jnp.stack([g[k], h[k], count_mask], axis=1)
                    qsk = {}
                with profiler.phase("build"):
                    tree_arrays, row_leaf, valid_rls = \
                        self._build_one_tree(gh, fmask, k, **qsk)
                    if self._quant and bool(
                            self.config.quant_train_renew_leaf):
                        tree_arrays = self._renew_jit(
                            tree_arrays, row_leaf, g_true[k], h_true[k])
                host = jax.tree.map(np.asarray, tree_arrays)
            num_leaves_trained = int(host.num_leaves)
            shrink = self.shrinkage
            tree = Tree.from_device(host, self.train_set.bin_mappers,
                                    self.train_set.used_features, shrink)
            if linear and num_leaves_trained > 1:
                self._fit_linear_leaves(tree, row_leaf, g_true[k],
                                        h_true[k], shrink)
            if num_leaves_trained > 1:
                should_continue = True
                with profiler.phase("update"):
                    if linear:
                        # linear outputs live on host (raw feature
                        # values); scores updated from the per-row
                        # linear deltas
                        delta = self._linear_score_delta(
                            tree, self.train_set.raw_values, row_leaf,
                            self.train_dd.r_pad)
                        self.scores = self.scores.at[k].add(
                            jnp.asarray(delta))
                        for vi, vrl in enumerate(valid_rls):
                            vds = self.valid_sets[vi]
                            vdelta = self._linear_score_delta(
                                tree, vds.raw_values, vrl,
                                self.valid_dd[vi].r_pad)
                            self.valid_scores[vi] = self.valid_scores[vi] \
                                .at[k].add(jnp.asarray(vdelta))
                    else:
                        lr = jnp.asarray(shrink, jnp.float32)
                        self.scores = self.scores.at[k].set(
                            self._update_score_jit(
                                self.scores[k], tree_arrays.leaf_values,
                                row_leaf, lr))
                        for vi, vrl in enumerate(valid_rls):
                            self.valid_scores[vi] = \
                                self.valid_scores[vi].at[k].set(
                                    self._update_score_jit(
                                        self.valid_scores[vi][k],
                                        tree_arrays.leaf_values, vrl, lr))
            bias = self._init_scores[k]
            if self.iter_ == 0 and abs(bias) > kEpsilon:
                # AddBias (gbdt.cpp:416): fold init score into first tree
                tree.leaf_value += bias
                tree.internal_value += bias
                if tree.is_linear:  # AddBias touches leaf_const too
                    tree.leaf_const += bias
                # scores already start at the init score; only the STORED
                # device tree carries the bias so later per-tree score
                # arithmetic (DART drop, rollback, refit) stays consistent
                tree_arrays = self._bias_adjust_device(tree_arrays, bias,
                                                       shrink)
            self.models.append(tree)
            if self.keep_device_trees:
                self.device_trees.append((tree_arrays, shrink))

        if not should_continue and self.iter_ > 0:
            # drop the no-op iteration, reference gbdt.cpp:441-447
            for _ in range(self.K):
                self.models.pop()
                if self.keep_device_trees:
                    self.device_trees.pop()
            return True
        self.iter_ += 1
        return False

    # ------------------------------------------------------------------
    def predict_device_tree(self, idx: int, which: int = -1) -> jax.Array:
        """[R] unshrunk per-row output of stored tree `idx` on the train
        (which=-1) or valid dataset's binned rows."""
        tree_arrays, _ = self.device_trees[idx]
        dd = self.train_dd if which < 0 else self.valid_dd[which]
        from ..ops.predict import predict_bins_value
        return predict_bins_value(tree_arrays, self.nan_bin_pf, dd.bins,
                                  bundle_meta=self._bundle_meta,
                                  num_bins_pf=self.num_bins_pf)

    # ------------------------------------------------------------------
    def rollback_one_iter(self):
        """RollbackOneIter (gbdt.cpp:454): subtract the last iteration's
        trees from every score and drop them. Replays the host trees over
        the binned matrix (threshold_bin traversal — the same decisions the
        device builder made), so repeated rollbacks work without keeping
        per-tree device state."""
        self.sync()        # deferred trees must exist before undoing one
        if self.iter_ <= 0:
            return
        if self.chunked:
            raise NotImplementedError(
                "rollback_one_iter replays trees over the resident "
                "binned matrix, which out-of-core chunked training "
                "never materializes")
        uf = self.train_set.used_features
        nan_bins = np.asarray(self.nan_bin_pf)
        bins_h = self._host_feature_bins(np.asarray(self.train_dd.bins))
        vbins_h = [self._host_feature_bins(np.asarray(dd.bins))
                   for dd in self.valid_dd]

        def row_outputs(tree, binned, raw, r_pad):
            # linear trees carry per-row outputs that the binned replay
            # cannot reproduce — replay them from raw feature values
            if tree.is_linear:
                out = np.zeros(r_pad, np.float32)
                out[:raw.shape[0]] = tree.predict(raw)
                return out
            return tree.predict_binned(binned, uf, nan_bins)

        for k in range(self.K):
            tree = self.models[-(self.K - k)]
            pred = row_outputs(tree, bins_h, self.train_set.raw_values,
                               self.train_dd.r_pad)
            self.scores = self.scores.at[k].add(
                -jnp.asarray(pred, jnp.float32))
            for vi, vb in enumerate(vbins_h):
                vpred = row_outputs(tree, vb,
                                    self.valid_sets[vi].raw_values,
                                    self.valid_dd[vi].r_pad)
                self.valid_scores[vi] = self.valid_scores[vi].at[k].add(
                    -jnp.asarray(vpred, jnp.float32))
        for _ in range(self.K):
            self.models.pop()
            if self.keep_device_trees:
                self.device_trees.pop()
        self.iter_ -= 1

    # ------------------------------------------------------------------
    # full-state checkpoint capture/restore (resilience subsystem)
    # ------------------------------------------------------------------
    def training_state(self) -> Tuple[dict, dict]:
        """Capture the complete mutable training state for a
        bit-identical-resume checkpoint: iteration counter, the two host
        RNG streams, the device score accumulators, and the cached
        bagging mask. Drains pending fused iterations first, so after
        this call ``iter_`` == materialized trees == host-RNG draws
        consumed — the invariant resume depends on. (Device PRNG keys
        are stateless ``fold_in(key, it)`` derivations, nothing to
        capture.)"""
        self.sync()
        if self.plan is not None and self.plan.multi_process:
            raise NotImplementedError(
                "full-state checkpoints are single-process only: "
                "multi-process meshes place per-host score blocks")
        if self.keep_device_trees:
            raise NotImplementedError(
                "full-state checkpoints do not capture per-tree device "
                "state (boosting=dart/goss with kept device trees); "
                "disable resume for this boosting mode")
        from ..resilience.checkpoint import _rng_state_to_json
        state = {
            "iter": int(self.iter_),
            "rng_bagging": _rng_state_to_json(
                self._rng_bagging.get_state()),
            "rng_feature": _rng_state_to_json(
                self._rng_feature.get_state()),
            "has_bag_mask": self._bag_mask is not None,
            # real-row counts: the saved score arrays are [K, r_pad]
            # with topology-dependent padding; restore onto a different
            # mesh keeps only these leading columns (elastic resume)
            "num_data": int(self.train_dd.num_data),
            "valid_num_data": [int(dd.num_data) for dd in self.valid_dd],
        }
        arrays = {"scores": np.asarray(self.scores)}
        for vi, vs in enumerate(self.valid_scores):
            arrays[f"valid_scores_{vi}"] = np.asarray(vs)
        if self._bag_mask is not None:
            arrays["bag_mask"] = np.asarray(self._bag_mask)
        return state, arrays

    def load_training_state(self, state: dict, arrays: dict,
                            trees: List[Tree]) -> None:
        """Restore a :meth:`training_state` capture into this live
        instance. Trees replace ``models`` IN PLACE so the engine's
        ``Booster._trees`` alias keeps pointing at the live list; score
        arrays are re-placed through the parallel plan's sharding.

        The capture's padded width is topology-dependent (serial pads
        to the scan block, a rows-sharded plan to ``block * shards``),
        so a checkpoint written on a different mesh arrives with the
        wrong trailing padding. Padded rows are initialized once and
        never mutated (``_update_score_impl`` gates on ``row_leaf >=
        0``; the bagging mask sets only real-row indices), so elastic
        restore is exact: keep the saved real-row columns, take the
        padding from this instance's freshly-initialized arrays.
        """
        if self.plan is not None and self.plan.multi_process:
            raise NotImplementedError(
                "full-state checkpoint restore is single-process only")
        from ..resilience.checkpoint import _rng_state_from_json
        self._pending.clear()
        self.models[:] = trees
        self.iter_ = int(state["iter"])
        self._rng_bagging.set_state(
            _rng_state_from_json(state["rng_bagging"]))
        self._rng_feature.set_state(
            _rng_state_from_json(state["rng_feature"]))
        rec_n = state.get("num_data")
        if rec_n is not None and int(rec_n) != self.train_dd.num_data:
            raise ValueError(
                f"checkpoint was written for {rec_n} training rows, "
                f"this run has {self.train_dd.num_data}: same config "
                "fingerprint but a different dataset")

        def _place_scores(a):
            return (self.plan.shard_scores(a) if self.plan is not None
                    else jnp.asarray(a))

        def _repad(saved, fresh, n):
            # fresh init already carries the correct values for every
            # padded row at THIS topology (init score broadcast); only
            # the real rows carry trained state worth restoring
            if saved.shape == fresh.shape:
                return saved
            merged = np.array(fresh, copy=True)
            merged[..., :n] = saved[..., :n]
            return merged

        n = int(self.train_dd.num_data)
        scores = _repad(arrays["scores"], np.asarray(self.scores), n)
        if scores is not arrays["scores"]:
            from .. import log as _log
            shards = (self.plan.num_shards if self.plan is not None
                      else 1)
            _log.info(
                "resume: re-sharding checkpoint state onto the current "
                f"topology (saved scores {arrays['scores'].shape} -> "
                f"{scores.shape}, {shards} shard(s))")
        self.scores = _place_scores(scores)
        self.valid_scores = [
            _place_scores(_repad(arrays[f"valid_scores_{vi}"],
                                 np.asarray(self.valid_scores[vi]),
                                 int(self.valid_dd[vi].num_data)))
            for vi in range(len(self.valid_scores))]
        if state.get("has_bag_mask") and "bag_mask" in arrays:
            m = arrays["bag_mask"]
            if m.shape[0] != scores.shape[-1]:
                # padded-row mask entries are always zero on every
                # topology (_host_bag_mask sets only real-row indices)
                m2 = np.zeros(scores.shape[-1], m.dtype)
                m2[:n] = m[:n]
                m = m2
            self._bag_mask = (self.plan.shard_rows(m)
                              if self.plan is not None
                              else jnp.asarray(m))
        else:
            self._bag_mask = None

    # ------------------------------------------------------------------
    def _host_feature_bins(self, bins_h: np.ndarray) -> np.ndarray:
        """Decode an EFB-bundled host bins matrix back to per-feature
        bins (identity when unbundled) — for host-side binned replay.
        Gated on the DEVICE layout (_bundle_meta), not the dataset's
        bundle_plan: tree_learner=feature stores the device matrix
        already unbundled and must not decode twice."""
        bp = self.train_set.bundle_plan
        if bp is None or self._bundle_meta is None:
            return bins_h
        from ..efb import decode_feature_bins
        nb = np.asarray(self.num_bins_pf)
        F = len(bp.feat_bundle)
        out = np.empty((bins_h.shape[0], F), np.int32)
        for f in range(F):
            raw = bins_h[:, bp.feat_bundle[f]].astype(np.int64)
            out[:, f] = decode_feature_bins(
                raw, int(bp.feat_offset[f]), int(nb[f]),
                int(bp.feat_mfb[f]))
        return out

    # ------------------------------------------------------------------
    def get_training_scores(self) -> np.ndarray:
        """Scores handed to custom objectives (GetTrainingScore analog,
        boosting.h; DART overrides to apply its dropout first)."""
        return self.eval_scores(-1)

    # ------------------------------------------------------------------
    def eval_scores(self, which: int = -1) -> np.ndarray:
        """Raw scores: which=-1 train, else valid index. [num_data, K].
        Multi-host: this process's rows only — per-machine metrics,
        exactly the reference's distributed-learner behavior."""
        dd = self.train_dd if which < 0 else self.valid_dd[which]
        arr = self.scores if which < 0 else self.valid_scores[which]
        self.host_sync_count += 1      # device -> host copy = one sync
        if self.plan is not None:
            return self.plan.host_local_cols(arr, dd.num_data).T
        return np.asarray(arr)[:, :dd.num_data].T

    def current_iteration(self) -> int:
        return self.iter_

    def num_trees(self) -> int:
        return len(self.models)
