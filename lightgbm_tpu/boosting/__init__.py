"""Boosting strategies: GBDT training loop, DART, RF, sampling."""
