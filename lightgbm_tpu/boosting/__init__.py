"""Boosting strategies: GBDT training loop, DART, RF, sampling.

Factory analog of ``Boosting::CreateBoosting`` (src/boosting/boosting.cpp:34);
``boosting=goss`` is resolved to gbdt + goss sampling by the Config layer.
"""

from .gbdt import GBDT


def create_boosting(config, train_set, objective, valid_sets=(), **kwargs):
    name = config.boosting
    if name == "gbdt":
        return GBDT(config, train_set, objective, valid_sets, **kwargs)
    if name == "dart":
        from .dart import DART
        return DART(config, train_set, objective, valid_sets, **kwargs)
    if name == "rf":
        from .rf import RF
        return RF(config, train_set, objective, valid_sets, **kwargs)
    raise ValueError(f"Unknown boosting type {name}")


__all__ = ["GBDT", "create_boosting"]
