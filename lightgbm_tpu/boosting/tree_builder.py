"""On-device leaf-wise tree growth.

TPU-native analog of the reference tree learner
(``src/treelearner/serial_tree_learner.cpp:179`` ``Train`` — the per-leaf
loop of §3.4 in SURVEY.md, with
``cuda/cuda_single_gpu_tree_learner.cpp:170-345`` as the
whole-loop-on-device architectural template).

Design (TPU-first; not a translation):
- The reference grows best-first one leaf per step with pointer-y data
  structures. Under XLA everything must be fixed-shape, so the tree lives in
  SoA node arrays sized ``2*num_leaves - 1`` (+1 dummy scatter slot) and the
  loop is a ``lax.while_loop`` whose every round:
    1. pops the top-``leaf_batch`` cached splits (``lax.top_k`` over the
       per-leaf best-gain cache — the argmax over ``best_split_per_leaf_``
       of serial_tree_learner.cpp:226, batched),
    2. applies them with one vectorized pass over ``row_leaf`` (the
       DataPartition::Split analog — no index reordering, just a dense
       leaf-id relabel),
    3. builds the SMALLER child's histogram over a compacted,
       dynamically-bounded row stream and derives the sibling by
       parent-minus-child subtraction from a per-leaf histogram cache
       (``hist_sub=True``; serial_tree_learner.cpp:567-592 ``Subtract``
       + dense_bin.hpp:105 iterating ``data_indices`` only). The matmul
       N-dim padding argument only covers the LEAF axis; the row stream
       is the real cost — without subtraction every round re-streams all
       R rows (~13x/tree at 255 leaves, ~254x in leaf_batch=1 modes).
       With it, each round streams only the smaller children's rows:
       compaction defers the bins gather to per-block inside
       ops/histogram.py, and the block loop is bounded by the live row
       count, so a round over a 1%-sized leaf pays ~1% of a full pass.
       The cache holds RAW histograms ([L+1, F, B, 3] f32, int32 when
       quantized — subtraction stays exact), ~5 MB at Higgs shape;
       callers disable hist_sub when the cache would not fit
       (histogram_pool_size analog),
    4. finds the children's best splits (ops/split.py) and scatters them
       into the per-leaf caches.
  ``leaf_batch=1`` reproduces the reference's exact best-first order;
  larger batches trade exact ordering for MXU width (trees differ slightly
  but gains are leaf-local, so selection differences are second-order).
- Bagging/GOSS enter as zeroed/scaled ``gh`` rows, never as shape changes.
- Validation sets ride along: their ``row_leaf`` is co-partitioned by the
  same split applications, so per-iteration validation scores are a gather —
  the analog of ScoreUpdater over valid data.
- Multi-chip: rows are sharded; the only cross-chip traffic is the
  histogram psum inside ops/histogram.py (ReduceScatter analog) — split
  selection then runs replicated and identically on every shard, which
  replaces SyncUpGlobalBestSplit (parallel_tree_learner.h:209) since a
  deterministic replicated argmax needs no sync.

Constraint machinery (all vectorized, no data-dependent shapes):
- Monotone constraints (basic mode, monotone_constraints.hpp:465-516):
  per-leaf output bounds [leaf_lo, leaf_hi]; on a numerical split of a
  constrained feature, mid = (left_out + right_out)/2 tightens the
  children's bounds. The split finder clamps candidate outputs and rejects
  direction violations.
- Interaction constraints (col_sampler.hpp:125-180 GetByNode): per-leaf
  used-feature sets [L+1, F] bool; a feature is allowed iff some constraint
  group contains the leaf's whole branch path — two boolean matmuls
  against the static group matrix.
- Per-node feature sampling (feature_fraction_bynode) and extra-trees
  random thresholds draw from a replicated PRNG key folded with the round
  counter, so every chip samples identically.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..native import jax_ffi as _jax_ffi

from ..ops.histogram import (build_histograms, resolve_impl, HIST_CH,
                             merge_histograms, _pvary)
# referenced as a module attribute (PH.fused_build_best_splits) so tests
# can monkeypatch interpret-mode wrappers in
from ..ops import pallas_histogram as PH
from ..ops.predict import row_feature_gather
from ..ops.split import (SplitParams, find_best_splits, leaf_gain,
                         leaf_output, monotone_penalty_factor)

__all__ = ["TreeArrays", "build_tree", "max_rounds_for"]

NEG_INF = -jnp.inf
F32_MAX = 3.4e38  # monotone bounds start effectively unconstrained


class TreeArrays(NamedTuple):
    """SoA tree (tree.h:135 analog). Arrays sized max_nodes = 2L-1 (+1 dummy
    at index max_nodes, trimmed on host)."""
    split_feature: jax.Array   # [N] int32, -1 => leaf
    threshold_bin: jax.Array   # [N] int32
    default_left: jax.Array    # [N] bool
    is_cat: jax.Array          # [N] bool
    left_child: jax.Array      # [N] int32
    right_child: jax.Array     # [N] int32
    gain: jax.Array            # [N] f32 split gain of internal nodes
    node_value: jax.Array      # [N] f32 leaf output (unshrunk)
    node_count: jax.Array      # [N] f32
    node_hess: jax.Array       # [N] f32
    cat_bitset: jax.Array      # [N, ceil(B/32)] uint32 LEFT subset (cat)
    leaf2node: jax.Array       # [L+1] int32
    leaf_values: jax.Array     # [L+1] f32 output per leaf slot (unshrunk)
    num_leaves: jax.Array      # scalar int32
    num_nodes: jax.Array       # scalar int32


def max_rounds_for(num_leaves: int, leaf_batch: int) -> int:
    cur, r = 1, 0
    while cur < num_leaves:
        cur += min(leaf_batch, cur, num_leaves - cur)
        r += 1
    return r


def _round_int(x):
    return jnp.floor(x + 0.5)


def build_tree(*args, hist_impl: str = "auto", traced: bool = False,
               class_batched: bool = False, **kwargs):
    """Unjitted entry: resolves ``hist_impl='auto'`` EAGERLY (the Pallas
    probe must compile outside any trace — staged into an ambient trace
    its try/except would pass vacuously) and dispatches to the jitted
    core. Same contract as :func:`_build_tree_impl` below.

    ``traced=True`` runs the plain (unjitted) core for callers that are
    ALREADY inside a trace — the fused boosting step of gbdt.py — so the
    build inlines into the enclosing program instead of nesting a pjit
    call boundary.

    ``class_batched=True`` grows ALL K per-class trees of one boosting
    iteration in one program (ISSUE 8): ``gh`` arrives [K, R, 3] (plus
    per-class ``rng_key``/``quant_scales`` when present) and the core is
    vmapped over the class axis — see
    :func:`_build_tree_class_batched`. The native FFI kernels carry no
    vmap batching rule, so the batched build remaps native -> scatter
    (bit-identical; tests/test_histogram.py native parity)."""
    impl = resolve_impl(hist_impl)
    if class_batched:
        if impl == "native":
            impl = "scatter"
        if traced:
            return _build_tree_class_batched(*args, hist_impl=impl,
                                             **kwargs)
        return _build_tree_cb_jit(*args, hist_impl=impl, **kwargs)
    if traced:
        return _build_tree_impl(*args, hist_impl=impl, **kwargs)
    return _build_tree_jit(*args, hist_impl=impl, **kwargs)


def _build_tree_impl(bins: jax.Array, gh: jax.Array, row_leaf0: jax.Array,
               num_bins_pf: jax.Array, nan_bin_pf: jax.Array,
               is_cat_pf: jax.Array, feature_mask: jax.Array,
               *, num_leaves: int, leaf_batch: int, max_depth: int,
               num_bins: int, split_params: SplitParams,
               axis_name: Optional[str] = None,
               hist_dtype: str = "bfloat16", hist_impl: str = "auto",
               block_rows: int = 0,
               valid_bins: Tuple[jax.Array, ...] = (),
               valid_row_leaf0: Tuple[jax.Array, ...] = (),
               mono_type_pf: Optional[jax.Array] = None,
               interaction_groups: Optional[jax.Array] = None,
               rng_key: Optional[jax.Array] = None,
               feature_fraction_bynode: float = 1.0,
               cat_sorted_mask: Optional[jax.Array] = None,
               parallel_mode: str = "data", top_k: int = 20,
               local_bins: Optional[jax.Array] = None,
               local_meta: Optional[Tuple] = None,
               feat_offset: Optional[jax.Array] = None,
               gain_scale: Optional[jax.Array] = None,
               cegb: Optional[Tuple] = None,
               bundle_meta: Optional[Tuple] = None,
               bundle_bins: int = 0,
               quant_scales: Optional[jax.Array] = None,
               mono_method: str = "basic",
               forced: Optional[Tuple] = None,
               hist_sub: bool = True,
               bins_cm: Optional[jax.Array] = None,
               feature_sharded: bool = False,
               hist_merge: str = "allreduce",
               n_shards: int = 1,
               fused_split: bool = False,
               root_hist: Optional[jax.Array] = None):
    """Grow one tree. Returns (TreeArrays, row_leaf, valid_row_leafs).

    ``parallel_mode`` (with ``axis_name`` set) selects the distributed
    strategy, mirroring tree_learner=data/feature/voting
    (tree_learner.cpp:15 factory):
    - "data": rows sharded. ``hist_merge`` picks the merge collective:
      * "allreduce" (psum): every chip receives the FULL merged
        histogram and split selection runs replicated (no winner sync
        needed — the original formulation, ~2x reduce-scatter's wire
        bytes and n-redundant split work);
      * "reduce_scatter" (``lax.psum_scatter`` along the feature axis,
        ``n_shards`` static): each chip receives only its F_pad/n
        feature-slot block — the reference's TRUE
        ``Network::ReduceScatter`` per-worker feature-block merge
        (data_parallel_tree_learner.cpp:284). Split finding runs on the
        local block only and winners merge SplitInfo-sized via
        ``_sync_best`` (SyncUpGlobalBestSplit). The per-leaf histogram
        cache is slot-sharded the same way, cutting its HBM footprint
        by n. EFB composes by unbundling the LOCAL histogram to feature
        space first (unbundling is linear, so it commutes with the
        scatter-sum); the cache then lives in scattered feature space.
    - "feature": rows replicated, split WORK feature-sharded
      (feature_parallel_tree_learner.cpp:38-77): each chip histograms
      only its ``local_bins`` [R, F_loc] slice (``local_meta`` = that
      slice's (num_bins_pf, nan_bin_pf, is_cat_pf, feature_mask,
      mono_type_pf-or-None); ``feat_offset`` = global id of local
      feature 0), then the winner is merged by gain-argmax across chips
      — SyncUpGlobalBestSplit (parallel_tree_learner.h:209) as a
      pmax/pmin pair + masked psum payload broadcast.
    - "voting": rows sharded, PV-Tree
      (voting_parallel_tree_learner.cpp:16-120): local histograms only;
      each chip votes its per-leaf top-``top_k`` features by local gain;
      votes are psum-merged; the global top-2k elected features' columns
      are gathered and psum'd (communication O(top_k·B), not O(F·B));
      the split is chosen from those global sub-histograms.
    """
    # 'auto' reaching here means a traced caller with no warm probe
    # cache — resolve_impl then answers conservatively (no mid-trace
    # probe); the eager wrapper above handles direct callers
    hist_impl = resolve_impl(hist_impl)
    # Row compaction redirects the bins stream through a gathered index
    # order. It pays off when the kernel's per-row cost dominates the
    # one-time [R, F] gather: the matmul one-hot (R*F*B bf16), the CPU
    # scatter, AND the Pallas kernel — its dynamic row bound (num_rows
    # scalar prefetch) skips whole row blocks past the compacted live
    # prefix, so the VMEM one-hot + MXU dot shrink with the small
    # child's row fraction (the dense_bin.hpp:105 data_indices saving,
    # VERDICT r4 #3). Only the native C kernel skips compaction: its
    # partition op already maintains exact per-leaf row lists, so a
    # cumsum + gather pass over R would cost more than it saves.
    hist_compact = hist_sub and hist_impl != "native"
    # native CPU backend: maintain the DataPartition analog — `perm`
    # holds row indices grouped by leaf (leaf_begin/leaf_cnt segments,
    # data_partition.hpp:116 Split semantics) as loop-carried state, so
    # the partition op touches only the split leaves' rows and the
    # histogram op walks exactly the requested children's rows (no scan
    # over R, no per-row branch). Bundled matrices decode bins in
    # feature space and keep the XLA formulation.
    if hist_impl == "native":
        # trace-time availability check; the call also compiles and
        # REGISTERS the FFI targets (build_histograms degrades to
        # scatter on its own when the toolchain is missing)
        from .. import native as _native
        if _native.hist_lib() is None:
            hist_impl = "scatter"
            hist_compact = hist_sub
    # sharded feature storage: no device holds the full matrix, so the
    # native CPU partition/relabel (which walk every column) cannot run
    use_native_part = (hist_impl == "native" and bundle_meta is None
                       and not feature_sharded)
    R = bins.shape[0]
    F = num_bins_pf.shape[0]   # per-FEATURE count (bins may be bundled)
    L = num_leaves
    W = max(1, min(leaf_batch, L - 1))
    MAXN = 2 * L - 1
    B = num_bins
    DUMMY_LEAF = L          # scatter sink for masked lanes
    DUMMY_NODE = MAXN
    BW = (B + 31) // 32     # cat bitset words

    f32 = jnp.float32

    # EFB (efb.py): bins is a [R, G] BUNDLED matrix; histograms are
    # built in bundle space (lattice G x bundle_bins) then gathered back
    # to per-feature space, with the most-frequent bin reconstructed via
    # FixHistogram accounting (dataset.cpp:1488 analog).
    use_bundle = bundle_meta is not None
    if use_bundle:
        b_gof, b_off, b_mfb = bundle_meta
        G = bins.shape[1]

        def unbundle(hg):
            # dtype-generic (f32 AND raw int32 quantized): every op here
            # is LINEAR in the histogram, so unbundling commutes with
            # cross-shard summation — the reduce-scatter merge unbundles
            # the LOCAL histogram first and scatters in feature space
            S = hg.shape[0]
            zero = jnp.zeros((), hg.dtype)
            hflat = hg.reshape(S, G * bundle_bins, HIST_CH)
            idx = (b_gof[:, None] * bundle_bins + b_off[:, None]
                   + jnp.arange(B, dtype=jnp.int32)[None, :])    # [F, B]
            bvalid = (jnp.arange(B, dtype=jnp.int32)[None, :]
                      < num_bins_pf[:, None])
            idx = jnp.clip(idx, 0, G * bundle_bins - 1)
            hf = jnp.take(hflat, idx.reshape(-1), axis=1).reshape(
                S, F, B, HIST_CH)
            hf = jnp.where(bvalid[None, :, :, None], hf, zero)
            totals = hg[:, 0, :, :].sum(axis=1)                  # [S, 3]
            mfb_oh = (jnp.arange(B, dtype=jnp.int32)[None, :]
                      == b_mfb[:, None])                         # [F, B]
            sum_all = hf.sum(axis=2)
            at_mfb = jnp.where(mfb_oh[None, :, :, None], hf,
                               zero).sum(axis=2)
            mfb_val = totals[:, None, :] - (sum_all - at_mfb)
            return jnp.where((mfb_oh & bvalid)[None, :, :, None],
                             mfb_val[:, :, None, :], hf)

        def feature_bin_of(bmat, feat):
            from ..efb import decode_feature_bins
            raw = row_feature_gather(bmat, jnp.take(b_gof, feat))
            return decode_feature_bins(
                raw, jnp.take(b_off, feat), jnp.take(num_bins_pf, feat),
                jnp.take(b_mfb, feat), xp=jnp)
    else:
        def feature_bin_of(bmat, feat):
            return row_feature_gather(bmat, feat)
    sp = split_params
    use_mono = mono_type_pf is not None
    # monotone_constraints_method=intermediate
    # (IntermediateLeafConstraints, monotone_constraints.hpp:516): on a
    # monotone split the children's output bounds tighten to the SIBLING's
    # output (not the midpoint), and the new outputs propagate to every
    # leaf whose region is adjacent along a monotone feature. The
    # reference finds those leaves with recursive Go{Up,Down} tree walks
    # approximated by the up-path's feature/threshold lists; here each
    # leaf carries its bin-space bounding box [box_lo, box_hi] and
    # adjacency is computed exactly and vectorized: two leaf boxes
    # interact along monotone dim q iff they are separated along q and
    # overlap in every other dim (disjoint boxes are separated along
    # exactly one dim in that case). Exact geometry constrains strictly
    # less than the reference's path approximation — same soundness,
    # more admissible splits. Stale best-split caches (the reference
    # recomputes them for `leaves_to_update_`) are instead handled by
    # clamping cached outputs into the leaf's CURRENT bounds at apply
    # time; cross-leaf propagation is only sound when splits apply one
    # at a time, so callers force leaf_batch=1 in this mode.
    use_mono_inter = use_mono and mono_method == "intermediate"
    # monotone_constraints_method=advanced ("precise" mode,
    # AdvancedLeafConstraints, monotone_constraints.hpp:858): constraints
    # become per-(feature, threshold) — a candidate split's LEFT child
    # only absorbs neighbors adjacent to the left SUB-box. The reference
    # maintains lazily-recomputed piecewise threshold segments per
    # feature; here the bounds are recomputed FRESH each round from the
    # live leaves' current outputs over the dense [slots, F, B] lattice
    # (exact box adjacency, same as intermediate, restricted per
    # candidate sub-box). Fresh recomputation subsumes the reference's
    # RecomputeConstraintsIfNeeded invalidation machinery.
    use_mono_adv = use_mono and mono_method == "advanced"
    if (use_mono_inter or use_mono_adv) and leaf_batch != 1:
        raise ValueError(
            "monotone_constraints_method=intermediate/advanced requires "
            "leaf_batch=1 (sequential split application)")
    use_boxes = use_mono_inter or use_mono_adv
    # forced splits (forcedsplits_filename; SerialTreeLearner::ForceSplits,
    # serial_tree_learner.cpp:636): the first n_forced rounds apply the
    # BFS-ordered forced list regardless of gain rank. Each entry is
    # (parent_index_in_list | -1 for root, is_right_child, feature,
    # threshold_bin). Slots resolve at RUNTIME from the parent's
    # recorded apply (left child keeps the parent's slot; right child is
    # the slot recorded when the parent actually applied), so a dropped
    # forced node (negative net gain, starved side, depth limit) drops
    # its whole subtree — the reference's forceSplitMap.erase semantics.
    # leaf_batch must be 1.
    use_forced = forced is not None and len(forced[0]) > 0
    if use_forced and leaf_batch != 1:
        raise ValueError("forced splits require leaf_batch=1")
    if use_forced:
        f_parent_a = jnp.asarray(forced[0], jnp.int32)
        f_isright_a = jnp.asarray(forced[1], bool)
        f_feats_a = jnp.asarray(forced[2], jnp.int32)
        f_thrs_a = jnp.asarray(forced[3], jnp.int32)
        # categorical forced nodes: one-hot on the category's bin;
        # thr=-1 marks an invalid (unseen) category the round must drop
        f_iscat_a = jnp.asarray(forced[4], bool)
        n_forced = len(forced[0])
    use_inter = interaction_groups is not None
    use_bynode = feature_fraction_bynode < 1.0
    use_rand = bool(sp.extra_trees)
    if (use_bynode or use_rand) and rng_key is None:
        raise ValueError("feature_fraction_bynode/extra_trees need rng_key")

    # CEGB (cost_effective_gradient_boosting.hpp): per-(leaf, feature)
    # gain penalties. cegb = (tradeoff, penalty_split, coupled[F]|None,
    # lazy[F]|None, feat_used0[F] bool, used_rows0[R, F] bool|None);
    # feat_used/used_rows persist ACROSS trees (model-level state) and
    # are returned updated.
    use_cegb = cegb is not None
    if use_cegb:
        (cegb_tradeoff, cegb_split, cegb_coupled, cegb_lazy,
         feat_used0, used_rows0) = cegb
        if axis_name is not None:
            raise NotImplementedError(
                "CEGB is single-device only (the reference ties it to "
                "the serial tree learner too)")

    mode = parallel_mode if axis_name is not None else "data"
    # reduce-scatter merge layouts (ISSUE 4): only meaningful on a mesh
    rs = (axis_name is not None and hist_merge == "reduce_scatter"
          and n_shards > 1)
    rs_data = rs and mode == "data"       # main hist feature-slot-sharded
    rs_vote = rs and mode == "voting"     # elected columns slot-sharded
    if rs_data and use_forced:
        # the forced-split gather reads a full-F histogram row from the
        # cache; callers (gbdt) route forced splits to allreduce
        raise ValueError(
            "forced splits need hist_merge=allreduce under "
            "tree_learner=data (full-feature histogram gather)")
    if rs_data and not use_bundle:
        # feature-slot shard geometry: F padded so it splits evenly;
        # pad features are trivial (1 bin, masked out), never selected
        F_pad_rs = -(-F // n_shards) * n_shards
        F_loc_rs = F_pad_rs // n_shards
        pf_rs = F_pad_rs - F
        nb_rs = jnp.pad(num_bins_pf, (0, pf_rs), constant_values=1)
        nan_rs = jnp.pad(nan_bin_pf, (0, pf_rs), constant_values=-1)
        cat_rs = jnp.pad(is_cat_pf, (0, pf_rs))
        mono_rs = (jnp.pad(mono_type_pf, (0, pf_rs))
                   if mono_type_pf is not None else None)
        csm_rs = (jnp.pad(cat_sorted_mask, (0, pf_rs))
                  if cat_sorted_mask is not None else None)
    elif rs_data:
        # EFB: the scatter slots along the BUNDLE axis (the storage
        # lattice the histogram is built in). Scattering unbundled
        # feature space instead would NOT be bit-stable: the
        # most-frequent-bin reconstruction (totals - sum of others) is
        # linear but reassociates under per-shard unbundling, and its
        # cancellation noise can flip near-tie splits. In bundle space
        # the scatter is elementwise-identical to the psum, each chip
        # owns whole bundles (= whole features; a feature never spans
        # bundles), and the cache stays raw/exact. Chips own
        # G_pad/n bundle columns; split finding masks to owned features.
        G_pad_rs = -(-G // n_shards) * n_shards
        G_loc_rs = G_pad_rs // n_shards

        def unbundle_shard(hg):
            """unbundle for this chip's [S, G_loc, bb, CH] scattered
            block of the MERGED bundle-space histogram -> [S, F, B, CH]
            feature space, zero outside the owned-feature set. Leaf
            totals (the mfb-reconstruction minuend) are computed by
            bundle 0's owner exactly as the replicated unbundle does —
            sum over the merged column's bins — and broadcast with a
            single-contributor psum, so every reconstructed value is
            bit-identical to the allreduce path's."""
            S = hg.shape[0]
            zero = jnp.zeros((), hg.dtype)
            gl0 = jax.lax.axis_index(axis_name) * jnp.int32(G_loc_rs)
            own = (b_gof >= gl0) & (b_gof < gl0 + G_loc_rs)      # [F]
            hflat = hg.reshape(S, G_loc_rs * bundle_bins, HIST_CH)
            gof_loc = jnp.clip(b_gof - gl0, 0, G_loc_rs - 1)
            idx = (gof_loc[:, None] * bundle_bins + b_off[:, None]
                   + jnp.arange(B, dtype=jnp.int32)[None, :])    # [F, B]
            bvalid = ((jnp.arange(B, dtype=jnp.int32)[None, :]
                       < num_bins_pf[:, None]) & own[:, None])
            idx = jnp.clip(idx, 0, G_loc_rs * bundle_bins - 1)
            hf = jnp.take(hflat, idx.reshape(-1), axis=1).reshape(
                S, F, B, HIST_CH)
            hf = jnp.where(bvalid[None, :, :, None], hf, zero)
            tot_loc = jnp.where(
                gl0 == 0, hg[:, 0, :, :].sum(axis=1),
                jnp.zeros((S, HIST_CH), hg.dtype))
            totals = jax.lax.psum(tot_loc, axis_name)            # [S, 3]
            mfb_oh = (jnp.arange(B, dtype=jnp.int32)[None, :]
                      == b_mfb[:, None])                         # [F, B]
            sum_all = hf.sum(axis=2)
            at_mfb = jnp.where(mfb_oh[None, :, :, None], hf,
                               zero).sum(axis=2)
            mfb_val = totals[:, None, :] - (sum_all - at_mfb)
            return jnp.where((mfb_oh & bvalid)[None, :, :, None],
                             mfb_val[:, :, None, :], hf)

        def rs_own_mask():
            """[F] bool — features whose bundle this chip owns."""
            gl0 = jax.lax.axis_index(axis_name) * jnp.int32(G_loc_rs)
            return (b_gof >= gl0) & (b_gof < gl0 + G_loc_rs)
    if use_bundle and mode == "feature":
        # internal invariant, not a user-facing limit: GBDT decodes the
        # bundled matrix to feature space before entering this mode
        # (Dataset.unbundled_bins), so bundle_meta never reaches here
        raise ValueError(
            "feature-parallel requires an unbundled bin matrix "
            "(caller must decode EFB storage first)")
    if mode == "feature":
        if local_bins is None or local_meta is None or feat_offset is None:
            raise ValueError(
                "feature-parallel needs local_bins/local_meta/feat_offset")
        (loc_nbpf, loc_nanpf, loc_catpf, loc_fmask, loc_mono) = local_meta
        F_loc = loc_nbpf.shape[0]
    if feature_sharded and mode != "feature":
        raise ValueError("feature_sharded requires parallel_mode='feature'")

    # Fused Pallas build+split (ISSUE 14): one VMEM-resident pass builds
    # a leaf batch's histograms AND runs the split-find epilogue on the
    # still-resident accumulator block, emitting only per-(leaf, chunk)
    # candidate records to HBM — the [F, B, 3] histogram round-trip
    # between the hist and split phases disappears. Gates (fall back to
    # histogram kernel + find_best_splits) are the lattice features the
    # epilogue can't express: sorted-subset categoricals, extra-trees
    # random thresholds, gain scale/penalty (feature_contri, CEGB),
    # advanced monotone bounds, forced-split gathers, every parallel /
    # EFB / feature-sharded plan (they need the full histogram for the
    # merge collective or subtraction), and unaligned chunk plans.
    use_smooth = split_params.path_smooth > 0.0
    pen_on = use_mono and split_params.monotone_penalty > 0.0
    use_fused = bool(
        fused_split and hist_impl == "pallas" and axis_name is None
        and not use_bundle and not use_rand and not use_cegb
        and not use_forced and not use_mono_adv
        and gain_scale is None and cat_sorted_mask is None
        and not feature_sharded
        and PH.fused_plan_ok(F, B, 2 * W) and PH.fused_plan_ok(F, B, W))

    # quantized training: histograms come back int32 (exact); descale to
    # (sum_g, sum_h, count) f32 once per build — the single-pass analog of
    # FindBestThresholdInt's per-bin descale (feature_histogram.hpp:177).
    # The [L, F, B, 3] result is tiny next to the R-sized matmul stream,
    # so all the int8 bandwidth win of the hot loop is kept.
    if quant_scales is not None:
        _dq_vec = jnp.concatenate(
            [quant_scales.astype(f32), jnp.ones((1,), f32)])

    def _dequant(h):
        if quant_scales is None:
            return h
        return h.astype(f32) * _dq_vec

    def hist_perm_for(slots, part, gh_in=None):
        """Histogram via the partition's ordered row lists (native CPU
        custom call): walks exactly the requested slots' segments."""
        mat = local_bins if mode == "feature" else bins
        nb_in = bundle_bins if use_bundle else B
        merge = mode not in ("feature", "voting")
        g = gh if gh_in is None else gh_in
        q = g.dtype == jnp.int8
        target = "lgbtpu_hist_perm_i8" if q else "lgbtpu_hist_perm_f32"
        S = slots.shape[0]
        out_sds = jax.ShapeDtypeStruct(
            (S, mat.shape[1], nb_in, HIST_CH),
            jnp.int32 if q else jnp.float32)
        bf16 = bool((not q) and jnp.dtype(hist_dtype) == jnp.bfloat16)
        h = _jax_ffi().ffi_call(target, out_sds)(
            mat, g, part[0], part[1], part[2], slots.astype(jnp.int32),
            bf16_round=bf16)
        if axis_name is not None:
            h = _pvary(h, axis_name)
            if merge:
                h = merge_histograms(
                    h, axis_name,
                    "reduce_scatter" if rs_data else True, n_shards)
        return h

    def hist_raw_for(slots, rl, gh_in=None, row_gather=None, num_rows=None,
                     part=None):
        """RAW histogram for the given leaf slots — before dequant and
        EFB unbundling, both of which are LINEAR, so parent-minus-child
        subtraction happens in this space (exactly, int32, when
        quantized). mode-specific shape/merge:
        - feature: [S, F_loc, B, 3], local feature slice, no collective;
        - voting: [S, F|G, B|bb, 3], LOCAL rows only (merge per elected
          feature later). EFB composes: unbundling locally commutes with
          the later psum of elected columns — votes and elections run in
          feature space, communication stays O(top_k * B);
        - data/serial, hist_merge=allreduce: [S, F|G, B|bb, 3],
          psum-merged over axis_name (replicated);
        - data, hist_merge=reduce_scatter: [S, (F|G)_pad/n, B|bb, 3] —
          this chip's slot block of the merged histogram, scattered
          along the STORAGE lattice's feature axis (bundle columns when
          EFB is on: a feature never spans bundles, so whole features
          stay chip-local and the raw cache stays exact)."""
        if use_native_part and part is not None:
            return hist_perm_for(slots, part, gh_in=gh_in)
        mat = local_bins if mode == "feature" else bins
        nb_in = bundle_bins if use_bundle else B
        if mode in ("feature", "voting"):
            merge = False
        elif rs_data:
            merge = "reduce_scatter"
        else:
            merge = True
        return build_histograms(
            mat, gh if gh_in is None else gh_in, rl, slots,
            num_bins=nb_in, block_rows=block_rows, axis_name=axis_name,
            merge=merge, n_shards=n_shards, hist_dtype=hist_dtype,
            impl=hist_impl, row_gather=row_gather, num_rows=num_rows)

    def hist_finish(hraw):
        """Raw -> per-feature f32 split-finding space. The scattered
        EFB layout unbundles this chip's bundle block (zeros outside
        the owned-feature set — split finding masks to owned)."""
        h = _dequant(hraw)
        if not use_bundle:
            return h
        return unbundle_shard(h) if rs_data else unbundle(h)

    def hist_for(slots, rl, part=None):
        return hist_finish(hist_raw_for(slots, rl, part=part))

    def _sync_best(bs):
        """Merge per-shard best splits by gain (SyncUpGlobalBestSplit).
        SplitInfo-sized (a handful of [S]-shaped collectives) — tagged
        ``winner_sync`` so the collective auditor (parallel/comms.py)
        separates it from histogram traffic."""
        from .. import profiler
        with profiler.phase("winner_sync"):
            return _sync_best_impl(bs)

    def _sync_best_impl(bs):
        gain = bs["gain"]
        gmax = jax.lax.pmax(gain, axis_name)
        idx = jax.lax.axis_index(axis_name)
        big = jnp.int32(1 << 30)
        mine = jnp.where((gain == gmax) & jnp.isfinite(gain), idx, big)
        win = jax.lax.pmin(mine, axis_name)
        is_win = idx == win
        def pick(v):
            m = is_win
            while m.ndim < v.ndim:
                m = m[..., None]
            if v.dtype == jnp.bool_:
                z = jnp.where(m, v, False).astype(jnp.int32)
                return jax.lax.psum(z, axis_name) > 0
            z = jnp.where(m, v, jnp.zeros_like(v))
            return jax.lax.psum(z, axis_name)
        out = {k: pick(v) for k, v in bs.items() if k != "gain"}
        out["gain"] = gmax
        return out

    nnb_pf = num_bins_pf - (nan_bin_pf >= 0).astype(jnp.int32)

    def slot_masks_and_bins(used_feat, slots_c, key):
        """Per-slot candidate features + extra-trees random thresholds."""
        S = slots_c.shape[0]
        fmask = jnp.broadcast_to(feature_mask[None, :], (S, F))
        if use_inter:
            used = jnp.take(used_feat, slots_c, axis=0)          # [S, F]
            # group ok iff no used feature outside it: used @ ~group == 0
            viol = used.astype(f32) @ (~interaction_groups).astype(f32).T
            allowed = ((viol == 0).astype(f32)
                       @ interaction_groups.astype(f32)) > 0     # [S, F]
            fmask = fmask & allowed
        if use_bynode:
            # GetCnt over the tree-sampled set, capped by the allowed set
            # (col_sampler.hpp:190-205)
            n_tree = feature_mask.sum().astype(f32)
            n_allow = fmask.sum(axis=1).astype(f32)              # [S]
            k = _round_int(n_tree * feature_fraction_bynode)
            k = jnp.minimum(jnp.maximum(k, 1.0), n_allow)
            k = jnp.maximum(k, jnp.minimum(1.0, n_allow)).astype(jnp.int32)
            u = jax.random.uniform(jax.random.fold_in(key, 1), (S, F))
            score = jnp.where(fmask, u, -1.0)
            kth = jnp.take_along_axis(
                -jnp.sort(-score, axis=1),
                jnp.maximum(k - 1, 0)[:, None], axis=1)
            fmask = fmask & (score >= kth)
        rand_bin = None
        if use_rand:
            u2 = jax.random.uniform(jax.random.fold_in(key, 2), (S, F))
            n_num = jnp.maximum(nnb_pf - 1, 1).astype(f32)       # thresholds
            n_cat = jnp.maximum(nnb_pf, 1).astype(f32)
            n_opt = jnp.where(is_cat_pf, n_cat, n_num)[None, :]
            rand_bin = jnp.floor(u2 * n_opt).astype(jnp.int32)
        return fmask, rand_bin

    def cegb_penalty_for(slots_c, rl, t, state):
        """[S, F] CEGB DeltaGain (cost_effective_gradient_boosting.hpp:
        80-98): split cost scaled by leaf size + one-time coupled
        feature cost + per-row lazy acquisition cost."""
        node_of = jnp.take(t.leaf2node, slots_c)
        n_leaf = jnp.take(t.node_count, node_of)              # [S]
        delta = (cegb_tradeoff * cegb_split * n_leaf)[:, None] \
            * jnp.ones((1, F), f32)
        if cegb_coupled is not None:
            delta = delta + cegb_tradeoff * jnp.where(
                state["cegb_feat_used"][None, :], 0.0,
                cegb_coupled[None, :])
        if cegb_lazy is not None:
            unused_cost = jnp.where(state["cegb_used_rows"], 0.0,
                                    cegb_lazy[None, :])          # [R, F]
            # dead/padded rows (rl < 0) route to the dummy segment L
            seg = jnp.where(rl < 0, L, rl)
            per_leaf = jax.ops.segment_sum(
                unused_cost, seg, num_segments=L + 1)
            delta = delta + cegb_tradeoff * jnp.take(
                per_leaf, jnp.clip(slots_c, 0, L), axis=0)
        return delta

    if use_mono_adv:
        _m_pos = mono_type_pf > 0
        _m_neg = mono_type_pf < 0

        def adv_bounds_for(slots_c, tree_now, box_lo, box_hi):
            """Fresh advanced-mode bounds for each slot's candidate
            children: ((lo_l, hi_l, lo_r, hi_r) [S, F, B], lo_s, hi_s
            [S]). A live leaf v constrains slot s along monotone dim d
            when their boxes are separated along exactly d; for a
            candidate split on q != d the constraint reaches a child
            only if v's q-range overlaps that child's q-range (the
            per-threshold-segment logic of UpdateConstraints,
            monotone_constraints.hpp:871-975, as one dense lattice).
            The scalar (lo_s, hi_s) are whole-leaf bounds for
            categorical candidates (no numeric partition)."""
            S = slots_c.shape[0]
            v_out = tree_now.leaf_values                    # [L+1]
            live = tree_now.leaf2node != DUMMY_NODE
            s_lo = jnp.take(box_lo, slots_c, axis=0)        # [S, F]
            s_hi = jnp.take(box_hi, slots_c, axis=0)
            ovl = ((box_lo[None] <= s_hi[:, None])
                   & (s_lo[:, None] <= box_hi[None]))       # [S, V, F]
            nno = (~ovl).sum(axis=2)
            selfm = (slots_c[:, None]
                     == jnp.arange(L + 1, dtype=jnp.int32)[None, :])
            base = (nno == 1) & live[None, :] & ~selfm      # [S, V]
            above = box_lo[None] > s_hi[:, None]
            below = box_hi[None] < s_lo[:, None]
            sep = base[:, :, None] & (~ovl)                 # sep along d
            hi_d = sep & ((above & _m_pos[None, None])
                          | (below & _m_neg[None, None]))
            lo_d = sep & ((below & _m_pos[None, None])
                          | (above & _m_neg[None, None]))
            t_io = jnp.arange(B, dtype=jnp.int32)
            cat_q = is_cat_pf[None, None, :, None]

            # The naive lattice is [S, V, F, B] (V = L+1): at 255
            # leaves x 128 features x 255 bins that is ~470M bools per
            # temporary. The V axis is purely a reduction, so it is
            # processed in chunks of Vc leaves with min/max carried
            # across chunks — peak memory S*Vc*F*B, identical results.
            V = L + 1
            Vc = max(1, min(V, (1 << 23) // max(1, S * F * B)))
            nch = (V + Vc - 1) // Vc
            Vp = nch * Vc
            pad = Vp - V

            def padV(a, fill):
                cfg = [(0, 0)] * a.ndim
                cfg[1] = (0, pad)
                return jnp.pad(a, cfg, constant_values=fill)

            # padded leaves carry no constraint (mask False)
            hi_dp = padV(hi_d, False)
            lo_dp = padV(lo_d, False)
            box_lo_p = jnp.pad(box_lo, ((0, pad), (0, 0)))
            box_hi_p = jnp.pad(box_hi, ((0, pad), (0, 0)))
            v_out_p = jnp.pad(v_out, (0, pad))

            def reduce_bounds(mask_d, kind, init):
                red_ax = jnp.min if kind == "min" else jnp.max
                red_el = jnp.minimum if kind == "min" else jnp.maximum
                cnt = mask_d.sum(axis=2)                    # [S, Vp]
                any_ex = ((cnt[:, :, None]
                           - mask_d.astype(cnt.dtype)) > 0)  # [S, Vp, F]

                def chunk(i, acc):
                    b_l0, b_r0, b_s0 = acc
                    md = jax.lax.dynamic_slice(
                        mask_d, (0, i * Vc, 0), (S, Vc, F))
                    ae = jax.lax.dynamic_slice(
                        any_ex, (0, i * Vc, 0), (S, Vc, F))
                    blo = jax.lax.dynamic_slice(
                        box_lo_p, (i * Vc, 0), (Vc, F))
                    bhi = jax.lax.dynamic_slice(
                        box_hi_p, (i * Vc, 0), (Vc, F))
                    vo = jax.lax.dynamic_slice(v_out_p, (i * Vc,), (Vc,))
                    l_ok = (blo[None, :, :, None] <= t_io) | cat_q
                    r_ok = (bhi[None, :, :, None] >= t_io + 1) | cat_q
                    m_l = md[:, :, :, None] | (ae[:, :, :, None] & l_ok)
                    m_r = md[:, :, :, None] | (ae[:, :, :, None] & r_ok)
                    vals = vo[None, :, None, None]
                    return (red_el(b_l0,
                                   red_ax(jnp.where(m_l, vals, init),
                                          axis=1)),
                            red_el(b_r0,
                                   red_ax(jnp.where(m_r, vals, init),
                                          axis=1)),
                            red_el(b_s0,
                                   red_ax(jnp.where(md.any(axis=2),
                                                    vo[None, :], init),
                                          axis=1)))

                init_l = jnp.full((S, F, B), init, f32)
                init_s = jnp.full((S,), init, f32)
                return jax.lax.fori_loop(
                    0, nch, chunk, (init_l, init_l, init_s))
            hi_l, hi_r, hi_s = reduce_bounds(hi_dp, "min", F32_MAX)
            lo_l, lo_r, lo_s = reduce_bounds(lo_dp, "max", -F32_MAX)
            return (lo_l, hi_l, lo_r, hi_r), lo_s, hi_s

    def best_for(hist2w, slot_depth, slot_valid, slots_c, t, state, key,
                 rl=None):
        lo = jnp.take(state["leaf_lo"], slots_c) if use_mono else None
        hi = jnp.take(state["leaf_hi"], slots_c) if use_mono else None
        adv = None
        if use_mono_adv:
            adv, lo, hi = adv_bounds_for(
                slots_c, t, state["box_lo"], state["box_hi"])
        node_of = jnp.take(t.leaf2node, slots_c)
        parent_out = jnp.take(t.node_value, node_of)
        fmask_s, rand_bin = slot_masks_and_bins(
            state.get("used_feat"), slots_c, key)
        gain_penalty = (cegb_penalty_for(slots_c, rl, t, state)
                        if use_cegb else None)
        if mode == "feature":
            # split search over this chip's feature slice only.
            # Interaction constraints / per-node sampling / extra-trees
            # compose by slicing the GLOBAL per-slot mask at this chip's
            # window: the constraint state and PRNG are replicated, so
            # every chip computes the identical global mask and takes
            # its block (the reference composes the same way via the
            # ColSampler living inside each templated learner,
            # tree_learner.cpp:15-57).
            S = slots_c.shape[0]
            fmask_loc = jax.lax.dynamic_slice(
                fmask_s, (0, feat_offset), (S, F_loc)) & loc_fmask[None, :]
            rand_loc = (jax.lax.dynamic_slice(
                rand_bin, (0, feat_offset), (S, F_loc))
                if rand_bin is not None else None)
            cs_loc = (jax.lax.dynamic_slice(
                cat_sorted_mask, (feat_offset,), (F_loc,))
                if cat_sorted_mask is not None else None)
            # advanced monotone composes the same replicated way: the
            # bounds lattice is computed over global F (box state and
            # tree are replicated) and sliced at this chip's window
            adv_loc = (tuple(jax.lax.dynamic_slice(
                a, (0, feat_offset, 0), (S, F_loc, a.shape[2]))
                for a in adv) if adv is not None else None)
            bs = find_best_splits(
                hist2w, loc_nbpf, loc_nanpf, loc_catpf, sp,
                feature_mask=fmask_loc, mono_type=loc_mono,
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth, rand_bin=rand_loc,
                cat_sorted_mask=cs_loc, adv_bounds=adv_loc)
            bs["feature"] = bs["feature"] + feat_offset
        elif mode == "voting":
            S = slots_c.shape[0]
            # 1. local candidate gains per (slot, feature)
            bs_loc = find_best_splits(
                hist2w, num_bins_pf, nan_bin_pf, is_cat_pf, sp,
                feature_mask=fmask_s, mono_type=mono_type_pf,
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth, rand_bin=rand_bin,
                cat_sorted_mask=cat_sorted_mask, adv_bounds=adv,
                return_feature_gain=True)
            fg = bs_loc["feature_gain"]                       # [S, F]
            k = min(top_k, F)
            k2 = min(2 * top_k, F)
            topg, topi = jax.lax.top_k(fg, k)
            # 2. vote: one ballot per locally-viable top-k feature
            votes = jnp.zeros((S, F), f32).at[
                jnp.arange(S)[:, None], topi].add(
                    (topg > NEG_INF).astype(f32))
            votes = jax.lax.psum(votes, axis_name)
            # 3. elect global top-2k (ties -> lower feature id)
            score = votes * (F + 1.0) - jnp.arange(F, dtype=f32)[None, :]
            _, elected = jax.lax.top_k(score, k2)             # [S, k2]
            # 4. merge ONLY the elected columns across chips. With
            # hist_merge=reduce_scatter the merge lands slot-SHARDED
            # (each chip receives its k2_pad/n elected-column block,
            # searches it, and the winner syncs SplitInfo-sized) —
            # closing the replicated-psum TODO of data_parallel.py:
            # wire bytes halve and the sub-split search stops being
            # n-redundant. Elections are replicated (votes psum'd), so
            # every chip slices consistently.
            sub_loc = jnp.take_along_axis(
                hist2w, elected[:, :, None, None], axis=1)    # [S,k2,...]
            if rs_vote:
                k2p = -(-k2 // n_shards) * n_shards
                k2_loc = k2p // n_shards
                pe = k2p - k2
                sub_hist = merge_histograms(
                    sub_loc, axis_name, "reduce_scatter", n_shards)
                off_v = (jax.lax.axis_index(axis_name)
                         * jnp.int32(k2_loc))
                # pad lane -> elected feature 0 with its mask forced
                # False (its scattered histogram block is zero anyway)
                elected = jax.lax.dynamic_slice(
                    jnp.pad(elected, ((0, 0), (0, pe))),
                    (jnp.int32(0), off_v), (S, k2_loc))
                lane_ok = jax.lax.dynamic_slice(
                    jnp.arange(k2p, dtype=jnp.int32) < k2,
                    (off_v,), (k2_loc,))[None, :]
            else:
                sub_hist = merge_histograms(sub_loc, axis_name, True)
                lane_ok = True
            sub_fmask = (jnp.take_along_axis(fmask_s, elected, axis=1)
                         if fmask_s.ndim == 2
                         else jnp.take(fmask_s, elected)) & lane_ok
            bs = find_best_splits(
                sub_hist, jnp.take(num_bins_pf, elected),
                jnp.take(nan_bin_pf, elected),
                jnp.take(is_cat_pf, elected), sp,
                feature_mask=sub_fmask,
                mono_type=(jnp.take(mono_type_pf, elected)
                           if use_mono else None),
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth,
                rand_bin=(jnp.take_along_axis(rand_bin, elected, axis=1)
                          if rand_bin is not None else None),
                # sorted-subset categoricals compose: the elected-column
                # metadata is per-slot [S, k2] and both finders
                # broadcast 2-D metadata
                cat_sorted_mask=(jnp.take(cat_sorted_mask, elected)
                                 if cat_sorted_mask is not None
                                 else None),
                # advanced monotone: gather the bounds lattice at the
                # elected columns ([S, F, B] -> [S, k2, B])
                adv_bounds=(tuple(jnp.take_along_axis(
                    a, elected[:, :, None], axis=1) for a in adv)
                    if adv is not None else None))
            bs["feature"] = jnp.take_along_axis(
                elected, bs["feature"][:, None], axis=1)[:, 0] \
                .astype(jnp.int32)
        elif rs_data and use_bundle:
            # scattered EFB shard: hist2w is already unbundled to FULL
            # feature space, zero outside this chip's owned-bundle
            # features — search all F columns with the ownership mask
            # (communication is the scattered bundle block; the search
            # itself is not divided because bundle->feature ownership
            # is not a contiguous slice), then merge winners.
            bs = find_best_splits(
                hist2w, num_bins_pf, nan_bin_pf, is_cat_pf, sp,
                feature_mask=fmask_s & rs_own_mask()[None, :],
                mono_type=mono_type_pf,
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth, rand_bin=rand_bin,
                cat_sorted_mask=cat_sorted_mask, adv_bounds=adv)
        elif rs_data:
            # scattered-shard split search (mode == "data",
            # hist_merge=reduce_scatter): hist2w is this chip's
            # [S, F_loc, B, 3] feature-slot block of the MERGED
            # histogram. Constraint masks and PRNG are replicated, so
            # the global [S, F] candidate mask is computed identically
            # everywhere and sliced at this chip's window — the same
            # composition rule the feature-parallel branch uses.
            S = slots_c.shape[0]
            off = jax.lax.axis_index(axis_name) * jnp.int32(F_loc_rs)
            z32 = jnp.int32(0)

            def _slice1(a):
                return jax.lax.dynamic_slice(a, (off,), (F_loc_rs,))

            def _slice2(a):
                return jax.lax.dynamic_slice(
                    jnp.pad(a, ((0, 0), (0, pf_rs))), (z32, off),
                    (S, F_loc_rs))
            bs = find_best_splits(
                hist2w, _slice1(nb_rs), _slice1(nan_rs),
                _slice1(cat_rs), sp,
                feature_mask=_slice2(fmask_s),
                mono_type=(_slice1(mono_rs) if use_mono else None),
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth,
                rand_bin=(_slice2(rand_bin)
                          if rand_bin is not None else None),
                cat_sorted_mask=(_slice1(csm_rs)
                                 if cat_sorted_mask is not None
                                 else None),
                adv_bounds=(tuple(jax.lax.dynamic_slice(
                    jnp.pad(a, ((0, 0), (0, pf_rs), (0, 0))),
                    (z32, off, z32), (S, F_loc_rs, a.shape[2]))
                    for a in adv) if adv is not None else None))
            bs["feature"] = bs["feature"] + off
        else:
            bs = find_best_splits(
                hist2w, num_bins_pf, nan_bin_pf, is_cat_pf, sp,
                feature_mask=fmask_s, mono_type=mono_type_pf,
                leaf_lo=lo, leaf_hi=hi, parent_output=parent_out,
                slot_depth=slot_depth, rand_bin=rand_bin,
                cat_sorted_mask=cat_sorted_mask,
                gain_scale=gain_scale, gain_penalty=gain_penalty,
                adv_bounds=adv)
        g = bs["gain"]
        if max_depth > 0:
            g = jnp.where(slot_depth < max_depth, g, NEG_INF)
        g = jnp.where(slot_valid, g, NEG_INF)
        bs["gain"] = g
        if mode == "feature" or rs_data or rs_vote:
            # feature-sharded search (by plan, or by the scattered
            # histogram layout): merge winners SplitInfo-sized
            bs = _sync_best(bs)
        return bs

    if use_fused:
        iw = jnp.arange(W, dtype=jnp.int32)

        def fused_call(slots, fmask_s, depth_s, lo, hi, po, rl,
                       gh_in=None, row_gather=None, num_rows=None,
                       emit_hist=False):
            """One fused launch over a leaf-slot lattice. Mirrors the
            metadata prep of best_for's serial arm; the kernel gates
            smoothing/monotone internally on params, so unused operands
            ride as zeros."""
            pen = (monotone_penalty_factor(depth_s, sp.monotone_penalty)
                   if pen_on else None)
            mat = (bins if row_gather is None
                   else jnp.take(bins, row_gather, axis=0))
            return PH.fused_build_best_splits(
                mat, gh if gh_in is None else gh_in, rl, slots,
                num_bins=B, params=sp, num_bins_pf=num_bins_pf,
                nan_bin_pf=nan_bin_pf, is_cat_pf=is_cat_pf,
                feature_mask=fmask_s, mono_type=mono_type_pf,
                leaf_lo=lo, leaf_hi=hi, parent_output=po, mono_pen=pen,
                quant_scales=quant_scales, hist_dtype=hist_dtype,
                num_rows=num_rows, emit_hist=emit_hist)

        def fused_children(st, t, row_leaf, sel_s, right_slot, valid,
                           slots2w, slots2w_c, depth2w, mid_state, keyr,
                           leaf_lo, leaf_hi):
            """Per-round children splits via the fused kernel. With the
            subtraction cache on, only the SMALLER child is streamed
            (fused, emitting its histogram for the cache); the sibling
            is parent-minus-child from the cache and scanned directly —
            the raw difference is already in split-finding space (f32
            serial; exact int32 + in-scan rescale when quantized). The
            per-slot masks are computed ONCE on the 2W lattice and
            sliced, so bynode/interaction draws match the legacy path
            bit-for-bit."""
            nsh = {}
            fmask2w, _ = slot_masks_and_bins(
                mid_state.get("used_feat"), slots2w_c, keyr)
            lo2w = jnp.take(leaf_lo, slots2w_c) if use_mono else None
            hi2w = jnp.take(leaf_hi, slots2w_c) if use_mono else None
            po2w = jnp.take(t.node_value, jnp.take(t.leaf2node, slots2w_c))
            if not hist_sub:
                bs, _ = fused_call(slots2w, fmask2w, depth2w, lo2w, hi2w,
                                   po2w, row_leaf, emit_hist=False)
                return bs, nsh
            rlc_n = jnp.where(row_leaf < 0, DUMMY_LEAF, row_leaf)
            raw_cnt = jax.ops.segment_sum(
                jnp.ones((R,), jnp.int32), rlc_n, num_segments=L + 1)
            l_raw = jnp.take(raw_cnt, jnp.clip(sel_s, 0, L))
            r_raw = jnp.take(raw_cnt, jnp.clip(right_slot, 0, L))
            small_is_left = l_raw <= r_raw
            small_slots = jnp.where(
                valid, jnp.where(small_is_left, sel_s, right_slot), -2)
            idx_small = jnp.where(small_is_left, iw, W + iw)
            idx_big = jnp.where(small_is_left, W + iw, iw)

            def _lane(a, idx):
                return None if a is None else jnp.take(a, idx, axis=0)

            # compacted small-child stream (same lut/cumsum pass as the
            # legacy hist_compact path)
            is_small = jnp.zeros((L + 2,), bool).at[
                jnp.clip(small_slots, -1, L) + 1].set(True) \
                .at[0].set(False)
            m = jnp.take(is_small, jnp.clip(row_leaf, -1, L) + 1)
            pos = jnp.cumsum(m.astype(jnp.int32)) - 1
            n_small = m.astype(jnp.int32).sum()
            c_idx = jnp.zeros((R,), jnp.int32).at[
                jnp.where(m, pos, R)].set(
                jnp.arange(R, dtype=jnp.int32), mode="drop")
            rl_c = jnp.where(
                jnp.arange(R, dtype=jnp.int32) < n_small,
                jnp.take(row_leaf, c_idx), -1)
            gh_c = jnp.take(gh, c_idx, axis=0)
            bs_s, hsmall = fused_call(
                small_slots, _lane(fmask2w, idx_small),
                _lane(depth2w, idx_small), _lane(lo2w, idx_small),
                _lane(hi2w, idx_small), _lane(po2w, idx_small),
                rl_c, gh_in=gh_c, row_gather=c_idx, num_rows=n_small,
                emit_hist=True)
            parent_raw = jnp.take(st["hist_cache"],
                                  jnp.clip(sel_s, 0, L), axis=0)
            hbig = parent_raw - hsmall
            sil = small_is_left.reshape((W,) + (1,) * (hsmall.ndim - 1))
            left_raw = jnp.where(sil, hsmall, hbig)
            right_raw = jnp.where(sil, hbig, hsmall)
            nsh["hist_cache"] = st["hist_cache"] \
                .at[jnp.where(valid, sel_s, DUMMY_LEAF)].set(left_raw) \
                .at[jnp.where(valid, right_slot, DUMMY_LEAF)] \
                .set(right_raw)
            bs_b = find_best_splits(
                hbig, num_bins_pf, nan_bin_pf, is_cat_pf, sp,
                feature_mask=_lane(fmask2w, idx_big),
                mono_type=mono_type_pf,
                leaf_lo=_lane(lo2w, idx_big),
                leaf_hi=_lane(hi2w, idx_big),
                parent_output=_lane(po2w, idx_big),
                slot_depth=_lane(depth2w, idx_big),
                quant_scales=quant_scales)

            def _mix(ks, kb):
                s_ = small_is_left.reshape((W,) + (1,) * (ks.ndim - 1))
                return jnp.concatenate([jnp.where(s_, ks, kb),
                                        jnp.where(s_, kb, ks)])
            bs = {k: _mix(bs_s[k], bs_b[k]) for k in bs_b}
            return bs, nsh

    # ---------------- state ----------------
    tree = TreeArrays(
        split_feature=jnp.full((MAXN + 1,), -1, jnp.int32),
        threshold_bin=jnp.zeros((MAXN + 1,), jnp.int32),
        default_left=jnp.zeros((MAXN + 1,), bool),
        is_cat=jnp.zeros((MAXN + 1,), bool),
        left_child=jnp.full((MAXN + 1,), -1, jnp.int32),
        right_child=jnp.full((MAXN + 1,), -1, jnp.int32),
        gain=jnp.zeros((MAXN + 1,), f32),
        node_value=jnp.zeros((MAXN + 1,), f32),
        node_count=jnp.zeros((MAXN + 1,), f32),
        node_hess=jnp.zeros((MAXN + 1,), f32),
        cat_bitset=jnp.zeros((MAXN + 1, BW), jnp.uint32),
        leaf2node=jnp.full((L + 1,), DUMMY_NODE, jnp.int32),
        leaf_values=jnp.zeros((L + 1,), f32),
        num_leaves=jnp.asarray(1, jnp.int32),
        num_nodes=jnp.asarray(1, jnp.int32),
    )
    tree = tree._replace(leaf2node=tree.leaf2node.at[0].set(0))

    # per-leaf best-split caches (best_split_per_leaf_ analog)
    bs_gain = jnp.full((L + 1,), NEG_INF, f32)
    bs_feat = jnp.zeros((L + 1,), jnp.int32)
    bs_thr = jnp.zeros((L + 1,), jnp.int32)
    bs_dl = jnp.zeros((L + 1,), bool)
    bs_cat = jnp.zeros((L + 1,), bool)
    bs_left = jnp.zeros((L + 1, HIST_CH), f32)
    bs_right = jnp.zeros((L + 1, HIST_CH), f32)
    bs_bits = jnp.zeros((L + 1, BW), jnp.uint32)
    bs_lout = jnp.zeros((L + 1,), f32)
    bs_rout = jnp.zeros((L + 1,), f32)
    leaf_depth = jnp.zeros((L + 1,), jnp.int32)

    state = dict(row_leaf=row_leaf0,
                 valid_row_leaf=tuple(valid_row_leaf0),
                 leaf_lo=jnp.full((L + 1,), -F32_MAX, f32),
                 leaf_hi=jnp.full((L + 1,), F32_MAX, f32),
                 r=jnp.asarray(0, jnp.int32))
    if use_forced:
        # per-forced-node runtime record: did it apply, at which slot,
        # and which slot its right child received
        state["f_ok"] = jnp.zeros((n_forced,), bool)
        state["f_slot_rec"] = jnp.zeros((n_forced,), jnp.int32)
        state["f_rslot"] = jnp.zeros((n_forced,), jnp.int32)
    if use_boxes:
        # inclusive bin-range box per leaf slot (feature space)
        state["box_lo"] = jnp.zeros((L + 1, F), jnp.int32)
        state["box_hi"] = jnp.full((L + 1, F), B - 1, jnp.int32)
    if use_inter:
        state["used_feat"] = jnp.zeros((L + 1, F), bool)
    if use_cegb:
        state["cegb_feat_used"] = feat_used0
        if cegb_lazy is not None:
            state["cegb_used_rows"] = used_rows0

    # ---------------- root ----------------
    part0 = None
    if use_native_part:
        # DataPartition init: live rows (all slot 0 at the root) first,
        # original order preserved; dead/padded rows trail unused
        live0 = row_leaf0 >= 0
        live_i = live0.astype(jnp.int32)
        n_live0 = live_i.sum()
        # stable live-first order WITHOUT a sort (XLA's 1M-row sort
        # costs ~95 ms on one core; this is three cheap passes)
        dest = jnp.where(live0, jnp.cumsum(live_i) - 1,
                         n_live0 + jnp.cumsum(1 - live_i) - 1)
        perm0 = jnp.zeros((R,), jnp.int32).at[dest].set(
            jnp.arange(R, dtype=jnp.int32))
        lb0 = jnp.zeros((L + 1,), jnp.int32)
        lc0 = jnp.zeros((L + 1,), jnp.int32).at[0].set(
            n_live0.astype(jnp.int32))
        if axis_name is not None:
            # the loop-carried partition state is per-shard (varying)
            perm0 = _pvary(perm0, axis_name)
            lb0 = _pvary(lb0, axis_name)
            lc0 = _pvary(lc0, axis_name)
        part0 = (perm0, lb0, lc0)
        state["perm"], state["leaf_begin"], state["leaf_cnt"] = part0
    root_slots = jnp.full((2 * W,), -2, jnp.int32).at[0].set(0)
    key0 = (jax.random.fold_in(rng_key, 0) if rng_key is not None else None)
    # path smoothing makes the root split depend on the root OUTPUT
    # (parent_output), which the fused single launch cannot know yet —
    # smooth roots keep the two-pass flow (the loop stays fused: there
    # the parent output is already in the tree)
    fused_root = use_fused and not use_smooth and root_hist is None
    bs0 = None
    if fused_root:
        # one VMEM-resident pass: root histogram (emitted only when the
        # subtraction cache needs seeding) AND its best split
        fmask0, _ = slot_masks_and_bins(state.get("used_feat"),
                                        root_slots.clip(0), key0)
        lo0 = (jnp.take(state["leaf_lo"], root_slots.clip(0))
               if use_mono else None)
        hi0 = (jnp.take(state["leaf_hi"], root_slots.clip(0))
               if use_mono else None)
        bs0, hraw0 = fused_call(
            root_slots, fmask0, jnp.zeros((2 * W,), jnp.int32), lo0, hi0,
            None, row_leaf0, emit_hist=hist_sub)
    elif root_hist is not None:
        # class-batched root dedupe (ISSUE 14 satellite): the K classes'
        # root histograms were built pre-vmap by ONE kernel streaming
        # the bins block once; non-root lattice slots are exact zeros in
        # both formulations (no row carries the -2 sentinel)
        hraw0 = jnp.zeros((2 * W,) + root_hist.shape,
                          root_hist.dtype).at[0].set(root_hist)
    else:
        hraw0 = hist_raw_for(root_slots, row_leaf0, part=part0)
    if fused_root and not hist_sub:
        # pure fused mode: the root histogram never exists — totals come
        # from the kernel's per-slot totals record (sum-then-rescale; in
        # float this can differ from the two-pass scale-then-sum in the
        # last bits, documented in the fused kernel contract)
        root_sums = bs0["slot_totals"][0]
    else:
        hist0 = hist_finish(hraw0)
        if hist_sub:
            # per-leaf RAW histogram cache (HistogramPool analog): slot i
            # holds leaf i's histogram as of its creation; rows of a leaf
            # only change when IT is split, so entries stay valid until
            # popped, when the entry is the subtraction minuend
            state["hist_cache"] = jnp.zeros(
                (L + 1,) + hraw0.shape[1:], hraw0.dtype).at[0].set(hraw0[0])
        root_sums = hist0[0, 0, :, :].sum(axis=0)   # all rows land in f0 bins
    if mode == "voting":
        # local hist -> global root sums (the Allreduce of root
        # (count, sum_g, sum_h), data_parallel_tree_learner.cpp:160-219)
        root_sums = jax.lax.psum(root_sums, axis_name)
    elif rs_data:
        # scattered layout: exactly ONE chip holds global feature 0's
        # merged column (chip 0 in the plain layout; the owner of
        # bundle b_gof[0] under EFB — hist0 is zero elsewhere), and its
        # bin sum is the global root totals. One [3]-sized psum
        # broadcasts the owner's value.
        if use_bundle:
            own0 = rs_own_mask()[0]
        else:
            own0 = jax.lax.axis_index(axis_name) == 0
        root_sums = jax.lax.psum(
            jnp.where(own0, root_sums, jnp.zeros_like(root_sums)),
            axis_name)
    root_val = leaf_output(root_sums[0], root_sums[1], sp.lambda_l1,
                           sp.lambda_l2, sp.max_delta_step)
    tree = tree._replace(
        node_value=tree.node_value.at[0].set(root_val),
        node_count=tree.node_count.at[0].set(root_sums[2]),
        node_hess=tree.node_hess.at[0].set(root_sums[1]),
        leaf_values=tree.leaf_values.at[0].set(root_val),
    )
    slot_valid0 = jnp.zeros((2 * W,), bool).at[0].set(True)
    if bs0 is None:
        bs0 = best_for(hist0, jnp.zeros((2 * W,), jnp.int32), slot_valid0,
                       root_slots.clip(0), tree, state, key0,
                       rl=row_leaf0)
    bs_gain = bs_gain.at[0].set(bs0["gain"][0])
    bs_feat = bs_feat.at[0].set(bs0["feature"][0])
    bs_thr = bs_thr.at[0].set(bs0["threshold"][0])
    bs_dl = bs_dl.at[0].set(bs0["default_left"][0])
    bs_cat = bs_cat.at[0].set(bs0["is_cat_split"][0])
    bs_left = bs_left.at[0].set(bs0["left_sum"][0])
    bs_right = bs_right.at[0].set(bs0["right_sum"][0])
    bs_bits = bs_bits.at[0].set(bs0["cat_bitset"][0])
    bs_lout = bs_lout.at[0].set(bs0["left_out"][0])
    bs_rout = bs_rout.at[0].set(bs0["right_out"][0])

    rounds_bound = max_rounds_for(L, W)

    state.update(tree=tree, bs_gain=bs_gain, bs_feat=bs_feat, bs_thr=bs_thr,
                 bs_dl=bs_dl, bs_cat=bs_cat, bs_left=bs_left,
                 bs_right=bs_right, bs_bits=bs_bits, bs_lout=bs_lout,
                 bs_rout=bs_rout, leaf_depth=leaf_depth)

    def cond(st):
        t = st["tree"]
        more_budget = t.num_leaves < L
        has_split = jnp.any(st["bs_gain"][:L] > NEG_INF)
        if use_forced:
            # forced rounds may proceed even when no cached candidate
            # is splittable (their gain check happens in-body)
            has_split = has_split | (st["r"] < n_forced)
        return (st["r"] < rounds_bound) & more_budget & has_split

    def body(st):
        t: TreeArrays = st["tree"]
        cur = t.num_leaves
        nodes = t.num_nodes
        # -- 1. pop top-W cached splits
        gains, sel = jax.lax.top_k(st["bs_gain"][:L], W)
        sel = sel.astype(jnp.int32)
        budget = L - cur
        valid = jnp.isfinite(gains) & (jnp.arange(W) < budget)
        n_valid = valid.sum().astype(jnp.int32)
        pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
        sel_s = jnp.where(valid, sel, DUMMY_LEAF)
        right_slot = jnp.where(valid, cur + pos, DUMMY_LEAF)
        ln = jnp.where(valid, nodes + 2 * pos, DUMMY_NODE)
        rn = jnp.where(valid, nodes + 2 * pos + 1, DUMMY_NODE)
        parent = jnp.where(valid, jnp.take(t.leaf2node, sel_s), DUMMY_NODE)

        sfeat = jnp.take(st["bs_feat"], sel_s)
        sthr = jnp.take(st["bs_thr"], sel_s)
        sdl = jnp.take(st["bs_dl"], sel_s)
        scat = jnp.take(st["bs_cat"], sel_s)
        sgain = jnp.take(st["bs_gain"], sel_s)
        slsum = jnp.take(st["bs_left"], sel_s, axis=0)
        srsum = jnp.take(st["bs_right"], sel_s, axis=0)
        sbits = jnp.take(st["bs_bits"], sel_s, axis=0)
        # constrained/smoothed outputs computed by the split finder
        # (SplitInfo::left_output/right_output analog)
        lval = jnp.take(st["bs_lout"], sel_s)
        rval = jnp.take(st["bs_rout"], sel_s)

        new_state_forced = {}
        if use_forced:
            # ForceSplits rounds: override lane 0 with the forced
            # candidate computed straight from the slot's histogram
            # (GatherInfoForThreshold analog; missing routes LEFT with
            # default_left=true, feature_histogram.hpp:588).
            # A dropped forced candidate falls back to this round's
            # normal top-gain pop and poisons its forced descendants.
            fr = jnp.clip(st["r"], 0, n_forced - 1)
            in_forced = st["r"] < n_forced
            pj = jnp.take(f_parent_a, fr)
            pjc = jnp.clip(pj, 0, n_forced - 1)
            parent_ok = jnp.where(pj < 0, True, jnp.take(st["f_ok"], pjc))
            f_slot = jnp.where(
                pj < 0, 0,
                jnp.where(jnp.take(f_isright_a, fr),
                          jnp.take(st["f_rslot"], pjc),
                          jnp.take(st["f_slot_rec"], pjc)))
            f_feat = jnp.take(f_feats_a, fr)
            f_thr = jnp.take(f_thrs_a, fr)
            if hist_sub:
                # the forced leaf's full histogram is already cached
                # (GatherInfoForThreshold reads the leaf's histogram;
                # the pool makes the re-histogram pass free)
                hist_fc0 = hist_finish(
                    st["hist_cache"][jnp.clip(f_slot, 0, L)][None])[0]
            else:
                fslots = jnp.full((2 * W,), -2, jnp.int32).at[0].set(f_slot)
                part_f = ((st["perm"], st["leaf_begin"], st["leaf_cnt"])
                          if use_native_part else None)
                hist_fc0 = jax.lax.cond(
                    in_forced,
                    lambda: hist_for(fslots, st["row_leaf"], part=part_f),
                    lambda: jnp.zeros((2 * W, F, B, HIST_CH),
                                      jnp.float32))[0]
            hrow = jnp.take(hist_fc0, f_feat, axis=0)         # [B, 3]
            f_cat = jnp.take(f_iscat_a, fr)
            nb_f = jnp.take(nan_bin_pf, f_feat)
            # GatherInfoForThresholdNumericalInner accumulates the RIGHT
            # side from the top bin down to threshold+1, SKIPPING the
            # NaN bin (feature_histogram.hpp:522-526 use_na_as_missing)
            # — so missing rows land LEFT and default_left=true below.
            # (MISSING_ZERO's zero bin stays an ordinary bin here, the
            # same treatment this implementation's regular split finder
            # gives it.)
            bval = (jnp.arange(B, dtype=jnp.int32)
                    != jnp.where(nb_f >= 0, nb_f, -1))
            cum = jnp.cumsum(jnp.where(bval[:, None], hrow, 0.0), axis=0)
            tot = hrow.sum(axis=0)
            nan_row = jnp.where(
                nb_f >= 0,
                jnp.take(hrow, jnp.clip(nb_f, 0, B - 1), axis=0),
                jnp.zeros((HIST_CH,), jnp.float32))
            lsum_num = (jnp.take(cum, jnp.clip(f_thr, 0, B - 1), axis=0)
                        + nan_row)
            # categorical: one-hot — left = the category's own bin only
            # (GatherInfoForThresholdCategoricalInner,
            # feature_histogram.hpp:604); thr=-1 (unseen category) is
            # rejected below in ok_f, matching the reference's
            # "Invalid categorical threshold" rejection (hpp:613)
            lsum_cat = jnp.take(hrow, jnp.clip(f_thr, 0, B - 1), axis=0)
            lsum = jnp.where(f_cat, lsum_cat, lsum_num)
            rsum = tot - lsum
            l1_, l2_ = sp.lambda_l1, sp.lambda_l2
            node_of_f = jnp.take(t.leaf2node,
                                 jnp.clip(f_slot, 0, L))
            po_f = jnp.take(t.node_value, node_of_f)
            sm_f = ({} if sp.path_smooth <= 0.0
                    else dict(path_smooth=sp.path_smooth,
                              parent_output=po_f))
            from ..ops.split import calc_output as _calc_out
            f_lout = _calc_out(lsum[0], lsum[1], l1_, l2_,
                               sp.max_delta_step,
                               count=lsum[2] if sm_f else None, **sm_f)
            f_rout = _calc_out(rsum[0], rsum[1], l1_, l2_,
                               sp.max_delta_step,
                               count=rsum[2] if sm_f else None, **sm_f)
            # NET gain: split - parent - min_gain_to_split, the same
            # shift GatherInfoForThreshold applies before the erase test
            f_gain = (leaf_gain(lsum[0], lsum[1], l1_, l2_)
                      + leaf_gain(rsum[0], rsum[1], l1_, l2_)
                      - leaf_gain(tot[0], tot[1], l1_, l2_)
                      - sp.min_gain_to_split)
            depth_f = jnp.take(st["leaf_depth"], jnp.clip(f_slot, 0, L))
            ok_f = (in_forced & parent_ok
                    & (~f_cat | (f_thr >= 0))   # unseen category: drop
                    & (lsum[2] >= sp.min_data_in_leaf)
                    & (rsum[2] >= sp.min_data_in_leaf)
                    & (lsum[1] >= sp.min_sum_hessian_in_leaf)
                    & (rsum[1] >= sp.min_sum_hessian_in_leaf)
                    & (f_gain > 0)   # strict: gain <= min_gain_shift
                                     # is rejected (hpp:562)
                    & ((max_depth <= 0) | (depth_f < max_depth))
                    & (jnp.take(t.leaf2node, f_slot) != DUMMY_NODE))
            new_state_forced = dict(
                f_ok=st["f_ok"].at[fr].set(
                    jnp.where(in_forced, ok_f, st["f_ok"][fr])),
                f_slot_rec=st["f_slot_rec"].at[fr].set(
                    jnp.where(in_forced, f_slot, st["f_slot_rec"][fr])),
                # with W=1 an applied split's right child gets slot `cur`
                f_rslot=st["f_rslot"].at[fr].set(
                    jnp.where(in_forced, cur, st["f_rslot"][fr])))

            def _ov(arr, new):
                return arr.at[0].set(jnp.where(ok_f, new, arr[0]))
            # re-derive the lane-0 selection chain under the override
            sel_s = _ov(sel_s, f_slot)
            valid = valid.at[0].set(ok_f | valid[0])
            n_valid = valid.sum().astype(jnp.int32)
            pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
            sel_s = jnp.where(valid, sel_s, DUMMY_LEAF)
            right_slot = jnp.where(valid, cur + pos, DUMMY_LEAF)
            ln = jnp.where(valid, nodes + 2 * pos, DUMMY_NODE)
            rn = jnp.where(valid, nodes + 2 * pos + 1, DUMMY_NODE)
            parent = jnp.where(valid, jnp.take(t.leaf2node, sel_s),
                               DUMMY_NODE)
            sfeat = _ov(sfeat, f_feat)
            sthr = _ov(sthr, f_thr)
            # numerical: missing left; categorical: default_left=false
            # (hpp:606) — cat routing is bitset membership anyway
            sdl = _ov(sdl, ~f_cat)
            scat = _ov(scat, f_cat)
            sgain = _ov(sgain, f_gain)
            slsum = slsum.at[0].set(jnp.where(ok_f, lsum, slsum[0]))
            srsum = srsum.at[0].set(jnp.where(ok_f, rsum, srsum[0]))
            # categorical LEFT subset = the single forced category bin
            f_bits = jnp.where(
                f_cat & (jnp.arange(BW, dtype=jnp.int32) == (f_thr >> 5)),
                jnp.uint32(1) << (f_thr & 31).astype(jnp.uint32),
                jnp.uint32(0))
            sbits = sbits.at[0].set(jnp.where(ok_f, f_bits, sbits[0]))
            lval = _ov(lval, f_lout)
            rval = _ov(rval, f_rout)

        if use_mono_inter:
            # stale-cache guard: neighbor propagation may have tightened
            # this leaf's bounds after its split was cached; clamp into
            # the CURRENT bounds (the reference instead recomputes best
            # splits for every leaf in `leaves_to_update_`)
            lo_s = jnp.take(st["leaf_lo"], sel_s)
            hi_s = jnp.take(st["leaf_hi"], sel_s)
            lval = jnp.clip(lval, lo_s, hi_s)
            rval = jnp.clip(rval, lo_s, hi_s)
        if use_mono_adv:
            # stale-cache guard, advanced form: recompute the bounds at
            # the WINNING (feature, threshold) against current outputs
            advw, lo_sw, hi_sw = adv_bounds_for(
                sel_s, t, st["box_lo"], st["box_hi"])

            def _at_win(a):
                af = jnp.take_along_axis(
                    a, sfeat[:, None, None], axis=1)[:, 0, :]
                return jnp.take_along_axis(af, sthr[:, None],
                                           axis=1)[:, 0]
            lo_lw = jnp.where(scat, lo_sw, _at_win(advw[0]))
            hi_lw = jnp.where(scat, hi_sw, _at_win(advw[1]))
            lo_rw = jnp.where(scat, lo_sw, _at_win(advw[2]))
            hi_rw = jnp.where(scat, hi_sw, _at_win(advw[3]))
            lval = jnp.clip(lval, lo_lw, hi_lw)
            rval = jnp.clip(rval, lo_rw, hi_rw)
            # re-impose the split feature's own direction if clamping
            # crossed the pair (conflicting fresh constraints; rare)
            mt_w = jnp.take(mono_type_pf, sfeat)
            lo_pair = jnp.minimum(lval, rval)
            hi_pair = jnp.maximum(lval, rval)
            lval = jnp.where(mt_w > 0, lo_pair,
                             jnp.where(mt_w < 0, hi_pair, lval))
            rval = jnp.where(mt_w > 0, hi_pair,
                             jnp.where(mt_w < 0, lo_pair, rval))

        # -- 2. record splits in node arrays
        t = t._replace(
            split_feature=t.split_feature.at[parent].set(sfeat),
            threshold_bin=t.threshold_bin.at[parent].set(sthr),
            default_left=t.default_left.at[parent].set(sdl),
            is_cat=t.is_cat.at[parent].set(scat),
            left_child=t.left_child.at[parent].set(ln),
            right_child=t.right_child.at[parent].set(rn),
            gain=t.gain.at[parent].set(sgain),
            node_value=t.node_value.at[ln].set(lval).at[rn].set(rval),
            node_count=t.node_count.at[ln].set(slsum[:, 2])
                                     .at[rn].set(srsum[:, 2]),
            node_hess=t.node_hess.at[ln].set(slsum[:, 1])
                                    .at[rn].set(srsum[:, 1]),
            cat_bitset=t.cat_bitset.at[parent].set(sbits),
            leaf2node=t.leaf2node.at[sel_s].set(ln).at[right_slot].set(rn),
            leaf_values=t.leaf_values.at[sel_s].set(lval)
                                     .at[right_slot].set(rval),
            num_leaves=cur + n_valid,
            num_nodes=nodes + 2 * n_valid,
        )
        new_depth = jnp.take(st["leaf_depth"], sel_s) + 1
        leaf_depth = st["leaf_depth"].at[sel_s].set(new_depth) \
                                     .at[right_slot].set(new_depth)

        # -- 2b. monotone bound propagation (BasicLeafConstraints::Update,
        # monotone_constraints.hpp:488-504): numerical splits on constrained
        # features tighten children's bounds around the output midpoint
        leaf_lo, leaf_hi = st["leaf_lo"], st["leaf_hi"]
        new_state_mono = {}
        if use_mono and not use_boxes:
            mid = (lval + rval) * 0.5
            mt_s = jnp.take(mono_type_pf, sfeat)
            upd = valid & (~scat) & (mt_s != 0)
            lo_p = jnp.take(leaf_lo, sel_s)
            hi_p = jnp.take(leaf_hi, sel_s)
            hi_l = jnp.where(upd & (mt_s > 0), jnp.minimum(hi_p, mid), hi_p)
            lo_l = jnp.where(upd & (mt_s < 0), jnp.maximum(lo_p, mid), lo_p)
            lo_r = jnp.where(upd & (mt_s > 0), jnp.maximum(lo_p, mid), lo_p)
            hi_r = jnp.where(upd & (mt_s < 0), jnp.minimum(hi_p, mid), hi_p)
            leaf_lo = leaf_lo.at[sel_s].set(lo_l).at[right_slot].set(lo_r) \
                             .at[DUMMY_LEAF].set(-F32_MAX)
            leaf_hi = leaf_hi.at[sel_s].set(hi_l).at[right_slot].set(hi_r) \
                             .at[DUMMY_LEAF].set(F32_MAX)
        if use_boxes:
            # maintain leaf boxes (shared by intermediate + advanced)
            box_lo, box_hi = st["box_lo"], st["box_hi"]
            num_upd = (valid & ~scat)[:, None]                   # [W, 1]
            par_lo = jnp.take(box_lo, sel_s, axis=0)             # [W, F]
            par_hi = jnp.take(box_hi, sel_s, axis=0)
            fone = jnp.arange(F, dtype=jnp.int32)[None, :] == sfeat[:, None]
            l_hi = jnp.where(fone & num_upd,
                             jnp.minimum(par_hi, sthr[:, None]), par_hi)
            r_lo = jnp.where(fone & num_upd,
                             jnp.maximum(par_lo, sthr[:, None] + 1), par_lo)
            box_lo = box_lo.at[sel_s].set(par_lo).at[right_slot].set(r_lo)
            box_hi = box_hi.at[sel_s].set(l_hi).at[right_slot].set(par_hi)
            box_lo = box_lo.at[DUMMY_LEAF].set(0)
            box_hi = box_hi.at[DUMMY_LEAF].set(B - 1)
            new_state_mono = dict(box_lo=box_lo, box_hi=box_hi)
        if use_mono_inter:
            # -- intermediate mode (module note above): push the new
            # outputs onto every adjacent leaf. The right child first
            # CLONES the parent's accumulated bounds
            # (entries_[new_leaf].reset(entries_[leaf]->clone()),
            # monotone_constraints.hpp:548) — its region is a subset of
            # the parent's, so every constraint on the parent applies.
            lo_p = jnp.take(leaf_lo, sel_s)
            hi_p = jnp.take(leaf_hi, sel_s)
            leaf_lo = leaf_lo.at[right_slot].set(lo_p)
            leaf_hi = leaf_hi.at[right_slot].set(hi_p)

            # neighbor updates (GoUp/GoDownToFindLeavesToUpdate analog,
            # monotone_constraints.hpp:624-805, exact-geometry form):
            # for new leaf u and any live leaf v separated along exactly
            # monotone dim q, v's output bound absorbs u's output.
            # Covers the sibling too (separated along the split feature),
            # which reproduces UpdateConstraintsWithOutputs (:545-558).
            u_slots = jnp.concatenate([sel_s, right_slot])       # [2W]
            u_out = jnp.concatenate([lval, rval])
            u_ok = jnp.concatenate([valid, valid])
            u_lo = jnp.take(box_lo, u_slots, axis=0)             # [2W, F]
            u_hi = jnp.take(box_hi, u_slots, axis=0)
            ovl = ((box_lo[None, :, :] <= u_hi[:, None, :])
                   & (u_lo[:, None, :] <= box_hi[None, :, :]))   # [2W,L+1,F]
            nno = jnp.sum(~ovl, axis=2)                          # [2W, L+1]
            above = box_lo[None, :, :] > u_hi[:, None, :]
            below = box_hi[None, :, :] < u_lo[:, None, :]
            m_pos = (mono_type_pf > 0)[None, None, :]
            m_neg = (mono_type_pf < 0)[None, None, :]
            live = jnp.take(t.leaf2node, jnp.arange(L + 1)) != DUMMY_NODE
            cond = ((nno == 1)[:, :, None] & (~ovl)
                    & u_ok[:, None, None] & live[None, :, None])
            raise_lo = (cond & ((above & m_pos) | (below & m_neg))) \
                .any(axis=2)                                     # [2W, L+1]
            drop_hi = (cond & ((below & m_pos) | (above & m_neg))) \
                .any(axis=2)
            leaf_lo = jnp.maximum(
                leaf_lo, jnp.where(raise_lo, u_out[:, None], -F32_MAX)
                .max(axis=0))
            leaf_hi = jnp.minimum(
                leaf_hi, jnp.where(drop_hi, u_out[:, None], F32_MAX)
                .min(axis=0))
            leaf_lo = leaf_lo.at[DUMMY_LEAF].set(-F32_MAX)
            leaf_hi = leaf_hi.at[DUMMY_LEAF].set(F32_MAX)

        # -- 2c. CEGB bookkeeping (UpdateLeafBestSplits): applied splits
        # mark their feature model-used (coupled) and their leaf's rows
        # feature-seen (lazy)
        new_state_extra = {}
        if use_cegb:
            fu = st["cegb_feat_used"]
            fbit_c = jnp.any((jnp.arange(F)[None, :] == sfeat[:, None])
                             & valid[:, None], axis=0)
            new_state_extra["cegb_feat_used"] = fu | fbit_c
        if use_inter:
            uf = st["used_feat"]
            parent_used = jnp.take(uf, sel_s, axis=0)            # [W, F]
            fbit = ((jnp.arange(F)[None, :] == sfeat[:, None])
                    & valid[:, None])
            new_used = parent_used | fbit
            uf = uf.at[sel_s].set(new_used).at[right_slot].set(new_used) \
                   .at[DUMMY_LEAF].set(False)
            new_state_extra["used_feat"] = uf

        # -- 3. vectorized partition update (DataPartition::Split analog)
        pend_active = jnp.zeros((L + 1,), bool).at[sel_s].set(valid) \
            .at[DUMMY_LEAF].set(False)
        pend_feat = jnp.zeros((L + 1,), jnp.int32).at[sel_s].set(sfeat)
        pend_thr = jnp.zeros((L + 1,), jnp.int32).at[sel_s].set(sthr)
        pend_dl = jnp.zeros((L + 1,), bool).at[sel_s].set(sdl)
        pend_cat = jnp.zeros((L + 1,), bool).at[sel_s].set(scat)
        pend_right = jnp.zeros((L + 1,), jnp.int32).at[sel_s].set(right_slot)
        pend_bits = jnp.zeros((L + 1, BW), jnp.uint32).at[sel_s].set(sbits)

        # native CPU path: the relabel runs as the lgbtpu_relabel custom
        # call — rows whose leaf is not splitting short-circuit after a
        # 4-byte read instead of streaming the full gather/select chain
        # (bundled matrices decode bins in feature space, so they keep
        # the XLA formulation)
        use_native_relabel = (hist_impl == "native" and not use_bundle
                              and not feature_sharded)

        def relabel(bmat, rl):
            # only VALID matrices reach the native relabel: the train
            # matrix goes through lgbtpu_partition whenever the native
            # backend is on (use_native_part == use_native_relabel)
            if use_native_relabel:
                # the matrix may be narrower than the padded per-feature
                # metadata (feature-parallel pads the TRAIN matrix's
                # feature axis; valid matrices stay unpadded)
                F_mat = bmat.shape[1]
                out = _jax_ffi().ffi_call(
                    "lgbtpu_relabel",
                    jax.ShapeDtypeStruct(rl.shape, jnp.int32))(
                    bmat, rl.astype(jnp.int32),
                    pend_active, pend_feat, pend_thr, pend_dl, pend_cat,
                    pend_right, pend_bits,
                    nan_bin_pf[:F_mat].astype(jnp.int32),
                    col_major=False)
                if axis_name is not None:
                    out = _pvary(out, axis_name)
                return out
            rlc = jnp.where(rl < 0, DUMMY_LEAF, rl)
            active = jnp.take(pend_active, rlc)
            feat = jnp.take(pend_feat, rlc)
            if feature_sharded:
                # each device holds only its [R, F_loc] column shard;
                # the split feature of a row's leaf is owned by exactly
                # ONE shard, so a masked local gather + psum over the
                # feature axis reconstructs the bin value everywhere
                # (one [R] int32 all-reduce per relabel — the sharded
                # analog of the reference's full-copy re-partition,
                # feature_parallel_tree_learner.cpp:77)
                F_m = bmat.shape[1]
                fl = feat - feat_offset
                owned = active & (fl >= 0) & (fl < F_m)
                bl = row_feature_gather(
                    bmat, jnp.clip(fl, 0, F_m - 1)).astype(jnp.int32)
                binv = jax.lax.psum(jnp.where(owned, bl, 0), axis_name)
            else:
                binv = feature_bin_of(bmat, feat)
            thr = jnp.take(pend_thr, rlc)
            nb = jnp.take(nan_bin_pf, feat)
            isnan = (binv == nb) & (nb >= 0)
            cat_row = jnp.take(pend_cat, rlc)
            # categorical: bitset membership (CategoricalDecision, tree.h)
            word = binv >> 5
            rbits = jnp.take(pend_bits, rlc, axis=0)             # [R, BW]
            wsel = jnp.arange(BW, dtype=jnp.int32)[None, :] == word[:, None]
            wval = jnp.sum(jnp.where(wsel, rbits, jnp.uint32(0)), axis=1)
            in_set = ((wval >> (binv & 31).astype(jnp.uint32))
                      & jnp.uint32(1)) == 1
            go_left = jnp.where(cat_row, in_set, binv <= thr)
            go_left = jnp.where(isnan & ~cat_row,
                                jnp.take(pend_dl, rlc), go_left)
            return jnp.where(active & ~go_left,
                             jnp.take(pend_right, rlc), rl)

        new_state_part = {}
        part_n = None
        if use_native_part:
            # DataPartition::Split as one custom call: stable in-place
            # partition of each split leaf's segment; only those rows
            # are touched (and only they change row_leaf)
            mat_p = bins if bins_cm is None else bins_cm
            outs = _jax_ffi().ffi_call(
                "lgbtpu_partition",
                (jax.ShapeDtypeStruct((R,), jnp.int32),
                 jax.ShapeDtypeStruct((R,), jnp.int32),
                 jax.ShapeDtypeStruct((L + 1,), jnp.int32),
                 jax.ShapeDtypeStruct((L + 1,), jnp.int32)),
                # donate the carry buffers: the handler partitions the
                # split segments in place instead of copying 2x[R]
                input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3})(
                mat_p, st["row_leaf"].astype(jnp.int32), st["perm"],
                st["leaf_begin"], st["leaf_cnt"], pend_active,
                pend_feat, pend_thr, pend_dl, pend_cat, pend_right,
                pend_bits, nan_bin_pf.astype(jnp.int32),
                col_major=bins_cm is not None)
            if axis_name is not None:
                outs = tuple(_pvary(o, axis_name) for o in outs)
            row_leaf, perm_n, lb_n, lc_n = outs
            part_n = (perm_n, lb_n, lc_n)
            new_state_part = dict(perm=perm_n, leaf_begin=lb_n,
                                  leaf_cnt=lc_n)
        else:
            row_leaf = relabel(bins, st["row_leaf"])
        valid_row_leaf = tuple(
            relabel(vb, vrl)
            for vb, vrl in zip(valid_bins, st["valid_row_leaf"]))

        if use_cegb and cegb_lazy is not None:
            # rows of split leaves have now "paid" for their feature
            rlc_pre = jnp.where(st["row_leaf"] < 0, DUMMY_LEAF,
                                st["row_leaf"])
            act_r = jnp.take(pend_active, rlc_pre)
            f_r = jnp.take(pend_feat, rlc_pre)
            ur = st["cegb_used_rows"]
            cur = ur[jnp.arange(R), f_r]
            new_state_extra["cegb_used_rows"] = ur.at[
                jnp.arange(R), f_r].set(cur | act_r)

        # -- 4. children histograms. hist_sub: the SMALLER child (by raw
        # row count — that is what bounds the stream) is histogrammed
        # directly over a compacted, dynamically-bounded row stream; the
        # sibling is parent minus child from the raw cache
        # (serial_tree_learner.cpp:567-592 Subtract). Otherwise both
        # children are histogrammed directly over all R rows.
        slots2w = jnp.concatenate([jnp.where(valid, sel_s, -2),
                                   jnp.where(valid, right_slot, -2)])
        new_state_hist = {}
        slots2w_c = jnp.where(slots2w >= 0, slots2w, DUMMY_LEAF)
        depth2w = jnp.take(leaf_depth,
                           jnp.concatenate([sel_s, right_slot]))
        keyr = (jax.random.fold_in(rng_key, st["r"] + 1)
                if rng_key is not None else None)
        mid_state = dict(leaf_lo=leaf_lo, leaf_hi=leaf_hi,
                         **new_state_extra, **new_state_mono)
        valid2w = jnp.concatenate([valid, valid])
        if use_fused:
            bs, nsh = fused_children(
                st, t, row_leaf, sel_s, right_slot, valid, slots2w,
                slots2w_c, depth2w, mid_state, keyr, leaf_lo, leaf_hi)
            new_state_hist.update(nsh)
            # same gain gating best_for applies after its lattice scan
            g = bs["gain"]
            if max_depth > 0:
                g = jnp.where(depth2w < max_depth, g, NEG_INF)
            bs["gain"] = jnp.where(valid2w, g, NEG_INF)
        elif hist_sub:
            if use_native_part:
                raw_cnt = lc_n          # partition maintains the counts
            else:
                rlc_n = jnp.where(row_leaf < 0, DUMMY_LEAF, row_leaf)
                raw_cnt = jax.ops.segment_sum(
                    jnp.ones((R,), jnp.int32), rlc_n, num_segments=L + 1)
            if axis_name is not None and mode != "feature":
                # replicate the small/big choice across row shards: in
                # data mode the psum inside hist_raw_for sums LOCAL
                # small-child histograms, so every shard must agree on
                # which child that is
                raw_cnt = jax.lax.psum(raw_cnt, axis_name)
            l_raw = jnp.take(raw_cnt, jnp.clip(sel_s, 0, L))
            r_raw = jnp.take(raw_cnt, jnp.clip(right_slot, 0, L))
            small_is_left = l_raw <= r_raw
            small_slots = jnp.where(
                valid, jnp.where(small_is_left, sel_s, right_slot), -2)
            if hist_compact:
                # membership via a [L+2] lut gather, not a [R, 2W]
                # broadcast compare (42x less traffic at W=21)
                is_small = jnp.zeros((L + 2,), bool).at[
                    jnp.clip(small_slots, -1, L) + 1].set(True) \
                    .at[0].set(False)           # -1/-2 sentinels
                m = jnp.take(is_small, jnp.clip(row_leaf, -1, L) + 1)
                pos = jnp.cumsum(m.astype(jnp.int32)) - 1
                n_small = m.astype(jnp.int32).sum()
                c_idx = jnp.zeros((R,), jnp.int32).at[
                    jnp.where(m, pos, R)].set(
                    jnp.arange(R, dtype=jnp.int32), mode="drop")
                rl_c = jnp.where(
                    jnp.arange(R, dtype=jnp.int32) < n_small,
                    jnp.take(row_leaf, c_idx), -1)
                gh_c = jnp.take(gh, c_idx, axis=0)
                hsmall = hist_raw_for(small_slots, rl_c, gh_in=gh_c,
                                      row_gather=c_idx,
                                      num_rows=n_small)
            else:
                # full masked stream (Pallas), or the partition's exact
                # row lists (native)
                hsmall = hist_raw_for(small_slots, row_leaf, part=part_n)
            parent_raw = jnp.take(st["hist_cache"],
                                  jnp.clip(sel_s, 0, L), axis=0)
            hbig = parent_raw - hsmall
            sil = small_is_left.reshape((W,) + (1,) * (hsmall.ndim - 1))
            left_raw = jnp.where(sil, hsmall, hbig)
            right_raw = jnp.where(sil, hbig, hsmall)
            new_state_hist["hist_cache"] = st["hist_cache"] \
                .at[jnp.where(valid, sel_s, DUMMY_LEAF)].set(left_raw) \
                .at[jnp.where(valid, right_slot, DUMMY_LEAF)] \
                .set(right_raw)
            hist2w = hist_finish(jnp.concatenate([left_raw, right_raw]))
        else:
            hist2w = hist_for(slots2w, row_leaf, part=part_n)
        if not use_fused:
            bs = best_for(hist2w, depth2w, valid2w,
                          slots2w_c, t, mid_state, keyr, rl=row_leaf)

        scatter_slots = slots2w_c
        bs_gain = st["bs_gain"].at[scatter_slots].set(bs["gain"]) \
                               .at[DUMMY_LEAF].set(NEG_INF)
        bs_feat = st["bs_feat"].at[scatter_slots].set(bs["feature"])
        bs_thr = st["bs_thr"].at[scatter_slots].set(bs["threshold"])
        bs_dl = st["bs_dl"].at[scatter_slots].set(bs["default_left"])
        bs_cat = st["bs_cat"].at[scatter_slots].set(bs["is_cat_split"])
        bs_left = st["bs_left"].at[scatter_slots].set(bs["left_sum"])
        bs_right = st["bs_right"].at[scatter_slots].set(bs["right_sum"])
        bs_bits = st["bs_bits"].at[scatter_slots].set(bs["cat_bitset"])
        bs_lout = st["bs_lout"].at[scatter_slots].set(bs["left_out"])
        bs_rout = st["bs_rout"].at[scatter_slots].set(bs["right_out"])

        out = dict(tree=t, row_leaf=row_leaf, valid_row_leaf=valid_row_leaf,
                   bs_gain=bs_gain, bs_feat=bs_feat, bs_thr=bs_thr,
                   bs_dl=bs_dl, bs_cat=bs_cat, bs_left=bs_left,
                   bs_right=bs_right, bs_bits=bs_bits, bs_lout=bs_lout,
                   bs_rout=bs_rout,
                   leaf_depth=leaf_depth, leaf_lo=leaf_lo, leaf_hi=leaf_hi,
                   r=st["r"] + 1, **new_state_extra, **new_state_mono,
                   **new_state_forced, **new_state_hist,
                   **new_state_part)
        return out

    state = jax.lax.while_loop(cond, body, state)
    if use_cegb:
        cegb_out = (state["cegb_feat_used"],
                    state.get("cegb_used_rows"))
        return (state["tree"], state["row_leaf"],
                state["valid_row_leaf"], cegb_out)
    return state["tree"], state["row_leaf"], state["valid_row_leaf"]


_build_tree_jit = functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "leaf_batch", "max_depth", "num_bins",
                     "split_params", "axis_name", "hist_dtype", "hist_impl",
                     "block_rows", "feature_fraction_bynode",
                     "parallel_mode", "top_k", "bundle_bins", "mono_method",
                     "forced", "hist_sub", "feature_sharded",
                     "hist_merge", "n_shards", "fused_split"))(
    _build_tree_impl)


def _build_tree_class_batched(bins, gh, row_leaf0, num_bins_pf,
                              nan_bin_pf, is_cat_pf, feature_mask, *,
                              rng_key=None, quant_scales=None,
                              forced=None, cegb=None,
                              hist_impl: str = "scatter", **kw):
    """Class-batched tree growth (ISSUE 8): all K per-class trees of one
    boosting iteration out of ONE staged program, by vmapping the
    leaf-wise core over the class axis.

    ``gh`` is [K, R, 3]; ``rng_key`` (when per-node sampling or
    extra-trees is on) is [K, 2] per-class keys — fold_in(it) then
    fold_in(k), the exact keys the sequential loop consumes; and
    ``quant_scales`` (quantized training) is [K, 2]. Everything else —
    the bin matrix, feature metadata, the root ``row_leaf0``, the valid
    sets — is identical across classes and rides unbatched, closed over
    by the vmapped function.

    Under vmap the class axis FUSES into the existing leaf-slot axis at
    every kernel instead of replaying the chain K times: the histogram
    one-hot/scatter/Pallas paths each lower to a single kernel whose
    slot dimension is K·S wide (the matmul becomes one dot_general with
    a K batch dim — one MXU dispatch per build round), ``lax.top_k``
    leaf selection, the partition relabel, and split finding batch
    elementwise, and the K per-class ``lax.while_loop``s collapse into
    ONE batched loop running max-over-classes rounds — a finished
    class's cond goes False and its carried state freezes, which is
    exactly the sequential fixed point (bit-parity verified in
    tests/test_class_batch.py). Data-parallel meshes compose: the
    histogram merge collective (psum / psum_scatter) and the
    ``_sync_best`` winner merge batch through their vmap rules with
    bytes-per-class unchanged.

    Returns (TreeArrays with a leading K on every field, row_leaf
    [K, R], valid_row_leafs tuple of [K, Rv] arrays).

    Not batchable here (callers gate these to the sequential path):
    forced splits and CEGB (cross-tree host state), and the native FFI
    kernels (no vmap rule over custom calls; ``build_tree`` remaps
    native -> scatter, which is bit-identical by the native parity
    tests).
    """
    if forced is not None:
        raise ValueError(
            "class-batched build does not support forced splits; use "
            "the sequential per-class path (class_batch=off)")
    if cegb is not None:
        raise ValueError(
            "class-batched build does not support CEGB; use the "
            "sequential per-class path (class_batch=off)")
    if hist_impl == "native":
        hist_impl = "scatter"

    # Class-batched root dedupe (ISSUE 14 satellite): vmapping the core
    # makes each class's ROOT histogram launch re-stream the bins block
    # — K reads of the widest operand for K identical one-hot encodings.
    # On the Pallas serial path, build the K root histograms pre-vmap
    # with ONE kernel whose MXU N-dim is the class axis (bins read once)
    # and hand each class its slice via the builder's ``root_hist``
    # seam. Gated to plans where the root build is a plain single-device
    # Pallas launch (no EFB bundling, no mesh merge, no feature shard).
    root_hist = None
    if (hist_impl == "pallas" and kw.get("axis_name") is None
            and kw.get("bundle_meta") is None
            and kw.get("local_bins") is None
            and not kw.get("feature_sharded", False)):
        root_hist = PH.build_root_histograms_classes(
            bins, gh, row_leaf0, num_bins=kw["num_bins"],
            hist_dtype=kw.get("hist_dtype", "bfloat16"))

    def one(gh_k, key_k, qs_k, rh_k):
        return _build_tree_impl(bins, gh_k, row_leaf0, num_bins_pf,
                                nan_bin_pf, is_cat_pf, feature_mask,
                                rng_key=key_k, quant_scales=qs_k,
                                hist_impl=hist_impl, root_hist=rh_k,
                                **kw)

    return jax.vmap(
        one, in_axes=(0,
                      None if rng_key is None else 0,
                      None if quant_scales is None else 0,
                      None if root_hist is None else 0))(
        gh, rng_key, quant_scales, root_hist)


_build_tree_cb_jit = functools.partial(
    jax.jit,
    static_argnames=("num_leaves", "leaf_batch", "max_depth", "num_bins",
                     "split_params", "axis_name", "hist_dtype", "hist_impl",
                     "block_rows", "feature_fraction_bynode",
                     "parallel_mode", "top_k", "bundle_bins", "mono_method",
                     "forced", "hist_sub", "feature_sharded",
                     "hist_merge", "n_shards", "fused_split"))(
    _build_tree_class_batched)
