"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

Analog of the reference ``src/boosting/dart.hpp`` (``DART`` :23):
per iteration a random subset of existing trees is "dropped" (their
contribution removed from the training scores before gradients are
computed), the new tree is trained at shrinkage lr/(1+k) (or the xgboost
variant lr/(lr+k)), and the dropped trees are rescaled by k/(k+1)
(resp. k/(lr+k)) so the ensemble stays normalized.

TPU mapping: the reference's ScoreUpdater::AddScore replays each dropped
tree over all rows on the CPU; here the replay is one jitted traversal of
the stored device tree over the binned matrix (ops/predict.py), and the
per-row prediction is cached for the restore step so each dropped tree is
traversed once per iteration.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..objectives import Objective
from .gbdt import GBDT

__all__ = ["DART"]


class DART(GBDT):
    keep_device_trees = True  # drop/restore replays stored trees

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[Objective],
                 valid_sets: Sequence[Dataset] = (), **kwargs):
        super().__init__(config, train_set, objective, valid_sets, **kwargs)
        if self.plan is not None and getattr(self.plan, "shard_storage",
                                             False):
            # drop/restore replays stored trees with predict_bins_value
            # over the device matrix; on a column-sharded matrix that
            # per-row gather would all-gather the full [R, F] onto every
            # chip — the exact OOM the sharded mode exists to avoid
            raise NotImplementedError(
                "boosting=dart is incompatible with feature_shard_storage"
                " (tree replay needs whole-matrix row gathers); use "
                "tree_learner=data for DART, or drop "
                "feature_shard_storage")
        self._rng_drop = np.random.RandomState(config.drop_seed)
        self._tree_weight: List[float] = []  # per-iteration weights
        self._sum_weight = 0.0
        self._dropped: Optional[tuple] = None  # (drop, preds) this iter

    # -- dart.hpp DroppingTrees ---------------------------------------
    def _select_drop(self) -> List[int]:
        cfg = self.config
        n = self.iter_
        drop: List[int] = []
        if self._rng_drop.rand() >= cfg.skip_drop and n > 0:
            drop_rate = cfg.drop_rate
            max_drop = cfg.max_drop if cfg.max_drop > 0 else np.inf
            if not cfg.uniform_drop:
                inv_avg = n / self._sum_weight
                if cfg.max_drop > 0:
                    drop_rate = min(
                        drop_rate,
                        cfg.max_drop * inv_avg / self._sum_weight)
                for i in range(n):
                    if self._rng_drop.rand() < \
                            drop_rate * self._tree_weight[i] * inv_avg:
                        drop.append(i)
                        if len(drop) >= max_drop:
                            break
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / n)
                for i in range(n):
                    if self._rng_drop.rand() < drop_rate:
                        drop.append(i)
                        if len(drop) >= max_drop:
                            break
        k = len(drop)
        if not cfg.xgboost_dart_mode:
            self.shrinkage = cfg.learning_rate / (1.0 + k)
        else:
            self.shrinkage = (cfg.learning_rate if k == 0 else
                              cfg.learning_rate / (cfg.learning_rate + k))
        return drop

    def _tree_preds(self, it: int):
        """Per-row unshrunk outputs of iteration `it`'s K trees on train
        and every valid set (each traversed once, cached per call)."""
        train = [self.predict_device_tree(it * self.K + k, -1)
                 for k in range(self.K)]
        valids = [[self.predict_device_tree(it * self.K + k, vi)
                   for k in range(self.K)]
                  for vi in range(len(self.valid_dd))]
        return train, valids

    def _ensure_dropped(self):
        """Drop once per iteration — triggered either by gradient
        computation (custom fobj path, mirroring dart.hpp
        GetTrainingScore/is_update_score_cur_iter_) or by train_one_iter."""
        if self._dropped is not None:
            return
        drop = self._select_drop()
        preds = {}
        for it in drop:
            preds[it] = self._tree_preds(it)
            w = self._tree_weight[it]
            tr, _ = preds[it]
            for ki in range(self.K):
                self.scores = self.scores.at[ki].add(-w * tr[ki])
        self._dropped = (drop, preds)

    def get_training_scores(self) -> np.ndarray:
        self._ensure_dropped()
        return super().get_training_scores()

    def train_one_iter(self, gradients=None, hessians=None, *,
                       defer: bool = False) -> bool:
        # defer is accepted for interface parity and ignored: DART's
        # drop/restore is per-iteration host work, so it always runs
        # the eager legacy loop
        cfg = self.config
        self._ensure_dropped()
        drop, preds = self._dropped
        self._dropped = None
        k = float(len(drop))

        stop = super().train_one_iter(gradients, hessians)
        if stop:
            # restore dropped contributions; iteration was a no-op
            for it in drop:
                w = self._tree_weight[it]
                tr, _ = preds[it]
                for ki in range(self.K):
                    self.scores = self.scores.at[ki].add(w * tr[ki])
            return True

        # normalize (dart.hpp Normalize)
        if k > 0:
            factor = (k / (k + 1.0) if not cfg.xgboost_dart_mode
                      else k / (k + cfg.learning_rate))
            for it in drop:
                w = self._tree_weight[it]
                new_w = w * factor
                tr, vas = preds[it]
                for ki in range(self.K):
                    mi = it * self.K + ki
                    # train: was fully dropped -> add back at new weight
                    self.scores = self.scores.at[ki].add(new_w * tr[ki])
                    for vi in range(len(self.valid_dd)):
                        self.valid_scores[vi] = self.valid_scores[vi] \
                            .at[ki].add(-(w - new_w) * vas[vi][ki])
                    # rescale the saved model + weight bookkeeping
                    self.models[mi].scale(factor)
                self._sum_weight -= w * (1.0 - factor)
                self._tree_weight[it] = new_w

        self._tree_weight.append(self.shrinkage)
        self._sum_weight += self.shrinkage
        return False
