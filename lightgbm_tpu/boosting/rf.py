"""Random forest mode.

Analog of the reference ``src/boosting/rf.hpp`` (``RF`` :25): no shrinkage,
bagging (or feature sampling) required, gradients computed ONCE from the
constant init score (no boosting), every tree carries the init-score bias
(AddBias), and the tracked score is the *running average* of tree outputs
(``MultiplyScore`` dance at rf.hpp:158-160) so metrics and prediction use
mean ensemble output (``average_output``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..objectives import Objective
from ..tree import Tree
from .gbdt import GBDT, kEpsilon

__all__ = ["RF"]


class RF(GBDT):
    average_output = True

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[Objective],
                 valid_sets: Sequence[Dataset] = (), **kwargs):
        if objective is None:
            raise ValueError("RF mode does not support custom objective "
                             "(rf.hpp Boosting check)")
        if config.data_sample_strategy == "bagging" and not (
                (config.bagging_freq > 0 and 0 < config.bagging_fraction < 1)
                or 0 < config.feature_fraction < 1):
            # rf.hpp Init: bagging strategy needs actual subsampling;
            # the goss strategy is accepted as-is (CHECK_EQ else-branch)
            raise ValueError(
                "RF needs bagging (bagging_freq > 0 and bagging_fraction "
                "< 1) or feature_fraction < 1 (rf.hpp Init check)")
        super().__init__(config, train_set, objective, valid_sets, **kwargs)
        self.shrinkage = 1.0
        if self.num_init_iteration > 0 and config.boost_from_average:
            # rf.hpp Boosting recomputes BoostFromAverage regardless of
            # num_init_iteration: continued-RF gradients are taken at the
            # label-average init score and new trees carry it as AddBias
            # (GBDT.__init__ zeroes _init_scores on the init_row_scores
            # path — that is the boosted-sum semantic, not RF's)
            self._init_scores = np.resize(np.asarray(
                self.objective.boost_from_score(),
                np.float64).reshape(-1), self.K)
        # constant gradients at the init score (rf.hpp Boosting): RF never
        # boosts, every tree fits the same residuals
        init = jnp.asarray(self._init_scores, jnp.float32)[:, None]
        tmp_scores = jnp.zeros_like(self.scores) + init
        if self.objective.num_model_per_iteration > 1:
            g, h = self.objective.get_gradients(
                tmp_scores.T, self.label_dev, self.weight_dev)
            self._g0, self._h0 = g.T, h.T
        else:
            g, h = self.objective.get_gradients(
                tmp_scores[0], self.label_dev, self.weight_dev)
            self._g0, self._h0 = g[None, :], h[None, :]
        # scores hold the running average of tree outputs, not a boosted
        # sum; start from zero (bias rides inside each tree). For continued
        # training the init_row_scores of an average_output base model are
        # already averages, so they stand as-is (rf.hpp Init MultiplyScore).
        if self.num_init_iteration == 0:
            self.scores = jnp.zeros_like(self.scores)
            self.valid_scores = [jnp.zeros_like(v)
                                 for v in self.valid_scores]

    def _grads(self, it: int):
        return self._g0, self._h0

    def train_one_iter(self, gradients=None, hessians=None, *,
                       defer: bool = False) -> bool:
        # defer accepted for interface parity, ignored (RF averages
        # scores with host-side iteration weights — eager loop only)
        if gradients is not None or hessians is not None:
            raise ValueError("RF mode does not support custom gradients")
        cfg = self.config
        g, h, count_mask = self._sampling(self.iter_, self._g0, self._h0)
        fmask = self._feature_mask()
        n = float(self.iter_ + self.num_init_iteration)
        for k in range(self.K):
            gh = jnp.stack([g[k], h[k], count_mask], axis=1)
            tree_arrays, row_leaf, valid_rls = self._build_one_tree(gh, fmask, k)
            host = jax.tree.map(np.asarray, tree_arrays)
            bias = float(self._init_scores[k])
            tree = Tree.from_device(host, self.train_set.bin_mappers,
                                    self.train_set.used_features, 1.0)
            grew = int(host.num_leaves) > 1
            # rf.hpp:148-176 — multi-leaf trees always carry the init bias
            # (AddBias); a no-split iteration stores the constant init tree
            # the FIRST time only, later no-split iterations store a zero
            # tree and leave the running average untouched
            add_bias = abs(bias) > kEpsilon and (grew or self.iter_ == 0)
            if add_bias:
                tree.leaf_value += bias
                tree.internal_value += bias
                tree_arrays = self._bias_adjust_device(tree_arrays, bias, 1.0)
            if grew or self.iter_ == 0:
                # running average with the global iteration count as
                # weight (rf.hpp:158-160 MultiplyScore(n) -> add ->
                # MultiplyScore(1/(n+1)))
                one = jnp.asarray(1.0, jnp.float32)
                new_tr = self._update_score_jit(
                    self.scores[k] * n, tree_arrays.leaf_values, row_leaf,
                    one)
                self.scores = self.scores.at[k].set(new_tr / (n + 1.0))
                for vi, vrl in enumerate(valid_rls):
                    new_va = self._update_score_jit(
                        self.valid_scores[vi][k] * n,
                        tree_arrays.leaf_values, vrl, one)
                    self.valid_scores[vi] = \
                        self.valid_scores[vi].at[k].set(new_va / (n + 1.0))
            self.models.append(tree)

        self.iter_ += 1
        return False  # RF never early-stops (rf.hpp TrainOneIter)

    def rollback_one_iter(self):
        """RF::RollbackOneIter (rf.hpp:184-203): scores are running
        AVERAGES, so undoing iteration n is Shrinkage(-1) +
        MultiplyScore(n) + AddScore + MultiplyScore(1/(n-1)), i.e.
        scores = (scores * n - tree_pred) / (n - 1) — NOT the boosted-sum
        subtraction GBDT does."""
        if self.iter_ <= 0:
            return
        n = float(self.iter_ + self.num_init_iteration)
        uf = self.train_set.used_features
        nan_bins = np.asarray(self.nan_bin_pf)
        bins_h = self._host_feature_bins(np.asarray(self.train_dd.bins))
        vbins_h = [self._host_feature_bins(np.asarray(dd.bins))
                   for dd in self.valid_dd]
        for k in range(self.K):
            tree = self.models[-(self.K - k)]
            pred = jnp.asarray(tree.predict_binned(bins_h, uf, nan_bins),
                               jnp.float32)
            if n > 1:
                new = (self.scores[k] * n - pred) / (n - 1.0)
            else:
                new = jnp.zeros_like(self.scores[k])
            self.scores = self.scores.at[k].set(new)
            for vi, vb in enumerate(vbins_h):
                vpred = jnp.asarray(tree.predict_binned(vb, uf, nan_bins),
                                    jnp.float32)
                if n > 1:
                    vnew = (self.valid_scores[vi][k] * n - vpred) / (n - 1.0)
                else:
                    vnew = jnp.zeros_like(self.valid_scores[vi][k])
                self.valid_scores[vi] = self.valid_scores[vi].at[k].set(vnew)
        for _ in range(self.K):
            self.models.pop()
        self.iter_ -= 1
