"""Device-time phase profiles from ``jax.profiler`` captures.

``collect_phase_totals`` (profiler.py) reports host wall-clock; the
roadmap's kernel work is judged on *device* time. This module parses
the trace-event JSON a capture leaves behind (``profiler.trace``, the
``/trace`` endpoint, ``jax.profiler.start_trace``) and attributes
device op time to the canonical phase set of ``phases.py``, yielding
numbers comparable across the fused/legacy drivers and serial/mesh
modes: per-phase device seconds, device-vs-host overlap, and dispatch
gaps per iteration.

Attribution runs three paths, in priority order, per device op event:

1. **Name prefix** — on TPU device tracks the op/``long_name`` carries
   the ``jax.named_scope`` path ("jit(f)/build/one_hot/dot_general"),
   so the first path component that is a canonical phase wins. This is
   the zero-setup path on real device timelines.
2. **Instruction map** — CPU (and some GPU) executor events carry only
   ``{hlo_module, hlo_op}`` args, no scope prefix. A *phase map* —
   ``{module_name: {instruction_name: phase}}`` built from the compiled
   module's ``op_name`` metadata (``costmodel.instruction_phase_map``)
   — recovers the phase. Captures taken through the telemetry server
   save it as ``phase_map.json`` next to the trace so offline
   ``monitor --perf`` gets fused-driver attribution for free.
3. **Host-span overlap** — the legacy driver dispatches one program per
   phase under a host ``TraceAnnotation`` span, so a device op's time
   is attributed to whichever host phase span(s) it overlaps.

Anything all three paths miss lands in the explicit ``unknown`` bucket
— attribution never silently drops device time.

Timestamps in trace-event JSON are microseconds. Device tracks are
*mostly* flat (one event per op execution), but the CPU runtime also
emits container events on the same threads — ``ThunkExecutor::
Execute`` wrapping a whole dispatch, ``while.N``/``call.N`` thunks
wrapping every body-op execution — so naive duration sums double-count
(a while loop's time lands once on the while event and again on its
276k body events). Each thread is therefore processed as a containment
stack: only *top-level* events count, events covered by an
already-counted ancestor are skipped, and pure runtime wrappers
(``ThunkExecutor``) are transparent — never counted themselves, their
children visible. Counting the ``while.N`` event rather than its body
ops also captures the loop's intra-body gaps, which is what makes the
phase sums comparable to wall-clock ``ms_per_tree``.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..phases import KNOWN_PHASES

__all__ = ["PhaseProfile", "parse_trace", "find_trace_files",
           "load_trace_events", "save_phase_map", "load_phase_map",
           "find_phase_map", "PHASE_MAP_NAME", "UNKNOWN"]

PHASE_MAP_NAME = "phase_map.json"
UNKNOWN = "unknown"

_STEP_NAME = "boost_iter"

Interval = Tuple[float, float]


# ----------------------------------------------------------------------
# Loading

def find_trace_files(source: str) -> List[str]:
    """Trace-event JSON files for a capture. ``source`` may be a trace
    file itself, a profiler log dir (``<dir>/plugins/profile/<ts>/
    <host>.trace.json.gz``), or a run dir holding several capture
    dirs — every ``*.trace.json[.gz]`` below it is returned (one per
    host; a multi-host capture merges)."""
    if os.path.isfile(source):
        return [source]
    if not os.path.isdir(source):
        return []
    hits: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits.extend(glob.glob(os.path.join(source, pat), recursive=True))
    return sorted(set(hits))


def load_trace_events(path: str) -> List[dict]:
    """The ``traceEvents`` list of one trace-event JSON file
    (gzipped or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        obj = json.load(f)
    if isinstance(obj, list):
        return obj
    return list(obj.get("traceEvents") or [])


def save_phase_map(log_dir: str, maps: Dict[str, Dict[str, str]]) -> str:
    """Write ``{module: {instruction: phase}}`` next to a capture so
    offline parsers attribute CPU/GPU executor events."""
    path = os.path.join(log_dir, PHASE_MAP_NAME)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(maps, f, sort_keys=True)
    return path


def load_phase_map(path: str) -> Dict[str, Dict[str, str]]:
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    return {str(m): {str(k): str(v) for k, v in (ops or {}).items()}
            for m, ops in (obj or {}).items()}


def find_phase_map(trace_file: str,
                   max_up: int = 4) -> Dict[str, Dict[str, str]]:
    """Walk up from a trace file looking for ``phase_map.json`` (the
    capture root is a few levels above ``plugins/profile/<ts>/``)."""
    d = os.path.dirname(os.path.abspath(trace_file))
    for _ in range(max_up):
        cand = os.path.join(d, PHASE_MAP_NAME)
        if os.path.isfile(cand):
            try:
                return load_phase_map(cand)
            except (OSError, ValueError):
                return {}
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return {}


# ----------------------------------------------------------------------
# Interval helpers (all in seconds)

def _union(intervals: List[Interval]) -> List[Interval]:
    if not intervals:
        return []
    out: List[Interval] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _total(intervals: List[Interval]) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(a: List[Interval], b: List[Interval]) -> List[Interval]:
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            out.append((s, e))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


# ----------------------------------------------------------------------
# Phase attribution

def phase_of_path(name: str) -> Optional[str]:
    """First canonical phase along a scope path: ``jit(f)/build/dot``
    → ``build``. Path components may carry trailing disambiguators
    (``build_1``, ``build.2``) which do not match — named_scope emits
    the raw phase string, so only exact components count."""
    for part in str(name).replace(":", "/").split("/"):
        if part in KNOWN_PHASES:
            return part
    return None


def _event_phase(name: str, args: Dict[str, Any],
                 phase_maps: Dict[str, Dict[str, str]]
                 ) -> Optional[str]:
    ph = phase_of_path(name)
    if ph is not None:
        return ph
    for key in ("long_name", "tf_op", "name"):
        v = args.get(key)
        if v:
            ph = phase_of_path(v)
            if ph is not None:
                return ph
    if phase_maps and ("hlo_op" in args or "hlo_module" in args):
        mod = str(args.get("hlo_module", ""))
        table = phase_maps.get(mod)
        if table is None and len(phase_maps) == 1:
            table = next(iter(phase_maps.values()))
        if table is not None:
            # executor events name the instruction either in args
            # (hlo_op) or as the event name itself
            for key in (args.get("hlo_op"), name):
                ph = table.get(str(key)) if key else None
                if ph in KNOWN_PHASES:
                    return ph
    return None


# ----------------------------------------------------------------------
# The profile

@dataclasses.dataclass
class PhaseProfile:
    """Parsed per-phase device/host time of one capture (or of several
    merged trace files)."""
    device_phase_s: Dict[str, float]           # merged across devices
    per_device: Dict[str, Dict[str, float]]    # device → phase → s
    host_phase_s: Dict[str, float]             # host TraceAnnotation
    device_busy_s: float      # union of device-busy time, summed/device
    host_phase_busy_s: float  # union of host phase spans
    overlap_s: float          # device busy ∩ host phase spans
    dispatch_gap_s: float     # device idle inside boost_iter windows
    steps: int                # boost_iter step markers in the capture
    step_span_s: float        # union of the step windows
    n_events: int
    sources: List[str]

    def iterations(self) -> int:
        return self.steps

    def device_s_per_iter(self,
                          iterations: Optional[int] = None
                          ) -> Dict[str, float]:
        """Per-phase device seconds per boost iteration (the number
        comparable to ``ms_per_tree``). Uses the capture's own
        ``boost_iter`` step count unless overridden."""
        it = int(iterations if iterations is not None else self.steps)
        if it <= 0:
            return {}
        return {k: v / it for k, v in self.device_phase_s.items()}

    def summary_dict(self) -> Dict[str, Any]:
        """JSON-ready summary (the ``/trace`` response body)."""
        d = {
            "device_phase_s": {k: round(v, 6) for k, v in
                               sorted(self.device_phase_s.items())},
            "host_phase_s": {k: round(v, 6) for k, v in
                             sorted(self.host_phase_s.items())},
            "devices": sorted(self.per_device),
            "device_busy_s": round(self.device_busy_s, 6),
            "overlap_s": round(self.overlap_s, 6),
            "dispatch_gap_s": round(self.dispatch_gap_s, 6),
            "steps": self.steps,
            "n_events": self.n_events,
        }
        per_iter = self.device_s_per_iter()
        if per_iter:
            d["device_s_per_iter"] = {k: round(v, 6)
                                      for k, v in sorted(per_iter.items())}
            d["dispatch_gap_s_per_iter"] = round(
                self.dispatch_gap_s / max(self.steps, 1), 6)
        return d

    def render(self) -> str:
        """Device-vs-host per-phase table for ``monitor --perf``."""
        rows = [f"devices: {', '.join(sorted(self.per_device)) or '-'}"
                f"  steps: {self.steps}  events: {self.n_events}"]
        names = sorted(set(self.device_phase_s) | set(self.host_phase_s))
        if names:
            rows.append(f"  {'phase':<12} {'device ms':>12} "
                        f"{'host ms':>12}"
                        + (f" {'device ms/iter':>16}" if self.steps
                           else ""))
            for name in names:
                dv = self.device_phase_s.get(name, 0.0) * 1e3
                hv = self.host_phase_s.get(name, 0.0) * 1e3
                line = f"  {name:<12} {dv:12.3f} {hv:12.3f}"
                if self.steps:
                    line += f" {dv / self.steps:16.4f}"
                rows.append(line)
        rows.append(f"  device busy {self.device_busy_s * 1e3:.3f} ms, "
                    f"host∩device overlap {self.overlap_s * 1e3:.3f} ms, "
                    f"dispatch gap {self.dispatch_gap_s * 1e3:.3f} ms"
                    + (f" ({self.dispatch_gap_s / self.steps * 1e3:.3f}"
                       " ms/iter)" if self.steps else ""))
        return "\n".join(rows)


def _is_wrapper(name: str) -> bool:
    """Pure runtime wrapper events: they cover whole dispatches on the
    same thread as the op events, carry no phase of their own, and
    would double-count everything beneath them."""
    return "ThunkExecutor" in name


def _is_device_thread(pname: str, tname: str) -> Optional[str]:
    """Device label for a (process, thread) track, or None for host
    tracks. TPU/GPU device processes are ``/device:TPU:0``-style; their
    step/module summary lines are excluded (op lines carry the time).
    On CPU there is no device process — the XLA executor threads
    (``tf_XLATfrtCpuClient...``) are the closest thing to a device
    timeline and merge into one ``cpu:0`` track."""
    low_t = tname.lower()
    if "/device:" in pname:
        if "step" in low_t or "module" in low_t:
            return None
        return pname.split("/device:", 1)[1] or pname
    if tname.startswith("tf_XLA") and "codegen" not in low_t \
            and "llvm" not in low_t:
        # the CPU runtime's executor + Eigen pool threads
        # (tf_XLATfrtCpuClient/..., tf_XLAEigen/...) — compile-time
        # codegen threads excluded
        return "cpu:0"
    return None


def parse_trace(source: str,
                phase_maps: Optional[Dict[str, Dict[str, str]]] = None
                ) -> PhaseProfile:
    """Parse one capture (file, log dir, or run dir — every trace file
    found under ``source`` merges into one profile). ``phase_maps``
    overrides the per-capture ``phase_map.json`` discovery."""
    files = find_trace_files(source)
    if not files:
        raise FileNotFoundError(f"no trace-event JSON under {source!r}")
    dev_phase: Dict[str, Dict[str, float]] = {}
    host_phase: Dict[str, float] = {}
    host_spans: List[Tuple[float, float, str]] = []
    dev_busy: Dict[str, List[Interval]] = {}
    pending: List[Tuple[str, float, float, float]] = []
    thread_evs: Dict[Tuple[str, Any, Any],
                     List[Tuple[float, float, str, Dict[str, Any]]]] = {}
    step_iv: List[Interval] = []
    step_count = 0
    n_events = 0

    for path in files:
        maps = phase_maps if phase_maps is not None \
            else find_phase_map(path)
        events = load_trace_events(path)
        procs: Dict[Any, str] = {}
        threads: Dict[Tuple[Any, Any], str] = {}
        for ev in events:
            if ev.get("ph") == "M":
                args = ev.get("args") or {}
                if ev.get("name") == "process_name":
                    procs[ev.get("pid")] = str(args.get("name", ""))
                elif ev.get("name") == "thread_name":
                    threads[(ev.get("pid"), ev.get("tid"))] = \
                        str(args.get("name", ""))
        for ev in events:
            if ev.get("ph") != "X":
                continue
            n_events += 1
            try:
                ts_us = float(ev["ts"])
                dur_us = float(ev.get("dur", 0.0))
            except (KeyError, TypeError, ValueError):
                continue
            ts, dur = ts_us / 1e6, dur_us / 1e6
            pname = procs.get(ev.get("pid"), "")
            tname = threads.get((ev.get("pid"), ev.get("tid")), "")
            name = str(ev.get("name", ""))
            args = ev.get("args") or {}
            dev = _is_device_thread(pname, tname)
            if dev is not None:
                dev_busy.setdefault(dev, []).append((ts, ts + dur))
                # containment below works on the RAW microsecond
                # values: integer-tick timestamps are exact there,
                # while seconds round — back-to-back ops would
                # float-drift into "covered by the previous op"
                thread_evs.setdefault(
                    (dev, ev.get("pid"), ev.get("tid")), []).append(
                        (ts_us, dur_us, name, args))
                continue
            # host track: canonical-phase TraceAnnotation spans and
            # boost_iter step markers
            if name in KNOWN_PHASES:
                host_phase[name] = host_phase.get(name, 0.0) + dur
                host_spans.append((ts, ts + dur, name))
            elif name == _STEP_NAME or name.startswith(_STEP_NAME):
                step_count += 1
                step_iv.append((ts, ts + dur))
        # Per-thread containment pass: sort by (start, -dur) so a
        # container sorts before the events it covers; an event under
        # an already-counted ancestor is skipped (its time is covered),
        # runtime wrappers are transparent.
        for (dev, _pid, _tid), evs in thread_evs.items():
            evs.sort(key=lambda t: (t[0], -t[1]))
            bucket = dev_phase.setdefault(dev, {})
            # (ts, dur, covered-by-children micros) per live wrapper —
            # the uncovered remainder is thunk-scheduling self-time,
            # real device time no op event accounts for
            wrappers: List[List[float]] = []
            stack: List[Tuple[float, bool, Optional[int]]] = []
            for ts_us, dur_us, name, args in evs:
                while stack and stack[-1][0] <= ts_us:
                    stack.pop()
                covered = any(counted for _, counted, _ in stack)
                if _is_wrapper(name):
                    widx: Optional[int] = None
                    if not covered:
                        for _, _, w in reversed(stack):
                            if w is not None:
                                # nested wrapper: its whole window is
                                # covered from the outer one's view
                                wrappers[w][2] += dur_us
                                break
                        widx = len(wrappers)
                        wrappers.append([ts_us, dur_us, 0.0])
                    stack.append((ts_us + dur_us, False, widx))
                    continue
                if covered:
                    stack.append((ts_us + dur_us, True, None))
                    continue
                for _, _, w in reversed(stack):
                    if w is not None:
                        wrappers[w][2] += dur_us
                        break
                stack.append((ts_us + dur_us, True, None))
                ph = _event_phase(name, args, maps or {})
                if ph is not None:
                    bucket[ph] = bucket.get(ph, 0.0) + dur_us / 1e6
                elif dur_us > 0:
                    pending.append((dev, ts_us / 1e6, dur_us / 1e6,
                                    dur_us / 1e6))
            for wts, wdur, wcov in wrappers:
                # A wrapper mostly covered by its own thread's ops is a
                # real execution window — its remainder is inter-thunk
                # scheduling time. One mostly empty on its own thread
                # is a dispatcher blocking on worker threads (the CPU
                # client thread waiting on the Eigen pool): counting
                # its time would double what the workers already
                # recorded, so it is dropped.
                self_us = wdur - wcov
                if wdur > 0 and wcov / wdur >= 0.5 and self_us > 1e-3:
                    pending.append((dev, wts / 1e6, wdur / 1e6,
                                    self_us / 1e6))
        thread_evs = {}

    # Path 3: host-span overlap for still-unattributed device events
    # (the legacy driver dispatches each phase inside its own host
    # span, so a device op's window picks its phase by time).
    spans_sorted = sorted(host_spans)
    for dev, ts, dur, self_dur in pending:
        end = ts + dur
        remaining = self_dur
        bucket = dev_phase.setdefault(dev, {})
        for s, e, ph in spans_sorted:
            if e <= ts:
                continue
            if s >= end or remaining <= 0:
                break
            ov = min(min(e, end) - max(s, ts), remaining)
            if ov > 0:
                bucket[ph] = bucket.get(ph, 0.0) + ov
                remaining -= ov
        if remaining > 1e-12:
            bucket[UNKNOWN] = bucket.get(UNKNOWN, 0.0) + remaining

    per_device = {d: dict(sorted(p.items()))
                  for d, p in sorted(dev_phase.items())}
    merged: Dict[str, float] = {}
    for p in per_device.values():
        for k, v in p.items():
            merged[k] = merged.get(k, 0.0) + v
    busy_unions = {d: _union(iv) for d, iv in dev_busy.items()}
    busy_total = sum(_total(u) for u in busy_unions.values())
    host_union = _union([(s, e) for s, e, _ in host_spans])
    all_busy = _union([iv for u in busy_unions.values() for iv in u])
    overlap = _total(_intersect(all_busy, host_union))
    steps_union = _union(step_iv)
    gap = max(_total(steps_union)
              - _total(_intersect(all_busy, steps_union)), 0.0)
    return PhaseProfile(
        device_phase_s=dict(sorted(merged.items())),
        per_device=per_device,
        host_phase_s=dict(sorted(host_phase.items())),
        device_busy_s=busy_total,
        host_phase_busy_s=_total(host_union),
        overlap_s=overlap,
        dispatch_gap_s=gap,
        steps=step_count,
        step_span_s=_total(steps_union),
        n_events=n_events,
        sources=files)
