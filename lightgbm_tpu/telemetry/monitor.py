"""``python -m lightgbm_tpu monitor <run_dir|events.jsonl>`` — render a
run-event log into a phase/throughput/faults report, or ``--check`` its
schema.

The offline half of the telemetry subsystem: the event log
(telemetry/events.py) is what a run leaves behind; this turns it back
into the operational picture — what the run was (header), how fast it
went (ms/tree trajectory, per-phase seconds from
``PhaseTotals.per_iteration``), and what went wrong (preemptions,
nan-guard trips, rollbacks, routed warnings). ``--check`` validates
every record against the schema table (``events.EVENT_TYPES``) and the
ordering invariants (monotone seq, no duplicate iteration records,
consistent header fingerprints) — the same self-check the chaos
harness applies to spliced resume logs.
"""

from __future__ import annotations

import argparse
import glob
import os
from typing import Any, Dict, List, Optional

from .events import check_records, read_events

__all__ = ["monitor_main", "find_event_logs", "render_report",
           "find_captures", "render_perf"]


def find_event_logs(target: str) -> List[str]:
    """A file is used as-is; a directory is scanned for
    ``*.events.jsonl`` (the ``event_log=auto`` naming) and
    ``events.jsonl``."""
    if os.path.isfile(target):
        return [target]
    if os.path.isdir(target):
        hits = sorted(glob.glob(os.path.join(target, "*.events.jsonl")))
        plain = os.path.join(target, "events.jsonl")
        if os.path.isfile(plain):
            hits.append(plain)
        return hits
    return []


def _topo_str(t: Any) -> str:
    """Compact one-line form of a checkpoint topology descriptor."""
    if not isinstance(t, dict):
        return str(t)
    merge = t.get("dp_hist_merge") or ""
    return (f"{t.get('tree_learner', '?')}x{t.get('num_shards', '?')}"
            + (f"/{merge}" if merge else "")
            + f" ({t.get('num_devices', '?')} dev)")


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} GiB"


def render_report(path: str, records: List[Dict[str, Any]]) -> str:
    out: List[str] = [f"== {path} ({len(records)} records) =="]
    headers = [r for r in records if r["event"] == "run_header"]
    iters = [r for r in records if r["event"] == "iteration"]
    if headers:
        h = headers[-1]
        ver = h.get("versions", {})
        out.append(
            f"run: {h.get('objective', '?')} driver={h.get('driver')} "
            f"mode={h.get('parallel_mode')}x{h.get('num_shards')} "
            f"class_batch={h.get('class_batch')} "
            f"eval_period={h.get('eval_period')}")
        out.append(
            f"fingerprint: {h.get('fingerprint')}  "
            f"(lightgbm_tpu {ver.get('lightgbm_tpu')}, "
            f"jax {ver.get('jax')})")
        if len(headers) > 1:
            out.append(f"segments: {len(headers)} "
                       "(resumed run, spliced log)")
    if iters:
        last = iters[-1]
        ms = [r.get("ms_per_tree", 0.0) for r in iters
              if r.get("ms_per_tree")]
        out.append(f"progress: {last.get('iter')} iterations over "
                   f"{len(iters)} eval points; ms/tree last="
                   f"{(ms[-1] if ms else 0):.2f} "
                   f"mean={(sum(ms) / len(ms) if ms else 0):.2f}")
        if last.get("metrics"):
            out.append("metrics @ last eval: " + "  ".join(
                f"{k}={v:.6g}" for k, v in
                sorted(last["metrics"].items())))
        # per-phase seconds: mean s_per_iter across eval points
        phases: Dict[str, List[float]] = {}
        for r in iters:
            for name, d in (r.get("phase_s") or {}).items():
                phases.setdefault(name, []).append(
                    float(d.get("s_per_iter", 0.0)))
        if phases:
            out.append("phase seconds/iter (mean over eval points):")
            for name in sorted(phases):
                vals = phases[name]
                out.append(f"  {name:<12} "
                           f"{sum(vals) / len(vals) * 1e3:9.2f} ms/iter")
    faults: List[str] = []
    for r in records:
        ev = r["event"]
        if ev == "preemption":
            faults.append(f"preemption (signal {r.get('signum')}) at "
                          f"iteration {r.get('iter')}")
        elif ev == "nan_guard":
            faults.append(f"nan_guard {r.get('action', '?')} at "
                          f"iteration {r.get('iter')}")
        elif ev == "checkpoint" and r.get("action") == "restore":
            faults.append(f"checkpoint restore to iteration "
                          f"{r.get('iter')}")
        elif ev == "checkpoint" and r.get("ok") is False:
            faults.append(f"checkpoint {r.get('action', 'write')} "
                          f"FAILED at iteration {r.get('iter')} "
                          "(run continued)")
        elif ev == "resume":
            faults.append(f"resumed at iteration {r.get('iter')} from "
                          f"{os.path.basename(str(r.get('path')))}")
        elif ev == "reshard":
            faults.append(
                f"resharded at iteration {r.get('iter')}: "
                f"{_topo_str(r.get('from'))} -> "
                f"{_topo_str(r.get('to'))}")
        elif ev == "degraded":
            faults.append(
                f"device loss at iteration {r.get('iter')}: "
                f"{r.get('action')} (attempt {r.get('attempt')})")
        elif ev == "log" and r.get("level") == "warning":
            faults.append(f"warning: {str(r.get('msg'))[:90]}")
    writes = sum(1 for r in records if r["event"] == "checkpoint"
                 and r.get("action") == "write")
    out.append(f"checkpoints: {writes} written")
    out.append("faults: " + (f"{len(faults)}" if faults else "none"))
    out.extend(f"  - {f}" for f in faults)
    ends = [r for r in records if r["event"] == "train_end"]
    if ends:
        e = ends[-1]
        out.append(f"ended: iteration {e.get('iter')}, "
                   f"{e.get('trees')} trees, "
                   f"wall {e.get('wall_s'):.1f}s")
    else:
        out.append("ended: NO train_end record (run killed or still "
                   "running)")
    return "\n".join(out)


def find_captures(target: str) -> List[str]:
    """Profiler capture dirs associated with a run: ``<run_dir>/traces/
    capture_NNNN`` (where the telemetry server lands them), or
    ``target`` itself when it directly holds ``capture_*`` dirs or is a
    single capture dir."""
    if not os.path.isdir(target):
        return []
    for root in (os.path.join(target, "traces"), target):
        caps = sorted(glob.glob(os.path.join(root, "capture_*")))
        caps = [c for c in caps if os.path.isdir(c)]
        if caps:
            return caps
    # a capture dir itself (holds plugins/profile/... trace files)
    if glob.glob(os.path.join(target, "**", "*.trace.json*"),
                 recursive=True):
        return [target]
    return []


def render_perf(capture: str,
                records: Optional[List[Dict[str, Any]]] = None) -> str:
    """``monitor --perf``: device-vs-host phase table of one capture
    (``xprof.parse_trace`` with the saved ``phase_map.json``), crossed
    against the event log's measured ms/tree when one is available.

    The comparison target is the log's UNPROFILED steady-state ms/tree
    — on CPU the per-event tracing tax inflates the profiled wall
    clock, so the capture's own step span is not an honest baseline."""
    from . import xprof
    out: List[str] = [f"-- capture {capture} --"]
    try:
        prof = xprof.parse_trace(capture)
    except (FileNotFoundError, ValueError) as e:
        return "\n".join(out + [f"  unparseable: {e}"])
    out.append(prof.render())
    dev_iter = prof.device_s_per_iter()
    fused_ms = sum(v for k, v in dev_iter.items()) * 1e3
    ms = [r.get("ms_per_tree", 0.0) for r in (records or [])
          if r.get("event") == "iteration" and r.get("ms_per_tree")]
    if fused_ms > 0 and ms:
        mean_ms = sum(ms) / len(ms)
        out.append(
            f"  phase device sum {fused_ms:.2f} ms/iter vs event-log "
            f"ms/tree mean {mean_ms:.2f} "
            f"(ratio {fused_ms / mean_ms:.3f}; <1 means host-side "
            "time the device never saw, >1 means tracing overhead "
            "landed inside op windows)")
    elif fused_ms > 0:
        out.append(f"  phase device sum {fused_ms:.2f} ms/iter "
                   "(no event log to compare against)")
    return "\n".join(out)


def monitor_main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu monitor",
        description="Render a telemetry event log into a "
                    "phase/throughput/faults report.")
    ap.add_argument("target", nargs="?", default=".",
                    help="run directory or events.jsonl file "
                         "(default: cwd)")
    ap.add_argument("--check", action="store_true",
                    help="events-schema self-check: validate every "
                         "record and the ordering invariants; rc=1 on "
                         "any problem")
    ap.add_argument("--perf", action="store_true",
                    help="parse the run's profiler captures "
                         "(<run_dir>/traces/capture_*) into "
                         "device-vs-host phase tables and compare the "
                         "fused phase sum against the event log's "
                         "measured ms/tree")
    ns = ap.parse_args(argv)
    paths = find_event_logs(ns.target)
    if ns.perf:
        captures = find_captures(ns.target if os.path.isdir(ns.target)
                                 else os.path.dirname(ns.target) or ".")
        if not captures and not paths:
            print(f"no captures or event logs under {ns.target!r} "
                  "(looked for traces/capture_* and *.events.jsonl)")
            return 1
        records: List[Dict[str, Any]] = []
        for path in paths:
            try:
                records.extend(read_events(path))
            except ValueError:
                pass  # --perf only borrows ms/tree; --check owns schema
        if not captures:
            print(f"no profiler captures under {ns.target!r} — "
                  "capture one via GET /trace?duration_ms=... or "
                  "profiler.trace()")
            return 1
        for cap in captures:
            print(render_perf(cap, records))
            print()
        return 0
    if not paths:
        print(f"no event logs found under {ns.target!r} "
              "(looked for *.events.jsonl / events.jsonl)")
        return 1
    rc = 0
    for path in paths:
        try:
            records = read_events(path)
        except ValueError as e:
            print(f"{path}: CORRUPT — {e}")
            rc = 1
            continue
        if ns.check:
            problems = check_records(records)
            if problems:
                rc = 1
                print(f"{path}: {len(problems)} problem(s)")
                for p in problems:
                    print(f"  - {p}")
            else:
                print(f"{path}: OK ({len(records)} records)")
        else:
            print(render_report(path, records))
            print()
    return rc
