"""Opt-in live introspection during training.

A long TPU training run is a black box between eval points; this module
makes it a server. ``engine.train`` starts one when ``telemetry_port``
is set (param or ``LIGHTGBM_TPU_TELEMETRY_PORT``; port 0 picks a free
port), serving:

- ``GET /metrics``  — Prometheus text render of the run's registry
  (training counters + device gauges; serving mounts its families the
  same way on its own server).
- ``GET /events?n=`` — tail of the run-event log as JSONL.
- ``GET /healthz``  — run liveness: current iteration, trees, state.
- ``GET /trace?duration_ms=`` — on-demand ``jax.profiler`` capture of
  the next N ms. The response carries the parsed per-phase device/host
  summary (``xprof.parse_trace``) plus the capture dir for
  ``tensorboard --logdir`` / Perfetto / ``monitor --perf``. Captures
  land as numbered ``capture_NNNN`` dirs under one tracked root with
  keep-last-N retention (older captures pruned, nothing leaks), the
  session's instruction→phase map is saved alongside as
  ``phase_map.json``, and a failed ``stop_trace`` returns a 500 error
  body — never a 200 naming a dangling dir. One capture at a time.
- ``SIGUSR1`` — dump the metrics snapshot + phase totals through
  ``log.info`` (the kill -USR1 runbook for a run with no port open).

Stdlib-only, same ThreadingHTTPServer shape as ``serving/server.py``.
Scrapes read host-side state exclusively (counters, gauges, the event
log file) — a scrape can never add a device sync to the training loop.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .core import MetricsRegistry
from .events import EventLog

__all__ = ["IntrospectionServer", "CaptureError", "install_sigusr1"]

_MAX_TRACE_MS = 60_000


class CaptureError(RuntimeError):
    """A profiler capture failed AFTER starting (stop_trace raised) —
    distinct from the 409 capture-already-running RuntimeError so the
    handler can answer 500 with the failure instead of a dangling
    log_dir."""


class IntrospectionServer:
    """Background HTTP server over one registry + event log."""

    def __init__(self, registry: MetricsRegistry,
                 event_log: Optional[EventLog] = None,
                 health_fn: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 capture_root: Optional[str] = None,
                 phase_map_fn: Optional[
                     Callable[[], Dict[str, Dict[str, str]]]] = None,
                 keep_captures: int = 4):
        self.registry = registry
        self.event_log = event_log
        self.health_fn = health_fn
        self.host, self.port = host, int(port)
        # profiler captures nest under one tracked root as
        # capture_NNNN dirs with keep-last-N retention; the telemetry
        # session points this at <run dir>/traces so monitor --perf
        # finds them next to the event log
        self.capture_root = capture_root
        # returns the session's instruction→phase maps, saved next to
        # each capture as phase_map.json. MUST only hand back maps
        # already built at a training sync point — building one lowers
        # the fused jit, and doing that from this HTTP thread would
        # race a concurrent dispatch's trace-time attribute rebinding.
        self.phase_map_fn = phase_map_fn
        self.keep_captures = max(1, int(keep_captures))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._trace_lock = threading.Lock()
        self._capture_seq = 0

    def start(self) -> int:
        """Bind + serve from a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        app = self

        class Handler(_Handler):
            server_app = app

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 32

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        # tight poll: shutdown() blocks a serve_forever poll period, and
        # the default 0.5 s would bill every telemetry session close
        # (train return) half a second of wall clock
        self._thread = threading.Thread(
            target=lambda: self._serve(self._httpd),
            name="telemetry-http", daemon=True)
        self._thread.start()
        return self.port

    @staticmethod
    def _serve(httpd: ThreadingHTTPServer) -> None:
        try:
            httpd.serve_forever(poll_interval=0.05)
        except Exception:  # noqa: BLE001 — the server must die quietly
            pass

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _capture_dir(self) -> str:
        if self.capture_root is None:
            self.capture_root = tempfile.mkdtemp(
                prefix="lgbtpu_traces_")
        os.makedirs(self.capture_root, exist_ok=True)
        self._capture_seq += 1
        d = os.path.join(self.capture_root,
                         f"capture_{self._capture_seq:04d}")
        os.makedirs(d, exist_ok=True)
        return d

    def _prune_captures(self) -> None:
        try:
            caps = sorted(e for e in os.listdir(self.capture_root)
                          if e.startswith("capture_"))
        except OSError:
            return
        for stale in caps[:-self.keep_captures]:
            shutil.rmtree(os.path.join(self.capture_root, stale),
                          ignore_errors=True)

    def capture_trace(self, duration_ms: int) -> dict:
        """Synchronous jax.profiler capture of the next N ms, parsed
        into the per-phase device/host summary before answering."""
        import time

        import jax

        from . import xprof
        duration_ms = max(1, min(int(duration_ms), _MAX_TRACE_MS))
        if not self._trace_lock.acquire(blocking=False):
            raise RuntimeError("a trace capture is already running")
        try:
            log_dir = self._capture_dir()
            jax.profiler.start_trace(log_dir)
            try:
                time.sleep(duration_ms / 1e3)
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    # a 200 naming this dir would hand the caller a
                    # capture that was never serialized
                    shutil.rmtree(log_dir, ignore_errors=True)
                    raise CaptureError(
                        f"stop_trace failed: {type(e).__name__}: {e}"
                    ) from e
            self._prune_captures()
            resp = {"log_dir": log_dir, "duration_ms": duration_ms}
            try:
                maps = self.phase_map_fn() if self.phase_map_fn else {}
                if maps:
                    xprof.save_phase_map(log_dir, maps)
                prof = xprof.parse_trace(log_dir,
                                         phase_maps=maps or None)
                resp.update(prof.summary_dict())
            except Exception as e:  # noqa: BLE001 — the capture is
                # still on disk and usable offline even if parsing it
                # inline failed
                resp["parse_error"] = f"{type(e).__name__}: {e}"
            return resp
        finally:
            self._trace_lock.release()


class _Handler(BaseHTTPRequestHandler):
    server_app: IntrospectionServer = None  # bound per-server subclass
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route through our logger
        from .. import log
        log.debug(f"telemetry: {self.address_string()} {fmt % args}")

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode(), "application/json")

    def do_GET(self):  # noqa: N802 (http.server API)
        app = self.server_app
        parsed = urlparse(self.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, app.registry.render().encode(),
                           "text/plain; version=0.0.4")
            elif path == "/healthz":
                health = {"status": "ok"}
                if app.health_fn is not None:
                    health.update(app.health_fn() or {})
                self._send_json(200, health)
            elif path == "/events":
                if app.event_log is None:
                    self._send_json(404, {"error": "no event log active"})
                    return
                q = parse_qs(parsed.query)
                n = int((q.get("n") or ["50"])[0])
                body = "".join(json.dumps(r, sort_keys=True) + "\n"
                               for r in app.event_log.tail(n))
                self._send(200, body.encode(), "application/x-ndjson")
            elif path == "/trace":
                q = parse_qs(parsed.query)
                ms = int((q.get("duration_ms") or ["1000"])[0])
                self._send_json(200, app.capture_trace(ms))
            else:
                self._send_json(404, {"error": f"unknown path {path}"})
        except CaptureError as e:
            self._send_json(500, {"error": str(e)})
        except RuntimeError as e:
            self._send_json(409, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — a scrape must not kill
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})


def install_sigusr1(dump_fn: Callable[[], None]):
    """Install a SIGUSR1 dump handler; returns a restore() callable.

    Signals can only be installed from the main thread — elsewhere
    (e.g. a test driving train() from a worker thread) this is a no-op
    whose restore() does nothing, matching PreemptionGuard's posture.
    """
    if threading.current_thread() is not threading.main_thread() \
            or not hasattr(signal, "SIGUSR1") or os.name == "nt":
        return lambda: None

    def _handler(signum, frame):
        try:
            dump_fn()
        except Exception:
            pass  # a dump must never take down training

    prev = signal.signal(signal.SIGUSR1, _handler)

    def restore():
        try:
            signal.signal(signal.SIGUSR1, prev)
        except (ValueError, TypeError):
            pass

    return restore
