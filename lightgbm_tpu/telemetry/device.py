"""Device-side accounting with zero device readbacks.

Three gauge groups, all host-side:

- **HBM/memory watermarks** — ``device.memory_stats()`` where the
  backend provides it (TPU/GPU runtimes report ``bytes_in_use`` /
  ``peak_bytes_in_use``); the CPU backend reports nothing, so the
  fallback is a live-buffer census over ``jax.live_arrays()``
  (addressable shards summed per device). Both are host bookkeeping —
  neither touches device queues, so sampling at sync points or scrape
  time cannot break dispatch-ahead.
- **Compile counters** — the same ``jax.monitoring`` event stream the
  recompile guard counts (``analysis/recompile_guard.COMPILE_EVENT``
  fires once per actual backend compile; cache hits don't fire).
  Steady-state training must hold these flat; a climbing compile count
  mid-run is the TD201 shape-leak signature, now visible on a live
  dashboard instead of only in tests.
- **Collective traffic** — the trace-time cost model made a run-time
  number: ``parallel/comms.py`` audits the compiled tree program once
  (static per-tree bytes, ``hist_bytes_per_tree``) and the gauge
  multiplies by trees built. Exact by construction — the program's
  collectives are fixed at compile time — with no per-iteration work
  and no device readback. The audit compile itself is lazy (first
  scrape that asks) and cached.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .core import MetricsRegistry

__all__ = ["DeviceWatch", "CollectiveWatch", "device_memory_bytes"]


def device_memory_bytes() -> Dict[str, Dict[str, int]]:
    """{device_label: {"bytes_in_use": n, "peak_bytes_in_use": n}} via
    ``memory_stats()``, falling back to a live-buffer census (peak not
    tracked by the census itself — DeviceWatch accumulates it)."""
    import jax
    out: Dict[str, Dict[str, int]] = {}
    devices = jax.devices()
    census_needed = []
    for d in devices:
        label = f"{d.platform}:{d.id}"
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            out[label] = {
                "bytes_in_use": int(stats["bytes_in_use"]),
                "peak_bytes_in_use": int(stats.get("peak_bytes_in_use",
                                                   0))}
        else:
            census_needed.append((d, label))
    if census_needed:
        by_dev: Dict[object, int] = {}
        try:
            for arr in jax.live_arrays():
                try:
                    for shard in arr.addressable_shards:
                        nbytes = getattr(shard.data, "nbytes", 0)
                        by_dev[shard.device] = (by_dev.get(shard.device, 0)
                                                + int(nbytes))
                except Exception:
                    continue
        except Exception:
            pass
        for d, label in census_needed:
            out[label] = {"bytes_in_use": int(by_dev.get(d, 0)),
                          "peak_bytes_in_use": 0}
    return out


class DeviceWatch:
    """HBM gauges + compile counters on a registry.

    ``sample()`` refreshes the in-use numbers and accumulates the peak
    watermark; it runs at engine sync points and at scrape time, never
    on the dispatch path. ``start()``/``stop()`` bound the monitoring
    listener's lifetime to the telemetry session."""

    def __init__(self, registry: MetricsRegistry):
        self._lock = threading.Lock()
        self._peaks: Dict[str, int] = {}
        self._compiles = 0
        self._compile_s = 0.0
        self._cb = None
        self._in_use = registry.gauge(
            "device_hbm_bytes_in_use",
            "Per-device bytes in use (memory_stats or live-buffer "
            "census)", labels=("device",))
        self._peak = registry.gauge(
            "device_hbm_bytes_peak",
            "Per-device peak bytes observed (runtime watermark, or max "
            "over samples)", labels=("device",))
        registry.gauge("xla_compiles_total",
                       "Backend compiles since telemetry start "
                       "(steady state must hold this flat)",
                       fn=lambda: self._compiles)
        registry.gauge("xla_compile_seconds_total",
                       "Seconds spent in backend compiles",
                       fn=lambda: self._compile_s)

    def _on_event(self, event, duration, **kw) -> None:
        from ..analysis.recompile_guard import COMPILE_EVENT
        if event == COMPILE_EVENT:
            with self._lock:
                self._compiles += 1
                self._compile_s += float(duration)

    def start(self) -> None:
        if self._cb is None:
            import jax
            self._cb = self._on_event
            jax.monitoring.register_event_duration_secs_listener(self._cb)

    def stop(self) -> None:
        if self._cb is not None:
            from ..analysis.recompile_guard import _unregister
            _unregister(self._cb)
            self._cb = None

    def sample(self) -> Dict[str, Dict[str, int]]:
        mem = device_memory_bytes()
        with self._lock:
            for label, stats in mem.items():
                peak = max(self._peaks.get(label, 0),
                           stats["peak_bytes_in_use"],
                           stats["bytes_in_use"])
                self._peaks[label] = peak
                self._in_use.labels(label).set(stats["bytes_in_use"])
                self._peak.labels(label).set(peak)
        return mem

    @property
    def compiles(self) -> int:
        return self._compiles


class CollectiveWatch:
    """Collective-traffic gauges: static per-tree bytes (comms audit of
    the sharding plan's tree program) × trees built.

    The audit compiles one synthetic tree-build program the first time
    a scrape asks (cached thereafter; serial runs short-circuit to 0),
    so the training path never pays for it and no device readback ever
    happens — invocation counts come from the host-side model list."""

    def __init__(self, registry: MetricsRegistry,
                 trees_fn: Callable[[], int]):
        self._lock = threading.Lock()
        self._gb = None
        self._per_tree: Optional[int] = None
        self._wire_per_tree: Optional[int] = None
        self._trees_fn = trees_fn
        registry.gauge(
            "train_collective_hist_bytes_per_tree",
            "Per-chip histogram-merge bytes for one tree (static comms "
            "audit of the compiled program)",
            fn=self._bytes_per_tree)
        registry.gauge(
            "train_collective_hist_bytes_total",
            "Per-chip histogram-merge bytes so far (static per-tree "
            "bytes x trees built; exact, no device readback)",
            fn=lambda: self._bytes_per_tree() * self._trees_fn())

    def attach(self, gbdt) -> None:
        """Bind the booster whose plan/shape the audit should mirror."""
        with self._lock:
            if gbdt is not self._gb:
                self._gb = gbdt
                self._per_tree = None

    def _bytes_per_tree(self) -> int:
        with self._lock:
            if self._per_tree is not None:
                return self._per_tree
            gb = self._gb
            if gb is None or getattr(gb, "plan", None) is None:
                self._per_tree = 0
                return 0
            try:
                import numpy as np

                from ..parallel.comms import (audit_tree_program,
                                              hist_bytes_per_tree)
                cfg = gb.config
                num_leaves = int(cfg.num_leaves)
                leaf_batch = max(1, min(int(cfg.leaf_batch),
                                        num_leaves - 1))
                report = audit_tree_program(
                    gb.plan, F=int(np.asarray(gb.num_bins_pf).shape[0]),
                    B=int(gb.B), num_leaves=num_leaves,
                    leaf_batch=leaf_batch,
                    hist_dtype=str(cfg.hist_dtype))
                self._per_tree = int(hist_bytes_per_tree(
                    report, num_leaves, leaf_batch))
            except Exception:
                self._per_tree = 0  # audit failure must not kill scrape
            return self._per_tree
