"""Perf-regression gate: tolerance-band comparison against a committed
baseline.

The collection half lives in ``scripts/perf_gate.py`` (it trains a
small canonical booster and prices it); this module is the pure
comparison layer so the tolerance semantics are unit-testable without
training anything:

- ``time`` metrics (ms/tree): only growth is a regression — a faster
  run than baseline passes and is the cue to re-bless via ``--update``.
- ``throughput`` metrics (rows/s, TFLOP/s): only shrinkage regresses.
- ``static`` metrics (XLA ``cost_analysis`` flops/bytes of a compiled
  program, op counts): drift in EITHER direction fails — these numbers
  are deterministic for a fixed config, so any change means the
  compiled program changed and must be blessed deliberately.

A metric present in the baseline but missing from the current run
fails (a silently vanished metric is a hole in the gate, not a pass);
metrics the runner deliberately skipped (timing on a loaded host) are
reported as ``skip`` without failing; metrics new in the current run
warn until ``--update`` adds them to the baseline.

Timing comparisons are only meaningful on an otherwise-idle machine:
:func:`host_quiet` (1-minute loadavg vs core count) is how the
collector decides, and the baseline records its host signature so a
baseline from a different machine degrades timing failures to
warnings instead of gating on apples-vs-oranges.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["Tolerance", "Check", "GateResult", "compare", "host_quiet",
           "host_signature", "load_baseline", "save_baseline",
           "DEFAULT_TOLERANCES", "BASELINE_NAME"]

BASELINE_NAME = "PERF_BASELINE.json"


@dataclasses.dataclass(frozen=True)
class Tolerance:
    """One metric's allowed band relative to baseline ``b``:

    - ``time``:       pass while ``cur <= b * ratio``
    - ``throughput``: pass while ``cur >= b / ratio``
    - ``static``:     pass while ``b / ratio <= cur <= b * ratio``
    """
    kind: str           # "time" | "throughput" | "static"
    ratio: float        # >= 1; 1.25 == 25% band

    def __post_init__(self):
        if self.kind not in ("time", "throughput", "static"):
            raise ValueError(f"unknown tolerance kind {self.kind!r}")
        if not self.ratio >= 1.0:
            raise ValueError(f"tolerance ratio must be >= 1, "
                             f"got {self.ratio}")

    def check(self, current: float, baseline: float
              ) -> Tuple[bool, str]:
        """(ok, detail) for one comparison."""
        if baseline == 0:
            ok = current == 0 if self.kind == "static" else True
            return ok, f"baseline 0, current {current:g}"
        rel = current / baseline
        band = (f"{rel:.3f}x baseline "
                f"(band {1 / self.ratio:.3f}..{self.ratio:.3f})")
        if self.kind == "time":
            return rel <= self.ratio, band
        if self.kind == "throughput":
            return rel >= 1.0 / self.ratio, band
        return 1.0 / self.ratio <= rel <= self.ratio, band


# Metric-name tolerance table for the canonical gate. Static
# cost-model numbers get tight bands (they only move when the compiled
# program moves); wall-clock gets a wide one (CI hosts are noisy).
DEFAULT_TOLERANCES: Dict[str, Tolerance] = {
    "ms_per_tree": Tolerance("time", 1.6),
    "predict_ms": Tolerance("time", 1.6),
    "hist_flops_xla": Tolerance("static", 1.25),
    "hist_bytes_xla": Tolerance("static", 1.25),
    # the analytical cross-check: XLA's priced flops over the
    # hand-derived count must stay within 2x in BOTH directions, else
    # one of the two models is wrong (ISSUE 11 acceptance band)
    "hist_flops_xla_ratio": Tolerance("static", 2.0),
    "cost_fused_step_flops": Tolerance("static", 1.25),
    "cost_fused_step_bytes": Tolerance("static", 1.25),
    "cost_fused_step_peak_bytes": Tolerance("static", 1.5),
    "cost_fused_step_n_ops": Tolerance("static", 1.25),
    "cost_predict_flops": Tolerance("static", 1.25),
    "cost_predict_bytes": Tolerance("static", 1.25),
    # out-of-core probe (ISSUE 13): wall-clock/throughput on shared CI
    # hosts, so the bands are wide; overlap_fraction is host-scheduling
    # dependent and only gates a total collapse
    "ingest_rows_per_s": Tolerance("throughput", 2.5),
    "ingest_chunked_ms_per_tree": Tolerance("time", 2.5),
    "ingest_resident_ms_per_tree": Tolerance("time", 2.5),
    "ingest_prefetch_overlap": Tolerance("throughput", 10.0),
    # fused build+split pass (ISSUE 14): the byte counts are pure
    # functions of the probe lattice and the kernel's chunk plan — any
    # drift means the cost model or _plan_chunks changed; the scan
    # wall-clock gets the usual noisy-CI band
    "hist_bytes_twopass": Tolerance("static", 1.1),
    "hist_bytes_fused": Tolerance("static", 1.1),
    "hist_fused_bytes_reduction": Tolerance("static", 1.1),
    "split_scan_ms": Tolerance("time", 2.5),
    # serving fleet (ISSUE 15): rows/s + tail latency through the async
    # front end on a loaded CI host — wide bands; the tensorized
    # program's price is static like every other compiled program
    "serve_rows_per_s": Tolerance("throughput", 2.5),
    "serve_p99_ms": Tolerance("time", 2.5),
    "compiled_predict_speedup": Tolerance("throughput", 2.5),
    "cost_compiled_predict_flops": Tolerance("static", 1.25),
    "cost_compiled_predict_bytes": Tolerance("static", 1.25),
}
_DEFAULT = Tolerance("static", 1.5)


@dataclasses.dataclass
class Check:
    metric: str
    status: str                 # pass | fail | missing | skip | new
    current: Optional[float]
    baseline: Optional[float]
    detail: str = ""

    @property
    def failed(self) -> bool:
        return self.status in ("fail", "missing")


@dataclasses.dataclass
class GateResult:
    checks: List[Check]

    @property
    def ok(self) -> bool:
        return not any(c.failed for c in self.checks)

    @property
    def failed(self) -> List[str]:
        return [c.metric for c in self.checks if c.failed]

    def render(self) -> str:
        rows = []
        for c in sorted(self.checks, key=lambda c: c.metric):
            cur = "-" if c.current is None else f"{c.current:g}"
            base = "-" if c.baseline is None else f"{c.baseline:g}"
            rows.append(f"  {c.status.upper():<7} {c.metric:<28} "
                        f"cur={cur:<14} base={base:<14} {c.detail}")
        verdict = "PASS" if self.ok else \
            f"FAIL ({', '.join(self.failed)})"
        return "\n".join(rows + [f"perf gate: {verdict}"])


def compare(current: Dict[str, float], baseline: Dict[str, float],
            tolerances: Optional[Dict[str, Tolerance]] = None,
            skipped: Iterable[str] = ()) -> GateResult:
    """Compare a collected metric dict against the baseline's.

    ``skipped`` names metrics the collector deliberately did not
    measure this run (e.g. timing on a loaded host): those report
    ``skip`` instead of ``missing`` and never fail the gate.
    """
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    skipped = set(skipped)
    checks: List[Check] = []
    for name, base in sorted(baseline.items()):
        if name in skipped:
            checks.append(Check(name, "skip", None, base,
                                "not measured this run"))
            continue
        if name not in current:
            checks.append(Check(name, "missing", None, base,
                                "metric vanished from the run"))
            continue
        cur = float(current[name])
        t = tol.get(name, _DEFAULT)
        ok, detail = t.check(cur, float(base))
        checks.append(Check(name, "pass" if ok else "fail",
                            cur, float(base), f"[{t.kind}] {detail}"))
    for name in sorted(set(current) - set(baseline)):
        checks.append(Check(name, "new", float(current[name]), None,
                            "not in baseline (bless via --update)"))
    return GateResult(checks)


def host_quiet(max_load_frac: float = 0.75) -> bool:
    """True when the 1-minute loadavg leaves headroom for a timing
    measurement (below ``max_load_frac`` of the core count). Platforms
    without getloadavg report quiet — better a noisy measurement than
    a permanently skipped gate."""
    try:
        load1 = os.getloadavg()[0]
    except (AttributeError, OSError):
        return True
    cores = os.cpu_count() or 1
    return load1 < cores * max_load_frac


def host_signature() -> Dict[str, Any]:
    """What timing numbers are comparable across: machine + core count
    + python/jax major surface. Stored in the baseline; a mismatch
    degrades timing failures to warnings."""
    try:
        import jax
        jax_ver = jax.__version__
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — signature must not need a device
        jax_ver, backend = "?", "?"
    return {
        "machine": platform.machine(),
        "cpu_count": os.cpu_count() or 1,
        "backend": backend,
        "jax": jax_ver,
    }


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "metrics" not in obj:
        raise ValueError(f"{path}: not a perf baseline "
                         "(want {'metrics': {...}, ...})")
    return obj


def save_baseline(path: str, metrics: Dict[str, float],
                  meta: Optional[Dict[str, Any]] = None) -> None:
    obj = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "host": host_signature(),
        "meta": meta or {},
        "metrics": {k: metrics[k] for k in sorted(metrics)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")
