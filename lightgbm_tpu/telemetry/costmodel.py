"""Compiled-program cost model: what XLA actually built, priced.

The bench's roofline numbers (``hist_tflops``/``hist_hbm_gbps``) were
hand-derived FLOP/byte formulas; this module prices the *compiled*
programs instead, via ``Compiled.cost_analysis()`` /
``memory_analysis()``, and cross-checks the analytical counts against
XLA's. It covers the staged programs the trace doctor already builds —
the fused boosting step, the data-parallel tree builder, the packed
ensemble predict, the serving batcher rungs — and attributes a
program's ops/result-bytes to the canonical phases of ``phases.py``
through the ``op_name`` metadata (``jax.named_scope`` prefixes) that
``analysis/hlo_walk.py`` parses.

Also owns the chip peak table (``TPU_PEAKS``, moved out of bench.py)
so live runs — not just the bench — can state MFU / bandwidth
utilization, and the instruction→phase map (`instruction_phase_map`)
the trace parser (``xprof.py``) uses to attribute CPU executor events
that carry only ``{hlo_module, hlo_op}``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.hlo_walk import parse_all_ops
from .xprof import UNKNOWN, phase_of_path

__all__ = ["TPU_PEAKS", "HIST_CH", "CostReport", "cost_report",
           "instruction_phase_map", "module_name",
           "fused_compiled", "booster_phase_maps",
           "staged_cost_reports", "analytical_hist_counts",
           "analytical_build_split_counts",
           "kernel_roofline_fields", "roofline_utilization",
           "hist_xla_cost", "chip_peaks"]

# bf16 matmul TFLOP/s and HBM GB/s peaks per chip generation (public
# spec-sheet numbers; used only to contextualize measured timings)
TPU_PEAKS = {"v4": (275.0, 1228.0), "v5e": (197.0, 819.0),
             "v5p": (459.0, 2765.0), "v6": (918.0, 1640.0)}

# histogram channels: (grad, hess, count)
HIST_CH = 3

_MODULE_RE = re.compile(r"^HloModule\s+([^\s,]+)", re.MULTILINE)
_COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.-]+)\s*\(.*\)\s*->"
                      r"\s*.+\{\s*$")
_CALLS_RE = re.compile(r"\b(?:calls|to_apply|body|condition)="
                       r"%?([\w.-]+)")

# opcodes that never execute (metadata / plumbing) — excluded from the
# per-phase op/byte attribution so it reflects real work
_NOOP_OPCODES = frozenset({"parameter", "constant", "tuple",
                           "get-tuple-element", "bitcast"})


def chip_peaks() -> Optional[Tuple[str, float, float]]:
    """(device_kind, peak TFLOP/s, peak HBM GB/s) of device 0, when it
    is a TPU generation the table knows; None elsewhere (CPU hosts)."""
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — peaks are garnish, never fatal
        return None
    for k, (pf, pb) in TPU_PEAKS.items():
        if k in kind:
            return kind, pf, pb
    return None


# ----------------------------------------------------------------------
# Analytical histogram-kernel counts (the formulas bench.py carried)

def analytical_hist_counts(R: int, F: int, B: int,
                           L: int) -> Tuple[float, float]:
    """(flops, bytes) of one histogram build as hand-derived: FLOPs
    count the one-hot matmul as executed on the MXU
    (2·R·(F·B)·(L·CH)); bytes count the irreducible streams (bins
    uint8 + gh f32 in, hist f32 out)."""
    flops = 2.0 * R * (F * B) * (L * HIST_CH)
    bytes_ = R * F + R * HIST_CH * 4 + F * B * L * HIST_CH * 4
    return flops, bytes_


def analytical_build_split_counts(R: int, F: int, B: int, L: int, *,
                                  fused: bool,
                                  emit_hist: bool = False
                                  ) -> Tuple[float, float]:
    """(flops, bytes) of one full BUILD+SPLIT pass — histogram plus the
    best-split gain scan, the quantity the fused kernel optimizes.

    Two-pass: the [F, B, L, CH] f32 histogram goes to HBM once
    (`analytical_hist_counts` already prices the write) and the split
    scan reads it back — one extra lattice-sized stream. Fused: the
    epilogue scans the VMEM-resident block, so the lattice never
    round-trips; the only extra HBM traffic is the per-(feature-chunk,
    leaf) candidate-record stream (`fused_candidate_bytes`), with the
    lattice write retained only in `emit_hist` mode (subtraction-cache
    feeding). The scan's flops (a few prefix-sum passes over the
    lattice) are identical either way and negligible next to the
    one-hot matmul; counted once as 8 ops/cell so the ratio stays a
    pure bytes story."""
    flops, hist_bytes = analytical_hist_counts(R, F, B, L)
    lattice = F * B * L * HIST_CH * 4
    flops += 8.0 * F * B * L * HIST_CH
    if not fused:
        return flops, hist_bytes + lattice
    from ..ops.pallas_histogram import fused_candidate_bytes
    bytes_ = (hist_bytes - lattice) + fused_candidate_bytes(F, B, L)
    if emit_hist:
        bytes_ += lattice
    return flops, bytes_


def roofline_utilization(tflops: float, gbps: float) -> Dict[str, Any]:
    """MFU / HBM utilization vs the chip peak, when on a known TPU."""
    peaks = chip_peaks()
    if peaks is None:
        return {}
    kind, pf, pb = peaks
    return {"hist_mfu": round(tflops / pf, 4),
            "hist_hbm_util": round(gbps / pb, 4),
            "chip": kind}


def kernel_roofline_fields(platform: str, t_hist_s: float,
                           R: int, F: int, B: int, L: int) -> dict:
    """Derived FLOP/s + HBM bandwidth for one histogram build vs chip
    peak (VERDICT r3 #1c — the numbers the >=5x-CUDA target is judged
    on). On CPU the same fields are emitted, labelled by `platform`,
    peak comparison omitted."""
    flops, bytes_ = analytical_hist_counts(R, F, B, L)
    out = {"hist_tflops": round(flops / t_hist_s / 1e12, 3),
           "hist_hbm_gbps": round(bytes_ / t_hist_s / 1e9, 2)}
    if platform == "tpu":
        out.update(roofline_utilization(out["hist_tflops"],
                                        out["hist_hbm_gbps"]))
    return out


def hist_xla_cost(R: int, F: int, B: int, L: int, *,
                  impl: str = "matmul",
                  hist_dtype: str = "bfloat16") -> Dict[str, float]:
    """XLA's own price of one histogram build: compile
    ``ops.histogram.build_histograms`` at the given lattice and read
    ``cost_analysis``. ``impl='matmul'`` is the formulation the
    analytical count models (one-hot MXU matmul), so these two must
    agree within 2x — the perf gate asserts it.

    Compiled with ``block_rows=R`` (one block): ``cost_analysis``
    prices a while-loop body ONCE regardless of trip count, so the
    production row-chunked program under-reports total flops by the
    number of blocks. The unchunked program does the same logical work
    in straight-line HLO, which is what both the analytical count and
    a measured wall-clock divide against."""
    import jax
    import jax.numpy as jnp

    from ..ops.histogram import build_histograms
    bins = jnp.zeros((R, F), jnp.uint8)
    gh = jnp.zeros((R, HIST_CH), jnp.float32)
    rl = jnp.zeros((R,), jnp.int32)
    lids = jnp.arange(L, dtype=jnp.int32)

    def fn(b, g, r, li):
        return build_histograms(b, g, r, li, num_bins=B,
                                hist_dtype=hist_dtype, impl=impl,
                                block_rows=R)
    compiled = jax.jit(fn).lower(bins, gh, rl, lids).compile()
    ca = _cost_dict(compiled)
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


# ----------------------------------------------------------------------
# CostReport over one compiled program

def _cost_dict(compiled) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend may not implement it
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if isinstance(ca, dict) else {}


@dataclasses.dataclass
class CostReport:
    """One compiled program, priced: XLA flop/byte totals, the memory
    footprint, and per-phase attribution from op_name metadata."""
    label: str
    flops: float
    transcendentals: float
    bytes_accessed: float
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int                 # argument + output + temp
    generated_code_bytes: int
    n_ops: int
    phase_ops: Dict[str, int]       # phase → executable op count
    phase_bytes: Dict[str, int]     # phase → result bytes of those ops

    def as_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k in ("flops", "transcendentals", "bytes_accessed"):
            d[k] = round(float(d[k]), 1)
        return d


def cost_report(compiled, label: str = "program",
                hlo_text: Optional[str] = None) -> CostReport:
    """Price one ``Compiled`` (jax ``.lower(...).compile()`` result)."""
    ca = _cost_dict(compiled)
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        pass

    def _m(attr: str) -> int:
        return int(getattr(mem, attr, 0) or 0) if mem is not None else 0

    if hlo_text is None:
        try:
            hlo_text = compiled.as_text()
        except Exception:  # noqa: BLE001
            hlo_text = ""
    phase_ops: Dict[str, int] = {}
    phase_bytes: Dict[str, int] = {}
    n_ops = 0
    for op, _comp, ph in _resolved_phases(hlo_text or ""):
        if op.opcode in _NOOP_OPCODES:
            continue
        n_ops += 1
        ph = ph or UNKNOWN
        phase_ops[ph] = phase_ops.get(ph, 0) + 1
        phase_bytes[ph] = phase_bytes.get(ph, 0) + op.out_bytes
    arg_b, out_b, tmp_b = (_m("argument_size_in_bytes"),
                           _m("output_size_in_bytes"),
                           _m("temp_size_in_bytes"))
    return CostReport(
        label=label,
        flops=float(ca.get("flops", 0.0)),
        transcendentals=float(ca.get("transcendentals", 0.0)),
        bytes_accessed=float(ca.get("bytes accessed", 0.0)),
        argument_bytes=arg_b, output_bytes=out_b, temp_bytes=tmp_b,
        peak_bytes=arg_b + out_b + tmp_b,
        generated_code_bytes=_m("generated_code_size_in_bytes"),
        n_ops=n_ops,
        phase_ops=dict(sorted(phase_ops.items())),
        phase_bytes=dict(sorted(phase_bytes.items())))


# ----------------------------------------------------------------------
# Instruction → phase maps (xprof attribution path 2)

def module_name(hlo_text: str) -> str:
    m = _MODULE_RE.search(hlo_text or "")
    return m.group(1) if m else ""


def _resolved_phases(hlo_text: str):
    """[(HloOp, computation, phase-or-None)] with hierarchical phase
    resolution: an instruction's own ``op_name`` metadata wins; an
    unannotated fusion/call takes the dominant phase of the computation
    it calls; remaining compiler-generated plumbing (loop-carry copies,
    induction arithmetic — XLA strips their metadata) inherits the
    dominant phase of its enclosing computation. The hierarchy matters
    on CPU, where while-loop-body micro-ops execute hundreds of
    thousands of times and would otherwise all land in ``unknown``."""
    comp = ""
    rows: List[Tuple[Any, str, Optional[str], Optional[str]]] = []
    votes: Dict[str, Dict[str, int]] = {}
    for line in (hlo_text or "").splitlines():
        mc = _COMP_RE.match(line)
        if mc and "= " not in line.split("{")[0]:
            comp = mc.group(1)
            continue
        parsed = parse_all_ops(line)
        if not parsed:
            continue
        op = parsed[0]
        own = phase_of_path(op.op_name)
        calls = _CALLS_RE.findall(line)
        rows.append((op, comp, own, calls[-1] if calls else None))
        if own is not None:
            v = votes.setdefault(comp, {})
            v[own] = v.get(own, 0) + 1
    dominant = {c: max(v, key=v.get) for c, v in votes.items() if v}
    out = []
    for op, c, own, callee in rows:
        ph = own
        if ph is None and callee is not None:
            ph = dominant.get(callee)
        if ph is None:
            ph = dominant.get(c)
        out.append((op, c, ph))
    return out


def instruction_phase_map(hlo_text: str
                          ) -> Tuple[str, Dict[str, str]]:
    """(module name, {instruction name → phase}) — the lookup table the
    trace parser uses for executor events that name only ``hlo_op``.
    Phases resolve hierarchically (see :func:`_resolved_phases`)."""
    table: Dict[str, str] = {}
    for op, _comp, ph in _resolved_phases(hlo_text):
        if ph is not None and op.name:
            table[op.name] = ph
    return module_name(hlo_text), table


# ----------------------------------------------------------------------
# The staged programs (same set the trace doctor lints)

def fused_compiled(bst, *, force: bool = True):
    """The trainer's own compiled fused step (donation flags and all),
    or None when the fused gate pins the legacy driver. ``force=False``
    refuses to trigger a fresh trace/compile — the mode for calls off
    the training thread, where ``_fused_step_entry``'s trace-time
    attribute rebinding must not race a concurrent dispatch."""
    from ..analysis.doctor import _fused_trace_args, _pin_fused
    gb = getattr(bst, "_gbdt", None) or bst
    with _pin_fused(True):
        reason = gb._fused_gate_reason()
    if reason:
        return None
    if gb._fused_jit is None:
        if not force:
            return None
        gb._fused_dispatch()
        gb.sync()
    args = _fused_trace_args(gb)
    return gb._fused_jit.lower(*args).compile()


def booster_phase_maps(bst, compiled=None, *,
                       force: bool = True) -> Dict[str, Dict[str, str]]:
    """Phase maps for a trained booster's staged programs (today: the
    fused step — the one whose CPU executor events need the lookup)."""
    if compiled is None:
        try:
            compiled = fused_compiled(bst, force=force)
        except Exception:  # noqa: BLE001 — maps are best-effort
            compiled = None
    if compiled is None:
        return {}
    mod, table = instruction_phase_map(compiled.as_text())
    return {mod: table} if table else {}


def staged_cost_reports(bst, *,
                        batcher_rows: int = 16) -> Dict[str, CostReport]:
    """CostReports over the staged programs of one trained booster:
    the fused step (when the gate allows), the packed-ensemble predict,
    one serving-batcher rung, and — on a multi-device host — the
    data-parallel tree builder."""
    import jax
    import jax.numpy as jnp
    reports: Dict[str, CostReport] = {}
    compiled = fused_compiled(bst)
    if compiled is not None:
        reports["fused_step"] = cost_report(compiled, "fused_step")
    from ..ops.predict_ensemble import _walk, pack_ensemble
    ens = pack_ensemble(bst._trees)
    F = bst.num_feature()
    for label, rows in (("predict", 256), (f"batcher_b{batcher_rows}",
                                           batcher_rows)):
        X = jnp.zeros((rows, F), jnp.float32)
        c = jax.jit(_walk).lower(ens, X).compile()
        reports[label] = cost_report(c, label)
    # the tensorized serving program (ISSUE 15): same 256-row shape as
    # the packed walk above, so the two predict paths gate against the
    # same lattice
    try:
        from ..codegen import CompiledEnsemble
        ce = CompiledEnsemble(bst)
        reports["compiled_predict"] = cost_report(
            ce.lower_serving(rows=256), "compiled_predict")
    except (ValueError, TypeError):   # non-tensorizable model: skip
        pass
    if len(jax.devices()) >= 2:
        try:
            reports["tree_builder"] = _tree_builder_report()
        except Exception:  # noqa: BLE001 — mesh probe is best-effort
            pass
    return reports


def _tree_builder_report(R: int = 256, F: int = 8,
                         B: int = 16) -> CostReport:
    import jax

    from ..ops.split import SplitParams
    from ..parallel.comms import _synthetic_inputs
    from ..parallel.data_parallel import DataParallelPlan
    plan = DataParallelPlan(hist_merge="reduce_scatter")
    bins, gh, rl0, meta = _synthetic_inputs(R, F, B)
    kw = dict(num_leaves=7, leaf_batch=4, max_depth=-1, num_bins=B,
              hist_dtype="float32", block_rows=R // plan.num_shards,
              split_params=SplitParams(min_data_in_leaf=2,
                                       min_sum_hessian_in_leaf=1e-3))

    def fn(b, g, rl):
        return plan.build_tree(b, g, rl, *meta, **kw)[0]
    sharded = (plan.shard_bins(bins), plan.shard_rows(gh),
               plan.shard_rows(rl0))
    c = jax.jit(fn).lower(*sharded).compile()
    return cost_report(c, "tree_builder")
