"""Structured run-event log: append-only JSONL, one record per
operational event.

Analog of the reference's per-iteration logger stream (gbdt.cpp's
"Iteration:%d, ..." lines) made machine-readable: a training run writes
a header record (config fingerprint, mesh/plan shape, feature flags,
versions) and typed records for eval-point iterations, checkpoint
write/restore, preemption, nan-guard trips, serving swap/rollback, and
warning/fatal log lines. ``python -m lightgbm_tpu monitor`` renders a
log into a phase/throughput/faults report.

Sync discipline: records are emitted ONLY at existing host sync points
(engine.train's eval-cadence sync, checkpoint boundaries, fault
handlers) — every number in an ``iteration`` record was already on the
host when the record is written, so steady state stays dispatch-ahead
with zero added device syncs.

Durability: appends go through ``resilience.atomic_io.
atomic_append_line`` (O_APPEND + single write — no torn lines); a
SIGKILL can truncate only the final record, which readers skip. On
``resume=auto`` the restored run *splices* the log: iteration/checkpoint
records beyond the restore point are dropped (they will be re-emitted
bit-identically by the resumed run) and the header is re-emitted with
the same config fingerprint, so a spliced log reads exactly like an
uninterrupted run's log plus its fault history.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..resilience.atomic_io import atomic_append_line, atomic_write_text

__all__ = ["EventLog", "EVENT_TYPES", "check_records", "read_events",
           "set_active", "active", "record_log", "record_serving"]

# Required payload fields per event type (beyond the envelope: every
# record carries ``event``, ``ts``, ``seq``). `monitor --check` and the
# chaos splice cell validate against this table.
EVENT_TYPES: Dict[str, tuple] = {
    "run_header": ("fingerprint", "driver", "versions"),
    "iteration": ("iter", "ms_per_tree", "metrics", "phase_s"),
    "checkpoint": ("action", "iter", "path"),
    "preemption": ("signum", "iter"),
    "nan_guard": ("iter", "policy"),
    "resume": ("iter", "path"),
    # elastic resilience: a resume re-sharded checkpoint state onto a
    # different topology; the supervisor retried/degraded after a
    # device loss. Fault records — the resume splice keeps them.
    "reshard": ("iter", "from", "to"),
    "degraded": ("iter", "attempt", "action"),
    "early_stop": ("iter", "best_iter"),
    "log": ("level", "msg"),
    "serving": ("action", "model"),
    "train_end": ("iter", "trees", "wall_s"),
    "cost_model": ("label", "flops", "bytes_accessed"),
    "perf_gate": ("status", "checked", "failed"),
    # out-of-core ingest (data/ingest.py): one record per completed
    # pass; shard writes are individually atomic so the log is
    # observability, not recovery state
    "ingest": ("action", "rows", "shards"),
}


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event log. A non-parsing FINAL line is an
    interrupted run's torn tail and is skipped; a non-parsing interior
    line is corruption and raises."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except FileNotFoundError:
        return []
    for i, ln in enumerate(lines):
        if not ln.strip():
            continue
        try:
            records.append(json.loads(ln))
        except ValueError:
            if i == len(lines) - 1:
                break  # torn tail from a killed writer
            raise ValueError(f"{path}:{i + 1}: corrupt event record")
    return records


def check_records(records: List[Dict[str, Any]]) -> List[str]:
    """Schema self-check (`monitor --check`): returns a list of
    problems, empty when the log is well-formed."""
    errors: List[str] = []
    last_seq = -1
    last_iter: Optional[int] = None
    header_fps = set()
    for i, rec in enumerate(records):
        where = f"record {i}"
        ev = rec.get("event")
        if ev not in EVENT_TYPES:
            errors.append(f"{where}: unknown event type {ev!r}")
            continue
        for key in ("ts", "seq") + EVENT_TYPES[ev]:
            if key not in rec:
                errors.append(f"{where} ({ev}): missing field {key!r}")
        seq = rec.get("seq", -1)
        if isinstance(seq, int):
            if seq <= last_seq:
                errors.append(f"{where} ({ev}): seq {seq} not "
                              f"increasing (prev {last_seq})")
            last_seq = seq
        if ev == "run_header":
            header_fps.add(rec.get("fingerprint"))
            last_iter = None  # resumed segment restarts the iter chain
        elif ev == "iteration":
            it = rec.get("iter")
            if isinstance(it, int) and last_iter is not None \
                    and it <= last_iter:
                errors.append(f"{where}: iteration {it} after "
                              f"{last_iter} (duplicate or out of order)")
            if isinstance(it, int):
                last_iter = it
    if not records:
        errors.append("empty event log")
    elif records[0].get("event") != "run_header":
        errors.append("first record is not a run_header")
    if len(header_fps) > 1:
        errors.append(f"run_header fingerprints disagree: "
                      f"{sorted(map(str, header_fps))}")
    return errors


class EventLog:
    """Append-only JSONL writer bound to one path.

    Thread-safe: the sequence counter and append are under one lock
    (the exporter's HTTP threads never write, but log.py routing can
    fire from any thread)."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self._lock = threading.Lock()
        self._closed = False
        # continue the sequence across resume: a spliced log keeps its
        # monotone seq so `check_records` can order segments
        self._seq = max((r.get("seq", -1) for r in read_events(self.path)
                         if isinstance(r.get("seq"), int)), default=-1)

    def append(self, event: str, **fields: Any) -> Dict[str, Any]:
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        with self._lock:
            if self._closed:
                return {}
            self._seq += 1
            rec = {"event": event, "ts": round(time.time(), 6),
                   "seq": self._seq}
            rec.update(fields)
            atomic_append_line(self.path,
                               json.dumps(rec, sort_keys=True))
        return rec

    def records(self) -> List[Dict[str, Any]]:
        return read_events(self.path)

    def tail(self, n: int = 50) -> List[Dict[str, Any]]:
        return self.records()[-max(int(n), 0):]

    def splice_to_iteration(self, iteration: int) -> int:
        """Resume splice: atomically rewrite the log without the
        iteration/checkpoint-write records BEYOND the restore point —
        the resumed run re-emits those bit-identically, so keeping them
        would duplicate the chain. Fault records (preemption,
        nan_guard, log) stay: they are history, not progress. Returns
        the number of records dropped."""
        with self._lock:
            records = read_events(self.path)
            keep = []
            for rec in records:
                ev = rec.get("event")
                it = rec.get("iter")
                beyond = isinstance(it, int) and it > iteration
                if ev == "iteration" and beyond:
                    continue
                if ev == "checkpoint" and rec.get("action") == "write" \
                        and beyond:
                    continue
                if ev == "train_end":
                    continue  # the resumed run owns the final record
                keep.append(rec)
            if len(keep) != len(records):
                atomic_write_text(
                    self.path,
                    "".join(json.dumps(r, sort_keys=True) + "\n"
                            for r in keep))
            return len(records) - len(keep)

    def close(self) -> None:
        with self._lock:
            self._closed = True


# ----------------------------------------------------------------------
# Active-run registration: log.py routes warning/fatal records here and
# serving routes swap/rollback, without either importing engine state.

_ACTIVE: Optional[EventLog] = None
_ROUTING = threading.local()


def set_active(log: Optional[EventLog]) -> None:
    global _ACTIVE
    _ACTIVE = log


def active() -> Optional[EventLog]:
    return _ACTIVE


def _route(event: str, **fields: Any) -> None:
    """Best-effort append to the active run's log. Reentrancy-guarded:
    an append failure that logged a warning must not recurse."""
    log = _ACTIVE
    if log is None or getattr(_ROUTING, "busy", False):
        return
    _ROUTING.busy = True
    try:
        log.append(event, **fields)
    except Exception:
        pass  # observability must never take down training
    finally:
        _ROUTING.busy = False


def record_log(level: str, msg: str) -> None:
    """log.py's single choke point: warning/fatal lines of an active
    run land in its event log verbatim (no second formatting path)."""
    _route("log", level=level, msg=msg)


def record_serving(action: str, model: str,
                   version: Optional[int] = None) -> None:
    """Serving swap/rollback events (model registry movements)."""
    _route("serving", action=action, model=model, version=version)
